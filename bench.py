"""Headline benchmark: Llama-3-family pretraining tokens/sec/chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference's headline metric is Llama-3-8B pretraining tokens/sec/chip
with MFU >= 40% as the north star (BASELINE.md).  This bench runs a
compiled (jit, donated-state) bf16 training step of the Llama-3
architecture at the TRUE recipe shape — vocab 128,256, sequence 8192 —
at the largest (model, batch) from the ladder that fits the local chip's
HBM, measures steady-state tokens/sec over >=20 iterations, and reports
BOTH MFU conventions (6N, and 6N + causal-attention FLOPs) as
BASELINE.md promises.  ``vs_baseline`` is MFU(6N)/0.40 (no
reference-published numbers exist: BASELINE.json ``published`` is {}).

``python bench.py --ladder`` additionally measures the BASELINE.md
measurement-ladder rows that fit one chip (GPT-2 124M, Llama true-shape,
Qwen2-MoE, decode tokens/sec) and prints one JSON line per row.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# Peak dense bf16 FLOP/s and HBM bytes per chip, by normalized
# PJRT device_kind substring (e.g. "TPU v5 lite" -> v5lite).
_CHIP_TABLE = [
    ("v6e", 918e12, 32e9), ("v6", 918e12, 32e9), ("v5p", 459e12, 95e9),
    ("v5e", 197e12, 16e9), ("v5lite", 197e12, 16e9), ("v4", 275e12, 32e9),
    ("v3", 123e12, 16e9), ("v2", 46e12, 8e9),
]


def _chip_info(kind: str):
    k = kind.lower().replace(" ", "").replace("tpu", "")
    for sub, peak, hbm in _CHIP_TABLE:
        if sub in k:
            return peak, hbm
    return None, None


# (name, hidden, intermediate, layers, heads, kv_heads)
_LADDER = [
    ("llama3-8b", 4096, 14336, 32, 32, 8),
    ("llama-3b", 3072, 8192, 26, 24, 8),
    ("llama-1b", 2048, 8192, 16, 16, 8),
    ("llama-770m", 1536, 6144, 16, 12, 4),
    ("llama-410m", 1024, 4096, 12, 8, 4),
    ("llama-tiny", 256, 512, 4, 8, 4),
]

_SEQ = 8192          # Llama-3-8B recipe sequence length (BASELINE.md)
_VOCAB = 128256      # Llama-3 true vocab — the lm-head/CE matmul at size


def _param_count(h, i, layers, heads, kv, vocab):
    head_dim = h // heads
    attn = h * heads * head_dim + 2 * h * kv * head_dim + heads * head_dim * h
    mlp = 3 * h * i
    per_layer = attn + mlp + 2 * h
    return layers * per_layer + 2 * vocab * h + h


def _fits(n_params, batch, seq, h, layers, hbm_bytes):
    # bf16 param + bf16 grad + 2x f32 adam moments = 12 B/param; remat'd
    # layer-boundary activations; fused CE keeps logits chunked.  Margins
    # calibrated on v5e (16 GB): llama-770m/b2/s8192/v128256 fits (13 GB
    # state+acts), b4 does not.
    acts = batch * seq * h * layers * 4
    need = (n_params * 12 + acts) * 1.15 + 0.9e9
    return need <= hbm_bytes


def _candidates():
    """Every (model, batch) in ladder order, largest first — the single
    enumeration shared by the analytic pick and the OOM backoff."""
    for name, h, i, layers, heads, kv in _LADDER:
        n = _param_count(h, i, layers, heads, kv, _VOCAB)
        for batch in (16, 8, 4, 2, 1):
            yield name, h, i, layers, heads, kv, batch, n


def _pick_config(hbm_bytes, seq):
    for cand in _candidates():
        name, h, i, layers, heads, kv, batch, n = cand
        if _fits(n, batch, seq, h, layers, hbm_bytes):
            return cand
    name, h, i, layers, heads, kv = _LADDER[-1]
    return name, h, i, layers, heads, kv, 1, _param_count(
        h, i, layers, heads, kv, _VOCAB)


def _device():
    import jax
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "cpu")
    peak, hbm_table = _chip_info(kind)
    stats = {}
    try:
        stats = dev.memory_stats() or {}
    except Exception:
        pass
    hbm = stats.get("bytes_limit") or hbm_table or 8e9
    on_tpu = dev.platform not in ("cpu",)
    return dev, kind, peak, hbm, on_tpu


def _time_step(step, data, iters):
    import jax
    loss = step(data)
    jax.device_get(loss)
    loss = step(data)
    jax.device_get(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(data)
    # device_get is the timing barrier: it forces materialization of the
    # whole donated-state chain (block_until_ready has been observed to
    # return early through the remote PJRT tunnel)
    jax.device_get(loss)
    dt = time.perf_counter() - t0
    return dt / iters, loss


def _mfu_pair(n_params, layers, h, seq, tokens_per_sec, peak):
    """Both BASELINE.md MFU conventions: 6N, and 6N + causal-attention
    FLOPs (per token per layer: QK^T + PV = 4*s*h full, /2 causal, x3
    fwd+bwd => 6*s*h)."""
    if not peak:
        return None, None
    f6n = 6 * n_params
    fattn = f6n + 6 * layers * seq * h
    return (f6n * tokens_per_sec / peak, fattn * tokens_per_sec / peak)


def _train_batch(vocab, batch, seq):
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, size=(batch, seq), dtype=np.int32)
    labels = np.concatenate(
        [ids[:, 1:], np.full((batch, 1), -100, np.int32)], axis=1)
    return {"input_ids": ids, "labels": labels}


def _is_oom(e: Exception) -> bool:
    s = str(e)
    return ("RESOURCE_EXHAUSTED" in s or "Ran out of memory" in s
            or "out of memory" in s.lower())


def _backoff_candidates(hbm, seq):
    """The analytic pick first, then every strictly-smaller
    (model, batch) from the SAME enumeration — probe-and-backoff for
    chips where the v5e-calibrated _fits margins misjudge (VERDICT r2
    weak #6)."""
    import itertools
    first = _pick_config(hbm, seq)
    yield first
    rest = itertools.dropwhile(lambda c: c != first, _candidates())
    for cand in itertools.islice(rest, 1, None):
        yield cand


def bench_headline(emit=True):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.jit.train import CompiledTrainStep
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    dev, kind, peak, hbm, on_tpu = _device()
    seq = _SEQ if on_tpu else 256
    last_err = None
    for cand in _backoff_candidates(hbm if on_tpu else 4e9, seq):
        name, h, i, layers, heads, kv, batch, n_params = cand
        cfg = LlamaConfig(
            vocab_size=_VOCAB if on_tpu else 1024, hidden_size=h,
            intermediate_size=i, num_hidden_layers=layers,
            num_attention_heads=heads, num_key_value_heads=kv,
            max_position_embeddings=seq, recompute=True,
            recompute_granularity="core_attn")
        if not on_tpu:
            n_params = _param_count(h, i, layers, heads, kv,
                                    cfg.vocab_size)
        try:
            model = LlamaForCausalLM(cfg)
            model = paddle.amp.decorate(model, level="O2",
                                        dtype="bfloat16")
            opt = paddle.optimizer.AdamW(
                learning_rate=1e-4, parameters=model.parameters(),
                grad_clip=paddle.ClipGradByGlobalNorm(1.0))
            step = CompiledTrainStep(
                model, lambda m, b: m(b["input_ids"],
                                      labels=b["labels"]), opt)
            data = _train_batch(cfg.vocab_size, batch, seq)
            step_time, loss = _time_step(step, data,
                                         20 if on_tpu else 2)
            break
        except Exception as e:
            if _is_oom(e) and on_tpu:
                last_err = e
                # release the failed attempt's device state (params +
                # moments) BEFORE probing the next candidate, or every
                # retry competes with the biggest failed allocation
                model = opt = step = None  # noqa: F841
                import gc
                gc.collect()
                print(json.dumps({"note": "oom_backoff",
                                  "config": f"{name}/b{batch}"}),
                      file=sys.stderr, flush=True)
                continue
            raise
    else:
        raise RuntimeError(
            f"no headline config fits this chip: {last_err}")

    tokens_per_sec = batch * seq / step_time
    mfu6n, mfu_attn = _mfu_pair(n_params, layers, h, seq, tokens_per_sec,
                                peak)
    vs_baseline = (mfu6n / 0.40) if mfu6n is not None else None

    result = {
        "metric": f"{name}_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(vs_baseline, 4) if vs_baseline else None,
        "extra": {"device_kind": kind, "params": n_params,
                  "batch": batch, "seq": seq,
                  "step_time_s": round(step_time, 4),
                  "mfu": round(mfu6n, 4) if mfu6n is not None else None,
                  "mfu_attn": round(mfu_attn, 4)
                  if mfu_attn is not None else None,
                  "vocab": cfg.vocab_size,
                  "final_loss": float(np.asarray(jax.device_get(loss)))},
    }
    if emit:
        print(json.dumps(result))
    return result


# ---------------------------------------------------------------------------
# BASELINE.md measurement ladder (--ladder)
# ---------------------------------------------------------------------------

def bench_gpt2():
    """Ladder #1: GPT-2 124M steps/sec (single device)."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.jit.train import CompiledTrainStep
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)

    _, kind, peak, _, on_tpu = _device()
    cfg = GPTConfig(vocab_size=50304, hidden_size=768,
                    num_hidden_layers=12, num_attention_heads=12,
                    max_position_embeddings=1024)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    step = CompiledTrainStep(model, lambda m, b: crit(m(b["x"]), b["y"]),
                             opt)
    batch, seq = (8, 1024) if on_tpu else (2, 128)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq + 1), dtype=np.int32)
    data = {"x": ids[:, :-1], "y": ids[:, 1:].astype(np.int64)}
    step_time, loss = _time_step(step, data, 20 if on_tpu else 2)
    return {"metric": "gpt2-124m_steps_per_sec", "unit": "steps/sec",
            "value": round(1.0 / step_time, 3),
            "extra": {"device_kind": kind, "batch": batch, "seq": seq,
                      "tokens_per_sec": round(batch * seq / step_time, 1),
                      "final_loss": float(np.asarray(jax.device_get(loss)))}}


def bench_moe():
    """Ladder #5: Qwen2-MoE-architecture tokens/sec (single chip; EP
    all-to-all becomes GSPMD collectives on a mesh)."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.jit.train import CompiledTrainStep
    from paddle_tpu.models.qwen2_moe import (Qwen2MoeConfig,
                                             Qwen2MoeForCausalLM)

    _, kind, peak, hbm, on_tpu = _device()
    # moe-360m-class: 8 experts top-2 + shared, fits v5e comfortably
    cfg = Qwen2MoeConfig(
        vocab_size=_VOCAB if on_tpu else 512, hidden_size=1024,
        moe_intermediate_size=704,
        shared_expert_intermediate_size=2816,
        num_hidden_layers=12 if on_tpu else 2,
        num_attention_heads=8, num_key_value_heads=4,
        num_experts=8, num_experts_per_tok=2, recompute=on_tpu,
        max_position_embeddings=4096 if on_tpu else 128)
    paddle.seed(0)
    model = Qwen2MoeForCausalLM(cfg)
    model = paddle.amp.decorate(model, level="O2", dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    step = CompiledTrainStep(model, lambda m, b: m(b["input_ids"],
                                                   labels=b["labels"]), opt)
    batch, seq = (4, 4096) if on_tpu else (2, 128)
    data = _train_batch(cfg.vocab_size, batch, seq)
    step_time, loss = _time_step(step, data, 20 if on_tpu else 2)
    return {"metric": "qwen2-moe-class_tokens_per_sec_per_chip",
            "unit": "tokens/sec", "value": round(batch * seq / step_time, 1),
            "extra": {"device_kind": kind, "batch": batch, "seq": seq,
                      "experts": 8,
                      "final_loss": float(np.asarray(jax.device_get(loss)))}}


def bench_ernie():
    """Ladder #3: ERNIE-4.5-class (dense backbone of the TP+PP recipe;
    pp/mp degrees only exist on multi-chip meshes — the dryrun validates
    them, this measures single-chip throughput of the same model)."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.jit.train import CompiledTrainStep
    from paddle_tpu.models.ernie import Ernie45Config, Ernie45ForCausalLM

    _, kind, peak, hbm, on_tpu = _device()
    if on_tpu:
        cfg = Ernie45Config(vocab_size=103424, hidden_size=1536,
                            intermediate_size=6144, num_hidden_layers=16,
                            num_attention_heads=12, num_key_value_heads=4,
                            max_position_embeddings=8192, recompute=True)
        batch, seq = 2, 8192
    else:
        from paddle_tpu.models.ernie import ernie45_tiny_config
        cfg = ernie45_tiny_config()
        batch, seq = 2, 64
    paddle.seed(0)
    model = Ernie45ForCausalLM(cfg)
    model = paddle.amp.decorate(model, level="O2", dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    step = CompiledTrainStep(model, lambda m, b: m(b["input_ids"],
                                                   labels=b["labels"]), opt)
    data = _train_batch(cfg.vocab_size, batch, seq)
    step_time, loss = _time_step(step, data, 20 if on_tpu else 2)
    h, layers = cfg.hidden_size, cfg.num_hidden_layers
    n = _param_count(h, cfg.intermediate_size, layers,
                     cfg.num_attention_heads, cfg.num_key_value_heads,
                     cfg.vocab_size)
    tps = batch * seq / step_time
    mfu6n, mfu_attn = _mfu_pair(n, layers, h, seq, tps, peak)
    return {"metric": "ernie45-class_tokens_per_sec_per_chip",
            "unit": "tokens/sec", "value": round(tps, 1),
            "extra": {"device_kind": kind, "batch": batch, "seq": seq,
                      "params": n,
                      "mfu": round(mfu6n, 4) if mfu6n else None,
                      "mfu_attn": round(mfu_attn, 4) if mfu_attn else None,
                      "final_loss": float(np.asarray(jax.device_get(loss)))}}


def bench_dit():
    """Ladder #4: DiT (conv+groupnorm family) imgs/sec."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.jit.train import CompiledTrainStep
    from paddle_tpu.models.dit import DiTConfig, DiTWithDiffusion

    _, kind, peak, hbm, on_tpu = _device()
    if on_tpu:
        # DiT-L/2-class on 32x32x4 latents (batch sized for 16 GB with
        # full activations — DiT has no remat knob yet)
        cfg = DiTConfig(input_size=32, patch_size=2, hidden_size=1024,
                        depth=24, num_heads=16)
        batch = 16
    else:
        from paddle_tpu.models.dit import dit_tiny_config
        cfg = dit_tiny_config()
        batch = 4
    paddle.seed(0)
    model = DiTWithDiffusion(cfg)
    model = paddle.amp.decorate(model, level="O2", dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    step = CompiledTrainStep(model, lambda m, b: m(b["x"], b["y"]), opt)
    rng = np.random.default_rng(0)
    data = {"x": rng.standard_normal(
        (batch, cfg.in_channels, cfg.input_size, cfg.input_size)
    ).astype(np.float32),
        "y": rng.integers(0, cfg.num_classes, (batch,)).astype(np.int32)}
    step_time, loss = _time_step(step, data, 20 if on_tpu else 2)
    return {"metric": "dit-l2_imgs_per_sec", "unit": "imgs/sec",
            "value": round(batch / step_time, 1),
            "extra": {"device_kind": kind, "batch": batch,
                      "step_time_s": round(step_time, 4),
                      "final_loss": float(np.asarray(jax.device_get(loss)))}}


def bench_decode():
    """Decode tokens/sec through the jitted generate() loop."""
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    _, kind, peak, hbm, on_tpu = _device()
    if on_tpu:
        cfg = LlamaConfig(vocab_size=_VOCAB, hidden_size=1536,
                          intermediate_size=6144, num_hidden_layers=16,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=2048)
        batch, prompt, new = 8, 128, 256
    else:
        from paddle_tpu.models.llama import llama_tiny_config
        cfg = llama_tiny_config()
        batch, prompt, new = 2, 8, 16
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, prompt), dtype=np.int32))
    out, _ = model.generate(ids, max_new_tokens=new)  # compile
    t0 = time.perf_counter()
    out, _ = model.generate(ids, max_new_tokens=new)
    out.numpy()
    dt = time.perf_counter() - t0
    return {"metric": "llama-770m_decode_tokens_per_sec",
            "unit": "tokens/sec", "value": round(batch * new / dt, 1),
            "extra": {"device_kind": kind, "batch": batch,
                      "prompt": prompt, "new_tokens": new,
                      "per_seq_tokens_per_sec": round(new / dt, 1)}}


def bench_moe_deepseek():
    """DeepSeekMoE-class kernel row (VERDICT r3 Weak #2): 64
    fine-grained experts top-6 at H=2048/F=1408 — the many-expert
    regime the grouped tiles were autotuned for in round 4.  Marginal
    per-iteration device time ((len40-len8)/32, cancels the tunnel's
    fixed dispatch cost) of the dropless grouped path vs the
    capacity-padded dense GShard einsums."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.grouped_matmul import dropless_moe_ffn

    _, kind, peak, hbm, on_tpu = _device()
    if not on_tpu:
        return {"metric": "deepseek_moe_grouped_vs_dense",
                "unit": "ratio", "value": -1.0,
                "extra": {"note": "tpu_only_row"}}
    E, H, F, K, T = 64, 2048, 1408, 6, 4096
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((T, H)) * 0.1, jnp.bfloat16)
    gv = jnp.asarray(np.abs(rng.standard_normal((T, K))), jnp.float32)
    ei = jnp.asarray(rng.integers(0, E, (T, K)), jnp.int32)
    wg = jnp.asarray(rng.standard_normal((E, H, F)) * .02, jnp.bfloat16)
    wu = jnp.asarray(rng.standard_normal((E, H, F)) * .02, jnp.bfloat16)
    wd = jnp.asarray(rng.standard_normal((E, F, H)) * .02, jnp.bfloat16)

    def marginal(mk_body):
        def run_n(n):
            def f(x, wg, wu, wd):
                c, _ = jax.lax.scan(mk_body(wg, wu, wd), x, None,
                                    length=n)
                return c.astype(jnp.float32).sum()
            g = jax.jit(f)
            jax.device_get(g(x, wg, wu, wd))
            best = 1e9
            for _ in range(3):
                t0 = time.perf_counter()
                jax.device_get(g(x, wg, wu, wd))
                best = min(best, time.perf_counter() - t0)
            return best
        return (run_n(40) - run_n(8)) / 32

    def grouped_mk(wg, wu, wd):
        def body(c, _):
            y = dropless_moe_ffn(c, gv, ei, wg, wu, wd)  # autotuned tm
            return (c + y.astype(c.dtype)) * jnp.bfloat16(0.5), None
        return body

    def dense_mk(wg, wu, wd):
        C = int(np.ceil(T * K / E * 1.25))
        onehot = jax.nn.one_hot(ei, E, dtype=jnp.int32)
        flat = onehot.reshape(T * K, E)
        pos = (jnp.cumsum(flat, axis=0) - flat).reshape(T, K, E)
        in_cap = (pos < C) & (onehot > 0)
        pc = jax.nn.one_hot(jnp.where(in_cap, pos, C), C + 1,
                            dtype=jnp.bfloat16)[..., :C]
        disp = jnp.einsum("tke,tkec->tec", onehot.astype(jnp.bfloat16)
                          * in_cap.astype(jnp.bfloat16), pc)

        def body(c, _):
            xe = jnp.einsum("tec,th->ech", disp, c)
            h1 = jax.nn.silu(jnp.einsum("ech,ehf->ecf", xe, wg))
            h1 = h1 * jnp.einsum("ech,ehf->ecf", xe, wu)
            eo = jnp.einsum("ecf,efh->ech", h1, wd)
            y = jnp.einsum("ech,tec->th", eo, disp)
            return (c + y.astype(c.dtype)) * jnp.bfloat16(0.5), None
        return body

    t_g = marginal(grouped_mk)
    t_d = marginal(dense_mk)
    return {"metric": "deepseek_moe_grouped_vs_dense", "unit": "ratio",
            "value": round(t_d / t_g, 3),
            "extra": {"device_kind": kind,
                      "experts": E, "top_k": K, "tokens": T,
                      "grouped_ms_per_layer": round(t_g * 1e3, 2),
                      "dense_ms_per_layer": round(t_d * 1e3, 2),
                      "note": "marginal (len40-len8)/32 in-graph; "
                              "r5: fused gate|up GLU kernel + "
                              "tm=256/full-K retune -> ~0.96x dense "
                              "(padding-bound at 64E, see BASELINE.md "
                              "5b); r3's auto tile was 1.39x SLOWER"}}


def bench_paged_kernel():
    """On-chip serving KERNEL row (VERDICT r3 Missing #6): per-decode-
    step device time of the fused paged append+attend kernel vs the
    dense-cache decode attention, both lax.scan-serialized IN-GRAPH so
    the axon tunnel's dispatch latency cannot contaminate the numbers
    (the engine row below is tunnel-bound).  llama-770m attention
    geometry at batch 8 x 2048 context."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops import _nn
    from paddle_tpu.ops.pallas.paged_attention import (
        paged_decode_append_attend)

    _, kind, peak, hbm, on_tpu = _device()
    if not on_tpu:
        return {"metric": "paged_decode_kernel_us_per_step",
                "unit": "us", "value": -1.0,
                "extra": {"note": "tpu_only_row"}}
    B, H, KVH, D, PAGE, CTX = 8, 12, 4, 128, 128, 2048
    MAXP, N = CTX // PAGE, 256
    rng = np.random.default_rng(0)
    kp0 = jnp.asarray(rng.standard_normal((KVH, B * MAXP, PAGE, D)) * .1,
                      jnp.bfloat16)
    vp0 = jnp.asarray(rng.standard_normal((KVH, B * MAXP, PAGE, D)) * .1,
                      jnp.bfloat16)
    table = jnp.asarray(rng.permutation(B * MAXP).reshape(B, MAXP),
                        jnp.int32)
    lens0 = jnp.full((B,), CTX - N - 1, jnp.int32)
    k_new = jnp.asarray(rng.standard_normal((B, KVH, D)), jnp.bfloat16)
    q3 = jnp.asarray(rng.standard_normal((B, H, D)), jnp.bfloat16)
    kd0 = jnp.asarray(rng.standard_normal((B, CTX, KVH, D)) * .1,
                      jnp.bfloat16)
    q4 = q3[:, None]

    def timed(f, *args):
        f = jax.jit(f)
        jax.block_until_ready(f(*args))
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(f(*args))
            best = min(best, time.perf_counter() - t0)
        return best / N * 1e6

    def paged(kp, vp, lens):
        def body(c, _):
            kp, vp, lens, q_ = c
            o, kp, vp = paged_decode_append_attend(
                q_, kp, vp, k_new, k_new, table, lens)
            return (kp, vp, lens + 1, q3 + o * 1e-6), None
        c, _ = jax.lax.scan(body, (kp, vp, lens, q3), None, length=N)
        return c[3]

    def dense(kd, vd, lens):
        def body(c, _):
            kd, vd, lens, q_ = c
            kd = jax.lax.dynamic_update_slice(
                kd, (k_new + kd[0, 0, 0, 0] * 0)[:, None],
                (0, lens[0], 0, 0))
            vd = jax.lax.dynamic_update_slice(vd, k_new[:, None],
                                              (0, lens[0], 0, 0))
            lens = lens + 1
            am = jnp.where(jnp.arange(CTX)[None, :] < lens[:, None],
                           0.0, -1e30)[:, None, None, :]
            o = _nn.scaled_dot_product_attention(q_, kd, vd,
                                                 attn_mask=am)
            return (kd, vd, lens, q4 + o * 1e-6), None
        c, _ = jax.lax.scan(body, (kd, vd, lens, q4), None, length=N)
        return c[3]

    t_paged = timed(paged, kp0, vp0, lens0)
    t_dense = timed(dense, kd0, vp0.reshape(B, CTX, KVH, D), lens0)

    # ragged-vs-split dispatch row (ISSUE 16): the SAME mixed batch —
    # 6 decode rows + 2 prefill chunks of 64 — as ONE ragged dispatch
    # vs the split path it replaced (decode kernel + one dispatch per
    # chunk).  Wall-clock per round on purpose: the delta IS the
    # tunnel dispatch overhead the ragged program amortizes away.
    from paddle_tpu.ops.pallas.paged_attention import (
        ragged_paged_append_attend)
    CH, S = 64, 8
    T = 6 + 2 * CH
    qr = jnp.asarray(rng.standard_normal((T, H, D)), jnp.bfloat16)
    knr = jnp.asarray(rng.standard_normal((T, KVH, D)), jnp.bfloat16)
    vnr = jnp.asarray(rng.standard_normal((T, KVH, D)), jnp.bfloat16)
    dec_kv, pre_kv = CTX - N - 1, 512        # 512 % PAGE == 0
    qs = jnp.asarray(list(range(6)) + [6, 6 + CH], jnp.int32)
    ql_mix = jnp.asarray([1] * 6 + [CH, CH], jnp.int32)
    kv_mix = jnp.asarray([dec_kv] * 6 + [pre_kv, pre_kv], jnp.int32)
    ql_chunk = [jnp.asarray([0] * 6 + ([CH, 0] if s == 0 else [0, CH]),
                            jnp.int32) for s in range(2)]
    qd, knd, vnd = qr[:6], knr[:6], vnr[:6]
    lens6 = jnp.full((6,), dec_kv, jnp.int32)

    def ragged_round(kp, vp):
        _, kp, vp = ragged_paged_append_attend(
            qr, kp, vp, knr, vnr, qs, ql_mix, kv_mix, table)
        return kp, vp

    def split_round(kp, vp):
        _, kp, vp = paged_decode_append_attend(
            qd, kp, vp, knd, vnd, table[:6], lens6)
        for ql in ql_chunk:                  # one dispatch per chunk
            _, kp, vp = ragged_paged_append_attend(
                qr, kp, vp, knr, vnr, qs, ql, kv_mix, table)
        return kp, vp

    def timed_round(fn, rounds=32):
        kp, vp = kp0 + 0, vp0 + 0            # donation consumes pools
        kp, vp = fn(kp, vp)                  # compile + warm
        jax.block_until_ready((kp, vp))
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(rounds):
                kp, vp = fn(kp, vp)
            jax.block_until_ready((kp, vp))
            best = min(best, time.perf_counter() - t0)
        return best / rounds * 1e6

    t_ragged = timed_round(ragged_round)
    t_split = timed_round(split_round)
    return {"metric": "paged_decode_kernel_us_per_step",
            "unit": "us", "value": round(t_paged, 1),
            "extra": {"device_kind": kind, "batch": B, "context": CTX,
                      "page_size": PAGE,
                      "dense_us_per_step": round(t_dense, 1),
                      "paged_over_dense": round(t_paged / t_dense, 2),
                      "ragged_mixed_us_per_round": round(t_ragged, 1),
                      "split_mixed_us_per_round": round(t_split, 1),
                      "ragged_over_split": round(t_ragged / t_split, 2),
                      "ragged_note": "6 decode rows + 2x64-token "
                                     "prefill chunks: ONE ragged "
                                     "dispatch vs decode kernel + "
                                     "per-chunk dispatches (wall-"
                                     "clock: the delta is tunnel "
                                     "dispatch overhead)",
                      "note": "fused append+attend kernel, in-graph "
                              "scan x256; r3 path was ~18x dense; the "
                              "dense comparator sped up ~25% when sdpa "
                              "moved to the shard_map flash dispatch "
                              "(r5), so expect ~1.25-1.35x — the "
                              "kernel itself is unchanged "
                              "(bisect-verified, BASELINE.md)"}}


def bench_engine_window():
    """Device-level serving-SYSTEM row (VERDICT r4 Missing #6): the
    ENGINE's multi-step decode window — sampling + page bookkeeping +
    the fused append+attend kernel, all inside one XLA program
    (_paged_decode_step) — timed as the MARGINAL cost per token
    between a 64-token and a 16-token window (cancels the tunnel's
    fixed dispatch cost), at the 770m geometry, batch 8 x 2048 ctx.
    Unlike the kernel row (attention only), this is the whole decode
    path the engine actually dispatches per window."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.inference.engine import LLMEngine, _paged_decode_step
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    _, kind, peak, hbm, on_tpu = _device()
    if not on_tpu:
        return {"metric": "llama-770m_engine_window_us_per_token",
                "unit": "us/token", "value": -1.0,
                "extra": {"note": "tpu_only_row"}}
    cfg = LlamaConfig(vocab_size=_VOCAB, hidden_size=1536,
                      intermediate_size=6144, num_hidden_layers=16,
                      num_attention_heads=12, num_key_value_heads=4,
                      max_position_embeddings=2048)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    batch, ctx, page = 8, 2048, 128
    eng = LLMEngine(model, max_seqs=batch, max_len=ctx, page_size=page,
                    dtype=jnp_bf16(), steps_per_sync=16)
    rng = np.random.default_rng(0)
    # the 1024-token prompts prefill each sequence to a realistic
    # cache depth; allocate() reserved page capacity for the decode
    for i in range(batch):
        eng.add_request(f"w{i}",
                        rng.integers(1, cfg.vocab_size, 1024).tolist(),
                        max_new_tokens=512)
    slots = np.array([r.slot for r in eng._active])
    lens = jnp.asarray(eng.cache.seq_lens[slots], np.int32)
    tables = jnp.asarray(eng.cache.page_table[slots])
    tokens = jnp.asarray([r.out[-1] for r in eng._active], np.int32)
    key = jax.random.PRNGKey(0)

    def run(n_steps):
        toks, kp, vp, ks, vs = _paged_decode_step(
            eng._stack, eng._norm_w, eng._head_w, eng._embed_w,
            eng._rope, eng.cache.k_pages, eng.cache.v_pages,
            eng.cache.k_scales, eng.cache.v_scales, tokens,
            lens, tables, lens, key, eps=eng.eps, kvh=eng.kvh,
            head_dim=eng.head_dim, transpose_head=eng._tied,
            strategy="greedy_search", n_steps=n_steps)
        eng.cache.k_pages, eng.cache.v_pages = kp, vp
        eng.cache.k_scales, eng.cache.v_scales = ks, vs
        return float(np.asarray(jax.device_get(toks))[0, 0])

    for n in (16, 64):                        # compile + warm both
        run(n)
    t16 = t64 = 1e9
    for _ in range(3):
        t0 = time.perf_counter(); run(16)
        t16 = min(t16, time.perf_counter() - t0)
        t0 = time.perf_counter(); run(64)
        t64 = min(t64, time.perf_counter() - t0)
    per_tok = (t64 - t16) / 48
    return {"metric": "llama-770m_engine_window_us_per_token",
            "unit": "us/token", "value": round(per_tok * 1e6, 1),
            "extra": {"device_kind": kind, "batch": batch,
                      "ctx_tokens": 1024, "page_size": page,
                      "tokens_per_sec_device":
                          round(batch / per_tok, 1),
                      "note": "marginal (64-16)-step windows; full "
                              "engine path in-graph (sampling + page "
                              "bookkeeping + fused append+attend)"}}


def bench_decode_window():
    """Scanned decode-window row (ISSUE 16): decode tokens/sec through
    the engine with the ``steps_per_sync`` window host-chained
    (``scan_decode=False``: nsteps dispatches per window) vs ON-DEVICE
    (one compiled while_loop program per window), at steps_per_sync
    1/4/16 on a decode-heavy small batch — the regime where
    per-dispatch overhead dominates.  CPU-runnable on the tiny config;
    rounds are INTERLEAVED best-of-3 so load drift cannot favor either
    path."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                         llama_tiny_config)

    _, kind, peak, hbm, on_tpu = _device()
    if on_tpu:
        cfg = LlamaConfig(vocab_size=_VOCAB, hidden_size=1536,
                          intermediate_size=6144, num_hidden_layers=16,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=2048)
        plens, new, page, mlen = [96, 57, 128, 101], 256, 128, 2048
        dtype = jnp_bf16()
    else:
        cfg = llama_tiny_config()
        plens, new, page, mlen = [8, 5], 33, 8, 64
        dtype = np.float32
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, n).tolist()
               for n in plens]

    def run(sps, scan):
        eng = LLMEngine(model, max_seqs=len(prompts), max_len=mlen,
                        page_size=page, dtype=dtype,
                        steps_per_sync=sps, scan_decode=scan)
        for i, p in enumerate(prompts):
            eng.add_request(f"w{i}", p, max_new_tokens=new)
        eng.step()                           # prefill outside the clock
        base = sum(len(r.out) for r in eng.requests.values())
        t0 = time.perf_counter()
        while eng.has_work():
            eng.step()
        dt = time.perf_counter() - t0
        toks = sum(len(r.out) for r in eng.requests.values()) - base
        return toks / dt

    cfgs = [(1, False), (4, False), (4, True), (16, False), (16, True)]
    for sps, scan in cfgs:                   # compile warm-up passes
        run(sps, scan)
    best = {c: 0.0 for c in cfgs}
    for _ in range(3):                       # interleaved best-of
        for c in cfgs:
            best[c] = max(best[c], run(*c))
    rows = {f"sps{sps}_{'scan' if sc else 'host'}_tokens_per_sec":
            round(v, 1) for (sps, sc), v in best.items()}
    return {"metric": "engine_decode_window_tokens_per_sec",
            "unit": "tokens/sec", "value": round(best[(16, True)], 1),
            "extra": {"device_kind": kind, "batch": len(prompts),
                      "new_tokens": new, **rows,
                      "scan_over_host_sps4":
                          round(best[(4, True)] / best[(4, False)], 2),
                      "scan_over_host_sps16":
                          round(best[(16, True)] / best[(16, False)],
                                2),
                      "window_compiles": LLMEngine.window_compiles(),
                      "note": "decode-heavy small batch; scanned "
                              "window = ONE while_loop program per "
                              "steps_per_sync window (early-exit on "
                              "all-rows-done) vs host-chained "
                              "per-token dispatch"}}


def bench_engine():
    """Serving-engine row: continuous-batching decode tokens/sec through
    the paged-KV LLMEngine (chunked ragged prefill admission + paged
    attention decode) — tunnel-dispatch-bound; the device-level number
    is bench_engine_window below."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    _, kind, peak, hbm, on_tpu = _device()
    if on_tpu:
        cfg = LlamaConfig(vocab_size=_VOCAB, hidden_size=1536,
                          intermediate_size=6144, num_hidden_layers=16,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=2048)
        batch, new, page = 8, 256, 128
        prompts = [96, 57, 128, 101, 77, 120, 64, 115]  # ragged lengths
    else:
        from paddle_tpu.models.llama import llama_tiny_config
        cfg = llama_tiny_config()
        batch, new, page = 2, 16, 8
        prompts = [8, 5]
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    rng = np.random.default_rng(0)
    dtype = np.float32 if not on_tpu else jnp_bf16()
    sync = 16 if on_tpu else 4   # multi-step decode amortizes dispatch
    eng = LLMEngine(model, max_seqs=batch, max_len=2048 if on_tpu else 32,
                    page_size=page, dtype=dtype, steps_per_sync=sync)
    for i, plen in enumerate(prompts):
        eng.add_request(
            f"w{i}", rng.integers(1, cfg.vocab_size, plen).tolist(),
            max_new_tokens=new)
    # warmup: one decode window compiles the step fn
    eng.step()
    produced0 = sum(len(r.out) for r in eng.requests.values())
    calls = 0
    t0 = time.perf_counter()
    while eng.has_work():
        eng.step()
        calls += 1
    dt = time.perf_counter() - t0
    total = sum(len(r.out) for r in eng.requests.values()) - produced0
    return {"metric": "llama-770m_engine_decode_tokens_per_sec",
            "unit": "tokens/sec", "value": round(total / dt, 1),
            "extra": {"device_kind": kind, "max_seqs": batch,
                      "prompt_lens": prompts, "new_tokens": new,
                      "steps_per_sync": sync, "dispatches": calls,
                      "prefill_compiles": LLMEngine.prefill_compiles(),
                      "decode_compiles": LLMEngine.decode_compiles()}}


def bench_serving_quant():
    """Quantized-serving row (ISSUE 1): decode tokens/sec through the
    engine with an fp KV cache vs the INT8 paged KV cache (per-token
    scales, in-kernel dequant on TPU), plus the EFFECTIVE PAGE
    CAPACITY the int8 cache buys at an equal HBM budget vs fp16 —
    the bandwidth/capacity win is the point of the subsystem, so the
    row reports both.  Same JSON shape as the headline metric so
    BENCH_*.json rounds can track the quantized path."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.inference.paged_cache import PagedKVCache
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    _, kind, peak, hbm, on_tpu = _device()
    if on_tpu:
        cfg = LlamaConfig(vocab_size=_VOCAB, hidden_size=1536,
                          intermediate_size=6144, num_hidden_layers=16,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=2048)
        batch, new, page, maxlen, sync = 8, 256, 128, 2048, 16
        prompts = [96, 57, 128, 101, 77, 120, 64, 115]
        fp_dtype = jnp_bf16()
        fp_kv = "bfloat16"
    else:
        # tiny model, but the SERVING head_dim (128): the capacity
        # claim is per-token bytes D+4 vs 2D, a function of head_dim
        cfg = LlamaConfig(vocab_size=256, hidden_size=256,
                          intermediate_size=512, num_hidden_layers=2,
                          num_attention_heads=2, num_key_value_heads=1,
                          max_position_embeddings=128,
                          rope_theta=10000.0)
        batch, new, page, maxlen, sync = 2, 16, 8, 64, 4
        prompts = [8, 5]
        fp_dtype = np.float32
        fp_kv = None
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    rng = np.random.default_rng(0)

    def run(kv_dtype):
        eng = LLMEngine(model, max_seqs=batch, max_len=maxlen,
                        page_size=page, dtype=fp_dtype,
                        steps_per_sync=sync, kv_dtype=kv_dtype)
        for i, plen in enumerate(prompts):
            eng.add_request(
                f"w{i}", rng.integers(1, cfg.vocab_size, plen).tolist(),
                max_new_tokens=new)
        eng.step()                   # warmup: compile the decode window
        produced0 = sum(len(r.out) for r in eng.requests.values())
        t0 = time.perf_counter()
        while eng.has_work():
            eng.step()
        dt = time.perf_counter() - t0
        total = sum(len(r.out) for r in eng.requests.values()) - produced0
        return total / dt, eng

    tps_fp, _ = run(fp_kv)
    tps_q, eng_q = run("int8")

    # effective page capacity at an EQUAL HBM budget, vs an fp16 cache
    # (honest accounting: int8 pages carry their f32 scale rows)
    head_dim = cfg.hidden_size // cfg.num_attention_heads
    geom = dict(n_pages=2, page_size=page,
                n_kv_heads=cfg.num_key_value_heads, head_dim=head_dim,
                max_seqs=1, max_len=page,
                num_layers=cfg.num_hidden_layers)
    bpt_fp16 = PagedKVCache(dtype=jnp.bfloat16, **geom) \
        .kv_bytes_per_token()
    bpt_int8 = eng_q.cache.kv_bytes_per_token()
    cap_ratio = bpt_fp16 / bpt_int8
    budget = hbm or 16e9
    page_bytes_fp16 = bpt_fp16 * page
    page_bytes_int8 = bpt_int8 * page
    return {
        "metric": "serving_decode_int8_vs_fp_kv_tokens_per_sec",
        "value": round(tps_q, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(tps_q / tps_fp, 3),
        "extra": {"device_kind": kind, "max_seqs": batch,
                  "new_tokens": new, "page_size": page,
                  "fp_kv_dtype": fp_kv or "float32",
                  "fp_tokens_per_sec": round(tps_fp, 1),
                  "int8_tokens_per_sec": round(tps_q, 1),
                  "kv_bytes_per_token_fp16": bpt_fp16,
                  "kv_bytes_per_token_int8": bpt_int8,
                  "int8_capacity_ratio_vs_fp16": round(cap_ratio, 3),
                  "pages_at_budget_fp16": int(budget // page_bytes_fp16),
                  "pages_at_budget_int8": int(budget // page_bytes_int8),
                  "hbm_budget_bytes": int(budget),
                  "prefill_compiles": LLMEngine.prefill_compiles(),
                  "decode_compiles": LLMEngine.decode_compiles()}}


def bench_serving_metrics():
    """Observability-overhead row (ISSUE 2): decode tokens/sec through
    the SAME engine workload with the metrics runtime off vs on.  The
    instrumentation records O(1) host floats per decode WINDOW (TPOT is
    a weighted histogram observe, not per-token), so the acceptance bar
    is <=2% throughput overhead with metrics enabled."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    _, kind, peak, hbm, on_tpu = _device()
    if on_tpu:
        cfg = LlamaConfig(vocab_size=_VOCAB, hidden_size=1536,
                          intermediate_size=6144, num_hidden_layers=16,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=2048)
        batch, new, page, maxlen, sync = 8, 256, 128, 2048, 16
        prompts = [96, 57, 128, 101, 77, 120, 64, 115]
        dtype = jnp_bf16()
    else:
        from paddle_tpu.models.llama import llama_tiny_config
        cfg = llama_tiny_config()
        batch, new, page, maxlen, sync = 4, 96, 8, 128, 4
        prompts = [8, 5, 12, 9]
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    if not on_tpu:
        dtype = np.float32

    def run(enable):
        rng = np.random.default_rng(0)
        eng = LLMEngine(model, max_seqs=batch, max_len=maxlen,
                        page_size=page, dtype=dtype,
                        steps_per_sync=sync, enable_metrics=enable)
        for i, plen in enumerate(prompts):
            eng.add_request(
                f"w{i}", rng.integers(1, cfg.vocab_size, plen).tolist(),
                max_new_tokens=new)
        eng.step()                     # warmup: compiles the window
        produced0 = sum(len(r.out) for r in eng.requests.values())
        t0 = time.perf_counter()
        while eng.has_work():
            eng.step()
        dt = time.perf_counter() - t0
        total = sum(len(r.out)
                    for r in eng.requests.values()) - produced0
        return total / dt, eng

    run(False)                         # shared compile + cache warmup
    # interleave the arms so host clock drift hits both equally; the
    # per-arm max is the usual best-of-N noise floor estimator (the
    # 1-core CI box jitters ~2-3% run to run, well above the true
    # instrumentation cost)
    off, on = [], []
    eng_on = None
    for _ in range(5):
        off.append(run(False)[0])
        rate, eng_on = run(True)
        on.append(rate)
    best_off, best_on = max(off), max(on)
    overhead = (best_off - best_on) / best_off
    snap = eng_on.metrics_snapshot()
    return {"metric": "llama_engine_metrics_overhead_pct",
            "unit": "percent", "value": round(overhead * 100, 2),
            "extra": {"device_kind": kind,
                      "tokens_per_sec_metrics_off": round(best_off, 1),
                      "tokens_per_sec_metrics_on": round(best_on, 1),
                      "ttft_p_mean_ms": round(
                          snap["ttft_seconds"]["mean"] * 1e3, 2),
                      "tpot_mean_us": round(
                          snap["tpot_seconds"]["mean"] * 1e6, 1),
                      "prefill_compiles": snap["prefill_compiles"],
                      "decode_compiles": snap["decode_compiles"],
                      "budget": "overhead <= 2%"}}


def bench_trace():
    """Tracing-overhead row (ISSUE 9): decode tokens/sec through the
    SAME scheduler-driven workload with the span tracer off vs on.
    Tracing-off is a strict no-op (one module-global read returning
    the NULL_SPAN singleton — the budget-guard test pins it), so the
    interesting number is tracing ON: spans are recorded per request /
    page chunk / decode WINDOW, never per token, and the acceptance
    bar is <=3% throughput overhead.  Also reports the TTFT tail
    (p50/p95) from the new histogram quantiles, and sanity-checks the
    compile-count invariants with tracing enabled."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.observability import tracing as obs_tracing
    from paddle_tpu.serving import Scheduler

    _, kind, peak, hbm, on_tpu = _device()
    if on_tpu:
        cfg = LlamaConfig(vocab_size=_VOCAB, hidden_size=1536,
                          intermediate_size=6144, num_hidden_layers=16,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=2048)
        batch, new, page, maxlen, sync = 8, 256, 128, 2048, 16
        prompts = [96, 57, 128, 101, 77, 120, 64, 115]
        dtype = jnp_bf16()
    else:
        from paddle_tpu.models.llama import llama_tiny_config
        cfg = llama_tiny_config()
        batch, new, page, maxlen, sync = 4, 96, 8, 128, 4
        prompts = [8, 5, 12, 9]
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    if not on_tpu:
        dtype = np.float32

    def run(enable):
        if enable:
            obs_tracing.enable_tracing(max_spans=16384)
        else:
            obs_tracing.disable_tracing()
        try:
            rng = np.random.default_rng(0)
            eng = LLMEngine(model, max_seqs=batch, max_len=maxlen,
                            page_size=page, dtype=dtype,
                            steps_per_sync=sync)
            sched = Scheduler(eng)
            for i, plen in enumerate(prompts):
                sched.submit(
                    f"t{i}",
                    rng.integers(1, cfg.vocab_size, plen).tolist(),
                    max_new_tokens=new)
            sched.step()               # warmup: compiles the window
            produced0 = sum(len(r.out)
                            for r in eng.requests.values())
            t0 = time.perf_counter()
            sched.run_until_idle()
            dt = time.perf_counter() - t0
            total = sum(
                len(sched.result(f"t{i}"))
                for i in range(len(prompts))) - produced0
            return total / dt, eng
        finally:
            obs_tracing.disable_tracing()

    run(False)                         # shared compile + cache warmup
    off, on = [], []
    eng_on = None
    for _ in range(5):                 # interleaved best-of (clock
        off.append(run(False)[0])      # drift hits both arms equally)
        rate, eng_on = run(True)
        on.append(rate)
    best_off, best_on = max(off), max(on)
    overhead = (best_off - best_on) / best_off
    snap = eng_on.metrics_snapshot()
    return {"metric": "llama_serving_tracing_overhead_pct",
            "unit": "percent", "value": round(overhead * 100, 2),
            "extra": {"device_kind": kind,
                      "tokens_per_sec_tracing_off": round(best_off, 1),
                      "tokens_per_sec_tracing_on": round(best_on, 1),
                      "ttft_p50_ms": round(
                          snap["ttft_seconds"]["p50"] * 1e3, 2),
                      "ttft_p95_ms": round(
                          snap["ttft_seconds"]["p95"] * 1e3, 2),
                      "tpot_p95_us": round(
                          snap["tpot_seconds"]["p95"] * 1e6, 1),
                      "prefill_compiles": snap["prefill_compiles"],
                      "decode_compiles": snap["decode_compiles"],
                      "budget": "overhead <= 3%"}}


def bench_fleet_health():
    """Fleet-health-plane overhead row (ISSUE 14): decode tokens/sec
    through the SAME scheduler-driven workload with the health plane
    off vs on.  Health-off is a strict no-op (one module-global read
    returning NULL_HEALTH — the budget-guard test pins it); health ON
    adds two SlidingWindow observes per TTFT / decode WINDOW (never
    per token), so the acceptance bar is <=3% throughput overhead,
    with tokens bit-identical and the compile counts unchanged.  Also
    runs a chaos-interrupted ``fit`` (stop mid-epoch, then
    auto_resume) and reports the GoodputMeter's fractions — they sum
    to 1.0 by construction and restart_replay is nonzero only in the
    resumed run."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.observability import health as obs_health
    from paddle_tpu.serving import FleetWatcher, ReplicaRouter, Scheduler

    _, kind, peak, hbm, on_tpu = _device()
    if on_tpu:
        cfg = LlamaConfig(vocab_size=_VOCAB, hidden_size=1536,
                          intermediate_size=6144, num_hidden_layers=16,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=2048)
        batch, new, page, maxlen, sync = 8, 256, 128, 2048, 16
        prompts = [96, 57, 128, 101, 77, 120, 64, 115]
        dtype = jnp_bf16()
    else:
        from paddle_tpu.models.llama import llama_tiny_config
        cfg = llama_tiny_config()
        batch, new, page, maxlen, sync = 4, 96, 8, 128, 4
        prompts = [8, 5, 12, 9]
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    if not on_tpu:
        dtype = np.float32

    def run(enable):
        # both arms ride the SAME path (scheduler behind a one-replica
        # router); the ON arm additionally enables the health plane AND
        # runs a live FleetWatcher thread scraping fleet_snapshot()
        # concurrently — the realistic always-on cost
        if enable:
            obs_health.enable_health()
        else:
            obs_health.disable_health()
        watcher = None
        try:
            rng = np.random.default_rng(0)
            eng = LLMEngine(model, max_seqs=batch, max_len=maxlen,
                            page_size=page, dtype=dtype,
                            steps_per_sync=sync)
            sched = Scheduler(eng)
            router = ReplicaRouter([sched], sleep=lambda s: None)
            if enable:
                watcher = FleetWatcher(router, interval=0.02)
                watcher.start()
            for i, plen in enumerate(prompts):
                router.submit(
                    f"h{i}",
                    rng.integers(1, cfg.vocab_size, plen).tolist(),
                    max_new_tokens=new)
            sched.step()               # warmup: compiles the window
            produced0 = sum(len(r.out)
                            for r in eng.requests.values())
            t0 = time.perf_counter()
            sched.run_until_idle()
            dt = time.perf_counter() - t0
            total = sum(
                len(sched.result(f"h{i}"))
                for i in range(len(prompts))) - produced0
            return total / dt, eng
        finally:
            if watcher is not None:
                watcher.stop()
            obs_health.disable_health()

    run(False)                         # shared compile + cache warmup
    off, on = [], []
    eng_on = None
    for _ in range(5):                 # interleaved best-of (clock
        off.append(run(False)[0])      # drift hits both arms equally)
        rate, eng_on = run(True)
        on.append(rate)
    best_off, best_on = max(off), max(on)
    overhead = (best_off - best_on) / best_off
    compiles = eng_on.prefill_compiles()

    # -- goodput/badput accounting under an injected mid-run kill ------
    import shutil
    import tempfile

    from paddle_tpu import nn, optimizer
    from paddle_tpu.hapi.callbacks import Callback
    from paddle_tpu.io.dataloader import CheckpointableLoader, Dataset

    class _Arr(Dataset):
        def __init__(self, n=32):
            r = np.random.default_rng(23)
            self.x = r.normal(size=(n, 6)).astype(np.float32)
            self.y = r.normal(size=(n, 3)).astype(np.float32)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return len(self.x)

    class _StopAfter(Callback):
        def __init__(self, n):
            super().__init__()
            self.n, self.seen = n, 0

        def on_train_batch_end(self, step, logs=None):
            self.seen += 1
            if self.seen >= self.n:
                self.model.stop_training = True

    def _fit(seed, ckdir, **kw):
        paddle.seed(seed)
        m = paddle.Model(nn.Sequential(
            nn.Linear(6, 12), nn.ReLU(), nn.Linear(12, 3)))
        m.prepare(optimizer.AdamW(learning_rate=5e-3), nn.MSELoss())
        loader = CheckpointableLoader(_Arr(), batch_size=4,
                                      shuffle=True, seed=7)
        m.fit(loader, epochs=2, verbose=0, checkpoint_dir=ckdir,
              save_steps=3, **kw)
        return obs_health.get_health().goodput.report()

    ckdir = tempfile.mkdtemp(prefix="bench-fleet-health-")
    try:
        obs_health.enable_health()
        _fit(1, ckdir, callbacks=[_StopAfter(5)])   # injected kill
        rep = _fit(9, ckdir, auto_resume=True)      # "fresh process"
    finally:
        obs_health.disable_health()
        shutil.rmtree(ckdir, ignore_errors=True)
    frac = rep["fractions"]

    return {"metric": "llama_serving_health_overhead_pct",
            "unit": "percent", "value": round(overhead * 100, 2),
            "extra": {"device_kind": kind,
                      "tokens_per_sec_health_off": round(best_off, 1),
                      "tokens_per_sec_health_on": round(best_on, 1),
                      "prefill_compiles": compiles,
                      "goodput_fraction": round(rep["goodput"], 4),
                      "fractions": {k: round(v, 4)
                                    for k, v in sorted(frac.items())},
                      "fractions_sum": round(sum(frac.values()), 6),
                      "restart_replay_seconds": round(
                          rep["seconds"]["restart_replay"], 4),
                      "budget": "overhead <= 3%"}}


def bench_introspection():
    """Compile/memory introspection-plane overhead row (ISSUE 15):
    decode tokens/sec through the SAME router-fronted scheduler
    workload with the CompileWatch off vs on.  Off is a strict no-op
    (watched_call reads one module global and tail-calls the jit
    function — the budget-guard test pins the NULL identity); ON adds
    a jit-cache-size read around each dispatch WINDOW plus, on the
    window that actually compiles, one AOT lowering for cost analysis
    — so the acceptance bar is <=3% throughput overhead with tokens
    bit-identical and the one-compile counters unchanged.  The ON arm
    also scrapes /compilez-shaped and /memz-shaped snapshots each
    iteration (the realistic always-on cost of a dashboard poll)."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.observability import introspection as obs_insp
    from paddle_tpu.serving import ReplicaRouter, Scheduler

    _, kind, peak, hbm, on_tpu = _device()
    if on_tpu:
        cfg = LlamaConfig(vocab_size=_VOCAB, hidden_size=1536,
                          intermediate_size=6144, num_hidden_layers=16,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=2048)
        batch, new, page, maxlen, sync = 8, 256, 128, 2048, 16
        prompts = [96, 57, 128, 101, 77, 120, 64, 115]
        dtype = jnp_bf16()
    else:
        from paddle_tpu.models.llama import llama_tiny_config
        cfg = llama_tiny_config()
        batch, new, page, maxlen, sync = 4, 96, 8, 128, 4
        prompts = [8, 5, 12, 9]
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    if not on_tpu:
        dtype = np.float32

    def run(enable):
        if enable:
            obs_insp.enable_compile_watch()
        else:
            obs_insp.disable_compile_watch()
        try:
            rng = np.random.default_rng(0)
            eng = LLMEngine(model, max_seqs=batch, max_len=maxlen,
                            page_size=page, dtype=dtype,
                            steps_per_sync=sync)
            sched = Scheduler(eng)
            router = ReplicaRouter([sched], sleep=lambda s: None)
            for i, plen in enumerate(prompts):
                router.submit(
                    f"c{i}",
                    rng.integers(1, cfg.vocab_size, plen).tolist(),
                    max_new_tokens=new)
            sched.step()               # warmup: compiles the window
            produced0 = sum(len(r.out)
                            for r in eng.requests.values())
            t0 = time.perf_counter()
            sched.run_until_idle()
            dt = time.perf_counter() - t0
            snap = None
            if enable:
                # the dashboard-poll cost rides inside the ON arm
                snap = obs_insp.compilez_snapshot()
                obs_insp.memz_snapshot()
            total = sum(
                len(sched.result(f"c{i}"))
                for i in range(len(prompts))) - produced0
            return total / dt, eng, snap
        finally:
            obs_insp.disable_compile_watch()

    run(True)                          # shared compile + cache warmup
    off, on = [], []
    eng_on, snap_on = None, None
    for _ in range(5):                 # interleaved best-of (clock
        off.append(run(False)[0])      # drift hits both arms equally)
        rate, eng_on, snap_on = run(True)
        on.append(rate)
    n_recompiles = len(snap_on["recompiles"])
    best_off, best_on = max(off), max(on)
    overhead = (best_off - best_on) / best_off
    return {"metric": "llama_serving_introspection_overhead_pct",
            "unit": "percent", "value": round(overhead * 100, 2),
            "extra": {"device_kind": kind,
                      "tokens_per_sec_watch_off": round(best_off, 1),
                      "tokens_per_sec_watch_on": round(best_on, 1),
                      "prefill_compiles": eng_on.prefill_compiles(),
                      "mixed_compiles": eng_on.mixed_compiles(),
                      "recompile_events": n_recompiles,
                      "budget": "overhead <= 3%"}}


def bench_capsule():
    """Request-capsule plane overhead row (ISSUE 17): decode
    tokens/sec through the SAME router-fronted scheduler workload
    with capture off vs armed.  Off is a strict no-op (every capture
    site reads one module global and bails on ``enabled``); ARMED
    records the per-request capsule — prompt, config fingerprint, the
    window key chain, lifecycle — plus a /capsulez-shaped snapshot
    scrape each iteration (the always-on dashboard-poll cost).
    Acceptance bar is <=3% throughput overhead; the ON arm also
    replays one captured request afterwards (outside the timed
    region) and reports that the replay was bit-exact."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.observability import capsule as obs_cap
    from paddle_tpu.serving import ReplicaRouter, Scheduler

    _, kind, peak, hbm, on_tpu = _device()
    if on_tpu:
        cfg = LlamaConfig(vocab_size=_VOCAB, hidden_size=1536,
                          intermediate_size=6144, num_hidden_layers=16,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=2048)
        batch, new, page, maxlen, sync = 8, 256, 128, 2048, 16
        prompts = [96, 57, 128, 101, 77, 120, 64, 115]
        dtype = jnp_bf16()
    else:
        from paddle_tpu.models.llama import llama_tiny_config
        cfg = llama_tiny_config()
        batch, new, page, maxlen, sync = 4, 96, 8, 128, 4
        prompts = [8, 5, 12, 9]
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    if not on_tpu:
        dtype = np.float32

    def run(enable):
        # (the armed store stays live past the ON run — the post-run
        # replay below reads it; the OFF run resets it at entry)
        if enable:
            obs_cap.enable_capsule_capture()
        else:
            obs_cap.disable_capsule_capture()
        rng = np.random.default_rng(0)
        eng = LLMEngine(model, max_seqs=batch, max_len=maxlen,
                        page_size=page, dtype=dtype,
                        steps_per_sync=sync)
        sched = Scheduler(eng)
        router = ReplicaRouter([sched], sleep=lambda s: None)
        for i, plen in enumerate(prompts):
            router.submit(
                f"c{i}",
                rng.integers(1, cfg.vocab_size, plen).tolist(),
                max_new_tokens=new)
        sched.step()                   # warmup: compiles the window
        produced0 = sum(len(r.out) for r in eng.requests.values())
        t0 = time.perf_counter()
        sched.run_until_idle()
        dt = time.perf_counter() - t0
        snap = None
        if enable:
            # the dashboard-poll cost rides inside the ON arm
            snap = obs_cap.get_capsule_store().capsulez()
        total = sum(
            len(sched.result(f"c{i}"))
            for i in range(len(prompts))) - produced0
        return total / dt, eng, snap

    run(True)                          # shared compile + cache warmup
    try:
        off, on = [], []
        eng_on, snap_on = None, None
        for _ in range(5):             # interleaved best-of (clock
            off.append(run(False)[0])  # drift hits both arms equally)
            rate, eng_on, snap_on = run(True)
            on.append(rate)
        # replay one capsule through the last ON engine — the proof
        # the recorded stream is bit-reproducible, untimed
        cap = obs_cap.get_capsule_store().get("c0")
        rep = obs_cap.replay_capsule(cap, eng_on)
        bit_exact = rep["first_divergence"] is None
    finally:
        obs_cap.disable_capsule_capture()
    best_off, best_on = max(off), max(on)
    overhead = (best_off - best_on) / best_off
    return {"metric": "llama_serving_capsule_overhead_pct",
            "unit": "percent", "value": round(overhead * 100, 2),
            "extra": {"device_kind": kind,
                      "tokens_per_sec_capture_off": round(best_off, 1),
                      "tokens_per_sec_capture_on": round(best_on, 1),
                      "captured_total": snap_on["captured_total"],
                      "replay_bit_exact": bit_exact,
                      "replay_steps_compared": rep["steps_compared"],
                      "budget": "overhead <= 3%"}}


def bench_serving_prefix():
    """Automatic-prefix-caching row (ISSUE 3): N requests sharing a
    long system prompt, admitted through the SAME engine workload with
    prefix caching off vs on (same process, so ``vs_baseline`` is an
    honest in-process ratio).  Reports the shared-prefix TTFT (the
    cached requests skip the shared chunks' prefill entirely) and the
    page capacity the sharing buys: pages in use after admission with
    sharing on vs off at the same request mix."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    _, kind, peak, hbm, on_tpu = _device()
    if on_tpu:
        cfg = LlamaConfig(vocab_size=_VOCAB, hidden_size=1536,
                          intermediate_size=6144, num_hidden_layers=16,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=2048)
        batch, new, page, maxlen, sync = 8, 32, 128, 2048, 8
        sys_len, sfx_len = 512, 17          # 4 shared pages per prompt
        dtype = jnp_bf16()
    else:
        from paddle_tpu.models.llama import llama_tiny_config
        cfg = llama_tiny_config()
        batch, new, page, maxlen, sync = 8, 8, 8, 128, 2
        sys_len, sfx_len = 16, 3            # 2 shared pages per prompt
        dtype = np.float32
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    rng = np.random.default_rng(0)
    sys_prompt = rng.integers(1, cfg.vocab_size, sys_len).tolist()
    suffixes = [rng.integers(1, cfg.vocab_size, sfx_len).tolist()
                for _ in range(batch)]

    def run(enable):
        eng = LLMEngine(model, max_seqs=batch, max_len=maxlen,
                        page_size=page, dtype=dtype,
                        steps_per_sync=sync,
                        enable_prefix_caching=enable)
        ttfts = []
        for i, sfx in enumerate(suffixes):
            t0 = time.perf_counter()
            eng.add_request(f"p{i}", sys_prompt + sfx,
                            max_new_tokens=new)
            ttfts.append(time.perf_counter() - t0)
        pages_used = (eng.cache.n_pages - 1) - eng.cache.free_page_count()
        while eng.has_work():
            eng.step()
        # request 0 is the compulsory miss that populates the cache;
        # the shared-prefix TTFT is the mean over the rest
        return float(np.mean(ttfts[1:])), pages_used, eng

    run(False)                        # warmup: compiles prefill+decode
    ttft_off, pages_off, _ = run(False)
    ttft_on, pages_on, eng = run(True)
    st = eng.prefix_stats
    return {
        "metric": "serving_prefix_cache_ttft_seconds",
        "value": round(ttft_on, 5),
        "unit": "seconds",
        "vs_baseline": round(ttft_on / ttft_off, 3),
        "extra": {"device_kind": kind, "requests": batch,
                  "sys_prompt_tokens": sys_len,
                  "suffix_tokens": sfx_len, "page_size": page,
                  "ttft_seconds_sharing_off": round(ttft_off, 5),
                  "ttft_seconds_sharing_on": round(ttft_on, 5),
                  "ttft_speedup": round(ttft_off / ttft_on, 3),
                  "pages_after_admission_sharing_off": pages_off,
                  "pages_after_admission_sharing_on": pages_on,
                  "capacity_ratio": round(pages_off / pages_on, 3),
                  "prefix_hit_rate": round(
                      st["hit_tokens"] /
                      (st["hit_tokens"] + st["miss_tokens"]), 3),
                  "shared_pages_mapped": st["shared_pages"],
                  "prefill_compiles": LLMEngine.prefill_compiles(),
                  "decode_compiles": LLMEngine.decode_compiles()}}


def bench_serving_sched():
    """Serving-scheduler row (ISSUE 4): GOODPUT — tokens delivered
    within their deadline per wall second — under an overload burst
    (demand > slot/page capacity), continuous-batching ``Scheduler``
    vs the naive FIFO admit-until-OOM loop every caller hand-rolled
    before the serving subsystem existed.  The naive loop burns wall
    time decoding requests that can no longer meet their deadline and
    discovers capacity by CATCHING the paged cache's OOM raise; the
    scheduler admission-checks capacity (zero OOM events) and sheds
    waiting requests whose deadline already passed.  The deadline is
    calibrated in-process to half the naive full-burst wall time, so
    the comparison is honest on any chip."""
    import paddle_tpu as paddle
    from paddle_tpu.common.errors import EnforceError
    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import Scheduler

    _, kind, peak, hbm, on_tpu = _device()
    if on_tpu:
        cfg = LlamaConfig(vocab_size=_VOCAB, hidden_size=1536,
                          intermediate_size=6144, num_hidden_layers=16,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=2048)
        seqs, page, maxlen = 8, 128, 2048
        burst, plen, new = 32, 256, 128
        dtype = jnp_bf16()
    else:
        from paddle_tpu.models.llama import llama_tiny_config
        cfg = llama_tiny_config()
        seqs, page, maxlen = 4, 8, 32
        burst, plen, new = 16, 6, 16
        dtype = np.float32
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    rng = np.random.default_rng(0)
    reqs = [(f"r{i}", rng.integers(1, cfg.vocab_size, plen).tolist())
            for i in range(burst)]

    def engine():
        # n_pages defaults to full per-slot budget: demand (burst) is
        # burst/seqs times the slot capacity -> a true overload
        return LLMEngine(model, max_seqs=seqs, max_len=maxlen,
                         page_size=page, dtype=dtype,
                         enable_prefix_caching=False)

    def run_naive(deadline):
        """FIFO admit-until-OOM: the pre-subsystem caller loop."""
        eng = engine()
        pend = list(reqs)
        finish = {}
        ooms = 0
        t0 = time.perf_counter()
        while pend or eng.has_work():
            while pend:
                rid, prompt = pend[0]
                try:
                    eng.add_request(rid, prompt, max_new_tokens=new)
                except EnforceError:
                    ooms += 1                 # slot/page capacity full
                    break
                pend.pop(0)
            if pend and not eng.has_work():
                break                         # head request can't ever fit
            eng.step()
            now = time.perf_counter()
            for rid, req in eng.requests.items():
                if req.done and rid not in finish:
                    finish[rid] = now
        wall = time.perf_counter() - t0
        ontime = sum(len(eng.result(rid)) for rid, t in finish.items()
                     if t - t0 <= deadline)
        return ontime / wall, wall, ontime, ooms

    def run_sched(deadline):
        eng = engine()
        sched = Scheduler(eng, max_queue=burst)
        t0 = time.perf_counter()
        for rid, prompt in reqs:
            sched.submit(rid, prompt, max_new_tokens=new,
                         deadline=deadline)
        sched.run_until_idle()
        wall = time.perf_counter() - t0
        ontime = sum(len(rec.tokens) for rec in sched._reqs.values()
                     if rec.state == "finished"
                     and not rec.deadline_missed)
        return (ontime / wall, wall, ontime,
                int(eng.cache.metrics_snapshot()["oom_events"]),
                dict(sched.shed_stats))

    run_naive(float("inf"))                   # warmup: compiles
    _, t_full, _, _ = run_naive(float("inf"))
    deadline = t_full / 2
    g_naive, w_naive, tok_naive, ooms_naive = run_naive(deadline)
    g_sched, w_sched, tok_sched, ooms_sched, shed = run_sched(deadline)
    return {
        "metric": "serving_sched_goodput_tokens_per_sec",
        "value": round(g_sched, 1),
        "unit": "tokens/sec (within deadline)",
        "vs_baseline": round(g_sched / g_naive, 3) if g_naive else None,
        "extra": {"device_kind": kind, "burst_requests": burst,
                  "slots": seqs, "max_new_tokens": new,
                  "deadline_seconds": round(deadline, 4),
                  "goodput_naive_fifo": round(g_naive, 1),
                  "wall_seconds_naive": round(w_naive, 4),
                  "wall_seconds_sched": round(w_sched, 4),
                  "ontime_tokens_naive": tok_naive,
                  "ontime_tokens_sched": tok_sched,
                  "oom_raises_caught_naive": ooms_naive,
                  "oom_events_sched": ooms_sched,
                  "shed": shed}}


def bench_serving_preempt():
    """Preemptive-scheduling row (ISSUE 5): priority-mixed OVERLOAD —
    low-priority long decodes saturate every slot, then high-priority
    short requests arrive.  The PR 4 scheduler (``preemption=False``)
    parks the high-priority work until a long decode finishes its full
    token budget; the preemptive scheduler suspends the
    lowest-priority active request (KV pages swap to the host pool),
    admits the high-priority request into the freed slot NOW, and
    resumes the victim afterwards with bit-identical tokens.  Headline
    value: mean high-priority TTFT (submit → first token).  Goodput
    (total tokens / wall) is reported too — preemption must not buy
    latency with meaningful throughput (the swap/replay overhead is
    the only tax)."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import Scheduler

    _, kind, peak, hbm, on_tpu = _device()
    if on_tpu:
        cfg = LlamaConfig(vocab_size=_VOCAB, hidden_size=1536,
                          intermediate_size=6144, num_hidden_layers=16,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=2048)
        seqs, page, maxlen = 4, 128, 2048
        n_low, n_high, plen, new_low, new_high = 4, 4, 256, 512, 32
        dtype = jnp_bf16()
    else:
        from paddle_tpu.models.llama import llama_tiny_config
        cfg = llama_tiny_config()
        seqs, page, maxlen = 2, 8, 32
        n_low, n_high, plen, new_low, new_high = 2, 2, 4, 24, 4
        dtype = np.float32
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    rng = np.random.default_rng(0)
    lows = [(f"lo{i}", rng.integers(1, cfg.vocab_size, plen).tolist())
            for i in range(n_low)]
    highs = [(f"hi{i}", rng.integers(1, cfg.vocab_size, plen).tolist())
             for i in range(n_high)]

    def run(preempt):
        eng = LLMEngine(model, max_seqs=seqs, max_len=maxlen,
                        page_size=page, dtype=dtype,
                        enable_prefix_caching=False)
        sched = Scheduler(eng, max_queue=n_low + n_high,
                          preemption=preempt,
                          max_preemptions_per_request=4)
        submit_t, ttft = {}, {}

        def watch(rid):
            def cb(ev):
                if ev["type"] == "tokens" and rid not in ttft:
                    ttft[rid] = time.perf_counter() - submit_t[rid]
            return cb

        t0 = time.perf_counter()
        for rid, prompt in lows:
            submit_t[rid] = time.perf_counter()
            sched.submit(rid, prompt, max_new_tokens=new_low,
                         priority=1, on_event=watch(rid))
        sched.step()                          # longs take every slot
        for rid, prompt in highs:
            submit_t[rid] = time.perf_counter()
            sched.submit(rid, prompt, max_new_tokens=new_high,
                         priority=0, on_event=watch(rid))
        sched.run_until_idle()
        wall = time.perf_counter() - t0
        tokens = sum(len(rec.tokens) for rec in sched._reqs.values()
                     if rec.state == "finished")
        hi_ttft = float(np.mean([ttft[r] for r, _ in highs]))
        snap = sched.metrics_snapshot()
        return (hi_ttft, tokens / wall, wall,
                snap.get("preempted", 0),
                int(snap["engine"]["kv_cache"]["oom_events"]),
                snap["engine"]["kv_cache"]["swap_out_pages"])

    run(True)                                 # warmup: compiles
    base_ttft, base_goodput, base_wall, _, base_oom, _ = run(False)
    pre_ttft, pre_goodput, pre_wall, n_preempt, pre_oom, swapped = \
        run(True)
    return {
        "metric": "serving_preempt_high_priority_ttft_seconds",
        "value": round(pre_ttft, 4),
        "unit": "seconds (mean, high priority)",
        "vs_baseline": round(base_ttft / pre_ttft, 3) if pre_ttft
        else None,
        "extra": {"device_kind": kind, "slots": seqs,
                  "low_priority_requests": n_low,
                  "high_priority_requests": n_high,
                  "max_new_low": new_low, "max_new_high": new_high,
                  "ttft_no_preemption": round(base_ttft, 4),
                  "goodput_preempt_tok_per_s": round(pre_goodput, 1),
                  "goodput_no_preempt_tok_per_s":
                      round(base_goodput, 1),
                  "wall_seconds_preempt": round(pre_wall, 4),
                  "wall_seconds_no_preempt": round(base_wall, 4),
                  "preemptions": n_preempt,
                  "swapped_out_pages": swapped,
                  "oom_events": pre_oom + base_oom}}


def bench_serving_drain():
    """Fault-tolerant multi-host row (ISSUE 6): drain a replica with
    in-flight decodes and resume them on a second replica.  The
    KV-MIGRATING drain ships each request's swap pages (serialized
    blob) and swap-ins at the destination; the baseline (swap pools
    disabled) must RECOMPUTE — replay the prompt through chunked
    prefill and every generated token through the decode program.
    Headline value: wall seconds from drain start to all drained
    requests finished, migration path; vs_baseline is the recompute
    path's wall on the same schedule.  Both paths must land
    bit-identical tokens and lose zero requests — the bench asserts
    it."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import ReplicaRouter, Scheduler

    _, kind, peak, hbm, on_tpu = _device()
    if on_tpu:
        cfg = LlamaConfig(vocab_size=_VOCAB, hidden_size=1536,
                          intermediate_size=6144, num_hidden_layers=16,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=2048)
        seqs, page, maxlen = 4, 128, 2048
        n_req, plen, n_new, warm_steps = 4, 256, 512, 256
        dtype = jnp_bf16()
    else:
        from paddle_tpu.models.llama import llama_tiny_config
        cfg = llama_tiny_config()
        seqs, page, maxlen = 4, 8, 64
        n_req, plen, n_new, warm_steps = 3, 4, 32, 16
        dtype = np.float32
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    rng = np.random.default_rng(0)
    prompts = {f"d{i}": rng.integers(1, cfg.vocab_size, plen).tolist()
               for i in range(n_req)}

    def reference(rid):
        eng = LLMEngine(model, max_seqs=seqs, max_len=maxlen,
                        page_size=page, dtype=dtype)
        eng.add_request("ref", prompts[rid], max_new_tokens=n_new)
        while eng.has_work():
            eng.step()
        return eng.result("ref")

    want = {rid: reference(rid) for rid in prompts}

    def run(swap_pool):
        engines = [LLMEngine(model, max_seqs=seqs, max_len=maxlen,
                             page_size=page, dtype=dtype,
                             swap_pool_pages=swap_pool)
                   for _ in range(2)]
        router = ReplicaRouter(
            [Scheduler(e, max_queue=n_req + 1) for e in engines],
            sleep=lambda s: None)
        for rid, prompt in prompts.items():
            router.submit(rid, prompt, max_new_tokens=n_new)
        src = router._owner[next(iter(prompts))]
        for _ in range(warm_steps):           # build decode history
            router.replicas[src].step()
        t0 = time.perf_counter()
        moved = router.drain_replica(src)
        router.run_until_idle()
        wall = time.perf_counter() - t0
        lost = [rid for rid in prompts
                if router.pop_result(rid) != want[rid]]
        assert not lost, f"drain lost/corrupted requests: {lost}"
        dst_cache = engines[1 - src].cache.metrics_snapshot()
        return wall, len(moved), dst_cache

    run(None)                                 # warmup: compiles
    mig_wall, mig_moved, mig_cache = run(None)     # swap pools on
    rec_wall, rec_moved, rec_cache = run(0)        # recompute only
    return {
        "metric": "serving_drain_migration_seconds",
        "value": round(mig_wall, 4),
        "unit": "seconds (drain -> all drained requests finished)",
        "vs_baseline": round(rec_wall / mig_wall, 3) if mig_wall
        else None,
        "extra": {"device_kind": kind, "replicas": 2,
                  "requests_moved": mig_moved,
                  "prompt_tokens": plen, "max_new_tokens": n_new,
                  "decode_steps_before_drain": warm_steps,
                  "wall_seconds_recompute": round(rec_wall, 4),
                  "swap_in_pages_migration":
                      mig_cache["swap_in_pages"],
                  "swap_imported_pages_migration":
                      mig_cache["swap_imported_pages"],
                  "swap_in_pages_recompute":
                      rec_cache["swap_in_pages"],
                  "lost_requests": 0}}


def jnp_bf16():
    import jax.numpy as jnp
    return jnp.bfloat16


def bench_ckpt():
    """Crash-safe training row (ISSUE 7): checkpoint overhead on the
    compiled training step — atomic staging commit + per-chunk sha256,
    saved every K steps through a CheckpointManager.  Headline value:
    async-save wall overhead vs a no-checkpoint run of the same steps
    (1.0 = free); vs_baseline is the SYNC overhead on the same schedule
    — the gap is what the bounded write-behind queue buys.  The bench
    asserts the last checkpoint validates (committed manifest, sha256)
    so the speed is never bought with a torn save."""
    import shutil
    import tempfile

    import paddle_tpu as paddle
    from paddle_tpu.distributed.checkpoint import validate_checkpoint
    from paddle_tpu.distributed.ckpt_manager import CheckpointManager
    from paddle_tpu.jit.train import CompiledTrainStep
    from paddle_tpu.models.gpt import (GPTForCausalLM,
                                       GPTPretrainingCriterion,
                                       gpt2_tiny_config)

    _, kind, peak, hbm, on_tpu = _device()
    cfg = gpt2_tiny_config()
    rng = np.random.default_rng(0)
    ids = ((np.arange(32)[None, :] + rng.integers(0, 8, (8, 1))) % 32
           ).astype(np.int32)
    batch = {"x": ids[:, :-1], "y": ids[:, 1:].astype(np.int64)}
    steps, save_every = 12, 3

    def make_step():
        paddle.seed(0)
        model = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion()
        opt = paddle.optimizer.AdamW(learning_rate=1e-3, weight_decay=0.01)
        return CompiledTrainStep(
            model, lambda m, b: crit(m(b["x"]), b["y"]), opt, seed=0)

    def run(mode, root):
        step = make_step()
        manager = None if mode == "none" else CheckpointManager(
            root, keep_last_n=2, async_save=(mode == "async"))
        loss = step(batch)                       # compile outside timing
        import jax
        jax.device_get(loss)
        t0 = time.perf_counter()
        for i in range(steps):
            loss = step(batch)
            if manager is not None and (i + 1) % save_every == 0:
                manager.save(step, i + 1)
        if manager is not None:
            manager.wait()                       # async saves must land
        jax.device_get(loss)
        wall = time.perf_counter() - t0
        if manager is not None:
            validate_checkpoint(manager.step_dir(steps))
        return wall

    root = tempfile.mkdtemp(prefix="bench_ckpt_")
    try:
        run("none", root)                        # warm the whole path
        base = run("none", root)
        sync_w = run("sync", os.path.join(root, "s"))
        async_w = run("async", os.path.join(root, "a"))
    finally:
        shutil.rmtree(root, ignore_errors=True)
    from paddle_tpu.observability import get_registry
    hist = get_registry().get("ckpt_save_seconds")
    means = {m: round(hist.labels(m).mean, 4)
             for m in ("sync", "async")} if hist is not None else {}
    return {
        "metric": "ckpt_async_step_overhead",
        "value": round(async_w / base, 4),
        "unit": "x wall vs no-checkpoint run (1.0 = free)",
        "vs_baseline": round(sync_w / base, 4),
        "extra": {"device_kind": kind, "steps": steps,
                  "save_every": save_every,
                  "wall_none_s": round(base, 4),
                  "wall_sync_s": round(sync_w, 4),
                  "wall_async_s": round(async_w, 4),
                  "save_seconds_mean": means}}


def bench_train_fused():
    """Fused-step-regions row (BENCH_r08): fused vs unfused compiled
    train step.  On TPU the fused path runs the one-pass Pallas
    clip+optimizer kernel (small-leaf tail packed into one launch) plus
    the add+RMSNorm and matmul+rope chains at the headline ladder pick;
    the MFU delta toward the ROADMAP >=0.55 target is the headline.
    Off TPU there is no Pallas: both paths lower to STRUCTURALLY
    IDENTICAL XLA programs (that is the bit-identity contract
    tests/test_fused_train.py pins), so the CPU fallback at the tiny
    ladder config validates parity — the honest expectation is a ratio
    ~1.0x, measured with interleaved best-of reps so the 1-core box's
    scheduling noise cannot manufacture a fake win either way."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.jit.train import CompiledTrainStep
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    dev, kind, peak, hbm, on_tpu = _device()
    seq = _SEQ if on_tpu else 128
    if on_tpu:
        name, h, i, layers, heads, kv, batch, n_params = _pick_config(
            hbm, seq)
    else:
        # llama-tiny geometry (the budget-guard-pinned CPU fallback)
        name, h, i, layers, heads, kv, batch = \
            "llama-tiny", 256, 512, 4, 8, 4, 4
    cfg = LlamaConfig(
        vocab_size=_VOCAB if on_tpu else 1024, hidden_size=h,
        intermediate_size=i, num_hidden_layers=layers,
        num_attention_heads=heads, num_key_value_heads=kv,
        max_position_embeddings=seq, recompute=on_tpu,
        recompute_granularity="core_attn")
    n_params = _param_count(h, i, layers, heads, kv, cfg.vocab_size)

    def build(fused):
        paddle.seed(12)
        model = LlamaForCausalLM(cfg)
        model = paddle.amp.decorate(model, level="O2", dtype="bfloat16")
        opt = paddle.optimizer.AdamW(
            learning_rate=1e-4, parameters=model.parameters(),
            grad_clip=paddle.ClipGradByGlobalNorm(1.0))
        return CompiledTrainStep(
            model, lambda m, b: m(b["input_ids"], labels=b["labels"]),
            opt, fused_step=fused)

    data = _train_batch(cfg.vocab_size, batch, seq)
    steps = {"fused": build(True), "unfused": build(False)}
    for s in steps.values():                      # compile + settle
        jax.device_get(s(data))
        jax.device_get(s(data))
    iters = 10 if on_tpu else 6
    reps = 3 if on_tpu else 5
    best = {k: float("inf") for k in steps}
    for _ in range(reps):
        for label, s in steps.items():            # interleaved best-of
            t0 = time.perf_counter()
            for _ in range(iters):
                loss = s(data)
            jax.device_get(loss)
            best[label] = min(best[label],
                              (time.perf_counter() - t0) / iters)
    tps = batch * seq / best["fused"]
    mfu_f, mfu_fa = _mfu_pair(n_params, layers, h, seq, tps, peak)
    mfu_u, _ = _mfu_pair(n_params, layers, h, seq,
                         batch * seq / best["unfused"], peak)
    speedup = best["unfused"] / best["fused"]
    return {
        "metric": f"{name}_fused_step_speedup",
        "value": round(speedup, 4),
        "unit": "x unfused step time (>1 = fused faster)",
        "vs_baseline": round(mfu_f / 0.55, 4) if mfu_f else None,
        "extra": {"device_kind": kind, "params": n_params,
                  "batch": batch, "seq": seq,
                  "step_ms_fused": round(best["fused"] * 1e3, 2),
                  "step_ms_unfused": round(best["unfused"] * 1e3, 2),
                  "mfu_fused": round(mfu_f, 4) if mfu_f else None,
                  "mfu_unfused": round(mfu_u, 4) if mfu_u else None,
                  "mfu_attn_fused": round(mfu_fa, 4) if mfu_fa else None,
                  "mfu_target": 0.55,
                  "kernels_active": bool(on_tpu),
                  "note": ("cpu fallback: fused==unfused programs "
                           "(bit-identity), parity expected"
                           if not on_tpu else
                           "pallas fused clip+update kernel + "
                           "add+norm/matmul+rope chains")},
    }


def bench_longseq():
    """Long-context row: 32k-token sequences on ONE chip (flash attention
    + selective remat + fused CE keep the S^2 and vocab terms off HBM).
    Multi-chip context parallelism (ring/Ulysses over sep) is validated
    functionally in tests/test_context_parallel.py; this row evidences
    the single-chip long-seq capability envelope (SURVEY.md §5
    long-context)."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.jit.train import CompiledTrainStep
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    dev, kind, peak, hbm, on_tpu = _device()
    seq = 32768 if on_tpu else 512
    h, i, layers, heads, kv = 1024, 4096, 12, 8, 4       # llama-410m
    # 410M @ 32k fits v5e HBM without remat (measured r3: 21.4k tok/s
    # vs 20.9k with flash-aware core_attn remat vs 17.5k with r2's full
    # remat); larger models should use recompute_granularity="core_attn"
    # — the round-3 policy saves (flash_out, flash_lse) so backward
    # never re-runs the attention kernel
    cfg = LlamaConfig(vocab_size=_VOCAB if on_tpu else 512, hidden_size=h,
                      intermediate_size=i, num_hidden_layers=layers,
                      num_attention_heads=heads, num_key_value_heads=kv,
                      max_position_embeddings=seq, recompute=False)
    model = paddle.amp.decorate(LlamaForCausalLM(cfg), level="O2",
                                dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    step = CompiledTrainStep(model, lambda m, b: m(b["input_ids"],
                                                   labels=b["labels"]), opt)
    data = _train_batch(cfg.vocab_size, 1, seq)
    step_time, loss = _time_step(step, data, 10 if on_tpu else 2)
    n = _param_count(h, i, layers, heads, kv, cfg.vocab_size)
    tps = seq / step_time
    mfu6n, mfu_attn = _mfu_pair(n, layers, h, seq, tps, peak)
    return {"metric": "llama-410m_seq32k_tokens_per_sec_per_chip",
            "unit": "tokens/sec", "value": round(tps, 1),
            "extra": {"device_kind": kind, "seq": seq, "batch": 1,
                      "params": n,
                      "mfu": round(mfu6n, 4) if mfu6n else None,
                      "mfu_attn": round(mfu_attn, 4) if mfu_attn else None,
                      "final_loss": float(np.asarray(jax.device_get(loss)))}}


def bench_serving_ragged():
    """Ragged-unified-step row (ISSUE 12): decode latency under a
    long-prompt + decode-heavy overload mix.  The split-program engine
    prefills an admitted prompt synchronously (chunk dispatches back
    to back), stalling every in-flight decode for the whole prompt —
    the head-of-line problem ROADMAP open item 2 named.  The ragged
    unified step packs the prompt's chunks INTO the decode batch (one
    compiled mixed program, per-sequence descriptors as traced
    scalars), so decode token inter-arrival stays near pure-decode
    TPOT while the prefill streams through.  Headline: p99 decode
    TPOT ratio split/unified (>1 = unified absorbs the prefill burst
    better); tokens stay bit-identical (tests/test_ragged_mixed.py
    pins that), so this row is pure scheduling latency.  Interleaved
    best-of reps keep 1-core scheduling noise honest."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import Scheduler

    _, kind, peak, hbm, on_tpu = _device()
    if on_tpu:
        cfg = LlamaConfig(vocab_size=_VOCAB, hidden_size=1536,
                          intermediate_size=6144, num_hidden_layers=16,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=4096)
        seqs, page, maxlen = 8, 128, 4096
        n_dec, new_dec = 6, 160
        n_long, plen_long, new_long = 2, 1536, 16
        dtype = jnp_bf16()
    else:
        from paddle_tpu.models.llama import llama_tiny_config
        cfg = llama_tiny_config()
        seqs, page, maxlen = 4, 8, 64
        n_dec, new_dec = 3, 24
        n_long, plen_long, new_long = 1, 40, 4
        dtype = np.float32
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    rng = np.random.default_rng(0)
    dec_prompts = [rng.integers(1, cfg.vocab_size, 4).tolist()
                   for _ in range(n_dec)]
    long_prompts = [rng.integers(1, cfg.vocab_size, plen_long).tolist()
                    for _ in range(n_long)]

    def run(unified):
        eng = LLMEngine(model, max_seqs=seqs, max_len=maxlen,
                        page_size=page, dtype=dtype,
                        enable_prefix_caching=False,
                        unified_step=unified)
        sched = Scheduler(eng, max_queue=64, chunked_prefill=unified)
        arriv = {}
        for i, p in enumerate(dec_prompts):
            sched.submit(f"d{i}", p, max_new_tokens=new_dec)
            arriv[f"d{i}"] = []
        submitted = False
        t0 = time.perf_counter()
        while sched.busy():
            out = sched.step()
            now = time.perf_counter()
            for rid, toks in out.items():
                if rid in arriv:
                    arriv[rid].extend([now] * len(toks))
            if not submitted and arriv and \
                    min(len(a) for a in arriv.values()) >= 3:
                # every decode is mid-stream: NOW the prompt arrives
                for j in range(n_long):
                    sched.submit(f"L{j}", long_prompts[j],
                                 max_new_tokens=new_long)
                submitted = True
        wall = time.perf_counter() - t0
        total = sum(len(sched.result(f"d{i}")) for i in range(n_dec))
        total += sum(len(sched.result(f"L{j}")) for j in range(n_long))
        gaps = np.concatenate([np.diff(np.asarray(a))
                               for a in arriv.values() if len(a) > 1])
        return total / wall, gaps

    for uni in (False, True):
        run(uni)                                  # warmup: compiles
    reps = 2 if on_tpu else 3
    best = {}
    for _ in range(reps):
        for label, uni in (("split", False), ("unified", True)):
            tps, gaps = run(uni)                  # interleaved best-of
            if label not in best or tps > best[label][0]:
                best[label] = (tps, gaps)
    p = {label: {q: float(np.percentile(g, q) * 1e3)
                 for q in (50, 99)}
         for label, (_, g) in best.items()}
    ratio = p["split"][99] / p["unified"][99]
    return {
        "metric": "llama_serving_ragged_p99_decode_tpot_ratio",
        "value": round(ratio, 3),
        "unit": "x split-program p99 decode TPOT (>1 = unified "
                "absorbs concurrent prefill better)",
        "extra": {"device_kind": kind, "decode_slots": n_dec,
                  "decode_new_tokens": new_dec,
                  "long_prompts": n_long, "long_prompt_len": plen_long,
                  "prefill_token_budget": page,
                  "tpot_p50_ms_split": round(p["split"][50], 3),
                  "tpot_p99_ms_split": round(p["split"][99], 3),
                  "tpot_p50_ms_unified": round(p["unified"][50], 3),
                  "tpot_p99_ms_unified": round(p["unified"][99], 3),
                  "tokens_per_sec_split": round(best["split"][0], 1),
                  "tokens_per_sec_unified": round(best["unified"][0], 1),
                  "mixed_compiles": LLMEngine.mixed_compiles(),
                  "prefill_compiles": LLMEngine.prefill_compiles(),
                  "decode_compiles": LLMEngine.decode_compiles()}}


def verify_dropout_smoke():
    """TPU-only dropout numerics smoke (VERDICT r3 Weak #6): the twin
    of the two CPU-perma-skipped tests in tests/test_pallas_flash.py
    (interpret mode stubs prng_random_bits) — deterministic per seed,
    seed-sensitive, actually drops, mean-preserving across seeds."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.flash_attention import flash_attention_raw

    _, kind, _, _, on_tpu = _device()
    if not on_tpu:
        return {"verify": "dropout_smoke", "ok": False,
                "note": "tpu_only"}
    rng = np.random.default_rng(5)
    b, s, h, d = 1, 256, 2, 128
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)

    def run(seed, p=0.5):
        return np.asarray(jax.jit(
            lambda q, k, v: flash_attention_raw(
                q, k, v, causal=False, dropout_p=p,
                seed=jnp.int32(seed)))(q, k, v))

    o1, o2 = run(42), run(42)
    deterministic = bool(np.array_equal(o1, o2))
    seed_sensitive = float(np.abs(o1 - run(7)).max()) > 1e-3
    base = np.asarray(jax.jit(
        lambda q, k, v: flash_attention_raw(q, k, v, causal=False))(
        q, k, v))
    drops = float(np.abs(o1 - base).max()) > 1e-3
    avg = sum(run(i).astype(np.float64) for i in range(16)) / 16
    mean_err = float(np.abs(avg - base).mean() / np.abs(base).mean())
    ok = deterministic and seed_sensitive and drops and mean_err < 0.35
    return {"verify": "dropout_smoke", "ok": bool(ok),
            "extra": {"device_kind": kind,
                      "deterministic": deterministic,
                      "seed_sensitive": bool(seed_sensitive),
                      "drops": bool(drops),
                      "mean_err": round(mean_err, 4)}}


def bench_serving_tp():
    """Sharded-serving row (ISSUE 18): the same staggered greedy
    workload through a tp=1 engine and a tp=2 tensor-parallel engine
    over a GSPMD mesh (forced-host CPU devices off-TPU, real chips on).
    The sharding discipline constrains only OUTPUT axes and gathers
    every contraction input first, so the row asserts tokens are
    BIT-IDENTICAL across tp — sharding is a pure capacity/latency
    lever, never a numerics knob.  Also measured: the one-compile
    invariant per mesh shape (a second tp=2 engine must add zero
    mixed/window compiles) and the per-chip KV-pool bytes from
    ``memory_rows()``.  Headline: the per-chip KV capacity multiplier
    of tp=2 + int8 KV over the tp=1 fp32 pool — the two levers
    (head-sharding the pools, per-token int8) multiply instead of
    fighting, which is the point of keeping the scale pools on the
    same KVH sharding."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.distributed.topology import serving_mesh
    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    _, kind, peak, hbm, on_tpu = _device()
    if on_tpu:
        cfg = LlamaConfig(vocab_size=_VOCAB, hidden_size=1536,
                          intermediate_size=6144, num_hidden_layers=16,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=2048)
        batch, new, page, maxlen, sync = 8, 128, 128, 2048, 16
        prompts = [96, 57, 128, 101, 77, 120, 64, 115]
        dtype = jnp_bf16()
    else:
        from paddle_tpu.models.llama import llama_tiny_config
        cfg = llama_tiny_config()
        batch, new, page, maxlen, sync = 4, 48, 8, 128, 4
        prompts = [8, 5, 12, 9]
        dtype = np.float32
    ndev = len(jax.devices())
    if ndev < 2:
        return {"metric": "llama_serving_tp_kv_per_chip_multiplier",
                "unit": "x", "value": 1.0,
                "extra": {"device_kind": kind, "note":
                          "single device — no tp mesh (run tests "
                          "under the forced 8-device CPU platform)"}}
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()

    def run(mesh, **kw):
        rng = np.random.default_rng(0)
        eng = LLMEngine(model, max_seqs=batch, max_len=maxlen,
                        page_size=page, dtype=dtype,
                        steps_per_sync=sync, unified_step=True,
                        mesh=mesh, **kw)
        for i, plen in enumerate(prompts):
            eng.add_request(
                f"t{i}", rng.integers(1, cfg.vocab_size, plen).tolist(),
                max_new_tokens=new)
            eng.step()                 # staggered: batches churn
        t0 = time.perf_counter()
        while eng.has_work():
            eng.step()
        dt = time.perf_counter() - t0
        toks = {f"t{i}": eng.result(f"t{i}")
                for i in range(len(prompts))}
        produced = sum(len(v) for v in toks.values())
        return eng, toks, produced / dt

    mesh2 = serving_mesh(2)
    eng1, want, rate1 = run(None)
    eng2, got, rate2 = run(mesh2)
    bit_identical = got == want
    base_m = LLMEngine.mixed_compiles()
    base_w = LLMEngine.window_compiles()
    run(mesh2)                         # second tp=2 engine, same mesh
    mixed_delta = LLMEngine.mixed_compiles() - base_m
    window_delta = LLMEngine.window_compiles() - base_w

    rows1 = eng1.cache.memory_rows()             # tp=1 fp32 pool
    eng_i8, _, _ = run(mesh2, kv_dtype="int8")
    rows_i8 = eng_i8.cache.memory_rows()         # tp=2 int8 + scales
    per_chip_fp1 = rows1["device_bytes_per_shard"]
    per_chip_i8tp2 = rows_i8["device_bytes_per_shard"]
    mult = per_chip_fp1 / max(per_chip_i8tp2, 1)
    return {"metric": "llama_serving_tp_kv_per_chip_multiplier",
            "unit": "x", "value": round(mult, 2),
            "extra": {"device_kind": kind, "tp": 2,
                      "bit_identical_tp1_vs_tp2": bit_identical,
                      "mixed_compile_delta_same_mesh": mixed_delta,
                      "window_compile_delta_same_mesh": window_delta,
                      "tokens_per_sec_tp1": round(rate1, 1),
                      "tokens_per_sec_tp2": round(rate2, 1),
                      "kv_bytes_per_chip_tp1_fp32": per_chip_fp1,
                      "kv_bytes_per_chip_tp2_int8": per_chip_i8tp2,
                      "budget": "bit_identical AND zero compile "
                                "delta on a warm mesh shape"}}


def bench_serving_moe():
    """MoE serving row (ISSUE 19): the same staggered greedy workload
    through a Qwen2-MoE engine with grouped-matmul dispatch (ONE
    grouped_matmul per layer over expert-sorted rows) vs the dense
    per-expert reference, at 8 and at 64 experts.  Rates are
    interleaved best-of-3 on WARM engines (both dispatch modes
    measured in alternation so ambient noise hits them equally).
    Also recorded: bit-identity between the two dispatch modes at
    each expert count (the acceptance bar — dispatch is a layout
    decision, never a numerics knob) and the mixed-program compile
    delta for a second same-geometry engine (expert descriptors are
    traced data: zero new compiles).  Headline: the grouped/dense
    decode-throughput ratio at 64 experts.  On TPU the grouped path
    feeds ONE MXU grouped_matmul kernel and should pull ahead of the
    dense reference's every-expert-for-every-row compute; on CPU
    both modes run the gathered-einsum reference, so grouping pays
    sort + tile-padding overhead with nothing to buy it back and the
    ratio lands BELOW 1 — the budget for this row is the numerics
    (bit-identity) and the compile invariant, not the CPU ratio."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.models.qwen2_moe import (Qwen2MoeConfig,
                                             Qwen2MoeForCausalLM)

    _, kind, peak, hbm, on_tpu = _device()
    batch, new, page, maxlen, sync = 4, 32, 8, 128, 4
    prompts = [8, 5, 12, 9]
    reps = 3

    def mk_cfg(e):
        return Qwen2MoeConfig(
            vocab_size=256, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
            moe_intermediate_size=32,
            shared_expert_intermediate_size=64,
            num_experts=e, num_experts_per_tok=2,
            max_position_embeddings=maxlen)

    def serve(eng, tag):
        rng = np.random.default_rng(0)
        for i, plen in enumerate(prompts):
            eng.add_request(
                f"{tag}_{i}", rng.integers(1, 256, plen).tolist(),
                max_new_tokens=new)
            eng.step()                 # staggered: batches churn
        t0 = time.perf_counter()
        while eng.has_work():
            eng.step()
        dt = time.perf_counter() - t0
        toks = [eng.result(f"{tag}_{i}")
                for i in range(len(prompts))]
        return toks, sum(len(t) for t in toks) / dt

    per_e, compile_delta = {}, None
    for n_experts in (8, 64):
        paddle.seed(0)
        model = Qwen2MoeForCausalLM(mk_cfg(n_experts))
        model.eval()
        engines = {d: LLMEngine(model, max_seqs=batch,
                                max_len=maxlen, page_size=page,
                                steps_per_sync=sync, moe_dispatch=d)
                   for d in ("grouped", "dense")}
        toks = {d: serve(engines[d], f"warm{n_experts}{d}")[0]
                for d in engines}      # warm: compile + first parity
        best = {d: 0.0 for d in engines}
        for rep in range(reps):        # interleaved best-of: noise
            for d, eng in engines.items():   # hits both modes alike
                best[d] = max(best[d],
                              serve(eng, f"r{rep}{n_experts}{d}")[1])
        if n_experts == 8:             # second same-geometry engine:
            base = LLMEngine.mixed_compiles()     # traced descriptors
            serve(LLMEngine(model, max_seqs=batch, max_len=maxlen,
                            page_size=page, steps_per_sync=sync),
                  "again8")            # -> zero new programs
            compile_delta = LLMEngine.mixed_compiles() - base
        per_e[n_experts] = {
            "bit_identical": toks["grouped"] == toks["dense"],
            "tokens_per_sec_grouped": round(best["grouped"], 1),
            "tokens_per_sec_dense": round(best["dense"], 1),
            "ratio": round(best["grouped"] / max(best["dense"], 1e-9),
                           3)}
    return {"metric": "qwen2moe_serving_grouped_vs_dense_speedup_e64",
            "unit": "x", "value": per_e[64]["ratio"],
            "extra": {"device_kind": kind,
                      "experts_8": per_e[8], "experts_64": per_e[64],
                      "top_k": 2, "best_of": reps,
                      "mixed_compile_delta_same_geometry":
                          compile_delta,
                      "budget": "bit_identical at BOTH expert counts "
                                "AND zero compile delta on a warm "
                                "geometry"}}


def bench_serving_spec():
    """Speculative decoding row (ISSUE 20): staggered greedy decode
    through an 8-layer llama target, plain engine (steps_per_sync=4
    on-device window — the repo's strongest non-speculative config)
    vs ``LLMEngine(draft_model=..., spec_k=4)`` with a 1-layer draft.
    Two draft points bound the acceptance sweep: a RANDOM 1-layer
    draft (near-zero agreement — the overhead floor, spec pays
    propose+verify and delivers ~1 token/window) and a DISTILLED
    1-layer draft (residual branches epsilon-scaled in both models,
    embed/head/final-norm shared, so both argmax from the
    embedding-dominated logits — acceptance ≈ 1, the regime a real
    distilled draft buys).  Rates are interleaved best-of-3 on WARM
    engines.  Also recorded: greedy BIT-IDENTITY of the speculative
    stream against plain decode at BOTH acceptance points (the
    tentpole bar — speculation is a latency trick, never a sampler)
    and each point's measured acceptance rate off the engine's own
    counters.  Headline: the spec/plain decode-throughput ratio with
    the distilled draft; budget >1.5x on CPU (one draft-scan dispatch
    + one ragged verify dispatch replace k+1 sequential 8-layer
    steps)."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    _, kind, peak, hbm, on_tpu = _device()
    batch, new, page, maxlen, sync, k = 4, 48, 8, 256, 4, 4
    prompts = [8, 5, 12, 9]
    reps = 5
    geo = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
               num_attention_heads=4, num_key_value_heads=2,
               max_position_embeddings=maxlen, rms_norm_eps=1e-5)

    paddle.seed(0)
    target = LlamaForCausalLM(LlamaConfig(num_hidden_layers=8, **geo))
    target.eval()

    def mk_draft(distilled):
        paddle.seed(1)
        d = LlamaForCausalLM(LlamaConfig(num_hidden_layers=1, **geo))
        d.eval()
        if distilled:
            # epsilon-scale the residual-branch outputs in BOTH
            # models and share embed/head/final-norm: logits become
            # embedding-dominated, so the 1-layer draft argmaxes with
            # the 8-layer target almost always — a stand-in for a
            # distillation run this bench can't afford
            for m in (target, d):
                for layer in m.llama.layers:
                    for lin in (layer.self_attn.o_proj,
                                layer.mlp.down_proj):
                        lin.weight.set_value(
                            np.asarray(lin.weight.value) * 1e-3)
            sd = target.state_dict()
            for dst, key in [(d.llama.embed_tokens,
                              "llama.embed_tokens.weight"),
                             (d.llama.norm, "llama.norm.weight"),
                             (d.lm_head, "lm_head.weight")]:
                dst.weight.set_value(np.asarray(sd[key]))
        return d

    def serve(eng, tag):
        rng = np.random.default_rng(0)
        for i, plen in enumerate(prompts):
            eng.add_request(
                f"{tag}_{i}", rng.integers(1, 256, plen).tolist(),
                max_new_tokens=new)
            eng.step()                 # staggered: batches churn
        t0 = time.perf_counter()
        while eng.has_work():
            eng.step()
        dt = time.perf_counter() - t0
        toks = [eng.result(f"{tag}_{i}")
                for i in range(len(prompts))]
        return toks, sum(len(t) for t in toks) / dt

    points = {}
    # random draft FIRST: mk_draft(True) mutates the shared target
    for name, distilled in (("random_draft", False),
                            ("distilled_draft", True)):
        draft = mk_draft(distilled)
        plain = LLMEngine(target, max_seqs=batch, max_len=maxlen,
                          page_size=page, steps_per_sync=sync)
        spec = LLMEngine(target, max_seqs=batch, max_len=maxlen,
                         page_size=page, draft_model=draft, spec_k=k)
        pt, _ = serve(plain, f"w_{name}_p")   # warm: compile parity
        st, _ = serve(spec, f"w_{name}_s")
        best_p = best_s = 0.0
        for rep in range(reps):        # interleaved best-of: noise
            best_p = max(best_p,       # hits both engines alike
                         serve(plain, f"p{rep}{name}")[1])
            best_s = max(best_s, serve(spec, f"s{rep}{name}")[1])
        s = spec.metrics_snapshot()["spec"]
        points[name] = {
            "bit_identical": pt == st,
            "acceptance_rate": round(s["acceptance_rate"], 3),
            "tokens_per_sec_plain": round(best_p, 1),
            "tokens_per_sec_spec": round(best_s, 1),
            "ratio": round(best_s / max(best_p, 1e-9), 3)}
    return {"metric": "serving_spec_decode_speedup_distilled_draft",
            "unit": "x", "value": points["distilled_draft"]["ratio"],
            "extra": {"device_kind": kind, "spec_k": k,
                      "target_layers": 8, "draft_layers": 1,
                      "plain_steps_per_sync": sync, "best_of": reps,
                      "random_draft": points["random_draft"],
                      "distilled_draft": points["distilled_draft"],
                      "budget": "bit_identical at BOTH acceptance "
                                "points AND distilled ratio > 1.5x "
                                "on CPU"}}


def bench_history(root=None, emit=True):
    """Fold every ``BENCH_rNN.json`` snapshot (the driver's one-file-
    per-round bench record) into ONE trajectory table: a row per
    (round, metric) with value, unit, and the delta (percent) against
    the SAME metric's most recent earlier round — how each headline
    number moved across the PR sequence, read from the repo itself.
    Tail lines that are not metric JSON (platform WARNINGs, *_ERROR
    rows) are skipped tolerantly; a malformed snapshot file skips
    whole, never aborts the fold.  Prints the table plus one summary
    JSON line (``emit=True``) and returns the full structure."""
    import glob
    import re
    root = root or os.path.dirname(os.path.abspath(__file__))
    files = []
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = re.match(r"BENCH_r(\d+)\.json$", os.path.basename(path))
        if m:
            files.append((int(m.group(1)), path))
    rows, last = [], {}
    for rnd, path in sorted(files):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        for line in (rec.get("tail") or "").splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue                       # platform WARNING noise
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            metric = obj.get("metric")
            if not metric or metric.endswith("_ERROR") or \
                    "value" not in obj:
                continue
            value = obj["value"]
            delta = None
            prev = last.get(metric)
            if isinstance(value, (int, float)) and \
                    prev not in (None, 0):
                delta = round((value - prev) / abs(prev) * 100, 2)
            rows.append({"round": rnd, "metric": metric,
                         "value": value, "unit": obj.get("unit"),
                         "delta_pct": delta})
            if isinstance(value, (int, float)):
                last[metric] = value
    out = {"metric": "bench_history", "unit": "rows",
           "value": len(rows),
           "rounds": sorted({r["round"] for r in rows}),
           "metrics": sorted(last), "rows": rows}
    if emit:
        w = max([len(r["metric"]) for r in rows] or [6])
        print(f"{'round':>5}  {'metric':<{w}}  {'value':>12}  "
              f"{'delta%':>8}  unit")
        for r in rows:
            d = "" if r["delta_pct"] is None \
                else f"{r['delta_pct']:+.2f}"
            print(f"{r['round']:>5}  {r['metric']:<{w}}  "
                  f"{r['value']:>12}  {d:>8}  {r['unit'] or ''}")
        print(json.dumps({k: v for k, v in out.items()
                          if k != "rows"}))
    return out


def main():
    if "--verify" in sys.argv:
        res = verify_dropout_smoke()
        print(json.dumps(res))
        if res.get("note") == "tpu_only":
            sys.exit(86)        # skip: no TPU — not a numerics failure
        sys.exit(0 if res["ok"] else 1)
    if "--history" in sys.argv:
        bench_history()
        return 0
    if "--ladder" in sys.argv:
        # stream each row as it completes: a transient tunnel error in
        # one row must not lose the rows already measured
        fns = [("bench_headline", lambda: bench_headline(emit=False)),
               ("bench_gpt2", bench_gpt2), ("bench_ernie", bench_ernie),
               ("bench_dit", bench_dit), ("bench_moe", bench_moe),
               ("bench_decode", bench_decode),
               ("bench_moe_deepseek", bench_moe_deepseek),
               ("bench_paged_kernel", bench_paged_kernel),
               ("bench_engine", bench_engine),
               ("bench_serving_quant", bench_serving_quant),
               ("bench_serving_metrics", bench_serving_metrics),
               ("bench_trace", bench_trace),
               ("bench_fleet_health", bench_fleet_health),
               ("bench_introspection", bench_introspection),
               ("bench_serving_prefix", bench_serving_prefix),
               ("bench_serving_sched", bench_serving_sched),
               ("bench_serving_preempt", bench_serving_preempt),
               ("bench_serving_drain", bench_serving_drain),
               ("bench_serving_ragged", bench_serving_ragged),
               ("bench_ckpt", bench_ckpt),
               ("bench_train_fused", bench_train_fused),
               ("bench_engine_window", bench_engine_window),
               ("bench_decode_window", bench_decode_window),
               ("bench_longseq", bench_longseq),
               ("bench_capsule", bench_capsule),
               ("bench_serving_tp", bench_serving_tp),
               ("bench_serving_moe", bench_serving_moe),
               ("bench_serving_spec", bench_serving_spec)]
        failed = 0
        for fname, fn in fns:
            try:
                print(json.dumps(fn()), flush=True)
            except Exception as e:
                failed += 1
                print(json.dumps({"metric": f"{fname}_ERROR",
                                  "error": str(e)[:300]}), flush=True)
        return 1 if failed else 0
    bench_headline()


if __name__ == "__main__":
    sys.exit(main())
