"""Headline benchmark: Llama-3-family pretraining tokens/sec/chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference's headline metric is Llama-3-8B pretraining tokens/sec/chip
with MFU >= 40% as the north star (BASELINE.md).  This bench runs a
compiled (jit, donated-state) bf16 training step of the Llama-3
architecture at the largest config that fits the local chip's HBM,
measures steady-state tokens/sec, and reports MFU vs the 40% target as
``vs_baseline`` (no reference-published numbers exist: BASELINE.json
``published`` is {}).
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

# Peak dense bf16 FLOP/s and HBM bytes per chip, by normalized
# PJRT device_kind substring (e.g. "TPU v5 lite" -> v5lite).
_CHIP_TABLE = [
    ("v6e", 918e12, 32e9), ("v6", 918e12, 32e9), ("v5p", 459e12, 95e9),
    ("v5e", 197e12, 16e9), ("v5lite", 197e12, 16e9), ("v4", 275e12, 32e9),
    ("v3", 123e12, 16e9), ("v2", 46e12, 8e9),
]


def _chip_info(kind: str):
    k = kind.lower().replace(" ", "").replace("tpu", "")
    for sub, peak, hbm in _CHIP_TABLE:
        if sub in k:
            return peak, hbm
    return None, None


# (name, hidden, intermediate, layers, heads, kv_heads, batch)
_LADDER = [
    ("llama3-8b", 4096, 14336, 32, 32, 8, 8),
    ("llama-3b", 3072, 8192, 26, 24, 8, 8),
    ("llama-1b", 2048, 8192, 16, 16, 8, 8),
    ("llama-770m", 1536, 6144, 16, 12, 4, 8),
    ("llama-410m", 1024, 4096, 12, 8, 4, 32),
    ("llama-tiny", 256, 512, 4, 8, 4, 8),
]

_SEQ = 2048
_VOCAB = 32000  # reduced from 128256: bench is compute-shape, not tokenizer


def _param_count(h, i, layers, heads, kv, vocab):
    head_dim = h // heads
    attn = h * heads * head_dim + 2 * h * kv * head_dim + heads * head_dim * h
    mlp = 3 * h * i
    per_layer = attn + mlp + 2 * h
    return layers * per_layer + 2 * vocab * h + h


def _pick_config(hbm_bytes):
    for name, h, i, layers, heads, kv, batch in _LADDER:
        n = _param_count(h, i, layers, heads, kv, _VOCAB)
        # bf16 param + bf16 grad + 2x f32 adam moments = 12 B/param;
        # logits stay chunked (fused_linear_cross_entropy) so only
        # remat'd activations + workspace matter beyond the state.
        acts = batch * _SEQ * h * layers * 4
        need = (n * 12 + acts) * 1.25 + 1.5e9
        if need <= hbm_bytes:
            return name, h, i, layers, heads, kv, batch, n
    name, h, i, layers, heads, kv, batch = _LADDER[-1]
    return name, h, i, layers, heads, kv, batch, _param_count(
        h, i, layers, heads, kv, _VOCAB)


def main():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.jit.train import CompiledTrainStep
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                         LlamaPretrainingCriterion)

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "cpu")
    peak, hbm_table = _chip_info(kind)
    stats = {}
    try:
        stats = dev.memory_stats() or {}
    except Exception:
        pass
    hbm = stats.get("bytes_limit") or hbm_table or 8e9
    on_tpu = dev.platform not in ("cpu",)

    name, h, i, layers, heads, kv, batch, n_params = _pick_config(
        hbm if on_tpu else 4e9)
    seq = _SEQ if on_tpu else 256
    cfg = LlamaConfig(vocab_size=_VOCAB, hidden_size=h,
                      intermediate_size=i, num_hidden_layers=layers,
                      num_attention_heads=heads, num_key_value_heads=kv,
                      max_position_embeddings=seq, recompute=True)

    model = LlamaForCausalLM(cfg)
    model = paddle.amp.decorate(model, level="O2", dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 grad_clip=paddle.ClipGradByGlobalNorm(1.0))

    def loss_fn(m, b):
        return m(b["input_ids"], labels=b["labels"])

    step = CompiledTrainStep(model, loss_fn, opt)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, _VOCAB, size=(batch, seq), dtype=np.int32)
    # next-token objective: position t predicts token t+1
    labels = np.concatenate(
        [ids[:, 1:], np.full((batch, 1), -100, np.int32)], axis=1)
    data = {"input_ids": ids, "labels": labels}

    # warmup / compile
    loss = step(data)
    jax.block_until_ready(loss)
    loss = step(data)
    jax.block_until_ready(loss)

    iters = 5 if on_tpu else 2
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(data)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * iters / dt
    flops_per_token = 6 * n_params  # fwd+bwd dense FLOPs (remat adds ~fwd)
    mfu = (flops_per_token * tokens_per_sec / peak) if peak else None
    vs_baseline = (mfu / 0.40) if mfu is not None else None

    print(json.dumps({
        "metric": f"{name}_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(vs_baseline, 4) if vs_baseline else None,
        "extra": {"device_kind": kind, "params": n_params,
                  "batch": batch, "seq": seq, "mfu": round(mfu, 4)
                  if mfu is not None else None,
                  "final_loss": float(np.asarray(jax.device_get(loss)))},
    }))


if __name__ == "__main__":
    sys.exit(main())
