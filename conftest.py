"""Root conftest: force the JAX CPU backend with a virtual 8-device mesh.

The reference's distributed tests run multi-process on one host with Gloo
(SURVEY.md §4); the TPU-native analog is a fake 8-device CPU platform via
``--xla_force_host_platform_device_count=8`` so mesh/sharding logic is
exercised without real chips.  This must run before the first ``import jax``
anywhere (the axon sitecustomize pins JAX_PLATFORMS=axon, so we re-pin to
cpu here for the test session only; bench.py / __graft_entry__.py do NOT
import this and keep the real TPU).
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
