"""paddle_tpu — a TPU-native deep-learning framework with a Paddle-shaped API.

Built from scratch on jax/XLA/Pallas (SURVEY.md is the blueprint; the
reference is tensor-tang/Paddle).  ``import paddle_tpu as paddle`` gives
the familiar surface: Tensor, nn.Layer, optimizer, amp, io.DataLoader,
distributed.fleet — all lowered to XLA with GSPMD sharding for the
parallelism stack.
"""
from . import common
from .common import dtype as _dtype_mod
from .common.dtype import (
    bfloat16, bool_, complex64, complex128, float16, float32, float64,
    int8, int16, int32, int64, uint8, finfo, iinfo,
)
from .common.flags import get_flags, set_flags
from .runtime import device
from .runtime.device import get_device, set_device, is_compiled_with_tpu
from .tensor import Parameter, Tensor, to_tensor
from .autograd.tape import enable_grad, grad, is_grad_enabled, no_grad, set_grad_enabled
from . import ops
from .ops import *  # noqa: F401,F403  — the paddle.* op surface
from .ops.random import seed, get_rng_state, set_rng_state
from . import autograd
from . import nn
from . import optimizer
from .nn.initializer import ParamAttr
from .nn.clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue
from . import amp
from . import io
from . import jit
from . import models
from . import incubate
from .framework import io as _framework_io
from .framework.io import load, save
from . import metric
from . import observability
from . import profiler
from . import visualdl
from . import hapi
from .hapi import Model
from .hapi import callbacks
from . import inference
from . import serving
from . import vision
from . import sparse
from . import audio
from . import fft
from . import distribution
from . import geometric
from . import quantization
from . import hub
from . import linalg
from . import regularizer
from . import signal
from . import utils
from . import version
__version__ = version.full_version

# Subsystem imports land as modules are built (amp, distributed, hapi,
# profiler are appended below once present).

from . import static
from .static import disable_static, enable_static

# paddle API aliases
bool = bool_  # noqa: A001

CPUPlace = lambda: device.Place("cpu", 0)
TPUPlace = lambda idx=0: device.Place("tpu", idx)
CUDAPlace = TPUPlace  # accel alias

