"""Automatic mixed precision.

Reference parity: python/paddle/amp/auto_cast.py — ``auto_cast`` context
(O1: per-op white/black lists; O2: model-wide low precision via
``decorate``), bf16/fp16 support.

TPU-native design: bf16 is the native MXU dtype, so O2-style "params and
compute in bf16, norms/softmax/losses in f32" is the performant scheme —
our norm/softmax/loss raw ops already compute statistics in f32
internally (ops/_nn.py), which supersedes the reference's black-list
mechanics under XLA.  O1 is still honored eagerly: inside ``auto_cast``
the white-listed ops (matmul/conv family) cast their float inputs to the
amp dtype at dispatch (hooked in tensor.apply_op).
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional, Set

from ..common.dtype import convert_dtype

__all__ = ["auto_cast", "amp_guard", "decorate", "white_list", "black_list",
           "amp_state"]

# ops whose inputs are cast down in O1 (matmul/conv compute on MXU)
WHITE_LIST: Set[str] = {
    "matmul", "mm", "bmm", "linear", "conv1d", "conv2d", "conv3d",
    "conv2d_transpose", "einsum", "scaled_dot_product_attention",
    "flash_attention_raw",
}
# ops forced to run in f32 (numerically sensitive)
BLACK_LIST: Set[str] = {
    "log", "log2", "log10", "log1p", "exp", "expm1", "pow", "square",
    "cross_entropy", "nll_loss", "binary_cross_entropy", "softmax_",
    "logsumexp", "norm", "mean_", "cumsum",
}


def white_list():
    return set(WHITE_LIST)


def black_list():
    return set(BLACK_LIST)


_state = threading.local()


def amp_state():
    return getattr(_state, "ctx", None)


@contextlib.contextmanager
def auto_cast(enable: bool = True, custom_white_list=None,
              custom_black_list=None, level: str = "O1",
              dtype: str = "bfloat16", use_promote: bool = True):
    """``paddle.amp.auto_cast`` context manager."""
    if not enable:
        yield
        return
    ctx = {
        "level": level,
        "dtype": convert_dtype(dtype),
        "white": WHITE_LIST | set(custom_white_list or ()),
        "black": BLACK_LIST | set(custom_black_list or ()),
    }
    prev = getattr(_state, "ctx", None)
    _state.ctx = ctx
    try:
        yield
    finally:
        _state.ctx = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level: str = "O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2: cast model params to the amp dtype (norm params stay f32 via
    their layers' internal f32 statistics).  Optimizer slots are f32 by
    construction (master weights — optimizer.py keeps moments in f32 and
    the reference's multi_precision flag is always-on behavior here)."""
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=dtype)
    if optimizers is None:
        return models if single_model else model_list
    return (models if single_model else model_list), optimizers
