"""Loss scaling for fp16 training.

Reference parity: python/paddle/amp/grad_scaler.py — GradScaler with
dynamic loss scaling (scale on overflow-free streaks, back off on
inf/nan).  On TPU bf16 shares f32's exponent range so scaling is usually
unnecessary — matching the reference's behavior where bf16 disables
scaling — but the fp16 path is fully functional for parity.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor

__all__ = ["GradScaler"]


class GradScaler:
    def __init__(self, enable: bool = True, init_loss_scaling: float = 2.0 ** 15,
                 incr_ratio: float = 2.0, decr_ratio: float = 0.5,
                 incr_every_n_steps: int = 1000,
                 decr_every_n_nan_or_inf: int = 2,
                 use_dynamic_loss_scaling: bool = True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def is_enable(self) -> bool:
        return self._enable

    def is_use_dynamic_loss_scaling(self) -> bool:
        return self._dynamic

    def get_loss_scaling(self) -> float:
        return self._scale

    def scale(self, loss: Tensor) -> Tensor:
        if not self._enable:
            return loss
        return loss * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        params = optimizer._parameter_list or []
        inv = 1.0 / self._scale
        found = False
        for p in params:
            if p._grad is None:
                continue
            g = p._grad * inv
            if bool(jnp.any(~jnp.isfinite(g))):
                found = True
            p._grad = g
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)

    def update(self):
        if not self._dynamic:
            self._found_inf = False
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "good_steps": self._good_steps, "bad_steps": self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state["scale"]
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)
