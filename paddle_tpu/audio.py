"""paddle.audio — spectral feature functions (python/paddle/audio
parity, SURVEY.md §2.2 row).

TPU-native: STFT/mel features are jnp FFT + matmul (XLA lowers FFT to
the TPU FFT unit; the mel filterbank matmul rides the MXU).  The
``features`` layers mirror paddle.audio.features.{Spectrogram,
MelSpectrogram, LogMelSpectrogram, MFCC}.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np

from .nn.layer import Layer
from .tensor import Tensor, apply_op

__all__ = ["functional", "features"]


class functional:
    """paddle.audio.functional namespace."""

    @staticmethod
    def hz_to_mel(f, htk: bool = False):
        f = np.asarray(f, np.float64)
        if htk:
            return 2595.0 * np.log10(1.0 + f / 700.0)
        f_min, f_sp = 0.0, 200.0 / 3
        mels = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        return np.where(f >= min_log_hz,
                        min_log_mel + np.log(
                            np.maximum(f, 1e-10) / min_log_hz) / logstep,
                        mels)

    @staticmethod
    def mel_to_hz(m, htk: bool = False):
        m = np.asarray(m, np.float64)
        if htk:
            return 700.0 * (10.0 ** (m / 2595.0) - 1.0)
        f_min, f_sp = 0.0, 200.0 / 3
        freqs = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        return np.where(m >= min_log_mel,
                        min_log_hz * np.exp(logstep * (m - min_log_mel)),
                        freqs)

    @staticmethod
    def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                             f_min: float = 0.0,
                             f_max: Optional[float] = None,
                             htk: bool = False, norm: str = "slaney"):
        """[n_mels, n_fft//2+1] mel filterbank (librosa/paddle slaney)."""
        f_max = f_max or sr / 2.0
        fft_freqs = np.linspace(0, sr / 2, n_fft // 2 + 1)
        mel_pts = np.linspace(functional.hz_to_mel(f_min, htk),
                              functional.hz_to_mel(f_max, htk), n_mels + 2)
        hz_pts = functional.mel_to_hz(mel_pts, htk)
        fb = np.zeros((n_mels, len(fft_freqs)))
        for i in range(n_mels):
            lo, ctr, hi = hz_pts[i], hz_pts[i + 1], hz_pts[i + 2]
            up = (fft_freqs - lo) / max(ctr - lo, 1e-10)
            down = (hi - fft_freqs) / max(hi - ctr, 1e-10)
            fb[i] = np.maximum(0.0, np.minimum(up, down))
        if norm == "slaney":
            enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])
            fb *= enorm[:, None]
        return fb.astype(np.float32)

    @staticmethod
    def get_window(window: str, win_length: int, fftbins: bool = True):
        n = win_length
        if window == "hann":
            w = np.hanning(n + 1)[:-1] if fftbins else np.hanning(n)
        elif window == "hamming":
            w = np.hamming(n + 1)[:-1] if fftbins else np.hamming(n)
        elif window == "blackman":
            w = np.blackman(n + 1)[:-1] if fftbins else np.blackman(n)
        else:
            raise ValueError(f"unsupported window {window!r}")
        return w.astype(np.float32)

    @staticmethod
    def power_to_db(s, ref_value: float = 1.0, amin: float = 1e-10,
                    top_db: Optional[float] = 80.0):
        import jax.numpy as jnp

        def raw(x):
            log_spec = 10.0 * jnp.log10(jnp.maximum(amin, x))
            log_spec = log_spec - 10.0 * math.log10(
                max(amin, ref_value))
            if top_db is not None:
                log_spec = jnp.maximum(log_spec,
                                       jnp.max(log_spec) - top_db)
            return log_spec
        return apply_op(raw, s) if isinstance(s, Tensor) else raw(s)


def _stft_power(x, n_fft, hop, win, power):
    """x: [..., T] -> [..., n_fft//2+1, frames] power spectrogram."""
    import jax.numpy as jnp
    pad = n_fft // 2
    x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(pad, pad)], mode="reflect")
    t = x.shape[-1]
    n_frames = 1 + (t - n_fft) // hop
    starts = jnp.arange(n_frames) * hop
    idx = starts[:, None] + jnp.arange(n_fft)[None, :]
    frames = x[..., idx] * win                       # [..., frames, n_fft]
    spec = jnp.fft.rfft(frames, axis=-1)
    mag = jnp.abs(spec) ** power
    return jnp.swapaxes(mag, -1, -2)                 # [..., bins, frames]


class features:
    """paddle.audio.features namespace (Layer-based extractors)."""

    class Spectrogram(Layer):
        def __init__(self, n_fft: int = 512,
                     hop_length: Optional[int] = None,
                     win_length: Optional[int] = None,
                     window: str = "hann", power: float = 2.0,
                     center: bool = True, pad_mode: str = "reflect",
                     dtype: str = "float32"):
            super().__init__()
            self.n_fft = n_fft
            self.hop = hop_length or n_fft // 4
            self.power = power
            wl = win_length or n_fft
            w = functional.get_window(window, wl)
            if wl < n_fft:                       # center-pad the window
                lp = (n_fft - wl) // 2
                w = np.pad(w, (lp, n_fft - wl - lp))
            self._win = w

        def forward(self, x):
            win = self._win
            return apply_op(
                lambda a: _stft_power(a, self.n_fft, self.hop, win,
                                      self.power), x)

    class MelSpectrogram(Layer):
        def __init__(self, sr: int = 22050, n_fft: int = 512,
                     hop_length: Optional[int] = None,
                     win_length: Optional[int] = None,
                     window: str = "hann", power: float = 2.0,
                     n_mels: int = 64, f_min: float = 50.0,
                     f_max: Optional[float] = None, htk: bool = False,
                     norm: str = "slaney", dtype: str = "float32"):
            super().__init__()
            self.spectrogram = features.Spectrogram(
                n_fft, hop_length, win_length, window, power)
            self._fbank = functional.compute_fbank_matrix(
                sr, n_fft, n_mels, f_min, f_max, htk, norm)

        def forward(self, x):
            spec = self.spectrogram(x)           # [..., bins, frames]
            fb = self._fbank
            return apply_op(
                lambda s: __import__("jax.numpy", fromlist=["x"]).einsum(
                    "mf,...ft->...mt", fb, s), spec)

    class LogMelSpectrogram(Layer):
        def __init__(self, sr: int = 22050, n_fft: int = 512,
                     hop_length: Optional[int] = None, n_mels: int = 64,
                     ref_value: float = 1.0, amin: float = 1e-10,
                     top_db: Optional[float] = None, **kwargs):
            super().__init__()
            self.mel = features.MelSpectrogram(
                sr=sr, n_fft=n_fft, hop_length=hop_length,
                n_mels=n_mels, **kwargs)
            self.ref_value, self.amin, self.top_db = ref_value, amin, \
                top_db

        def forward(self, x):
            return functional.power_to_db(self.mel(x), self.ref_value,
                                          self.amin, self.top_db)

    class MFCC(Layer):
        def __init__(self, sr: int = 22050, n_mfcc: int = 40,
                     n_fft: int = 512, n_mels: int = 64, **kwargs):
            super().__init__()
            self.logmel = features.LogMelSpectrogram(
                sr=sr, n_fft=n_fft, n_mels=n_mels, **kwargs)
            # DCT-II basis [n_mfcc, n_mels], orthonormal
            n = np.arange(n_mels)
            k = np.arange(n_mfcc)[:, None]
            basis = np.cos(np.pi / n_mels * (n + 0.5) * k)
            basis[0] *= 1.0 / math.sqrt(2)
            basis *= math.sqrt(2.0 / n_mels)
            self._dct = basis.astype(np.float32)

        def forward(self, x):
            lm = self.logmel(x)                  # [..., mels, frames]
            dct = self._dct
            return apply_op(
                lambda s: __import__("jax.numpy", fromlist=["x"]).einsum(
                    "cm,...mt->...ct", dct, s), lm)
