from .tape import backward, enable_grad, grad, is_grad_enabled, no_grad, set_grad_enabled
