from .tape import backward, enable_grad, grad, is_grad_enabled, no_grad, set_grad_enabled
from .py_layer import PyLayer, PyLayerContext, once_differentiable
from .functional import hessian, jacobian, jvp, vjp
