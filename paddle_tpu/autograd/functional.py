"""paddle.autograd functional transforms: jacobian / hessian / jvp /
vjp.

Reference parity: python/paddle/autograd (paddle 3.x public jacobian/
hessian; incubate.autograd jvp/vjp).  TPU-native: these ARE jax's
transforms — the wrappers only translate Tensor <-> jax array pytrees,
so every result is exact reverse/forward-mode AD, not finite
differences, and composes with jit."""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["jacobian", "hessian", "jvp", "vjp"]


def _unwrap(x):
    from ..tensor import Tensor
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(v) for v in x)
    return x.value if isinstance(x, Tensor) else jnp.asarray(x)


def _wrap(x):
    from ..tensor import Tensor
    if isinstance(x, (list, tuple)):
        return type(x)(_wrap(v) for v in x)
    return Tensor(x)


def _as_jax_fn(func):
    """Lift a Tensor->Tensor python function to arrays->arrays (the
    tape ops run fine on Tensors built from traced arrays)."""

    def fn(*arrays):
        out = func(*[_wrap(a) for a in arrays])
        return _unwrap(out)
    return fn


def _no_create_graph(create_graph):
    from ..common.errors import enforce
    enforce(not create_graph,
            "create_graph=True is not supported on the eager tape: the "
            "result would be detached. Differentiate through jacobians "
            "inside a compiled step (jax transforms compose under jit) "
            "instead")


def jacobian(func, xs, create_graph=False, allow_unused=False):
    """d func / d xs.  Single input -> Jacobian tensor [*out, *in];
    tuple input -> tuple of Jacobians (paddle's contract)."""
    _no_create_graph(create_graph)
    single = not isinstance(xs, (list, tuple))
    args = (xs,) if single else tuple(xs)
    arrays = tuple(_unwrap(a) for a in args)
    jac = jax.jacrev(_as_jax_fn(func), argnums=tuple(range(len(arrays))))(
        *arrays)
    jac = _wrap(jac)
    return jac[0] if single else jac


def hessian(func, xs, create_graph=False, allow_unused=False):
    """d^2 func / d xs^2 for a SCALAR-output func (paddle contract)."""
    _no_create_graph(create_graph)
    single = not isinstance(xs, (list, tuple))
    args = (xs,) if single else tuple(xs)
    arrays = tuple(_unwrap(a) for a in args)

    fn = _as_jax_fn(func)

    def scalar_fn(*a):
        out = fn(*a)
        return jnp.reshape(out, ())
    hes = jax.hessian(scalar_fn, argnums=tuple(range(len(arrays))))(
        *arrays)
    hes = _wrap(hes)
    return hes[0][0] if single else hes


def vjp(func, xs, v=None):
    """Returns (func(xs), vjp_result): reverse-mode products (paddle
    incubate.autograd.vjp contract; v defaults to ones)."""
    single = not isinstance(xs, (list, tuple))
    args = (xs,) if single else tuple(xs)
    arrays = tuple(_unwrap(a) for a in args)
    out, pullback = jax.vjp(_as_jax_fn(func), *arrays)
    if v is None:
        cot = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        cot = _unwrap(v)
    grads = pullback(cot)
    return _wrap(out), (_wrap(grads[0]) if single else _wrap(grads))


def jvp(func, xs, v=None):
    """Returns (func(xs), jvp_result): forward-mode products."""
    single = not isinstance(xs, (list, tuple))
    args = (xs,) if single else tuple(xs)
    arrays = tuple(_unwrap(a) for a in args)
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrays)
    else:
        tv = _unwrap(v)
        tangents = (tv,) if single else tuple(tv)
    out, tan = jax.jvp(_as_jax_fn(func), arrays, tangents)
    return _wrap(out), _wrap(tan)
