"""paddle.autograd.PyLayer — user-defined forward/backward.

Reference parity: python/paddle/autograd/py_layer.py (``PyLayer`` with
static ``forward(ctx, *args)`` / ``backward(ctx, *grads)`` and
``ctx.save_for_backward``) over the eager PyLayer grad node.

TPU-native design: ``apply`` wraps the user functions in a
``jax.custom_vjp`` and dispatches through :func:`apply_op`, so the
custom backward is honored BOTH by the eager tape (loss.backward) and
by jax autodiff inside compiled training steps (jax.grad sees the
custom_vjp) — one definition, both engines.
"""
from __future__ import annotations

from typing import Any, List

import jax

from ..common.errors import enforce

__all__ = ["PyLayer", "PyLayerContext"]


class PyLayerContext:
    def __init__(self):
        self._saved: List[Any] = []

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    def saved_tensor(self):
        return list(self._saved)

    # paddle also allows arbitrary attributes on ctx — plain object attrs
    # work here (the ctx object itself is threaded through the closure)


class PyLayer:
    """Subclass with @staticmethod forward(ctx, *args, **kwargs) and
    @staticmethod backward(ctx, *grad_outputs); call via .apply."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..autograd import tape
        from ..tensor import Tensor, apply_op
        import jax.numpy as jnp

        ctx = PyLayerContext()

        # float tensors are the differentiable primals of the custom op;
        # everything else (ints, python values) is closed over in place
        is_diff = [isinstance(a, Tensor)
                   and jnp.issubdtype(jnp.asarray(a.value).dtype,
                                      jnp.floating)
                   for a in args]
        diff_pos = [i for i, d in enumerate(is_diff) if d]

        def run_forward(diff_arrays):
            full = list(args)
            for j, i in enumerate(diff_pos):
                full[i] = Tensor(diff_arrays[j], stop_gradient=True)
            with tape.no_grad():
                outs = cls.forward(ctx, *full, **kwargs)
            single = not isinstance(outs, (list, tuple))
            outs_t = [outs] if single else list(outs)
            arrs = tuple(o.value if isinstance(o, Tensor) else jnp.asarray(o)
                         for o in outs_t)
            return arrs, single

        @jax.custom_vjp
        def op(*diff_arrays):
            arrs, _ = run_forward(diff_arrays)
            return arrs

        def op_fwd(*diff_arrays):
            arrs, _ = run_forward(diff_arrays)
            saved = tuple(t.value if isinstance(t, Tensor) else t
                          for t in ctx._saved)
            return arrs, saved

        def op_bwd(saved, cts):
            ctx._saved = [Tensor(s, stop_gradient=True) for s in saved]
            with tape.no_grad():
                grads = cls.backward(
                    ctx, *[Tensor(c, stop_gradient=True) for c in cts])
            grads = [grads] if not isinstance(grads, (list, tuple)) \
                else list(grads)
            enforce(len(grads) == len(diff_pos),
                    f"{cls.__name__}.backward returned {len(grads)} grads "
                    f"for {len(diff_pos)} differentiable inputs")
            out = []
            for g, i in zip(grads, diff_pos):
                ref = args[i]
                if g is None:
                    out.append(jnp.zeros_like(ref.value))
                else:
                    out.append((g.value if isinstance(g, Tensor)
                                else jnp.asarray(g)).astype(ref.dtype))
            return tuple(out)

        op.defvjp(op_fwd, op_bwd)
        op.__name__ = f"pylayer_{cls.__name__}"

        result = apply_op(op, *[args[i] for i in diff_pos])
        # op always returns a tuple; unwrap the singleton like paddle does
        # when forward returned a bare Tensor
        outs = result if isinstance(result, (list, tuple)) else [result]
        return outs[0] if len(outs) == 1 else tuple(outs)


def once_differentiable(fn):  # paddle API-parity decorator (no-op here)
    return fn
