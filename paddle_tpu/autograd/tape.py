"""Define-by-run autograd engine over jax.vjp.

Reference parity: the eager autograd engine (paddle/fluid/eager/ —
``GradNodeBase``, ``AutogradMeta``, ``egr::Backward`` with its ready-queue
over the grad-node graph, grad-accumulation nodes, hooks).  TPU-native
design: each eager op is executed through ``jax.vjp`` so the backward pass
is XLA-differentiated per-op; the tape only stores the vjp closures and the
producer graph.  Under ``jax.jit`` tracing the same machinery traces cleanly
(jax.vjp is traceable), so compiled mode reuses this engine; the fast path
for training compiles a pure function with ``jax.grad`` and bypasses the
tape entirely (see jit/to_static and hapi trainer).
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "GradNode",
    "no_grad",
    "enable_grad",
    "is_grad_enabled",
    "set_grad_enabled",
    "backward",
    "grad",
]

_grad_state = threading.local()


def is_grad_enabled() -> bool:
    return getattr(_grad_state, "enabled", True)


def set_grad_enabled(mode: bool) -> None:
    _grad_state.enabled = bool(mode)


class _GradModeCtx(contextlib.ContextDecorator):
    def __init__(self, mode: bool):
        self._mode = mode

    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(self._mode)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False


def no_grad():
    """``paddle.no_grad()`` — usable as context manager or decorator."""
    return _GradModeCtx(False)


def enable_grad():
    return _GradModeCtx(True)


class GradNode:
    """One executed op on the tape.

    ``vjp_fn`` maps output cotangents (matching the op's primal output
    structure) to input cotangents, one per differentiable input.  Each
    input edge is either another node's output (``('n', node, out_idx)``)
    or a leaf tensor (``('l', tensor)``) whose ``.grad`` accumulates.

    ``out_hooks`` (out_idx -> [hook]) are ``Tensor.register_hook`` user
    hooks on this node's outputs — fired on the tensor's final
    accumulated cotangent before it enters ``vjp_fn``.  ``saved`` keeps
    what :func:`grad`'s ``create_graph`` mode needs to re-express the
    VJP as an explicit function of the primals (see _grad_create_graph).
    """

    __slots__ = ("name", "vjp_fn", "in_edges", "n_outputs", "out_tree",
                 "hooks", "out_hooks", "saved")

    def __init__(self, name: str, vjp_fn: Callable, in_edges: List[Tuple],
                 n_outputs: int, out_tree, saved=None):
        self.name = name
        self.vjp_fn = vjp_fn
        self.in_edges = in_edges
        self.n_outputs = n_outputs
        self.out_tree = out_tree
        self.hooks: List[Callable] = []
        self.out_hooks = {}
        self.saved = saved

    def __repr__(self):
        return f"GradNode({self.name}, n_out={self.n_outputs})"


def _topo_order(root: GradNode) -> List[GradNode]:
    """Iterative post-order DFS → topological order (producers first)."""
    order: List[GradNode] = []
    seen = set()
    stack: List[Tuple[GradNode, bool]] = [(root, False)]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for edge in node.in_edges:
            if edge[0] == "n" and id(edge[1]) not in seen:
                stack.append((edge[1], False))
    return order


def backward(tensor, grad_tensor=None, retain_graph: bool = False,
             watch: Optional[dict] = None,
             leaf_filter: Optional[set] = None) -> None:
    """Run the tape backward from ``tensor``, accumulating into leaf
    ``.grad`` slots (paddle ``Tensor.backward()`` semantics).

    ``watch`` optionally maps ``(id(node), out_idx) -> Tensor`` so grads of
    *intermediate* (non-leaf) tensors can be captured (used by
    :func:`grad`)."""
    from ..tensor import Tensor  # local import to avoid a cycle

    watch = watch or {}
    root_node = tensor._node
    if root_node is None:
        if not tensor.stop_gradient:
            g = jnp.ones_like(tensor.value) if grad_tensor is None else (
                grad_tensor.value if isinstance(grad_tensor, Tensor) else grad_tensor)
            tensor._accumulate_grad(g)
        return
    if grad_tensor is None:
        g0 = jnp.ones_like(tensor.value)
    else:
        g0 = grad_tensor.value if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)

    # cotangent accumulators: id(node) -> [ct or None] * n_outputs
    cts = {id(root_node): [None] * root_node.n_outputs}
    cts[id(root_node)][tensor._out_idx] = g0
    w = watch.get((id(root_node), tensor._out_idx))
    if w is not None:
        w._accumulate_grad(g0)

    order = _topo_order(root_node)  # producers first
    # leaf cotangents are summed across ALL consumer edges first; leaf
    # hooks then fire ONCE on the final accumulated grad (paddle
    # register_hook semantics — firing per partial contribution gives
    # wrong results for any non-linear hook)
    leaf_cts: dict = {}  # id(leaf) -> [leaf, ct]
    for node in reversed(order):    # consumers first
        node_cts = cts.get(id(node))
        if node_cts is None:
            continue
        filled = [
            ct if ct is not None else jnp.zeros(shape, dtype)
            for ct, (shape, dtype) in zip(node_cts, node.out_tree["avals"])
        ]
        # Tensor.register_hook on this node's outputs: hook sees (and may
        # replace) the final accumulated grad of that tensor
        for idx, hooks in node.out_hooks.items():
            filled[idx] = _run_tensor_hooks(hooks, filled[idx], Tensor)
        out_struct = jax.tree_util.tree_unflatten(node.out_tree["treedef"], filled)
        in_cts = node.vjp_fn(out_struct)
        for hook in node.hooks:
            in_cts = hook(in_cts) or in_cts
        for edge, ct in zip(node.in_edges, in_cts):
            if ct is None:
                continue
            if edge[0] == "n":
                _, producer, out_idx = edge
                slot = cts.setdefault(id(producer), [None] * producer.n_outputs)
                slot[out_idx] = ct if slot[out_idx] is None else slot[out_idx] + ct
                w = watch.get((id(producer), out_idx))
                if w is not None:
                    w._accumulate_grad(ct)
            else:
                leaf = edge[1]
                if leaf_filter is None or id(leaf) in leaf_filter:
                    ent = leaf_cts.get(id(leaf))
                    if ent is None:
                        leaf_cts[id(leaf)] = [leaf, ct]
                    else:
                        ent[1] = ent[1] + ct
        if not retain_graph:
            node.vjp_fn = _freed_vjp
            # drop the saved primals too — keeping every op's inputs
            # alive after backward pins the whole forward's activations
            # for as long as the output tensor lives (create_graph reuse
            # of a freed graph raises anyway, matching vjp_fn above)
            node.saved = None
        del cts[id(node)]
    for leaf, ct in leaf_cts.values():
        hooks = getattr(leaf, "_hooks", None)
        if hooks:
            ct = _run_tensor_hooks(hooks, ct, Tensor)
        leaf._accumulate_grad(ct)


def _run_tensor_hooks(hooks, ct, Tensor):
    """Run user grad hooks: hook(Tensor) -> Tensor | None (keep)."""
    for hook in hooks:
        res = hook(Tensor(ct))
        if res is not None:
            ct = res.value if isinstance(res, Tensor) else jnp.asarray(res)
    return ct


def _freed_vjp(*_a, **_k):
    raise RuntimeError(
        "grad graph already freed; call backward(retain_graph=True) to reuse it"
    )


def grad(outputs, inputs, grad_outputs=None, retain_graph=False,
         create_graph=False, allow_unused=False):
    """``paddle.grad`` — functional grads w.r.t. explicit inputs.

    Implemented by running the tape backward into temporary accumulators
    instead of ``.grad`` slots.  ``create_graph`` is currently unsupported
    on the eager tape (use the jit/compiled path for higher-order grads).
    """
    from ..tensor import Tensor

    if create_graph:
        return _grad_create_graph(outputs, inputs, grad_outputs,
                                  allow_unused)
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)

    saved = [(t, t._grad) for t in inputs]
    for t in inputs:
        t._grad = None
    watch = {
        (id(t._node), t._out_idx): t for t in inputs if t._node is not None
    }
    leaf_filter = {id(t) for t in inputs}
    try:
        for out, g in zip(outputs, grad_outputs):
            backward(out, g, retain_graph=True, watch=watch,
                     leaf_filter=leaf_filter)
        results = []
        for t in inputs:
            if t._grad is None and not allow_unused:
                raise RuntimeError(
                    "an input was not used in the graph (pass allow_unused=True)")
            results.append(Tensor(t._grad) if t._grad is not None else None)
    finally:
        for t, old in saved:
            t._grad = old
    if not retain_graph:
        for out in outputs:
            if out._node is not None:
                for n in _topo_order(out._node):
                    n.vjp_fn = _freed_vjp
    return results


# ---------------------------------------------------------------------------
# create_graph (double-grad): tensor-mode tape walk
# ---------------------------------------------------------------------------

def _grad_create_graph(outputs, inputs, grad_outputs=None,
                       allow_unused=False):
    """``paddle.grad(create_graph=True)``: walk the tape with TENSOR
    cotangents, re-expressing each node's VJP as an apply_op over
    (cotangents, primals) — so the produced grads are themselves taped
    and differentiable (the reference's double-grad, fluid/eager
    higher-order path).

    Requires nodes recorded with ``saved`` info (all apply_op nodes);
    nodes built without it (custom engines) raise.
    """
    from ..tensor import Tensor, apply_op

    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)

    # tensor-cotangent accumulators
    cts = {}

    def add_ct(key, t):
        cur = cts.get(key)
        cts[key] = t if cur is None else cur + t

    roots = []
    for out, g in zip(outputs, grad_outputs):
        if out._node is None:
            continue
        g0 = Tensor(jnp.ones_like(out.value)) if g is None else (
            g if isinstance(g, Tensor) else Tensor(g))
        add_ct((id(out._node), out._out_idx), g0)
        roots.append(out._node)

    order: List[GradNode] = []
    seen = set()
    for r in roots:
        for n in _topo_order(r):
            if id(n) not in seen:
                seen.add(id(n))
                order.append(n)

    # leaf grads keyed by id(tensor)
    leaf_grads = {}
    input_ids = {id(t) for t in inputs}

    for node in reversed(order):
        node_ct_ts = [cts.get((id(node), i)) for i in range(node.n_outputs)]
        if all(t is None for t in node_ct_ts):
            continue
        if node.saved is None:
            raise RuntimeError(
                f"node {node.name} lacks saved primals; create_graph "
                "needs apply_op-recorded nodes")
        raw_fn, template, kwargs, leaves, diff_idx, arrays = node.saved
        filled = [t if t is not None else Tensor(jnp.zeros(s_, d_))
                  for t, (s_, d_) in zip(node_ct_ts,
                                         node.out_tree["avals"])]
        treedef = node.out_tree["treedef"]
        n_out = len(filled)
        n_diff = len(diff_idx)

        def vjp_of_op(*flat_args, _raw=raw_fn, _template=template,
                      _kwargs=kwargs, _diff=diff_idx, _arrays=arrays,
                      _treedef=treedef, _n_out=n_out):
            ct_flat = flat_args[:_n_out]
            primals = flat_args[_n_out:]

            def rebuild(arrs):
                # _template (default-arg bound), NOT the loop variable
                from ..tensor import rebuild_from_template
                return rebuild_from_template(_template, arrs)

            def f(*diff_arrays):
                full = list(_arrays)
                for j, i in enumerate(_diff):
                    full[i] = diff_arrays[j]
                return _raw(*rebuild(full), **_kwargs)

            _, vjp_fn = jax.vjp(f, *primals)
            return vjp_fn(jax.tree_util.tree_unflatten(_treedef,
                                                       list(ct_flat)))

        primal_tensors = [leaves[i] if isinstance(leaves[i], Tensor)
                          else Tensor(arrays[i]) for i in diff_idx]
        in_ct = apply_op(vjp_of_op, *filled, *primal_tensors)
        in_ct = in_ct if isinstance(in_ct, (list, tuple)) else [in_ct]
        for edge, ct_t in zip(node.in_edges, in_ct):
            if ct_t is None:
                continue
            if edge[0] == "n":
                add_ct((id(edge[1]), edge[2]), ct_t)
            else:
                leaf = edge[1]
                cur = leaf_grads.get(id(leaf))
                leaf_grads[id(leaf)] = ct_t if cur is None else cur + ct_t

    results = []
    for t in inputs:
        g = None
        if t._node is not None:
            g = cts.get((id(t._node), t._out_idx))
        if g is None:
            g = leaf_grads.get(id(t))
        if g is None and not allow_unused:
            raise RuntimeError(
                "an input was not used in the graph (pass "
                "allow_unused=True)")
        results.append(g)
    return results
