from . import dtype, errors, flags
from .dtype import convert_dtype
from .errors import enforce
from .flags import define_flag, get_flag, get_flags, set_flags
