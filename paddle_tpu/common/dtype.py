"""Dtype system: paddle-shaped dtype names over jax/numpy dtypes.

Reference parity: paddle's ``paddle.float32``-style dtype objects
(paddle/phi/common/data_type.h in the reference tree; python surface
``paddle.dtype``).  Here dtypes ARE numpy dtypes (what jax uses natively),
exposed under the paddle names, with a converter that accepts strings,
numpy dtypes, jax dtypes, and paddle-style ``paddle.float32`` objects.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes

__all__ = [
    "float16", "float32", "float64", "bfloat16",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64",
    "bool_", "complex64", "complex128",
    "float8_e4m3fn", "float8_e5m2",
    "convert_dtype", "is_floating_point", "is_integer", "is_complex",
    "finfo", "iinfo", "promote_types",
]

float16 = np.dtype("float16")
float32 = np.dtype("float32")
float64 = np.dtype("float64")
bfloat16 = np.dtype(ml_dtypes.bfloat16)
float8_e4m3fn = np.dtype(ml_dtypes.float8_e4m3fn)
float8_e5m2 = np.dtype(ml_dtypes.float8_e5m2)
int8 = np.dtype("int8")
int16 = np.dtype("int16")
int32 = np.dtype("int32")
int64 = np.dtype("int64")
uint8 = np.dtype("uint8")
uint16 = np.dtype("uint16")
uint32 = np.dtype("uint32")
uint64 = np.dtype("uint64")
bool_ = np.dtype("bool")
complex64 = np.dtype("complex64")
complex128 = np.dtype("complex128")

_ALIASES = {
    "bool": bool_,
    "float": float32,
    "double": float64,
    "half": float16,
    "bf16": bfloat16,
    "fp16": float16,
    "fp32": float32,
    "fp64": float64,
}

_FLOAT_DTYPES = {float16, float32, float64, bfloat16, float8_e4m3fn, float8_e5m2}
_INT_DTYPES = {int8, int16, int32, int64, uint8, uint16, uint32, uint64}
_COMPLEX_DTYPES = {complex64, complex128}


def convert_dtype(dtype) -> np.dtype:
    """Normalize any dtype-like (str | np.dtype | jnp dtype | python type)."""
    if dtype is None:
        raise ValueError("dtype must not be None")
    if isinstance(dtype, str):
        if dtype in _ALIASES:
            return _ALIASES[dtype]
        return np.dtype(dtype)
    if dtype is bool:
        return bool_
    if dtype is int:
        return int64
    if dtype is float:
        return float32
    return np.dtype(dtype)


def is_floating_point(dtype) -> bool:
    return convert_dtype(dtype) in _FLOAT_DTYPES


def is_integer(dtype) -> bool:
    return convert_dtype(dtype) in _INT_DTYPES


def is_complex(dtype) -> bool:
    return convert_dtype(dtype) in _COMPLEX_DTYPES


def finfo(dtype):
    return jnp.finfo(convert_dtype(dtype))


def iinfo(dtype):
    return jnp.iinfo(convert_dtype(dtype))


def promote_types(a, b) -> np.dtype:
    return np.dtype(jnp.promote_types(convert_dtype(a), convert_dtype(b)))
