"""Enforce-style error checking.

Reference parity: ``PADDLE_ENFORCE*`` macros (paddle/common/enforce.h) and
the typed error taxonomy (paddle/common/errors.h): InvalidArgument,
NotFound, OutOfRange, Unimplemented, PreconditionNotMet, etc.  The macros'
error-stack formatting collapses to plain Python exceptions with the same
category names so user-facing messages keep the reference's shape.
"""
from __future__ import annotations

__all__ = [
    "EnforceError",
    "InvalidArgumentError",
    "NotFoundError",
    "OutOfRangeError",
    "AlreadyExistsError",
    "PermissionDeniedError",
    "PreconditionNotMetError",
    "UnimplementedError",
    "UnavailableError",
    "ExecutionTimeoutError",
    "CorruptCheckpointError",
    "enforce",
    "enforce_eq",
    "enforce_gt",
    "enforce_not_none",
]


class EnforceError(RuntimeError):
    category = "Fatal"

    def __init__(self, msg: str):
        super().__init__(f"({self.category}) {msg}")


class InvalidArgumentError(EnforceError, ValueError):
    category = "InvalidArgument"


class NotFoundError(EnforceError, KeyError):
    category = "NotFound"


class OutOfRangeError(EnforceError, IndexError):
    category = "OutOfRange"


class AlreadyExistsError(EnforceError):
    category = "AlreadyExists"


class PermissionDeniedError(EnforceError):
    category = "PermissionDenied"


class PreconditionNotMetError(EnforceError):
    category = "PreconditionNotMet"


class UnimplementedError(EnforceError, NotImplementedError):
    category = "Unimplemented"


class UnavailableError(EnforceError):
    category = "Unavailable"


class ExecutionTimeoutError(EnforceError):
    category = "ExecutionTimeout"


class CorruptCheckpointError(EnforceError):
    """A checkpoint directory failed integrity checks: missing/torn
    manifest, uncommitted staging state, missing chunk files, or a
    per-chunk sha256 mismatch.  Callers (CheckpointManager.restore,
    auto_resume) catch this to fall back to the previous valid
    checkpoint."""
    category = "CorruptCheckpoint"


def enforce(cond, msg: str, error_cls=InvalidArgumentError):
    if not cond:
        raise error_cls(msg)


def enforce_eq(a, b, msg: str = "", error_cls=InvalidArgumentError):
    if a != b:
        raise error_cls(f"expected {a!r} == {b!r}. {msg}")


def enforce_gt(a, b, msg: str = "", error_cls=InvalidArgumentError):
    if not a > b:
        raise error_cls(f"expected {a!r} > {b!r}. {msg}")


def enforce_not_none(x, name: str = "value", error_cls=InvalidArgumentError):
    if x is None:
        raise error_cls(f"{name} must not be None")
    return x
