"""Typed global flag registry with environment-variable overlay.

Reference parity: the three-tier config system of SURVEY.md §5 — C++ global
flags (``PHI_DEFINE_EXPORTED_*`` in paddle/phi/core/flags.cc and
paddle/common/flags.cc, settable via ``FLAGS_x`` env vars or
``paddle.set_flags``).  Here it is one typed Python registry: flags are
declared with :func:`define_flag`, overridden by ``FLAGS_<name>`` in the
environment at definition time, and mutated at runtime via
:func:`set_flags` / read via :func:`get_flags` (the paddle-shaped API).
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Union

__all__ = [
    "define_flag",
    "get_flag",
    "set_flags",
    "get_flags",
]


def _parse_bool(s: str) -> bool:
    s = s.strip().lower()
    if s in ("1", "true", "yes", "on"):
        return True
    if s in ("0", "false", "no", "off", ""):
        return False
    raise ValueError(f"cannot parse {s!r} as bool")


_PARSERS: Dict[type, Callable[[str], Any]] = {
    bool: _parse_bool,
    int: lambda s: int(s, 0),
    float: float,
    str: lambda s: s,
}


@dataclass
class _Flag:
    name: str
    default: Any
    type: type
    help: str
    value: Any = None


_REGISTRY: Dict[str, _Flag] = {}


def define_flag(name: str, default: Any, help: str = "", type: Optional[type] = None):
    """Declare a global flag. ``FLAGS_<name>`` in the environment overrides
    ``default`` at declaration time."""
    if name.startswith("FLAGS_"):
        name = name[len("FLAGS_"):]
    ftype = type if type is not None else default.__class__
    if ftype not in _PARSERS:
        raise TypeError(f"flag type must be one of {list(_PARSERS)}, got {ftype}")
    value = default
    env = os.environ.get("FLAGS_" + name)
    if env is not None:
        value = _PARSERS[ftype](env)
    flag = _Flag(name=name, default=default, type=ftype, help=help, value=value)
    _REGISTRY[name] = flag
    return flag


def _canon(name: str) -> str:
    return name[len("FLAGS_"):] if name.startswith("FLAGS_") else name


def get_flag(name: str) -> Any:
    return _REGISTRY[_canon(name)].value


def set_flags(flags: Dict[str, Any]) -> None:
    """paddle.set_flags-shaped: ``set_flags({'FLAGS_check_nan_inf': 1})``."""
    for name, value in flags.items():
        key = _canon(name)
        if key not in _REGISTRY:
            raise KeyError(f"unknown flag {name!r}")
        flag = _REGISTRY[key]
        if not isinstance(value, flag.type):
            value = _PARSERS[flag.type](str(value))
        flag.value = value


def get_flags(names: Union[str, List[str]]) -> Dict[str, Any]:
    if isinstance(names, str):
        names = [names]
    return {n: _REGISTRY[_canon(n)].value for n in names}


# ---------------------------------------------------------------------------
# Core flags (analogs of the reference's most-used FLAGS_*)
# ---------------------------------------------------------------------------
define_flag("check_nan_inf", False, "scan op outputs for NaN/Inf (debug)")
define_flag("use_pallas", True, "use Pallas kernels where available (TPU)")
define_flag("eager_jit_ops", False, "jit each eager op call (per-op cache)")
define_flag("log_level", 0, "VLOG-style verbosity; higher = chattier")
define_flag("allocator_strategy", "auto_growth", "kept for API parity; XLA owns memory")
define_flag("moe_log_drops", False,
            "print exact dropped-row counts from the capacity-bounded "
            "expert-parallel MoE exchange (jax.debug.print, works "
            "under jit)")
