"""Version-tolerance shims for the jax API surface.

The distributed layers (pipeline 1F1B, expert/context parallel, the
pallas spmd wrappers) target the promoted ``jax.shard_map`` API —
``axis_names`` selects the manual mesh axes and ``check_vma`` toggles
the varying-mesh-axes checker.  Older jax releases only ship
``jax.experimental.shard_map.shard_map`` with the ancestral spelling:
``auto`` names the NON-manual axes and the checker is ``check_rep``.
``shard_map`` here dispatches to whichever the interpreter provides so
one call site works on both; everything in-repo goes through it.
"""
from __future__ import annotations

import jax

__all__ = ["axis_size", "shard_map", "named_sharding",
           "with_sharding_constraint"]


def axis_size(name):
    """``jax.lax.axis_size`` when available, else the ``psum(1, name)``
    spelling older jax understands (same compile-time constant)."""
    from jax import lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(name)
    return lax.psum(1, name)


def named_sharding(mesh, *spec):
    """``NamedSharding(mesh, PartitionSpec(*spec))`` — one import site
    for the ``jax.sharding`` spelling (0.4.x) with the ancestral
    ``jax.experimental`` fallback kept for very old interpreters."""
    try:
        from jax.sharding import NamedSharding, PartitionSpec
    except ImportError:                                  # pragma: no cover
        from jax.experimental.sharding import NamedSharding
        from jax.experimental import PartitionSpec
    return NamedSharding(mesh, PartitionSpec(*spec))


def with_sharding_constraint(x, sharding):
    """``jax.lax.with_sharding_constraint`` when available (0.4.x+),
    else the ``jax.experimental.pjit`` spelling.  Accepts any Sharding
    (build one with ``named_sharding``)."""
    from jax import lax
    if hasattr(lax, "with_sharding_constraint"):
        return lax.with_sharding_constraint(x, sharding)
    from jax.experimental.pjit import (                  # pragma: no cover
        with_sharding_constraint as _wsc)
    return _wsc(x, sharding)


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma=None):
    """``jax.shard_map`` when available, else the experimental API.

    ``axis_names`` is the promoted-API meaning: the set of mesh axes the
    body is manual over (None = all of them).  On the experimental
    fallback it is translated to ``auto`` (its complement w.r.t. the
    mesh) and ``check_vma`` to ``check_rep``; ``check_rep`` defaults OFF
    there because partial-auto meshes predate reliable replication
    checking in that API.
    """
    if hasattr(jax, "shard_map"):
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        if check_vma is not None:
            kw["check_vma"] = check_vma
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map
    auto = frozenset()
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, auto=auto,
                      check_rep=bool(check_vma) if check_vma is not None
                      else False)
