"""Native (C++) runtime components.

Reference parity: the reference's native core is C++ behind pybind11
(SURVEY.md §1); the TPU build keeps XLA as the compute engine and
implements the RUNTIME pieces natively where the reference's are —
rendezvous store (tcp_store.cpp), data-reader core (dataio.cpp) —
compiled on first use with the system toolchain and loaded via ctypes
(pybind11 is not in this image).  Every consumer has a pure-python
fallback so the package still works without a compiler.
"""
from .build import load_native

__all__ = ["load_native"]
