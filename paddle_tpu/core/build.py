"""First-use native build: csrc/*.cpp -> _lib/libpaddle_tpu_native.so.

Cached by source content hash; rebuilds only when sources change.
Returns None (callers fall back to python) when no toolchain exists.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess
import threading
from typing import Optional

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

_SRC_DIR = os.path.join(os.path.dirname(__file__), "csrc")
_LIB_DIR = os.path.join(os.path.dirname(__file__), "_lib")


def _sources():
    return sorted(
        os.path.join(_SRC_DIR, f) for f in os.listdir(_SRC_DIR)
        if f.endswith(".cpp"))


def _digest(files) -> str:
    h = hashlib.sha256()
    for f in files:
        with open(f, "rb") as fp:
            h.update(fp.read())
    return h.hexdigest()[:16]


def load_native() -> Optional[ctypes.CDLL]:
    """Compile-once loader for the native runtime library."""
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        srcs = _sources()
        if not srcs:
            return None
        tag = _digest(srcs)
        so = os.path.join(_LIB_DIR, f"libpaddle_tpu_native-{tag}.so")
        if not os.path.exists(so):
            gxx = shutil.which("g++") or shutil.which("c++")
            if gxx is None:
                return None
            os.makedirs(_LIB_DIR, exist_ok=True)
            tmp = so + f".tmp{os.getpid()}"
            cmd = [gxx, "-O2", "-fPIC", "-shared", "-pthread",
                   "-std=c++17", "-o", tmp] + srcs
            try:
                subprocess.run(cmd, check=True, capture_output=True,
                               timeout=240)
                os.replace(tmp, so)   # atomic: concurrent builds race safely
            except (subprocess.CalledProcessError,
                    subprocess.TimeoutExpired) as e:
                err = getattr(e, "stderr", b"") or b""
                import warnings
                warnings.warn(
                    f"native build failed, using python fallbacks: "
                    f"{err.decode(errors='replace')[-500:]}")
                return None
        try:
            _LIB = ctypes.CDLL(so)
        except OSError:
            return None
        _configure(_LIB)
        return _LIB


def _configure(lib: ctypes.CDLL):
    c = ctypes
    lib.tcp_store_server_start.restype = c.c_void_p
    lib.tcp_store_server_start.argtypes = [c.c_char_p, c.c_int,
                                           c.POINTER(c.c_int)]
    lib.tcp_store_server_stop.argtypes = [c.c_void_p]
    lib.tcp_store_connect.restype = c.c_int
    lib.tcp_store_connect.argtypes = [c.c_char_p, c.c_int, c.c_int]
    lib.tcp_store_close.argtypes = [c.c_int]
    lib.tcp_store_set.restype = c.c_int
    lib.tcp_store_set.argtypes = [c.c_int, c.c_char_p, c.c_int,
                                  c.c_char_p, c.c_uint64]
    lib.tcp_store_get.restype = c.c_int
    lib.tcp_store_get.argtypes = [c.c_int, c.c_char_p, c.c_int,
                                  c.c_uint64,
                                  c.POINTER(c.POINTER(c.c_char)),
                                  c.POINTER(c.c_uint64)]
    lib.tcp_store_add.restype = c.c_int
    lib.tcp_store_add.argtypes = [c.c_int, c.c_char_p, c.c_int,
                                  c.c_int64, c.POINTER(c.c_int64)]
    lib.tcp_store_wait.restype = c.c_int
    lib.tcp_store_wait.argtypes = [c.c_int, c.c_char_p, c.c_int,
                                   c.c_uint64]
    lib.tcp_store_delete.restype = c.c_int
    lib.tcp_store_delete.argtypes = [c.c_int, c.c_char_p, c.c_int]
    lib.tcp_store_check.restype = c.c_int
    lib.tcp_store_check.argtypes = [c.c_int, c.c_char_p, c.c_int,
                                    c.POINTER(c.c_int)]
    lib.tcp_store_free.argtypes = [c.POINTER(c.c_char)]

    lib.dataio_open.restype = c.c_void_p
    lib.dataio_open.argtypes = [c.c_char_p, c.c_int, c.c_int64, c.c_int64,
                                c.c_int, c.c_int64]
    lib.dataio_num_batches.restype = c.c_int64
    lib.dataio_num_batches.argtypes = [c.c_void_p]
    lib.dataio_num_seqs.restype = c.c_int64
    lib.dataio_num_seqs.argtypes = [c.c_void_p]
    lib.dataio_next.restype = c.c_int64
    lib.dataio_next.argtypes = [c.c_void_p, c.c_void_p]
    lib.dataio_close.argtypes = [c.c_void_p]
