// dataio — native pretraining data reader.
//
// Reference parity: the reference's C++ DataLoader core (multiprocess
// workers + shared-memory queues feeding the trainer, SURVEY.md §2.2
// io row).  TPU-native design: pretraining data is a flat binary token
// file (np.memmap layout); this reader mmaps it, slices fixed
// [batch, seq_len] blocks, and assembles them into a ring of
// ready-to-ship host buffers on BACKGROUND THREADS so the accelerator
// step never waits on input assembly (the host→HBM transfer overlaps
// compute via jax dispatch).  Optional epoch shuffling permutes
// sequence windows with a seeded Fisher-Yates on the index table.
//
// C ABI via ctypes (no pybind11 in this image).
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <random>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Reader {
  int fd = -1;
  const uint8_t *map = nullptr;
  size_t file_bytes = 0;
  int dtype_size = 0;
  int64_t seq_len = 0;
  int64_t batch = 0;
  int64_t n_seqs = 0;
  int64_t n_batches = 0;
  std::vector<int64_t> order;       // sequence index permutation

  // ring of assembled batches
  int64_t ring_cap = 0;
  size_t batch_bytes = 0;
  std::vector<std::vector<uint8_t>> ring;
  std::vector<int64_t> ring_tag;    // which batch index occupies a slot
  std::atomic<int64_t> next_fill{0};
  int64_t next_serve = 0;
  std::mutex mu;
  std::condition_variable cv_full, cv_empty;
  std::vector<int64_t> ready;       // filled slot flags (-1 empty)
  std::vector<int64_t> expect;      // next batch index owed to a slot
  std::atomic<bool> stop{false};
  std::vector<std::thread> workers;

  ~Reader() {
    {
      // store+notify under the mutex: a lock-free notify can land while
      // a worker holds mu evaluating its wait predicate -> lost wakeup
      // -> join() blocks forever
      std::lock_guard<std::mutex> lk(mu);
      stop.store(true);
    }
    cv_full.notify_all();
    cv_empty.notify_all();
    for (auto &w : workers)
      if (w.joinable()) w.join();
    if (map) munmap(const_cast<uint8_t *>(map), file_bytes);
    if (fd >= 0) close(fd);
  }

  void assemble(int64_t bidx, uint8_t *dst) const {
    for (int64_t r = 0; r < batch; ++r) {
      int64_t seq = order[bidx * batch + r];
      const uint8_t *src =
          map + static_cast<size_t>(seq) * seq_len * dtype_size;
      memcpy(dst + static_cast<size_t>(r) * seq_len * dtype_size, src,
             static_cast<size_t>(seq_len) * dtype_size);
    }
  }

  void worker() {
    for (;;) {
      int64_t bidx = next_fill.fetch_add(1);
      int64_t slot = bidx % ring_cap;
      std::unique_lock<std::mutex> lk(mu);
      // claim the slot only when it is empty AND this batch is the one
      // the slot is owed next (expect) — claiming on empty alone lets a
      // faster worker lap a stalled one and fill slot k with batch
      // k+ring_cap, deadlocking the consumer waiting for batch k
      cv_empty.wait(lk, [&] {
        return stop.load() ||
               (ready[slot] == -1 && expect[slot] == bidx);
      });
      if (stop.load()) return;
      ready[slot] = -2;  // filling
      lk.unlock();
      int64_t wrapped = bidx % n_batches;
      assemble(wrapped, ring[slot].data());
      lk.lock();
      ring_tag[slot] = bidx;
      ready[slot] = bidx;
      expect[slot] = bidx + ring_cap;
      cv_full.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void *dataio_open(const char *path, int dtype_size, int64_t seq_len,
                  int64_t batch, int n_threads, int64_t shuffle_seed) {
  auto *r = new Reader();
  r->fd = ::open(path, O_RDONLY);
  if (r->fd < 0) { delete r; return nullptr; }
  struct stat st;
  fstat(r->fd, &st);
  r->file_bytes = static_cast<size_t>(st.st_size);
  r->map = static_cast<const uint8_t *>(
      mmap(nullptr, r->file_bytes, PROT_READ, MAP_PRIVATE, r->fd, 0));
  if (r->map == MAP_FAILED) { delete r; return nullptr; }
  madvise(const_cast<uint8_t *>(r->map), r->file_bytes, MADV_SEQUENTIAL);
  r->dtype_size = dtype_size;
  r->seq_len = seq_len;
  r->batch = batch;
  r->n_seqs = static_cast<int64_t>(r->file_bytes) /
              (seq_len * dtype_size);
  r->n_batches = r->n_seqs / batch;
  if (r->n_batches == 0) { delete r; return nullptr; }
  r->order.resize(r->n_seqs);
  for (int64_t i = 0; i < r->n_seqs; ++i) r->order[i] = i;
  if (shuffle_seed >= 0) {
    std::mt19937_64 g(static_cast<uint64_t>(shuffle_seed));
    for (int64_t i = r->n_seqs - 1; i > 0; --i) {
      std::uniform_int_distribution<int64_t> d(0, i);
      std::swap(r->order[i], r->order[d(g)]);
    }
  }
  r->batch_bytes =
      static_cast<size_t>(batch) * seq_len * dtype_size;
  r->ring_cap = std::max<int64_t>(2, 2 * std::max(1, n_threads));
  r->ring.resize(r->ring_cap);
  for (auto &b : r->ring) b.resize(r->batch_bytes);
  r->ready.assign(r->ring_cap, -1);
  r->ring_tag.assign(r->ring_cap, -1);
  r->expect.resize(r->ring_cap);
  for (int64_t i = 0; i < r->ring_cap; ++i) r->expect[i] = i;
  int nt = std::max(1, n_threads);
  for (int i = 0; i < nt; ++i)
    r->workers.emplace_back(&Reader::worker, r);
  return r;
}

int64_t dataio_num_batches(void *h) {
  return static_cast<Reader *>(h)->n_batches;
}

int64_t dataio_num_seqs(void *h) {
  return static_cast<Reader *>(h)->n_seqs;
}

// Copies the next [batch, seq_len] block into out; returns the batch's
// epoch-local index, or -1 on shutdown.
int64_t dataio_next(void *h, uint8_t *out) {
  auto *r = static_cast<Reader *>(h);
  int64_t want = r->next_serve;
  int64_t slot = want % r->ring_cap;
  std::unique_lock<std::mutex> lk(r->mu);
  r->cv_full.wait(lk, [&] {
    return r->stop.load() || r->ready[slot] == want;
  });
  if (r->stop.load()) return -1;
  memcpy(out, r->ring[slot].data(), r->batch_bytes);
  r->ready[slot] = -1;
  r->next_serve = want + 1;
  r->cv_empty.notify_all();
  return want % r->n_batches;
}

void dataio_close(void *h) { delete static_cast<Reader *>(h); }

}  // extern "C"
