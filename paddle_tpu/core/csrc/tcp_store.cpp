// TCPStore — native rendezvous KV store.
//
// Reference parity: paddle/phi/core/distributed/store/tcp_store.cc
// (SURVEY.md §2.4): rank-0 hosts a TCP server holding a key->bytes map;
// every rank (including 0) connects as a client; primitives are SET /
// GET (blocking until the key exists) / ADD (atomic counter) / WAIT /
// DELETE.  Barriers and elastic heartbeats are built on ADD+WAIT.
//
// TPU-native role: jax's coordination service owns the *device* runtime
// rendezvous; this store is the framework-level side-channel the
// reference exposes publicly (paddle.distributed.TCPStore) — used by
// the launch controller for gang bookkeeping and by user recipes.
//
// Single-file C ABI, loaded via ctypes (no pybind11 in this image).
// Protocol (little-endian):
//   request:  u8 op | u32 klen | key bytes | u64 arg_or_vlen | value
//   response: u64 len | payload   (ADD: payload = i64 new value)
#include <arpa/inet.h>
#include <netdb.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <vector>
#include <condition_variable>
#include <atomic>
#include <set>

namespace {

enum Op : uint8_t { SET = 0, GET = 1, ADD = 2, WAIT = 3, DEL = 4,
                    CHECK = 5 };

// hostname OR dotted-quad -> in_addr (inet_addr alone cannot resolve
// names like "localhost")
bool resolve_ipv4(const char *host, in_addr *out) {
  if (inet_pton(AF_INET, host, out) == 1) return true;
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  if (getaddrinfo(host, nullptr, &hints, &res) != 0 || !res) return false;
  *out = reinterpret_cast<sockaddr_in *>(res->ai_addr)->sin_addr;
  freeaddrinfo(res);
  return true;
}

struct Server {
  int listen_fd = -1;
  std::thread accept_thread;
  std::atomic<bool> stop{false};
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::string> kv;
  std::vector<std::thread> workers;
  std::set<int> client_fds;

  ~Server() { shutdown(); }

  void shutdown() {
    stop.store(true);
    if (listen_fd >= 0) {
      ::shutdown(listen_fd, SHUT_RDWR);
      ::close(listen_fd);
      listen_fd = -1;
    }
    {
      // wake workers blocked in recv() on live client connections —
      // without this the destructor join()s forever
      std::lock_guard<std::mutex> lk(mu);
      for (int fd : client_fds) ::shutdown(fd, SHUT_RDWR);
    }
    cv.notify_all();
    if (accept_thread.joinable()) accept_thread.join();
    for (auto &w : workers)
      if (w.joinable()) w.join();
  }
};

bool read_n(int fd, void *buf, size_t n) {
  char *p = static_cast<char *>(buf);
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_n(int fd, const void *buf, size_t n) {
  const char *p = static_cast<const char *>(buf);
  while (n) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

void serve_client(Server *s, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  struct FdGuard {
    Server *s;
    int fd;
    ~FdGuard() {
      std::lock_guard<std::mutex> lk(s->mu);
      s->client_fds.erase(fd);
      ::close(fd);
    }
  } guard{s, fd};
  for (;;) {
    uint8_t op;
    uint32_t klen;
    uint64_t arg;
    if (!read_n(fd, &op, 1) || !read_n(fd, &klen, 4)) break;
    std::string key(klen, '\0');
    if (klen && !read_n(fd, key.data(), klen)) break;
    if (!read_n(fd, &arg, 8)) break;

    std::string payload;
    switch (op) {
      case SET: {
        std::string val(arg, '\0');
        if (arg && !read_n(fd, val.data(), arg)) return;
        {
          std::lock_guard<std::mutex> lk(s->mu);
          s->kv[key] = std::move(val);
        }
        s->cv.notify_all();
        break;
      }
      case GET:    // blocking get: arg = timeout ms (0 = forever)
      case WAIT: {
        std::unique_lock<std::mutex> lk(s->mu);
        auto ready = [&] { return s->kv.count(key) || s->stop.load(); };
        if (arg == 0) {
          s->cv.wait(lk, ready);
        } else if (!s->cv.wait_for(lk, std::chrono::milliseconds(arg),
                                   ready)) {
          uint64_t len = UINT64_MAX;  // timeout sentinel
          write_n(fd, &len, 8);
          continue;
        }
        if (s->stop.load()) return;
        payload = (op == GET) ? s->kv[key] : std::string();
        break;
      }
      case ADD: {
        std::lock_guard<std::mutex> lk(s->mu);
        int64_t cur = 0;
        auto it = s->kv.find(key);
        if (it != s->kv.end() && it->second.size() == 8)
          memcpy(&cur, it->second.data(), 8);
        cur += static_cast<int64_t>(arg);
        std::string v(8, '\0');
        memcpy(v.data(), &cur, 8);
        s->kv[key] = std::move(v);
        payload.assign(reinterpret_cast<char *>(&cur), 8);
        s->cv.notify_all();
        break;
      }
      case DEL: {
        std::lock_guard<std::mutex> lk(s->mu);
        s->kv.erase(key);
        break;
      }
      case CHECK: {
        std::lock_guard<std::mutex> lk(s->mu);
        payload = s->kv.count(key) ? "1" : "0";
        break;
      }
      default:
        return;
    }
    uint64_t len = payload.size();
    if (!write_n(fd, &len, 8)) break;
    if (len && !write_n(fd, payload.data(), len)) break;
  }
}

void accept_loop(Server *s) {
  for (;;) {
    int fd = ::accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (s->stop.load()) return;
      if (errno == EINTR) continue;
      return;
    }
    {
      std::lock_guard<std::mutex> lk(s->mu);
      s->client_fds.insert(fd);
    }
    s->workers.emplace_back(serve_client, s, fd);
  }
}

}  // namespace

extern "C" {

// returns opaque handle, or null; *out_port gets the bound port
void *tcp_store_server_start(const char *host, int port, int *out_port) {
  auto *s = new Server();
  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) { delete s; return nullptr; }
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  addr.sin_addr.s_addr = INADDR_ANY;
  if (host && *host && !resolve_ipv4(host, &addr.sin_addr)) {
    ::close(s->listen_fd);
    delete s;
    return nullptr;
  }
  if (::bind(s->listen_fd, reinterpret_cast<sockaddr *>(&addr),
             sizeof(addr)) < 0 ||
      ::listen(s->listen_fd, 128) < 0) {
    ::close(s->listen_fd);
    delete s;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(s->listen_fd, reinterpret_cast<sockaddr *>(&addr), &alen);
  if (out_port) *out_port = ntohs(addr.sin_port);
  s->accept_thread = std::thread(accept_loop, s);
  return s;
}

void tcp_store_server_stop(void *h) {
  delete static_cast<Server *>(h);
}

// -- client ----------------------------------------------------------------

int tcp_store_connect(const char *host, int port, int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  for (;;) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (!resolve_ipv4(host, &addr.sin_addr)) { ::close(fd); return -1; }
    if (::connect(fd, reinterpret_cast<sockaddr *>(&addr),
                  sizeof(addr)) == 0) {
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      return fd;
    }
    ::close(fd);
    if (std::chrono::steady_clock::now() > deadline) return -1;
    usleep(50 * 1000);
  }
}

void tcp_store_close(int fd) { ::close(fd); }

static int request(int fd, uint8_t op, const char *key, uint32_t klen,
                   uint64_t arg, const char *val,
                   char **out, uint64_t *out_len) {
  *out = nullptr;       // error/timeout paths must leave these safe to
  *out_len = 0;         // inspect/free in every caller
  if (!write_n(fd, &op, 1) || !write_n(fd, &klen, 4) ||
      (klen && !write_n(fd, key, klen)) || !write_n(fd, &arg, 8))
    return -1;
  if (op == SET && arg && !write_n(fd, val, arg)) return -1;
  uint64_t len;
  if (!read_n(fd, &len, 8)) return -1;
  if (len == UINT64_MAX) return -2;  // timeout
  *out_len = len;
  if (len) {
    *out = static_cast<char *>(malloc(len));
    if (!read_n(fd, *out, len)) { free(*out); return -1; }
  }
  return 0;
}

int tcp_store_set(int fd, const char *key, int klen, const char *val,
                  uint64_t vlen) {
  char *out = nullptr;
  uint64_t olen = 0;
  return request(fd, SET, key, static_cast<uint32_t>(klen), vlen, val,
                 &out, &olen);
}

// caller frees *out via tcp_store_free
int tcp_store_get(int fd, const char *key, int klen, uint64_t timeout_ms,
                  char **out, uint64_t *out_len) {
  return request(fd, GET, key, static_cast<uint32_t>(klen), timeout_ms,
                 nullptr, out, out_len);
}

int tcp_store_add(int fd, const char *key, int klen, int64_t delta,
                  int64_t *result) {
  char *out = nullptr;
  uint64_t olen = 0;
  int rc = request(fd, ADD, key, static_cast<uint32_t>(klen),
                   static_cast<uint64_t>(delta), nullptr, &out, &olen);
  if (rc == 0 && olen == 8) memcpy(result, out, 8);
  if (olen) free(out);
  return rc;
}

int tcp_store_wait(int fd, const char *key, int klen,
                   uint64_t timeout_ms) {
  char *out = nullptr;
  uint64_t olen = 0;
  int rc = request(fd, WAIT, key, static_cast<uint32_t>(klen), timeout_ms,
                   nullptr, &out, &olen);
  if (olen) free(out);
  return rc;
}

int tcp_store_delete(int fd, const char *key, int klen) {
  char *out = nullptr;
  uint64_t olen = 0;
  int rc = request(fd, DEL, key, static_cast<uint32_t>(klen), 0, nullptr,
                   &out, &olen);
  if (olen) free(out);
  return rc;
}

int tcp_store_check(int fd, const char *key, int klen, int *exists) {
  char *out = nullptr;
  uint64_t olen = 0;
  int rc = request(fd, CHECK, key, static_cast<uint32_t>(klen), 0,
                   nullptr, &out, &olen);
  if (rc == 0 && olen == 1) *exists = out[0] == '1';
  if (olen) free(out);
  return rc;
}

void tcp_store_free(char *p) { free(p); }

}  // extern "C"
