"""paddle.distributed surface: fleet, collectives, auto-parallel, sharding."""
from . import env
from .store import TCPStore
from . import auto_parallel
from . import checkpoint
from . import collective
from . import context_parallel
from . import fleet as _fleet_mod
from . import parallel_layers
from . import sharding
from . import strategy
from . import topology
from .auto_parallel import (
    Partial,
    ProcessMesh,
    Replicate,
    Shard,
    dtensor_from_fn,
    get_mesh,
    reshard,
    set_mesh,
    shard_layer,
    shard_tensor,
)
from .collective import (
    ReduceOp,
    all_gather,
    all_reduce,
    all_to_all,
    alltoall,
    barrier,
    broadcast,
    get_group,
    new_group,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    stream,
)
from .env import get_rank, get_world_size
from .fleet import fleet
from .strategy import DistributedStrategy
from .topology import CommGroup, HybridCommunicateGroup, build_mesh


def init_parallel_env():
    """paddle.distributed.init_parallel_env — pure-DP default init."""
    return fleet.init()


def is_initialized() -> bool:
    from .fleet import get_hybrid_communicate_group
    return get_hybrid_communicate_group() is not None


def get_backend() -> str:
    return "xla"  # ICI/DCN collectives via XLA (reference: nccl)
