from . import env
