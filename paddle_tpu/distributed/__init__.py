"""paddle.distributed surface: fleet, collectives, auto-parallel, sharding."""
from . import env
from .store import TCPStore
from . import auto_parallel
from . import checkpoint
from . import collective
from . import context_parallel
from . import fleet as _fleet_mod
from . import parallel_layers
from . import sharding
from . import strategy
from . import topology
from .auto_parallel import (
    Partial,
    ProcessMesh,
    Replicate,
    Shard,
    dtensor_from_fn,
    get_mesh,
    reshard,
    set_mesh,
    shard_layer,
    shard_tensor,
    unshard_dtensor,
)
from .collective import (
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    all_to_all,
    all_to_all_single,
    alltoall,
    alltoall_single,
    barrier,
    broadcast,
    broadcast_object_list,
    destroy_process_group,
    gather,
    get_group,
    irecv,
    isend,
    new_group,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    scatter_object_list,
    send,
    stream,
    wait,
)
from . import ckpt_manager
from .checkpoint import (CorruptCheckpointError, load_state_dict,
                         save_state_dict, validate_checkpoint)
from .ckpt_manager import CheckpointManager
from .env import ParallelEnv, get_rank, get_world_size, spawn
from .fleet import fleet
from .strategy import DistributedStrategy
from .topology import CommGroup, HybridCommunicateGroup, build_mesh


def init_parallel_env():
    """paddle.distributed.init_parallel_env — pure-DP default init."""
    return fleet.init()


def is_initialized() -> bool:
    from .fleet import get_hybrid_communicate_group
    return get_hybrid_communicate_group() is not None


def get_backend() -> str:
    return "xla"  # ICI/DCN collectives via XLA (reference: nccl)
