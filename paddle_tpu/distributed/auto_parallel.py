"""Auto-parallel (semi-auto) API: shard_tensor / placements / reshard.

Reference parity: python/paddle/distributed/auto_parallel/ (api.py —
``shard_tensor(t, mesh, [Shard(0), Replicate()])`` building DistTensor
with TensorDistAttr) + phi/core/distributed/auto_parallel reshard
functions + phi/infermeta/spmd_rules (per-op sharding propagation).

TPU-native design: this IS GSPMD (SURVEY.md §2.3) — placements map
1:1 onto jax.sharding.PartitionSpec / NamedSharding; the reference's
hand-written per-op SPMD rules and reshard transfer functions collapse
into XLA's sharding propagation pass; ``reshard`` is a device_put /
with_sharding_constraint.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..common.errors import enforce
from ..tensor import Tensor

__all__ = ["ProcessMesh", "Shard", "Replicate", "Partial", "shard_tensor",
           "reshard", "dtensor_from_fn", "shard_layer", "get_mesh",
           "set_mesh", "placements_to_spec"]


class Placement:
    pass


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = dim

    def __repr__(self):
        return f"Shard({self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)


class Partial(Placement):
    """Pending-reduction placement.  GSPMD materializes partial sums only
    transiently inside the partitioner; a user-held Partial tensor is
    reduced eagerly on creation (documented semantic difference)."""

    def __init__(self, reduce_type: str = "sum"):
        self.reduce_type = reduce_type

    def __repr__(self):
        return f"Partial({self.reduce_type})"


class ProcessMesh:
    """paddle.distributed.ProcessMesh — here a named wrapper over
    jax.sharding.Mesh."""

    def __init__(self, mesh=None, dim_names: Optional[List[str]] = None,
                 shape=None, process_ids=None):
        if isinstance(mesh, Mesh):
            self._mesh = mesh
            self.dim_names = list(mesh.axis_names)
        else:
            if mesh is None and (shape is not None or
                                 process_ids is not None):
                ids = (process_ids if process_ids is not None
                       else range(int(np.prod(shape))))
                arr = np.asarray(ids)
                if shape is not None:
                    arr = arr.reshape(shape)
            elif mesh is not None:
                arr = np.asarray(mesh)
            else:
                arr = np.asarray(range(len(jax.devices())))
            devices = np.asarray(jax.devices())[arr.reshape(-1)]
            self.dim_names = dim_names or [f"d{i}" for i in range(arr.ndim)]
            self._mesh = Mesh(devices.reshape(arr.shape),
                              tuple(self.dim_names))
        self.shape = list(self._mesh.devices.shape)

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    @property
    def process_ids(self):
        return [d.id for d in self._mesh.devices.reshape(-1)]

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dims={self.dim_names})"


_GLOBAL_MESH: Optional[ProcessMesh] = None


def set_mesh(mesh: Union[ProcessMesh, Mesh]):
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh if isinstance(mesh, ProcessMesh) else ProcessMesh(mesh)
    return _GLOBAL_MESH


def get_mesh() -> Optional[ProcessMesh]:
    return _GLOBAL_MESH


def placements_to_spec(placements: Sequence[Placement], mesh: Mesh,
                       ndim: int) -> PartitionSpec:
    """[Shard(0), Replicate()] on mesh axes (a, b) → PartitionSpec per
    TENSOR dim: placements are per-MESH-dim (paddle convention)."""
    entries: List[Optional[object]] = [None] * ndim
    for mesh_dim, placement in enumerate(placements):
        axis_name = mesh.axis_names[mesh_dim]
        if isinstance(placement, Shard):
            d = placement.dim
            enforce(0 <= d < ndim, f"Shard dim {d} out of range for ndim {ndim}")
            if entries[d] is None:
                entries[d] = axis_name
            elif isinstance(entries[d], tuple):
                entries[d] = entries[d] + (axis_name,)
            else:
                entries[d] = (entries[d], axis_name)
    return PartitionSpec(*entries)


def shard_tensor(x, mesh: Union[ProcessMesh, Mesh],
                 placements: Sequence[Placement],
                 dtype=None, stop_gradient: Optional[bool] = None) -> Tensor:
    """Place ``x`` on the mesh with the given per-mesh-dim placements.
    Returns a Tensor whose .value is a globally-sharded jax.Array."""
    m = mesh.mesh if isinstance(mesh, ProcessMesh) else mesh
    t = x if isinstance(x, Tensor) else Tensor(x, dtype=dtype)
    spec = placements_to_spec(placements, m, t.ndim)
    sharding = NamedSharding(m, spec)
    arr = jax.device_put(t.value, sharding)
    # user-held Partial: reduce eagerly (see Partial docstring)
    for p in placements:
        if isinstance(p, Partial):
            raise NotImplementedError(
                "Partial placements are internal to the partitioner on TPU; "
                "reduce before sharding")
    out = Tensor(arr, stop_gradient=t.stop_gradient
                 if stop_gradient is None else stop_gradient)
    out.name = t.name
    if hasattr(t, "trainable"):  # keep Parameter-ness
        out._stop_gradient = t._stop_gradient
    return out


def reshard(x: Tensor, mesh: Union[ProcessMesh, Mesh],
            placements: Sequence[Placement]) -> Tensor:
    """Reshard a (possibly already sharded) tensor — the reference's
    ReshardFunction family (s→r, r→s, cross-mesh) collapses into one
    device_put with the target NamedSharding."""
    return shard_tensor(x, mesh, placements)


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs) -> Tensor:
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def unshard_dtensor(x: Tensor) -> Tensor:
    """Gather a distributed tensor back to a fully-replicated local
    tensor (paddle.distributed.unshard_dtensor)."""
    import jax

    val = x.value if isinstance(x, Tensor) else x
    if hasattr(val, "is_fully_addressable") and \
            not val.is_fully_addressable:
        import numpy as np
        val = jax.numpy.asarray(
            np.asarray(jax.experimental.multihost_utils
                       .process_allgather(val)))
    out = Tensor(jax.device_get(val))
    if hasattr(x, "trainable"):
        out._stop_gradient = x._stop_gradient
    return out


def shard_layer(layer, process_mesh, shard_fn=None, input_fn=None,
                output_fn=None):
    """paddle.distributed.shard_layer: apply shard_fn(name, layer, mesh)
    over sublayers to place parameters."""
    if shard_fn is None:
        def shard_fn(name, sublayer, mesh):  # replicate by default
            for pname, p in sublayer._parameters.items():
                if p is not None:
                    placements = [Replicate()] * len(mesh.shape)
                    sublayer._parameters[pname] = _shard_param(p, mesh,
                                                               placements)
    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, process_mesh)
    return layer


def _shard_param(p, mesh, placements):
    from ..tensor import Parameter
    m = mesh.mesh if isinstance(mesh, ProcessMesh) else mesh
    spec = placements_to_spec(placements, m, p.ndim)
    arr = jax.device_put(p.value, NamedSharding(m, spec))
    new = Parameter.__new__(Parameter)
    Tensor.__init__(new, arr, stop_gradient=p.stop_gradient)
    new.trainable = getattr(p, "trainable", True)
    new.optimize_attr = getattr(p, "optimize_attr", {"learning_rate": 1.0})
    new.regularizer = getattr(p, "regularizer", None)
    new.dist_spec = getattr(p, "dist_spec", None)  # keep TP annotations
    new.is_distributed = True
    new.name = p.name
    return new
