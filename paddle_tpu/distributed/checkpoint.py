"""Distributed sharded checkpoint with reshard-on-load and atomic commit.

Reference parity: python/paddle/distributed/checkpoint/
(``save_state_dict`` / ``load_state_dict`` — per-rank shard files plus a
global metadata manifest, with load-time resharding across different
meshes/degrees; SURVEY.md §5 Checkpoint/resume).

TPU-native design: a checkpoint is a directory of ``.npy`` chunk files —
one per unique (non-replica) shard of every array in the state pytree —
plus ``metadata.json`` recording each array's global shape, dtype, the
index box every chunk covers, and each chunk's sha256.  Saving walks
``jax.Array.addressable_shards`` and writes only ``replica_id == 0``
shards (so replicated axes are stored once and every multi-host process
writes a disjoint set of files); loading rebuilds each array with
``jax.make_array_from_callback`` against the *target* sharding, reading
only the chunk bytes that overlap each requested index box (chunks are
memory-mapped, so resharding from an (8-way) checkpoint onto 1 device or
any other mesh never materializes more than the requested slices).

Crash safety (the atomic-commit contract):

- Every save builds the whole checkpoint in a ``<path>.tmp-<nonce>``
  staging directory next to the destination: chunk files (fsync'd), then
  the manifest carrying ``"committed": true`` plus per-chunk sha256.
- Fresh destination: commit is ONE ``os.rename(staging, path)`` — a kill
  at any byte offset leaves either no ``path`` at all (plus an orphaned
  staging dir that later saves / ``CheckpointManager.gc_stale`` sweep)
  or the complete committed checkpoint.  A torn checkpoint is never
  visible under ``path``.
- Existing destination (re-save in place): the fresh ``data-<nonce>``
  chunk dir is renamed into ``path`` first, then the manifest is
  atomically replaced — readers see the OLD complete checkpoint until
  the manifest swap, never a mix.
- Load verifies each chunk file's sha256 against the manifest before
  reading it and raises :class:`CorruptCheckpointError` (typed) on any
  mismatch/missing file, so bit-rot or a torn write from a pre-atomic
  writer can't be silently consumed.

Async saves return an :class:`AsyncSaveHandle`; a background-writer
failure re-raises on ``wait()``/``join()`` and — if never waited — at
the next ``save_state_dict`` call, and live writer threads are joined at
interpreter exit.  Failures are never silently dropped.
"""
from __future__ import annotations

import atexit
import hashlib
import io as _io
import json
import os
import re
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import jax
import numpy as np

from ..common.errors import CorruptCheckpointError, enforce
from ..tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict", "get_checkpoint_metadata",
           "validate_checkpoint", "AsyncSaveHandle", "CorruptCheckpointError",
           "ChaosCrash", "set_chaos", "clear_chaos"]

_METADATA = "metadata.json"
_VERSION = 2                 # v2: "committed" flag + per-chunk sha256/bytes
_KNOWN_VERSIONS = (1, 2)     # v1 (pre-atomic) checkpoints still load


# ---------------------------------------------------------------------------
# chaos injection (crash-at-point, used by the trainer chaos harness)
# ---------------------------------------------------------------------------

class ChaosCrash(RuntimeError):
    """In-process stand-in for a SIGKILL at a save point (chaos tests)."""


_CHAOS_POINTS = ("mid-chunk", "pre-manifest", "pre-rename", "post-commit")
_chaos_plan: Optional[Dict[str, Any]] = None


def set_chaos(point: str, nth: int = 1, mode: str = "raise"):
    """Arm a crash at the given save point on its ``nth`` visit.
    ``mode="raise"`` raises :class:`ChaosCrash` (in-process tests);
    ``mode="exit"`` calls ``os._exit(17)`` (subprocess kill tests).
    The env var ``PADDLE_TPU_CKPT_CHAOS=point[:nth[:mode]]`` arms the
    same plan across a process boundary."""
    global _chaos_plan
    enforce(point in _CHAOS_POINTS, f"unknown chaos point {point!r}; "
            f"one of {_CHAOS_POINTS}")
    _chaos_plan = {"point": point, "n": int(nth), "mode": mode}


def clear_chaos():
    global _chaos_plan
    _chaos_plan = None
    os.environ.pop("PADDLE_TPU_CKPT_CHAOS", None)


def _chaos_spec() -> Optional[Dict[str, Any]]:
    global _chaos_plan
    if _chaos_plan is None:
        env = os.environ.get("PADDLE_TPU_CKPT_CHAOS")
        if env:
            parts = env.split(":")
            _chaos_plan = {"point": parts[0],
                           "n": int(parts[1]) if len(parts) > 1 else 1,
                           "mode": parts[2] if len(parts) > 2 else "exit"}
    return _chaos_plan


def _chaos_hit(point: str) -> bool:
    plan = _chaos_spec()
    if plan is None or plan["point"] != point:
        return False
    plan["n"] -= 1
    return plan["n"] <= 0


def _chaos_crash(point: str):
    plan = _chaos_spec()
    mode = plan["mode"] if plan else "raise"
    clear_chaos()
    if mode == "exit":
        os._exit(17)
    raise ChaosCrash(f"injected crash at checkpoint save point {point!r}")


# ---------------------------------------------------------------------------
# staging-dir registry (conftest leak guard) + fsync helpers
# ---------------------------------------------------------------------------

_STAGING_LOCK = threading.Lock()
_LIVE_STAGING: Set[str] = set()


def _track_staging(p: str):
    with _STAGING_LOCK:
        _LIVE_STAGING.add(p)


def _untrack_staging(p: str):
    with _STAGING_LOCK:
        _LIVE_STAGING.discard(p)


def staging_dirs_alive() -> List[str]:
    """Staging dirs created but never committed/GC'd that still exist on
    disk — the tests/ conftest fails any test that leaves one behind."""
    with _STAGING_LOCK:
        return sorted(p for p in _LIVE_STAGING if os.path.isdir(p))


def _fsync_dir(d: str):
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _npy_bytes(arr: np.ndarray) -> bytes:
    buf = _io.BytesIO()
    np.save(buf, arr, allow_pickle=False)
    return buf.getvalue()


# ---------------------------------------------------------------------------
# metrics (observability wiring — lazy so import stays cheap)
# ---------------------------------------------------------------------------

def _reg():
    from ..observability import get_registry
    return get_registry()


_SECONDS_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                    30.0, 60.0, 300.0)


def _save_metrics():
    reg = _reg()
    return (reg.histogram("ckpt_save_seconds",
                          "checkpoint save duration (host->disk flush)",
                          labelnames=("mode",), buckets=_SECONDS_BUCKETS),
            reg.counter("ckpt_bytes_written_total",
                        "checkpoint chunk+manifest bytes flushed to disk"))


def _load_metrics():
    return _reg().histogram("ckpt_load_seconds",
                            "checkpoint load duration (disk->device)",
                            buckets=_SECONDS_BUCKETS)


# ---------------------------------------------------------------------------
# async save handles — failures must surface, never vanish
# ---------------------------------------------------------------------------

class AsyncSaveHandle:
    """Returned by ``save_state_dict(async_save=True)``.

    ``wait(timeout=None)`` joins the background writer and re-raises its
    exception, if any (every call re-raises until the save is re-tried).
    ``join`` is an alias so Thread-shaped callers keep working.  A
    handle whose writer failed and was never waited re-raises at the
    next ``save_state_dict`` call; live writers are joined at
    interpreter exit."""

    def __init__(self, path: str):
        self.path = path
        self.bytes_written = 0
        self._thread: Optional[threading.Thread] = None
        self._exc: Optional[BaseException] = None
        self._surfaced = False

    def done(self) -> bool:
        return self._thread is not None and not self._thread.is_alive()

    def exception(self) -> Optional[BaseException]:
        """The writer's exception (marks it surfaced), or None."""
        if self._exc is not None:
            self._surfaced = True
        return self._exc

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Join the writer.  Returns False on timeout; raises the
        writer's exception when the save failed."""
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                return False
        _forget_handle(self)
        if self._exc is not None:
            self._surfaced = True
            raise self._exc
        return True

    def join(self, timeout: Optional[float] = None):
        self.wait(timeout)


_HANDLES_LOCK = threading.Lock()
_LIVE_HANDLES: Set[AsyncSaveHandle] = set()
_ATEXIT_ARMED = False


def _remember_handle(h: AsyncSaveHandle):
    global _ATEXIT_ARMED
    with _HANDLES_LOCK:
        _LIVE_HANDLES.add(h)
        if not _ATEXIT_ARMED:
            _ATEXIT_ARMED = True
            atexit.register(_join_live_writers)


def _forget_handle(h: AsyncSaveHandle):
    with _HANDLES_LOCK:
        _LIVE_HANDLES.discard(h)


def _surface_failed_async_saves():
    """Called at every save entry: a finished-but-failed handle nobody
    waited on re-raises HERE rather than vanishing with its thread."""
    with _HANDLES_LOCK:
        handles = list(_LIVE_HANDLES)
    for h in handles:
        if not h.done():
            continue
        _forget_handle(h)
        if h._exc is not None and not h._surfaced:
            h._surfaced = True
            raise RuntimeError(
                f"previous async checkpoint save to {h.path!r} failed "
                f"(surfacing at next save; call handle.wait() to catch "
                f"it at the save site)") from h._exc


def _join_live_writers():
    """atexit: never let the interpreter tear down under an in-flight
    checkpoint writer (a half-written staging dir is recoverable, but a
    silently-truncated flush that LOOKED returned is not)."""
    with _HANDLES_LOCK:
        handles = list(_LIVE_HANDLES)
    for h in handles:
        if h._thread is not None:
            h._thread.join(timeout=600.0)
        if h._exc is not None and not h._surfaced:
            import sys
            print(f"paddle_tpu: async checkpoint save to {h.path!r} "
                  f"failed and was never waited on: {h._exc!r}",
                  file=sys.stderr)


# ---------------------------------------------------------------------------
# pytree <-> flat {path: leaf}
# ---------------------------------------------------------------------------

def _flatten(tree, prefix="") -> Dict[str, Any]:
    """Flatten nested dict/list/tuple into {"a/b/0": leaf}.  Tensor leaves
    stay whole (not entered as pytrees)."""
    out: Dict[str, Any] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            enforce("/" not in str(k), f"state key {k!r} may not contain '/'")
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}/"))
    else:
        out[prefix[:-1] if prefix else ""] = tree
    return out


def _set_in(tree, path: str, value):
    parts = path.split("/")
    node = tree
    for p in parts[:-1]:
        node = node[p] if isinstance(node, dict) else node[int(p)]
    last = parts[-1]
    if isinstance(node, dict):
        old = node.get(last)
    else:
        last = int(last)
        old = node[last]
    if isinstance(old, Tensor):
        old._value = value if isinstance(value, jax.Array) else \
            jax.numpy.asarray(value)
        old._node = None
    elif isinstance(node, list):
        node[last] = value
    elif isinstance(node, dict):
        node[last] = value
    else:  # tuple — rebuild is the caller's job; tuples of arrays are rare
        raise TypeError(f"cannot assign into tuple at {path!r}; use lists "
                        "or dicts in checkpointable state")


def _is_array(x) -> bool:
    # python int/float/bool/str round-trip as JSON literals (so e.g. an LR
    # scheduler's last_epoch stays a python int across save/load); numpy
    # scalars count as 0-d arrays
    return isinstance(x, (jax.Array, np.ndarray, np.generic))


def _fname(key: str, box: Sequence[Tuple[int, int]]) -> str:
    # readable prefix + short key hash: sanitizing '/'→'_' alone is not
    # injective ('a/b_c' vs 'a_b/c'), the hash keeps filenames collision-free
    safe = re.sub(r"[^A-Za-z0-9_.-]", "_", key)[-80:]
    h = hashlib.md5(key.encode()).hexdigest()[:8]
    tag = "-".join(f"{a}_{b}" for a, b in box) if box else "scalar"
    return f"{safe}.{h}.{tag}.npy"


def _norm_box(idx: Sequence[slice], shape: Sequence[int]
              ) -> Tuple[Tuple[int, int], ...]:
    out = []
    for sl, dim in zip(idx, shape):
        start, stop, step = sl.indices(dim)
        enforce(step == 1, "strided shard indices unsupported")
        out.append((start, stop))
    return tuple(out)


# ---------------------------------------------------------------------------
# save
# ---------------------------------------------------------------------------

def save_state_dict(state_dict, path: str, process_group=None,
                    coordinator_rank: int = 0, async_save: bool = False
                    ) -> Optional[AsyncSaveHandle]:
    """Write ``state_dict`` (any pytree of Tensors / jax or numpy arrays /
    scalars / literals) as a sharded checkpoint directory at ``path``.

    Each process writes only its own non-replica shards; the coordinator
    writes the manifest.  With ``async_save=True`` the host->disk writes
    happen on a background thread (device->host copies are still taken
    synchronously so training may mutate/donate the state immediately)
    and an :class:`AsyncSaveHandle` is returned; a writer failure
    re-raises on ``handle.wait()`` or — unwaited — at the next save.

    Crash safety: the whole checkpoint is staged in ``<path>.tmp-<nonce>``
    (chunks fsync'd, manifest carrying ``committed: true`` + per-chunk
    sha256) and committed by a single directory rename (fresh path) or a
    data-dir move + atomic manifest replace (re-save in place), so a
    kill at any byte offset leaves either the previous checkpoint fully
    intact or the new one fully committed — never a torn mix.  Orphaned
    staging dirs from kills are swept by the next successful save to the
    same path (and by ``CheckpointManager.gc_stale``).  Multi-host
    callers must call this collectively from the main thread: the save
    nonce is agreed via a broadcast at entry (which doubles as an entry
    barrier); per-host completion markers carry each host's chunk
    hashes so the coordinator can write a complete manifest.
    """
    _surface_failed_async_saves()
    nproc = jax.process_count()
    pidx = jax.process_index()
    if nproc > 1:
        from jax.experimental import multihost_utils
        seed = np.uint32(int.from_bytes(os.urandom(4), "little"))
        nonce = format(int(multihost_utils.broadcast_one_to_all(
            seed, is_source=pidx == coordinator_rank)), "08x")
    else:
        nonce = format(int.from_bytes(os.urandom(4), "little"), "08x")

    path = path.rstrip("/")
    parent = os.path.dirname(path) or "."
    os.makedirs(parent, exist_ok=True)
    in_place = os.path.isdir(path)
    staging = f"{path}.tmp-{nonce}"
    data_dir = f"data-{nonce}"
    os.makedirs(os.path.join(staging, data_dir), exist_ok=True)
    _track_staging(staging)

    flat = _flatten(state_dict)
    manifest: Dict[str, Any] = {"version": _VERSION, "committed": True,
                                "arrays": {}, "literals": {},
                                "data_dir": data_dir}
    writes: List[Tuple[str, np.ndarray]] = []

    def chunk_rel(key, box):
        return f"{data_dir}/{_fname(key, box)}"

    for key, leaf in flat.items():
        if isinstance(leaf, Tensor):
            leaf = leaf.value
        if not _is_array(leaf):
            enforce(leaf is None or isinstance(leaf, (str, int, float, bool)),
                    f"unsupported checkpoint leaf at {key!r}: {type(leaf)}")
            manifest["literals"][key] = leaf
            continue
        if not isinstance(leaf, jax.Array):
            # host-local numpy leaf: identical on every process by the
            # collective-call contract — only the coordinator writes it
            leaf = np.asarray(leaf)
            box = _norm_box((slice(None),) * leaf.ndim, leaf.shape)
            if pidx == coordinator_rank:
                writes.append((chunk_rel(key, box), np.asarray(leaf)))
            manifest["arrays"][key] = {
                "global_shape": list(leaf.shape), "dtype": str(leaf.dtype),
                "chunks": [{"file": chunk_rel(key, box),
                            "box": [list(b) for b in box]}]}
            continue

        shape = leaf.shape
        # global chunk list: every unique index box across ALL devices
        # (deterministic on every process — sharding metadata is global)
        idx_map = leaf.sharding.devices_indices_map(shape)
        boxes = sorted({_norm_box(idx, shape) for idx in idx_map.values()})
        manifest["arrays"][key] = {
            "global_shape": list(shape), "dtype": str(leaf.dtype),
            "chunks": [{"file": chunk_rel(key, b),
                        "box": [list(x) for x in b]} for b in boxes]}
        # process-local (fully-addressable) arrays look identical on every
        # multi-host process — e.g. an RNG key or a host-replicated scalar.
        # Only the coordinator writes them: otherwise N processes would race
        # on the same chunk path, and per-process divergence (differently
        # seeded hosts) would be collapsed nondeterministically.  Global
        # arrays are written by whichever process holds the replica-0 shard.
        if (leaf.is_fully_addressable and nproc > 1
                and pidx != coordinator_rank):
            continue
        for shard in leaf.addressable_shards:
            if shard.replica_id != 0:
                continue
            box = _norm_box(shard.index, shape)
            writes.append((chunk_rel(key, box), np.asarray(shard.data)))

    handle = AsyncSaveHandle(path) if async_save else None
    mode = "async" if async_save else "sync"

    def flush():
        t0 = time.monotonic()
        total_bytes = 0
        digests: Dict[str, Dict[str, Any]] = {}
        # chaos points count SAVES (not chunks) so `point:N` schedules
        # uniformly mean "the Nth save" — a mid-chunk hit tears the
        # first chunk at half its bytes and dies there
        torn_save = _chaos_hit("mid-chunk")
        for i, (rel, arr) in enumerate(writes):
            data = _npy_bytes(arr)
            if torn_save and i == 0:
                data = data[:max(1, len(data) // 2)]
            fpath = os.path.join(staging, rel)
            with open(fpath, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            if torn_save and i == 0:
                _chaos_crash("mid-chunk")
            digests[rel] = {"sha256": hashlib.sha256(data).hexdigest(),
                            "bytes": len(data)}
            total_bytes += len(data)
        if torn_save and not writes:
            _chaos_crash("mid-chunk")
        _fsync_dir(os.path.join(staging, data_dir))
        if _chaos_hit("pre-manifest"):
            _chaos_crash("pre-manifest")

        # multi-host sync uses per-save-nonce marker files in the (shared)
        # staging dir — NOT a device collective, which on a background
        # thread could interleave with the main thread's training
        # collectives and deadlock.  Markers carry this host's chunk
        # digests so the coordinator's manifest covers every chunk.
        if nproc > 1:
            marker = os.path.join(staging, f".{nonce}.proc{pidx}.done")
            with open(marker, "w") as f:
                json.dump(digests, f)
            if pidx != coordinator_rank:
                # the coordinator owns the commit (and the rename that
                # consumes the staging dir) — stop tracking it here
                _untrack_staging(staging)
                return
            deadline = time.monotonic() + 600.0
            want = [os.path.join(staging, f".{nonce}.proc{i}.done")
                    for i in range(nproc)]
            while not all(os.path.exists(w) for w in want):
                enforce(time.monotonic() < deadline,
                        "timed out waiting for other hosts' shards")
                time.sleep(0.2)
            for w in want:
                with open(w) as f:
                    digests.update(json.load(f))
                os.remove(w)

        # fill per-chunk integrity info, then the COMMITTED manifest —
        # written only after every chunk is flushed.  Inside the private
        # staging dir a plain write is safe; atomicity comes from the
        # commit rename below.
        for entry in manifest["arrays"].values():
            for chunk in entry["chunks"]:
                d = digests.get(chunk["file"])
                if d is not None:
                    chunk.update(d)
        mdata = json.dumps(manifest, indent=1).encode()
        with open(os.path.join(staging, _METADATA), "wb") as f:
            f.write(mdata)
            f.flush()
            os.fsync(f.fileno())
        total_bytes += len(mdata)
        _fsync_dir(staging)
        if _chaos_hit("pre-rename"):
            _chaos_crash("pre-rename")

        # commit
        if in_place:
            # readers see the OLD manifest (complete old checkpoint)
            # until the manifest replace lands
            os.rename(os.path.join(staging, data_dir),
                      os.path.join(path, data_dir))
            tmp = os.path.join(path, _METADATA + f".tmp-{nonce}")
            os.rename(os.path.join(staging, _METADATA), tmp)
            os.replace(tmp, os.path.join(path, _METADATA))
            _fsync_dir(path)
            shutil.rmtree(staging, ignore_errors=True)
        else:
            os.rename(staging, path)
            _fsync_dir(parent)
        _untrack_staging(staging)
        if _chaos_hit("post-commit"):
            _chaos_crash("post-commit")

        # GC (only AFTER the commit point): data dirs from older /
        # interrupted in-place saves, stale marker files, and orphaned
        # sibling staging dirs from earlier killed saves to this path
        for entry in os.listdir(path):
            full = os.path.join(path, entry)
            if entry.startswith("data-") and entry != data_dir:
                shutil.rmtree(full, ignore_errors=True)
            elif (entry.startswith(".") and entry.endswith(".done")) or \
                    entry.startswith(_METADATA + ".tmp-"):
                # stale markers, and a manifest tmp left by a crash
                # between the two commit renames of an in-place re-save
                try:
                    os.remove(full)
                except OSError:
                    pass
        base = os.path.basename(path)
        for entry in os.listdir(parent):
            if entry.startswith(base + ".tmp-"):
                full = os.path.join(parent, entry)
                if full != staging and os.path.isdir(full):
                    shutil.rmtree(full, ignore_errors=True)
                    _untrack_staging(full)

        hist, bytes_ctr = _save_metrics()
        hist.labels(mode).observe(time.monotonic() - t0)
        bytes_ctr.inc(total_bytes)
        if handle is not None:
            handle.bytes_written = total_bytes

    if async_save:
        def run():
            try:
                flush()
            except BaseException as e:   # surfaced via handle/next save
                handle._exc = e

        t = threading.Thread(target=run, daemon=False,
                             name="paddle-tpu-ckpt-writer")
        handle._thread = t
        _remember_handle(handle)
        t.start()
        return handle
    flush()
    return None


# ---------------------------------------------------------------------------
# load + validation
# ---------------------------------------------------------------------------

def get_checkpoint_metadata(path: str) -> Dict[str, Any]:
    """Parse and sanity-check the manifest.  Raises
    :class:`CorruptCheckpointError` when it is missing, torn, from an
    unknown version, or was never committed."""
    mpath = os.path.join(path, _METADATA)
    try:
        with open(mpath) as f:
            meta = json.load(f)
    except FileNotFoundError:
        raise CorruptCheckpointError(
            f"{path}: no {_METADATA} — not a committed checkpoint "
            f"(torn write from a crashed save, or wrong directory)")
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise CorruptCheckpointError(f"{path}: torn {_METADATA}: {e}")
    if meta.get("version") not in _KNOWN_VERSIONS:
        raise CorruptCheckpointError(
            f"{path}: unknown checkpoint version {meta.get('version')}")
    if meta.get("version", 0) >= 2 and not meta.get("committed"):
        raise CorruptCheckpointError(
            f"{path}: manifest present but not committed")
    return meta


def _hash_file(fpath: str) -> str:
    h = hashlib.sha256()
    with open(fpath, "rb") as f:
        for blk in iter(lambda: f.read(1 << 20), b""):
            h.update(blk)
    return h.hexdigest()


def _verify_chunk(root: str, chunk: Dict[str, Any], cache: Set[str]):
    """sha256-verify one chunk file against the manifest (once per load:
    the cache spans the whole load_state_dict call)."""
    rel = chunk["file"]
    if rel in cache:
        return
    fpath = os.path.join(root, rel)
    if not os.path.exists(fpath):
        raise CorruptCheckpointError(
            f"{root}: missing chunk file {rel!r}")
    want_bytes = chunk.get("bytes")
    if want_bytes is not None and os.path.getsize(fpath) != want_bytes:
        raise CorruptCheckpointError(
            f"{root}: chunk {rel!r} is {os.path.getsize(fpath)} bytes, "
            f"manifest says {want_bytes} (truncated write?)")
    want = chunk.get("sha256")
    if want is not None and _hash_file(fpath) != want:
        raise CorruptCheckpointError(
            f"{root}: chunk {rel!r} sha256 mismatch (bit-rot or torn "
            f"write)")
    cache.add(rel)


def validate_checkpoint(path: str, deep: bool = True) -> Dict[str, Any]:
    """Integrity-check a checkpoint dir WITHOUT materializing arrays:
    committed manifest, every chunk file present with the manifest's
    size, and (``deep=True``) matching sha256.  Returns the metadata;
    raises :class:`CorruptCheckpointError` on any failure."""
    meta = get_checkpoint_metadata(path)
    cache: Set[str] = set()
    for entry in meta["arrays"].values():
        for chunk in entry["chunks"]:
            if deep:
                _verify_chunk(path, chunk, cache)
            else:
                fpath = os.path.join(path, chunk["file"])
                if not os.path.exists(fpath):
                    raise CorruptCheckpointError(
                        f"{path}: missing chunk file {chunk['file']!r}")
                want_bytes = chunk.get("bytes")
                if want_bytes is not None and \
                        os.path.getsize(fpath) != want_bytes:
                    raise CorruptCheckpointError(
                        f"{path}: chunk {chunk['file']!r} size mismatch")
    return meta


def _read_box(path: str, entry: Dict[str, Any], want: Tuple[slice, ...],
              shape: Sequence[int], dtype,
              verify_cache: Optional[Set[str]] = None) -> np.ndarray:
    """Assemble the requested index box from the chunk files that overlap
    it.  Chunks are mmap'd so only the overlapping bytes are read; with a
    ``verify_cache``, each touched chunk file is sha256-verified first."""
    want_box = _norm_box(want, shape)
    out = np.empty([b - a for a, b in want_box], dtype=dtype)
    filled = 0
    for chunk in entry["chunks"]:
        cbox = [tuple(b) for b in chunk["box"]]
        inter = [(max(a0, b0), min(a1, b1))
                 for (a0, a1), (b0, b1) in zip(want_box, cbox)]
        if any(a >= b for a, b in inter):
            continue
        if verify_cache is not None:
            _verify_chunk(path, chunk, verify_cache)
        src = np.load(os.path.join(path, chunk["file"]), mmap_mode="r",
                      allow_pickle=False)
        if src.dtype != dtype:
            # extension dtypes (bfloat16, fp8) round-trip through npy as
            # raw void bytes; reinterpret against the manifest dtype
            src = src.view(dtype)
        src_sl = tuple(slice(a - c0, b - c0)
                       for (a, b), (c0, _) in zip(inter, cbox))
        dst_sl = tuple(slice(a - w0, b - w0)
                       for (a, b), (w0, _) in zip(inter, want_box))
        out[dst_sl] = src[src_sl]
        filled += int(np.prod([b - a for a, b in inter]))
    enforce(filled == out.size,
            f"checkpoint chunks do not cover requested box {want_box} "
            f"(covered {filled}/{out.size} elements)",
            error_cls=CorruptCheckpointError)
    return out


def _target_sharding(leaf) -> Optional[jax.sharding.Sharding]:
    if isinstance(leaf, Tensor):
        leaf = leaf.value
    if isinstance(leaf, jax.Array):
        return leaf.sharding
    return None


def load_state_dict(state_dict, path: str, process_group=None,
                    coordinator_rank: int = 0, metadata=None,
                    verify: bool = True):
    """Fill ``state_dict`` (a template pytree — e.g. a freshly-initialized
    model/optimizer state, possibly sharded over a *different* mesh than
    the checkpoint was saved from) from the checkpoint at ``path``.

    Tensor leaves are updated in place; the (re-built) tree is also
    returned for functional callers (raw jax pytrees).  Each array is
    materialized directly into the template leaf's sharding.

    With ``verify=True`` (default) every chunk file read is
    sha256-checked against the manifest first and any corruption raises
    :class:`CorruptCheckpointError` BEFORE the template is mutated —
    a partially-restored state is never left behind.
    """
    t0 = time.monotonic()
    meta = metadata if metadata is not None else get_checkpoint_metadata(path)
    enforce(meta.get("version") in _KNOWN_VERSIONS,
            f"unknown checkpoint version {meta.get('version')}",
            error_cls=CorruptCheckpointError)
    verify_cache: Optional[Set[str]] = set() if verify else None
    flat = _flatten(state_dict)
    new_flat: Dict[str, Any] = {}
    for key, leaf in flat.items():
        if key in meta["literals"]:
            new_flat[key] = meta["literals"][key]
            continue
        entry = meta["arrays"].get(key)
        enforce(entry is not None, f"{key!r} not found in checkpoint {path}")
        shape = tuple(entry["global_shape"])
        dtype = np.dtype(entry["dtype"])
        # materialize in the TEMPLATE's dtype: a bf16 train state restored
        # from an f32 checkpoint (or vice versa) must keep its configured
        # precision rather than silently adopting the checkpoint's
        tmpl_arr = leaf.value if isinstance(leaf, Tensor) else leaf
        out_dtype = tmpl_arr.dtype if isinstance(
            tmpl_arr, (jax.Array, np.ndarray)) else dtype
        sharding = _target_sharding(leaf)
        if sharding is None:
            arr = jax.numpy.asarray(
                _read_box(path, entry, (slice(None),) * len(shape), shape,
                          dtype, verify_cache).astype(out_dtype))
        else:
            enforce(tuple(tmpl_arr.shape) == shape,
                    f"{key!r}: template shape {tuple(tmpl_arr.shape)} != "
                    f"checkpoint global shape {shape}")
            arr = jax.make_array_from_callback(
                shape, sharding,
                lambda idx, e=entry: _read_box(path, e, idx, shape,
                                               dtype, verify_cache
                                               ).astype(out_dtype))
        new_flat[key] = arr

    for key, val in new_flat.items():
        _set_in(state_dict, key, val)
    _load_metrics().observe(time.monotonic() - t0)
    return state_dict
