"""Distributed sharded checkpoint with reshard-on-load.

Reference parity: python/paddle/distributed/checkpoint/
(``save_state_dict`` / ``load_state_dict`` — per-rank shard files plus a
global metadata manifest, with load-time resharding across different
meshes/degrees; SURVEY.md §5 Checkpoint/resume).

TPU-native design: a checkpoint is a directory of ``.npy`` chunk files —
one per unique (non-replica) shard of every array in the state pytree —
plus ``metadata.json`` recording each array's global shape, dtype, and
the index box every chunk covers.  Saving walks
``jax.Array.addressable_shards`` and writes only ``replica_id == 0``
shards (so replicated axes are stored once and every multi-host process
writes a disjoint set of files); loading rebuilds each array with
``jax.make_array_from_callback`` against the *target* sharding, reading
only the chunk bytes that overlap each requested index box (chunks are
memory-mapped, so resharding from an (8-way) checkpoint onto 1 device or
any other mesh never materializes more than the requested slices).
This is the same contract as the reference's load-time reshard
(per-rank files + metadata → arbitrary target placement), with
tensorstore's chunked-read role played by mmap'd npy chunks.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..common.errors import enforce
from ..tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict", "get_checkpoint_metadata"]

_METADATA = "metadata.json"
_VERSION = 1


# ---------------------------------------------------------------------------
# pytree <-> flat {path: leaf}
# ---------------------------------------------------------------------------

def _flatten(tree, prefix="") -> Dict[str, Any]:
    """Flatten nested dict/list/tuple into {"a/b/0": leaf}.  Tensor leaves
    stay whole (not entered as pytrees)."""
    out: Dict[str, Any] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            enforce("/" not in str(k), f"state key {k!r} may not contain '/'")
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}/"))
    else:
        out[prefix[:-1] if prefix else ""] = tree
    return out


def _set_in(tree, path: str, value):
    parts = path.split("/")
    node = tree
    for p in parts[:-1]:
        node = node[p] if isinstance(node, dict) else node[int(p)]
    last = parts[-1]
    if isinstance(node, dict):
        old = node.get(last)
    else:
        last = int(last)
        old = node[last]
    if isinstance(old, Tensor):
        old._value = value if isinstance(value, jax.Array) else \
            jax.numpy.asarray(value)
        old._node = None
    elif isinstance(node, list):
        node[last] = value
    elif isinstance(node, dict):
        node[last] = value
    else:  # tuple — rebuild is the caller's job; tuples of arrays are rare
        raise TypeError(f"cannot assign into tuple at {path!r}; use lists "
                        "or dicts in checkpointable state")


def _is_array(x) -> bool:
    # python int/float/bool/str round-trip as JSON literals (so e.g. an LR
    # scheduler's last_epoch stays a python int across save/load); numpy
    # scalars count as 0-d arrays
    return isinstance(x, (jax.Array, np.ndarray, np.generic))


def _fname(key: str, box: Sequence[Tuple[int, int]]) -> str:
    # readable prefix + short key hash: sanitizing '/'→'_' alone is not
    # injective ('a/b_c' vs 'a_b/c'), the hash keeps filenames collision-free
    safe = re.sub(r"[^A-Za-z0-9_.-]", "_", key)[-80:]
    h = hashlib.md5(key.encode()).hexdigest()[:8]
    tag = "-".join(f"{a}_{b}" for a, b in box) if box else "scalar"
    return f"{safe}.{h}.{tag}.npy"


def _norm_box(idx: Sequence[slice], shape: Sequence[int]
              ) -> Tuple[Tuple[int, int], ...]:
    out = []
    for sl, dim in zip(idx, shape):
        start, stop, step = sl.indices(dim)
        enforce(step == 1, "strided shard indices unsupported")
        out.append((start, stop))
    return tuple(out)


# ---------------------------------------------------------------------------
# save
# ---------------------------------------------------------------------------

def save_state_dict(state_dict, path: str, process_group=None,
                    coordinator_rank: int = 0, async_save: bool = False
                    ) -> Optional[threading.Thread]:
    """Write ``state_dict`` (any pytree of Tensors / jax or numpy arrays /
    scalars / literals) as a sharded checkpoint directory at ``path``.

    Each process writes only its own non-replica shards; the coordinator
    writes the manifest.  With ``async_save=True`` the host->disk writes
    happen on a background thread (device->host copies are still taken
    synchronously so training may mutate/donate the state immediately);
    the returned Thread can be join()ed.

    Crash safety: every save writes its chunks into a fresh
    ``data-<nonce>/`` subdirectory and commits by atomically replacing
    the manifest afterwards, so re-saving into the same path can never
    mix chunks of two saves under one manifest; a crash mid-save leaves
    the previous checkpoint fully intact (the orphaned data dir is
    garbage-collected by the next successful save).  Multi-host callers
    must call this collectively from the main thread: the save nonce is
    agreed via a broadcast at entry (which doubles as an entry barrier,
    invalidating any stale completion markers from interrupted saves).
    """
    os.makedirs(path, exist_ok=True)
    nproc = jax.process_count()
    pidx = jax.process_index()
    if nproc > 1:
        from jax.experimental import multihost_utils
        seed = np.uint32(int.from_bytes(os.urandom(4), "little"))
        nonce = format(int(multihost_utils.broadcast_one_to_all(
            seed, is_source=pidx == coordinator_rank)), "08x")
    else:
        nonce = format(int.from_bytes(os.urandom(4), "little"), "08x")
    data_dir = f"data-{nonce}"
    os.makedirs(os.path.join(path, data_dir), exist_ok=True)

    flat = _flatten(state_dict)
    manifest: Dict[str, Any] = {"version": _VERSION, "arrays": {},
                               "literals": {}, "data_dir": data_dir}
    writes: List[Tuple[str, np.ndarray]] = []

    def chunk_path(key, box):
        return f"{data_dir}/{_fname(key, box)}"

    for key, leaf in flat.items():
        if isinstance(leaf, Tensor):
            leaf = leaf.value
        if not _is_array(leaf):
            enforce(leaf is None or isinstance(leaf, (str, int, float, bool)),
                    f"unsupported checkpoint leaf at {key!r}: {type(leaf)}")
            manifest["literals"][key] = leaf
            continue
        if not isinstance(leaf, jax.Array):
            # host-local numpy leaf: identical on every process by the
            # collective-call contract — only the coordinator writes it
            leaf = np.asarray(leaf)
            box = _norm_box((slice(None),) * leaf.ndim, leaf.shape)
            if pidx == coordinator_rank:
                writes.append((os.path.join(path, chunk_path(key, box)),
                               np.asarray(leaf)))
            manifest["arrays"][key] = {
                "global_shape": list(leaf.shape), "dtype": str(leaf.dtype),
                "chunks": [{"file": chunk_path(key, box),
                            "box": [list(b) for b in box]}]}
            continue

        shape = leaf.shape
        # global chunk list: every unique index box across ALL devices
        # (deterministic on every process — sharding metadata is global)
        idx_map = leaf.sharding.devices_indices_map(shape)
        boxes = sorted({_norm_box(idx, shape) for idx in idx_map.values()})
        manifest["arrays"][key] = {
            "global_shape": list(shape), "dtype": str(leaf.dtype),
            "chunks": [{"file": chunk_path(key, b),
                        "box": [list(x) for x in b]} for b in boxes]}
        # process-local (fully-addressable) arrays look identical on every
        # multi-host process — e.g. an RNG key or a host-replicated scalar.
        # Only the coordinator writes them: otherwise N processes would race
        # on the same chunk path, and per-process divergence (differently
        # seeded hosts) would be collapsed nondeterministically.  Global
        # arrays are written by whichever process holds the replica-0 shard.
        if (leaf.is_fully_addressable and nproc > 1
                and pidx != coordinator_rank):
            continue
        for shard in leaf.addressable_shards:
            if shard.replica_id != 0:
                continue
            box = _norm_box(shard.index, shape)
            writes.append((os.path.join(path, chunk_path(key, box)),
                           np.asarray(shard.data)))

    def flush():
        for fpath, arr in writes:
            np.save(fpath, arr, allow_pickle=False)
        # the manifest is the commit point: written only after every chunk
        # is flushed, via tmp+rename so readers never see a manifest that
        # references missing/truncated chunk files.  Multi-host sync uses
        # per-save-nonce marker files on the (shared) checkpoint dir — NOT
        # a device collective, which on a background thread could
        # interleave with the main thread's training collectives and
        # deadlock.  The nonce in the marker name means markers from an
        # interrupted earlier save can never satisfy this wait.
        if nproc > 1:
            with open(os.path.join(path, f".{nonce}.proc{pidx}.done"),
                      "w"):
                pass
        if pidx == coordinator_rank:
            if nproc > 1:
                deadline = time.monotonic() + 600.0
                want = [os.path.join(path, f".{nonce}.proc{i}.done")
                        for i in range(nproc)]
                while not all(os.path.exists(w) for w in want):
                    enforce(time.monotonic() < deadline,
                            "timed out waiting for other hosts' shards")
                    time.sleep(0.2)
            tmp = os.path.join(path, _METADATA + ".tmp")
            with open(tmp, "w") as f:
                json.dump(manifest, f, indent=1)
            os.replace(tmp, os.path.join(path, _METADATA))
            # GC: orphaned data dirs from older/interrupted saves, and
            # this save's markers (only AFTER the commit point)
            import shutil
            for entry in os.listdir(path):
                full = os.path.join(path, entry)
                if entry.startswith("data-") and entry != data_dir:
                    shutil.rmtree(full, ignore_errors=True)
                elif entry.startswith(".") and entry.endswith(".done"):
                    try:
                        os.remove(full)
                    except OSError:
                        pass

    if async_save:
        t = threading.Thread(target=flush, daemon=False)
        t.start()
        return t
    flush()
    return None


# ---------------------------------------------------------------------------
# load
# ---------------------------------------------------------------------------

def get_checkpoint_metadata(path: str) -> Dict[str, Any]:
    with open(os.path.join(path, _METADATA)) as f:
        return json.load(f)


def _read_box(path: str, entry: Dict[str, Any], want: Tuple[slice, ...],
              shape: Sequence[int], dtype) -> np.ndarray:
    """Assemble the requested index box from the chunk files that overlap
    it.  Chunks are mmap'd so only the overlapping bytes are read."""
    want_box = _norm_box(want, shape)
    out = np.empty([b - a for a, b in want_box], dtype=dtype)
    filled = 0
    for chunk in entry["chunks"]:
        cbox = [tuple(b) for b in chunk["box"]]
        inter = [(max(a0, b0), min(a1, b1))
                 for (a0, a1), (b0, b1) in zip(want_box, cbox)]
        if any(a >= b for a, b in inter):
            continue
        src = np.load(os.path.join(path, chunk["file"]), mmap_mode="r",
                      allow_pickle=False)
        if src.dtype != dtype:
            # extension dtypes (bfloat16, fp8) round-trip through npy as
            # raw void bytes; reinterpret against the manifest dtype
            src = src.view(dtype)
        src_sl = tuple(slice(a - c0, b - c0)
                       for (a, b), (c0, _) in zip(inter, cbox))
        dst_sl = tuple(slice(a - w0, b - w0)
                       for (a, b), (w0, _) in zip(inter, want_box))
        out[dst_sl] = src[src_sl]
        filled += int(np.prod([b - a for a, b in inter]))
    enforce(filled == out.size,
            f"checkpoint chunks do not cover requested box {want_box} "
            f"(covered {filled}/{out.size} elements)")
    return out


def _target_sharding(leaf) -> Optional[jax.sharding.Sharding]:
    if isinstance(leaf, Tensor):
        leaf = leaf.value
    if isinstance(leaf, jax.Array):
        return leaf.sharding
    return None


def load_state_dict(state_dict, path: str, process_group=None,
                    coordinator_rank: int = 0, metadata=None):
    """Fill ``state_dict`` (a template pytree — e.g. a freshly-initialized
    model/optimizer state, possibly sharded over a *different* mesh than
    the checkpoint was saved from) from the checkpoint at ``path``.

    Tensor leaves are updated in place; the (re-built) tree is also
    returned for functional callers (raw jax pytrees).  Each array is
    materialized directly into the template leaf's sharding.
    """
    meta = metadata if metadata is not None else get_checkpoint_metadata(path)
    enforce(meta.get("version") == _VERSION,
            f"unknown checkpoint version {meta.get('version')}")
    flat = _flatten(state_dict)
    new_flat: Dict[str, Any] = {}
    for key, leaf in flat.items():
        if key in meta["literals"]:
            new_flat[key] = meta["literals"][key]
            continue
        entry = meta["arrays"].get(key)
        enforce(entry is not None, f"{key!r} not found in checkpoint {path}")
        shape = tuple(entry["global_shape"])
        dtype = np.dtype(entry["dtype"])
        # materialize in the TEMPLATE's dtype: a bf16 train state restored
        # from an f32 checkpoint (or vice versa) must keep its configured
        # precision rather than silently adopting the checkpoint's
        tmpl_arr = leaf.value if isinstance(leaf, Tensor) else leaf
        out_dtype = tmpl_arr.dtype if isinstance(
            tmpl_arr, (jax.Array, np.ndarray)) else dtype
        sharding = _target_sharding(leaf)
        if sharding is None:
            arr = jax.numpy.asarray(
                _read_box(path, entry, (slice(None),) * len(shape), shape,
                          dtype).astype(out_dtype))
        else:
            enforce(tuple(tmpl_arr.shape) == shape,
                    f"{key!r}: template shape {tuple(tmpl_arr.shape)} != "
                    f"checkpoint global shape {shape}")
            arr = jax.make_array_from_callback(
                shape, sharding,
                lambda idx, e=entry: _read_box(path, e, idx, shape,
                                               dtype).astype(out_dtype))
        new_flat[key] = arr

    for key, val in new_flat.items():
        _set_in(state_dict, key, val)
    return state_dict
