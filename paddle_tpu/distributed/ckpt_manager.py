"""CheckpointManager — crash-safe training checkpoint lifecycle.

Owns the step-numbered checkpoint directory a training run writes into::

    <root>/step_00000100/     committed checkpoint (atomic, see
    <root>/step_00000200/     distributed/checkpoint.py)
    <root>/step_00000200.tmp-<nonce>/   crashed save — swept by gc_stale

and the policies around it:

- **Retention**: ``keep_last_n`` most recent checkpoints always survive;
  ``keep_every_k`` additionally pins every k-th step (long-horizon
  rollback points).  Pruning runs only after a save has committed.
- **Bounded async saves**: ``async_save=True`` keeps at most
  ``max_inflight`` background writers; the next ``save`` blocks on the
  oldest writer first.  A failed background save re-raises at the next
  ``save``/``wait`` — it must surface, not vanish.
- **auto_resume / restore**: picks the latest checkpoint that passes
  integrity validation, falling back past corrupt ones (counted in
  ``ckpt_corruption_total``) — a torn or bit-rotted latest checkpoint
  silently costs a few steps, never the run.
- **SIGTERM hook**: ``install_preemption_hook()`` flips ``preempted``
  when the scheduler sends SIGTERM; the training loop (hapi ``fit``)
  checks it between steps, saves, and stops cleanly.
"""
from __future__ import annotations

import os
import re
import shutil
import signal
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..common.errors import CorruptCheckpointError, enforce
from . import checkpoint as _ckpt

__all__ = ["CheckpointManager"]

_STEP_RE = re.compile(r"step_(\d+)$")


class CheckpointManager:
    def __init__(self, root: str, keep_last_n: int = 3,
                 keep_every_k: Optional[int] = None,
                 async_save: bool = False, max_inflight: int = 2):
        enforce(keep_last_n >= 1, "keep_last_n must be >= 1")
        enforce(max_inflight >= 1, "max_inflight must be >= 1")
        self.root = str(root)
        self.keep_last_n = keep_last_n
        self.keep_every_k = keep_every_k
        self.async_save = async_save
        self.max_inflight = max_inflight
        self.preempted = False
        self._prev_sigterm = None
        self._on_preempt = None
        # (step, handle) in submission order — bounded write-behind queue
        self._inflight: "deque[Tuple[int, _ckpt.AsyncSaveHandle]]" = deque()
        self._lock = threading.Lock()
        os.makedirs(self.root, exist_ok=True)
        from ..observability import get_registry
        reg = get_registry()
        self._depth = reg.gauge(
            "ckpt_async_queue_depth",
            "in-flight background checkpoint writers")
        self._corrupt = reg.counter(
            "ckpt_corruption_total",
            "checkpoints skipped by restore/auto_resume as corrupt")
        self.gc_stale()

    # -- paths ---------------------------------------------------------------
    def step_dir(self, step: int) -> str:
        return os.path.join(self.root, f"step_{step:08d}")

    def steps_on_disk(self) -> List[int]:
        """Committed (dir-exists) step numbers, ascending.  Staging dirs
        (``*.tmp-*``) are crashed saves, never listed."""
        out = []
        for entry in os.listdir(self.root):
            m = _STEP_RE.fullmatch(entry)
            if m and os.path.isdir(os.path.join(self.root, entry)):
                out.append(int(m.group(1)))
        return sorted(out)

    def gc_stale(self) -> List[str]:
        """Sweep staging dirs (``*.tmp-<nonce>``) left by killed saves.
        Safe at any time: a staging dir is by construction never a
        committed checkpoint.  Returns the swept paths."""
        swept = []
        for entry in os.listdir(self.root):
            full = os.path.join(self.root, entry)
            if ".tmp-" in entry and os.path.isdir(full):
                shutil.rmtree(full, ignore_errors=True)
                _ckpt._untrack_staging(full)
                swept.append(full)
        return swept

    # -- save ----------------------------------------------------------------
    def save(self, state, step: int, extra_state: Optional[Dict] = None):
        """Checkpoint ``state`` as ``<root>/step_<step>``.

        ``state`` is either a train step object exposing
        ``save_checkpoint(path, async_save=, extra_state=)``
        (CompiledTrainStep / ShardedTrainStep) or a raw pytree for
        ``save_state_dict``.  Synchronous by default; with the manager's
        ``async_save=True`` the host snapshot is still taken before this
        returns (training may mutate/donate immediately) and disk writes
        happen on a bounded background queue.  Raises a previous
        background save's failure before starting a new one."""
        from ..observability import health as _health
        from ..observability import tracing as _tracing
        with self._lock, _health.goodput_region(
                "checkpoint_save"), _tracing.span(
                "train.checkpoint_save",
                attrs={"step": step,
                       "mode": "async" if self.async_save
                       else "sync"}):
            # bounded queue: block on the oldest writer for a free slot,
            # surfacing its failure here if it had one.  The span
            # covers the host-side snapshot (async mode) or the whole
            # committed write (sync) — what the training loop WAITS on
            self._drain_locked(want_free_slot=True)
            path = self.step_dir(step)
            if hasattr(state, "save_checkpoint"):
                handle = state.save_checkpoint(
                    path, async_save=self.async_save,
                    extra_state=extra_state)
            else:
                enforce(extra_state is None,
                        "extra_state needs a train-step saver "
                        "(save_checkpoint); raw pytrees don't carry it")
                handle = _ckpt.save_state_dict(
                    state, path, async_save=self.async_save)
            if handle is not None:
                self._inflight.append((step, handle))
            else:
                self._retain_locked()
            self._depth.set(len(self._inflight))
            return handle

    def _drain_locked(self, want_free_slot: bool = False):
        while self._inflight:
            _s, h = self._inflight[0]
            if not h.done() and not (
                    want_free_slot and
                    len(self._inflight) >= self.max_inflight):
                break
            self._inflight.popleft()
            self._depth.set(len(self._inflight))
            try:
                h.wait()          # re-raises the writer's failure — loud
            finally:
                self._depth.set(len(self._inflight))
            self._retain_locked()

    def wait(self):
        """Block until every queued background save has committed,
        re-raising the first failure.  Call before relying on the latest
        checkpoint (end of training, pre-preemption shutdown)."""
        with self._lock:
            while self._inflight:
                _s, h = self._inflight.popleft()
                self._depth.set(len(self._inflight))
                h.wait()
                self._retain_locked()

    def _retain_locked(self):
        """keep-last-N + keep-every-K pruning of committed checkpoints
        (runs only after a commit; in-flight steps are never pruned)."""
        steps = self.steps_on_disk()
        pending = {s for s, _h in self._inflight}
        keep = set(steps[-self.keep_last_n:])
        if self.keep_every_k:
            keep |= {s for s in steps if s % self.keep_every_k == 0}
        for s in steps:
            if s not in keep and s not in pending:
                shutil.rmtree(self.step_dir(s), ignore_errors=True)

    # -- resume --------------------------------------------------------------
    def auto_resume(self, deep: bool = True
                    ) -> Optional[Tuple[int, str]]:
        """(step, path) of the latest checkpoint that passes integrity
        validation, or None.  Corrupt candidates are counted and skipped
        — a torn latest checkpoint falls back to the previous one."""
        self.gc_stale()
        for s in reversed(self.steps_on_disk()):
            path = self.step_dir(s)
            try:
                _ckpt.validate_checkpoint(path, deep=deep)
                return s, path
            except CorruptCheckpointError:
                self._corrupt.inc()
        return None

    def restore(self, state) -> Optional[Tuple[int, Optional[Dict]]]:
        """Load the latest VALID checkpoint into ``state`` (a train step
        object with ``load_checkpoint`` or a template pytree).  Returns
        ``(step, extra_state)`` — extra_state is the trainer-loop dict
        saved alongside (epoch/loader position), None for raw trees or
        when nothing valid exists.  Corruption during the load itself
        (sha mismatch on read) also falls back to the previous
        checkpoint; the template is never left half-mutated."""
        self.gc_stale()
        for s in reversed(self.steps_on_disk()):
            path = self.step_dir(s)
            try:
                if hasattr(state, "load_checkpoint"):
                    extra = state.load_checkpoint(path)
                else:
                    _ckpt.load_state_dict(state, path)
                    extra = None
                return s, extra
            except CorruptCheckpointError:
                self._corrupt.inc()
        return None

    # -- preemption ----------------------------------------------------------
    def install_preemption_hook(self, on_preempt=None):
        """Arm SIGTERM → ``self.preempted = True`` (+ optional callback).
        The training loop checks the flag between steps, saves, and
        exits; the handler itself only flips the flag — no checkpoint
        IO happens in signal context.  Chains a previously-installed
        python handler.  Main-thread only (signal module contract)."""
        self._on_preempt = on_preempt
        prev = signal.getsignal(signal.SIGTERM)

        def handler(signum, frame):
            self.preempted = True
            # flight recorder (when one is enabled): the preemption
            # moment and what the process was doing land on disk even
            # if the post-save shutdown never completes.  Flag flip
            # stays first — a failing dump cannot lose the preemption.
            from ..observability import tracing as _tracing
            rec = _tracing.get_flight_recorder()
            if rec is not None:
                rec.record("preempted", signum=int(signum))
                try:
                    rec.dump(reason="preempted")
                except Exception:
                    pass
            if self._on_preempt is not None:
                self._on_preempt()
            if callable(prev) and prev not in (
                    signal.SIG_DFL, signal.SIG_IGN, signal.default_int_handler):
                prev(signum, frame)

        self._prev_sigterm = prev
        signal.signal(signal.SIGTERM, handler)

    def uninstall_preemption_hook(self):
        if self._prev_sigterm is not None:
            signal.signal(signal.SIGTERM, self._prev_sigterm)
            self._prev_sigterm = None
        self._on_preempt = None
