"""Collective communication API.

Reference parity: paddle.distributed.communication (all_reduce/all_gather/
reduce_scatter/all_to_all/broadcast/send/recv + ReduceOp + new_group) over
the C++ ProcessGroup/NCCL stack (SURVEY.md §2.4).

TPU-native design: two layers —
  1. **In-mesh primitives** (the hot path): thin wrappers over
     ``jax.lax.psum / all_gather / psum_scatter / all_to_all / ppermute``
     taking a CommGroup/axis-name; usable inside ``shard_map`` regions.
     These are what PP schedules and ring attention use — XLA lowers them
     to ICI collectives.
  2. **Eager module functions** with paddle signatures.  Under a tracer
     they dispatch to (1).  On concrete values: multi-process runtimes
     get TRUE per-rank semantics (each process contributes its local
     value through a tiny process-spanning XLA program — the reference's
     ProcessGroup contract); in a single-controller process a concrete
     array is already the global value, so all_reduce/broadcast are
     identities there (documented mapping, SURVEY.md §2.4).
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..common.errors import enforce
from ..tensor import Tensor, apply_op
from .topology import CommGroup

__all__ = ["ReduceOp", "all_reduce", "all_gather", "reduce_scatter",
           "all_to_all", "broadcast", "scatter", "reduce", "barrier",
           "new_group", "get_group", "send", "recv", "psum", "pmean",
           "pmax", "ppermute", "axis_index", "stream"]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


_GROUPS = {}
_DEFAULT_GROUP: Optional[CommGroup] = None


def _default_group() -> CommGroup:
    global _DEFAULT_GROUP
    if _DEFAULT_GROUP is None:
        from . import fleet
        hcg = fleet.get_hybrid_communicate_group()
        enforce(hcg is not None,
                "call paddle.distributed.fleet.init() (or init_parallel_env) "
                "before collectives")
        _DEFAULT_GROUP = hcg.get_data_parallel_group()
    return _DEFAULT_GROUP


def _set_default_group(g: CommGroup):
    global _DEFAULT_GROUP
    _DEFAULT_GROUP = g


class ProcessSubsetGroup:
    """Eager process-level group over an explicit rank subset (reference
    ``new_group(ranks=[...])``).  Usable with the EAGER collectives
    (all_reduce/all_gather/broadcast/barrier on concrete values — they
    run a tiny process-spanning XLA program); not usable inside
    compiled SPMD regions, where groups are mesh axes."""

    def __init__(self, ranks: List[int]):
        import numpy as np
        self.ranks = sorted(int(r) for r in ranks)
        # one representative device per member process
        per_proc = {}
        for d in jax.devices():
            per_proc.setdefault(d.process_index, d)
        enforce(all(r in per_proc for r in self.ranks),
                f"new_group ranks {ranks} outside process world "
                f"{sorted(per_proc)}")
        self.devices = [per_proc[r] for r in self.ranks]
        self.mesh = Mesh(np.array(self.devices), ("pg",))

    @property
    def nranks(self) -> int:
        return len(self.ranks)

    world_size = nranks

    @property
    def is_member(self) -> bool:
        return jax.process_index() in self.ranks

    def rank_in_group(self, rank=None) -> int:
        r = jax.process_index() if rank is None else rank
        return self.ranks.index(r) if r in self.ranks else -1


def new_group(ranks: Optional[List[int]] = None, backend=None,
              axis: Optional[Union[str, Sequence[str]]] = None):
    """paddle.distributed.new_group.  Inside compiled SPMD programs a
    group is a mesh axis (pass ``axis=``); an explicit rank list builds
    a process-subset group for the eager collectives."""
    from . import fleet
    if axis is not None:
        hcg = fleet.get_hybrid_communicate_group()
        enforce(hcg is not None, "fleet.init() first")
        g = CommGroup(hcg.mesh, tuple([axis] if isinstance(axis, str)
                                      else axis))
    else:
        hcg = fleet.get_hybrid_communicate_group()
        # "all ranks" in either unit (paddle idiom): process count or
        # mesh device count -> the default all-ranks group
        all_ranks = [list(range(jax.process_count()))]
        if hcg is not None:
            all_ranks.append(list(range(int(hcg.mesh.devices.size))))
        if ranks is not None and sorted(ranks) not in all_ranks:
            g = ProcessSubsetGroup(ranks)
        else:
            enforce(hcg is not None, "fleet.init() first")
            g = hcg.get_check_parallel_group()
    _GROUPS[id(g)] = g
    return g


def get_group(gid=None) -> CommGroup:
    return _default_group()


def _is_traced(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _unwrap(t):
    return t.value if isinstance(t, Tensor) else jnp.asarray(t)


# ---------------------------------------------------------------------------
# Layer 1: in-mesh primitives (shard_map bodies, Pallas loops)
# ---------------------------------------------------------------------------

def psum(x, group: Union[CommGroup, str]):
    axis = group.axis_name if isinstance(group, CommGroup) else group
    return lax.psum(x, axis)


def pmean(x, group: Union[CommGroup, str]):
    axis = group.axis_name if isinstance(group, CommGroup) else group
    return lax.pmean(x, axis)


def pmax(x, group: Union[CommGroup, str]):
    axis = group.axis_name if isinstance(group, CommGroup) else group
    return lax.pmax(x, axis)


def ppermute(x, group: Union[CommGroup, str], perm):
    axis = group.axis_name if isinstance(group, CommGroup) else group
    return lax.ppermute(x, axis, perm)


def axis_index(group: Union[CommGroup, str]):
    axis = group.axis_name if isinstance(group, CommGroup) else group
    return lax.axis_index(axis)


# ---------------------------------------------------------------------------
# Cross-process eager transport (multi-host: each process contributes its
# LOCAL value — the reference's per-rank collective semantics)
# ---------------------------------------------------------------------------

_WORLD_PG: Optional[ProcessSubsetGroup] = None


def _world_proc_group() -> ProcessSubsetGroup:
    global _WORLD_PG
    if _WORLD_PG is None or _WORLD_PG.nranks != jax.process_count():
        _WORLD_PG = ProcessSubsetGroup(list(range(jax.process_count())))
    return _WORLD_PG


_CROSS_JITS = {}


def _cross_process(val, fn, group=None, fn_key=None):
    """Stack each member process's local ``val`` on a leading axis
    sharded over one-device-per-process, apply ``fn`` replicated (GSPMD
    emits the DCN/ICI collective), return the host result — or None for
    non-members.  The jitted program is cached per (fn_key, mesh) so a
    per-step eager collective does not retrace/recompile every call."""
    import numpy as np
    pg = group if isinstance(group, ProcessSubsetGroup) \
        else _world_proc_group()
    if not pg.is_member:
        return None
    if isinstance(val, jax.Array) and not val.is_fully_addressable:
        raise ValueError(
            "eager collective on a non-fully-addressable global jax.Array "
            "(e.g. an output of a compiled SPMD step): its data lives on "
            "other processes' devices, so the per-rank host transfer is "
            "impossible.  Use the mesh/shard_map collectives inside the "
            "compiled step, or reshard/gather the array first.")
    arr_np = np.asarray(val)
    sh = NamedSharding(pg.mesh, PartitionSpec("pg"))
    gshape = (pg.nranks,) + tuple(arr_np.shape)
    mine = [d for d in pg.devices
            if d.process_index == jax.process_index()]
    local = [jax.device_put(arr_np[None], d) for d in mine]
    arr = jax.make_array_from_single_device_arrays(gshape, sh, local)
    cache_key = (fn_key if fn_key is not None else fn, pg.mesh)
    jitted = _CROSS_JITS.get(cache_key)
    if jitted is None:
        jitted = jax.jit(fn, out_shardings=NamedSharding(
            pg.mesh, PartitionSpec()))
        _CROSS_JITS[cache_key] = jitted
    out = jitted(arr)
    return np.asarray(jax.device_get(out))


def _gather_tiled(a):
    return a.reshape((-1,) + a.shape[2:])


def _gather_stacked(a):
    return a


def _take_row(a, idx):
    return a[idx]


_EAGER_REDUCERS = {
    ReduceOp.SUM: lambda a: jnp.sum(a, 0),
    ReduceOp.MAX: lambda a: jnp.max(a, 0),
    ReduceOp.MIN: lambda a: jnp.min(a, 0),
    ReduceOp.PROD: lambda a: jnp.prod(a, 0),
    ReduceOp.AVG: lambda a: jnp.mean(a, 0),
}


# ---------------------------------------------------------------------------
# Layer 2: paddle-shaped eager API
# ---------------------------------------------------------------------------

def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op: bool = True):
    # a mesh-axis CommGroup passed explicitly keeps single-controller
    # semantics on concrete values (identity); only the default group or
    # a ProcessSubsetGroup gets the cross-process eager transport
    cross_ok = group is None or isinstance(group, ProcessSubsetGroup)
    if not isinstance(group, ProcessSubsetGroup):
        group = group or _default_group()
    val = _unwrap(tensor)
    if _is_traced(val):
        enforce(isinstance(group, CommGroup),
                "traced collectives need a mesh-axis group")
        if op == ReduceOp.PROD:
            # no lax.pprod: gather the axis and reduce locally
            gathered = lax.all_gather(val, group.axis_name)
            out = jnp.prod(gathered, axis=0)
        else:
            fns = {ReduceOp.SUM: lax.psum, ReduceOp.MAX: lax.pmax,
                   ReduceOp.MIN: lax.pmin, ReduceOp.AVG: lax.pmean}
            enforce(op in fns, f"unsupported ReduceOp {op!r}")
            out = fns[op](val, group.axis_name)
        return Tensor(out) if isinstance(tensor, Tensor) else out
    if jax.process_count() > 1 and cross_ok:
        # true per-rank semantics across processes (reference contract)
        res = _cross_process(
            val, _EAGER_REDUCERS[op],
            group if isinstance(group, ProcessSubsetGroup) else None,
            fn_key=("reduce", op))
        if res is None:
            return tensor
        return Tensor(res) if isinstance(tensor, Tensor) else res
    # single controller, concrete global array: already globally reduced
    _warn_concrete_identity("all_reduce", group)
    return tensor


_IDENTITY_WARNED = set()


def _warn_concrete_identity(opname: str, group) -> None:
    """Single-controller eager collective on a concrete value is an
    identity BY DESIGN (one logical value), but a user porting a
    multi-process recipe may expect a real reduce — say so once
    (VERDICT r2 weak #8: don't be silent about it)."""
    n = getattr(group, "nranks", 1)
    if n <= 1 or opname in _IDENTITY_WARNED:
        return
    _IDENTITY_WARNED.add(opname)
    import warnings
    warnings.warn(
        f"paddle.distributed.{opname} on a concrete array in a "
        "single-controller runtime is an identity: a jax global array "
        "already holds the one logical value. For a real collective, "
        "run inside the compiled step (mesh sharding / shard_map) or "
        "launch multi-process (paddle.distributed.launch).",
        stacklevel=3)


def all_gather(tensor_or_list, tensor=None, group: Optional[CommGroup] = None,
               sync_op: bool = True):
    """Both signatures supported: paddle's
    ``all_gather(tensor_list, tensor)`` and functional
    ``out = all_gather(tensor)``."""
    cross_ok = group is None or isinstance(group, ProcessSubsetGroup)
    if not isinstance(group, ProcessSubsetGroup):
        group = group or _default_group()
    if isinstance(tensor_or_list, list) and tensor is not None:
        val = _unwrap(tensor)
        if _is_traced(val):
            out = lax.all_gather(val, group.axis_name)
            n = group.nranks
            tensor_or_list.extend(Tensor(out[i]) for i in range(n))
            return
        if jax.process_count() > 1 and cross_ok:
            res = _cross_process(
                val, _gather_stacked,
                group if isinstance(group, ProcessSubsetGroup) else None,
                fn_key="gather_stacked")
            if res is not None:
                tensor_or_list.extend(Tensor(res[i])
                                      for i in range(res.shape[0]))
                return
        _warn_concrete_identity("all_gather", group)
        tensor_or_list.extend(Tensor(val) for _ in range(group.nranks))
        return
    val = _unwrap(tensor_or_list)
    if _is_traced(val):
        out = lax.all_gather(val, group.axis_name, tiled=True)
        return Tensor(out) if isinstance(tensor_or_list, Tensor) else out
    if jax.process_count() > 1 and cross_ok:
        res = _cross_process(
            val, _gather_tiled,
            group if isinstance(group, ProcessSubsetGroup) else None,
            fn_key="gather_tiled")
        if res is not None:
            return Tensor(res) if isinstance(tensor_or_list, Tensor) \
                else res
    _warn_concrete_identity("all_gather", group)
    return tensor_or_list


def reduce_scatter(tensor, op=ReduceOp.SUM, group: Optional[CommGroup] = None,
                   sync_op: bool = True):
    group = group or _default_group()
    val = _unwrap(tensor)
    if _is_traced(val):
        out = lax.psum_scatter(val, group.axis_name, tiled=True)
        return Tensor(out) if isinstance(tensor, Tensor) else out
    _warn_concrete_identity("reduce_scatter", group)
    return tensor


def all_to_all(out_tensor_list, in_tensor_list=None,
               group: Optional[CommGroup] = None, sync_op: bool = True):
    """Paddle list signature and functional array signature."""
    group = group or _default_group()
    if in_tensor_list is None:
        # functional: single stacked array, alltoall over leading dim
        val = _unwrap(out_tensor_list)
        if _is_traced(val):
            out = lax.all_to_all(val, group.axis_name, split_axis=0,
                                 concat_axis=0, tiled=True)
            return Tensor(out) if isinstance(out_tensor_list, Tensor) else out
        return out_tensor_list
    vals = [_unwrap(t) for t in in_tensor_list]
    if vals and _is_traced(vals[0]):
        stacked = jnp.stack(vals)
        out = lax.all_to_all(stacked, group.axis_name, split_axis=0,
                             concat_axis=0)
        out_tensor_list.extend(Tensor(out[i]) for i in range(out.shape[0]))
        return
    out_tensor_list.extend(Tensor(v) for v in vals)


alltoall = all_to_all


def broadcast(tensor, src: int = 0, group=None, sync_op: bool = True):
    val = _unwrap(tensor)
    if not _is_traced(val) and jax.process_count() > 1 and (
            group is None or isinstance(group, ProcessSubsetGroup)):
        pg = group if isinstance(group, ProcessSubsetGroup) \
            else _world_proc_group()
        idx = pg.rank_in_group(src)
        enforce(idx >= 0, f"broadcast src {src} not in group {pg.ranks}")
        res = _cross_process(val, functools.partial(_take_row, idx=idx),
                             pg, fn_key=("bcast", idx))
        if res is None:
            return tensor
        return Tensor(res) if isinstance(tensor, Tensor) else res
    # single controller SPMD: one logical value — broadcast is identity
    if not _is_traced(val):
        _warn_concrete_identity("broadcast", group)
    return tensor


def scatter(tensor, tensor_list=None, src: int = 0,
            group: Optional[CommGroup] = None, sync_op: bool = True):
    group = group or _default_group()
    if tensor_list is not None:
        val = _unwrap(tensor_list[0])
        if not _is_traced(val):
            _warn_concrete_identity("scatter", group)
        return Tensor(val)
    if not _is_traced(_unwrap(tensor)):
        _warn_concrete_identity("scatter", group)
    return tensor


def reduce(tensor, dst: int = 0, op=ReduceOp.SUM,
           group: Optional[CommGroup] = None, sync_op: bool = True):
    return all_reduce(tensor, op, group, sync_op)


def barrier(group=None):
    if jax.process_count() > 1 and (
            group is None or isinstance(group, ProcessSubsetGroup)):
        _cross_process(jnp.zeros((1,)), _EAGER_REDUCERS[ReduceOp.SUM],
                       group if isinstance(group, ProcessSubsetGroup)
                       else None, fn_key=("reduce", ReduceOp.SUM))
        return
    jax.block_until_ready(jnp.zeros(()))


def send(tensor, dst: int, group: Optional[CommGroup] = None,
         sync_op: bool = True):
    raise NotImplementedError(
        "point-to-point send/recv: use ppermute inside shard_map (the PP "
        "schedule does) — per-process p2p does not exist under SPMD")


def recv(tensor, src: int, group: Optional[CommGroup] = None,
         sync_op: bool = True):
    raise NotImplementedError(
        "point-to-point send/recv: use ppermute inside shard_map")


def isend(tensor, dst: int, group: Optional[CommGroup] = None):
    raise NotImplementedError(
        "point-to-point isend/irecv: use ppermute inside shard_map "
        "(the PP schedule does) — per-process p2p does not exist "
        "under SPMD")


def irecv(tensor, src: int, group: Optional[CommGroup] = None):
    raise NotImplementedError(
        "point-to-point isend/irecv: use ppermute inside shard_map")


def wait(tensor, group=None, use_calc_stream: bool = True):
    """XLA collectives are synchronous at the python level — block on
    the value (reference parity for the sync path)."""
    val = _unwrap(tensor)
    if not _is_traced(val):
        jax.block_until_ready(val)
    return tensor


def all_to_all_single(out_tensor, in_tensor,
                      out_split_sizes=None, in_split_sizes=None,
                      group: Optional[CommGroup] = None,
                      sync_op: bool = True):
    """Single-array alltoall (equal splits; ragged splits are the
    ragged_all_to_all path in expert_parallel)."""
    enforce(out_split_sizes is None and in_split_sizes is None,
            "all_to_all_single supports equal splits; ragged exchange "
            "is distributed.expert_parallel's ragged_all_to_all")
    res = all_to_all(in_tensor, group=group, sync_op=sync_op)
    if hasattr(out_tensor, "_replace_from"):
        out_tensor._replace_from(res if isinstance(res, Tensor)
                                 else Tensor(res))
        return out_tensor
    return res


alltoall_single = all_to_all_single


def gather(tensor, gather_list=None, dst: int = 0,
           group: Optional[CommGroup] = None, sync_op: bool = True):
    """paddle.distributed.gather: dst receives every rank's tensor.
    Under single-program SPMD every controller holds the gathered
    list (a superset of the reference's contract)."""
    if gather_list is None:
        gather_list = []
    all_gather(gather_list, tensor, group)
    return gather_list


def destroy_process_group(group=None):
    """Tear down eager-collective state (the jax runtime itself stays
    up — the reference's NCCL communicator destruction has no XLA
    analog).  With a specific ``group``, only that group is
    deregistered; with None, ALL group state and caches drop so the
    next collective requires a fresh init."""
    global _DEFAULT_GROUP, _WORLD_PG
    if group is not None:
        _GROUPS.pop(id(group), None)
        if group is _DEFAULT_GROUP:
            _DEFAULT_GROUP = None
        return
    _GROUPS.clear()
    _DEFAULT_GROUP = None
    _WORLD_PG = None
    _CROSS_JITS.clear()
    _IDENTITY_WARNED.clear()


# -- object collectives (pickle over the array collectives) -----------------

def _obj_to_buf(obj):
    import pickle
    import numpy as np
    return np.frombuffer(pickle.dumps(obj), np.uint8)


def all_gather_object(object_list, obj, group=None):
    """Gather python objects: two array collectives (lengths, then
    max-padded pickle payloads)."""
    import pickle
    import numpy as np
    data = _obj_to_buf(obj)
    lens = []
    all_gather(lens, Tensor(jnp.asarray(
        np.asarray([len(data)], np.int32))), group)
    nlens = [int(np.asarray(_unwrap(v))[0]) for v in lens]
    pad = np.zeros(max(nlens), np.uint8)
    pad[:len(data)] = data
    bufs = []
    all_gather(bufs, Tensor(jnp.asarray(pad)), group)
    object_list.extend(
        pickle.loads(np.asarray(_unwrap(b))[:n].tobytes())
        for b, n in zip(bufs, nlens))
    return object_list


def broadcast_object_list(object_list, src: int = 0, group=None):
    """Broadcast a list of python objects from src (in place)."""
    import pickle
    import numpy as np
    data = _obj_to_buf(object_list)
    ln = broadcast(Tensor(jnp.asarray(
        np.asarray([len(data)], np.int32))), src, group)
    n = int(np.asarray(_unwrap(ln))[0])
    pad = np.zeros(max(n, len(data)), np.uint8)
    pad[:len(data)] = data
    out = broadcast(Tensor(jnp.asarray(pad[:n])), src, group)
    got = pickle.loads(np.asarray(_unwrap(out))[:n].tobytes())
    object_list[:] = got
    return object_list


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """Each rank receives in_object_list[its GROUP rank] from src —
    one broadcast of src's list (not an all-gather of every rank's)."""
    from . import env as _env
    if isinstance(group, ProcessSubsetGroup):
        enforce(group.rank_in_group(src) >= 0,
                f"scatter src {src} not in group {group.ranks}")
        my_in_group = group.rank_in_group(_env.get_rank())
        enforce(my_in_group >= 0,
                f"rank {_env.get_rank()} is not a member of group "
                f"{group.ranks}")
    else:
        my_in_group = _env.get_rank() if jax.process_count() > 1 else 0
    src_list = list(in_object_list) if in_object_list is not None else []
    broadcast_object_list(src_list, src=src, group=group)
    enforce(my_in_group < len(src_list),
            f"scatter_object_list needs one object per group rank: "
            f"got {len(src_list)} for rank {my_in_group}")
    out_object_list[:] = [src_list[my_in_group]]
    return out_object_list


class stream:
    """paddle.distributed.stream.* namespace parity (sync collectives)."""
    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    reduce_scatter = staticmethod(reduce_scatter)
    alltoall = staticmethod(all_to_all)
