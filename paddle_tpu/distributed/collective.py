"""Collective communication API.

Reference parity: paddle.distributed.communication (all_reduce/all_gather/
reduce_scatter/all_to_all/broadcast/send/recv + ReduceOp + new_group) over
the C++ ProcessGroup/NCCL stack (SURVEY.md §2.4).

TPU-native design: two layers —
  1. **In-mesh primitives** (the hot path): thin wrappers over
     ``jax.lax.psum / all_gather / psum_scatter / all_to_all / ppermute``
     taking a CommGroup/axis-name; usable inside ``shard_map`` regions.
     These are what PP schedules and ring attention use — XLA lowers them
     to ICI collectives.
  2. **Eager module functions** with paddle signatures.  Under a tracer
     they dispatch to (1).  On concrete global arrays the single-
     controller model means the tensor is already global: all_reduce is
     the identity on replicated values, all_gather/reduce_scatter/
     broadcast become resharding ops.  (The reference's per-process view
     does not exist under SPMD — documented mapping, SURVEY.md §2.4.)
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..common.errors import enforce
from ..tensor import Tensor, apply_op
from .topology import CommGroup

__all__ = ["ReduceOp", "all_reduce", "all_gather", "reduce_scatter",
           "all_to_all", "broadcast", "scatter", "reduce", "barrier",
           "new_group", "get_group", "send", "recv", "psum", "pmean",
           "pmax", "ppermute", "axis_index", "stream"]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


_GROUPS = {}
_DEFAULT_GROUP: Optional[CommGroup] = None


def _default_group() -> CommGroup:
    global _DEFAULT_GROUP
    if _DEFAULT_GROUP is None:
        from . import fleet
        hcg = fleet.get_hybrid_communicate_group()
        enforce(hcg is not None,
                "call paddle.distributed.fleet.init() (or init_parallel_env) "
                "before collectives")
        _DEFAULT_GROUP = hcg.get_data_parallel_group()
    return _DEFAULT_GROUP


def _set_default_group(g: CommGroup):
    global _DEFAULT_GROUP
    _DEFAULT_GROUP = g


def new_group(ranks: Optional[List[int]] = None, backend=None,
              axis: Optional[Union[str, Sequence[str]]] = None) -> CommGroup:
    """paddle.distributed.new_group.  On the mesh model a group is a mesh
    axis (pass ``axis=``); explicit rank lists are accepted only for the
    trivial all-ranks case."""
    from . import fleet
    hcg = fleet.get_hybrid_communicate_group()
    enforce(hcg is not None, "fleet.init() first")
    if axis is not None:
        g = CommGroup(hcg.mesh, tuple([axis] if isinstance(axis, str)
                                      else axis))
    else:
        g = hcg.get_check_parallel_group()
        if ranks is not None:
            from .env import get_world_size
            # "all ranks" in either unit: process count (paddle's
            # get_world_size idiom) or mesh device count
            all_ranks = (list(range(get_world_size())),
                         list(range(g.nranks)))
            if sorted(ranks) not in all_ranks:
                raise NotImplementedError(
                    f"new_group(ranks={ranks}): arbitrary rank subsets do "
                    "not map onto the SPMD mesh — pass axis='dp'/'mp'/... "
                    "to get the per-axis group instead")
    _GROUPS[id(g)] = g
    return g


def get_group(gid=None) -> CommGroup:
    return _default_group()


def _is_traced(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _unwrap(t):
    return t.value if isinstance(t, Tensor) else jnp.asarray(t)


# ---------------------------------------------------------------------------
# Layer 1: in-mesh primitives (shard_map bodies, Pallas loops)
# ---------------------------------------------------------------------------

def psum(x, group: Union[CommGroup, str]):
    axis = group.axis_name if isinstance(group, CommGroup) else group
    return lax.psum(x, axis)


def pmean(x, group: Union[CommGroup, str]):
    axis = group.axis_name if isinstance(group, CommGroup) else group
    return lax.pmean(x, axis)


def pmax(x, group: Union[CommGroup, str]):
    axis = group.axis_name if isinstance(group, CommGroup) else group
    return lax.pmax(x, axis)


def ppermute(x, group: Union[CommGroup, str], perm):
    axis = group.axis_name if isinstance(group, CommGroup) else group
    return lax.ppermute(x, axis, perm)


def axis_index(group: Union[CommGroup, str]):
    axis = group.axis_name if isinstance(group, CommGroup) else group
    return lax.axis_index(axis)


# ---------------------------------------------------------------------------
# Layer 2: paddle-shaped eager API
# ---------------------------------------------------------------------------

def all_reduce(tensor, op=ReduceOp.SUM, group: Optional[CommGroup] = None,
               sync_op: bool = True):
    group = group or _default_group()
    val = _unwrap(tensor)
    if _is_traced(val):
        if op == ReduceOp.PROD:
            # no lax.pprod: gather the axis and reduce locally
            gathered = lax.all_gather(val, group.axis_name)
            out = jnp.prod(gathered, axis=0)
        else:
            fns = {ReduceOp.SUM: lax.psum, ReduceOp.MAX: lax.pmax,
                   ReduceOp.MIN: lax.pmin, ReduceOp.AVG: lax.pmean}
            enforce(op in fns, f"unsupported ReduceOp {op!r}")
            out = fns[op](val, group.axis_name)
        return Tensor(out) if isinstance(tensor, Tensor) else out
    # concrete global array: already globally reduced under SPMD
    return tensor


def all_gather(tensor_or_list, tensor=None, group: Optional[CommGroup] = None,
               sync_op: bool = True):
    """Both signatures supported: paddle's
    ``all_gather(tensor_list, tensor)`` and functional
    ``out = all_gather(tensor)``."""
    group = group or _default_group()
    if isinstance(tensor_or_list, list) and tensor is not None:
        val = _unwrap(tensor)
        if _is_traced(val):
            out = lax.all_gather(val, group.axis_name)
            n = group.nranks
            tensor_or_list.extend(Tensor(out[i]) for i in range(n))
            return
        tensor_or_list.extend(Tensor(val) for _ in range(group.nranks))
        return
    val = _unwrap(tensor_or_list)
    if _is_traced(val):
        out = lax.all_gather(val, group.axis_name, tiled=True)
        return Tensor(out) if isinstance(tensor_or_list, Tensor) else out
    return tensor_or_list


def reduce_scatter(tensor, op=ReduceOp.SUM, group: Optional[CommGroup] = None,
                   sync_op: bool = True):
    group = group or _default_group()
    val = _unwrap(tensor)
    if _is_traced(val):
        out = lax.psum_scatter(val, group.axis_name, tiled=True)
        return Tensor(out) if isinstance(tensor, Tensor) else out
    return tensor


def all_to_all(out_tensor_list, in_tensor_list=None,
               group: Optional[CommGroup] = None, sync_op: bool = True):
    """Paddle list signature and functional array signature."""
    group = group or _default_group()
    if in_tensor_list is None:
        # functional: single stacked array, alltoall over leading dim
        val = _unwrap(out_tensor_list)
        if _is_traced(val):
            out = lax.all_to_all(val, group.axis_name, split_axis=0,
                                 concat_axis=0, tiled=True)
            return Tensor(out) if isinstance(out_tensor_list, Tensor) else out
        return out_tensor_list
    vals = [_unwrap(t) for t in in_tensor_list]
    if vals and _is_traced(vals[0]):
        stacked = jnp.stack(vals)
        out = lax.all_to_all(stacked, group.axis_name, split_axis=0,
                             concat_axis=0)
        out_tensor_list.extend(Tensor(out[i]) for i in range(out.shape[0]))
        return
    out_tensor_list.extend(Tensor(v) for v in vals)


alltoall = all_to_all


def broadcast(tensor, src: int = 0, group: Optional[CommGroup] = None,
              sync_op: bool = True):
    # SPMD: one logical value — broadcast is identity
    return tensor


def scatter(tensor, tensor_list=None, src: int = 0,
            group: Optional[CommGroup] = None, sync_op: bool = True):
    group = group or _default_group()
    if tensor_list is not None:
        return Tensor(_unwrap(tensor_list[0]))
    return tensor


def reduce(tensor, dst: int = 0, op=ReduceOp.SUM,
           group: Optional[CommGroup] = None, sync_op: bool = True):
    return all_reduce(tensor, op, group, sync_op)


def barrier(group: Optional[CommGroup] = None):
    jax.block_until_ready(jnp.zeros(()))


def send(tensor, dst: int, group: Optional[CommGroup] = None,
         sync_op: bool = True):
    raise NotImplementedError(
        "point-to-point send/recv: use ppermute inside shard_map (the PP "
        "schedule does) — per-process p2p does not exist under SPMD")


def recv(tensor, src: int, group: Optional[CommGroup] = None,
         sync_op: bool = True):
    raise NotImplementedError(
        "point-to-point send/recv: use ppermute inside shard_map")


class stream:
    """paddle.distributed.stream.* namespace parity (sync collectives)."""
    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    reduce_scatter = staticmethod(reduce_scatter)
    alltoall = staticmethod(all_to_all)
