"""Context parallelism: ring attention + Ulysses over the ``sep`` axis.

Reference parity: the reference's sequence/context-parallel stack —
fleet/base/topology.py ``sep`` comm group + communication/all_to_all
(Ulysses head<->seq reshard) and the PaddleNLP ring-flash-attention
recipes built on them (SURVEY.md §2.3 sep row, §5 long-context).

TPU-native design (both behind one ``sep_degree`` knob):

* **Ring attention** — inside ``shard_map`` manual over ``sep``, each
  device keeps its Q chunk resident and streams K/V chunks around the
  ring with ``lax.ppermute`` over ICI, merging per-chunk partial
  attention with the online-softmax (logsumexp) rule.  The ring is a
  *static* python loop (sep is a mesh constant), so each hop is one
  ppermute + one chunk-attention kernel; causally-dead hops are skipped
  per-device with ``lax.cond``.  Backward re-runs the ring with the
  saved global logsumexp: dK/dV accumulators travel WITH their K/V
  chunks and arrive home after a full cycle (the FlashAttention-2
  backward split generalized across devices).
* **Ulysses** — two ``lax.all_to_all``s reshard [B, S/n, H, D] ->
  [B, S, H/n, D]; full-sequence flash attention runs locally per head
  group, then the inverse all_to_all restores the seq-sharded layout.
  Differentiable end-to-end (all_to_all transposes to itself).

Chunk/local attention uses the Pallas flash kernel on TPU (forward
normalized-out + logsumexp) and a jnp oracle elsewhere — the merge and
ring logic are identical, so the CPU parity tests cover the TPU path's
structure.  Ring requires seq % sep == 0; Ulysses additionally needs
heads (incl. KV heads) % sep == 0 — ``sep_attention_raw`` picks
automatically (FLAGS_sep_impl overrides: ring | ulysses | auto).
"""
from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from ..compat import shard_map as _compat_shard_map
from ..compat import axis_size as _compat_axis_size

from ..common.flags import define_flag, get_flag

__all__ = ["ring_attention_local", "ulysses_attention_local",
           "sep_attention_raw"]

define_flag("sep_impl", "auto",
            "context-parallel attention impl: auto | ring | ulysses")

_NEG_INF = float(-jnp.inf)


def _use_flash() -> bool:
    from ..runtime.device import is_compiled_with_tpu
    return bool(get_flag("use_pallas")) and is_compiled_with_tpu()


def _flash_eligible(lq: int, lk: int, h: int, hk: int, d: int,
                    causal: bool) -> bool:
    if causal and lq != lk:
        return False
    return d in (64, 128, 256) and h % hk == 0 and lq % 8 == 0 \
        and lk % 8 == 0


# ---------------------------------------------------------------------------
# chunk attention: normalized out + logsumexp (flash on TPU, jnp oracle)
# ---------------------------------------------------------------------------

def _chunk_attn_jnp(q, k, v, causal: bool, q_off, k_off
                    ) -> Tuple[jax.Array, jax.Array]:
    """q [b,lq,h,d], k/v [b,lk,hk,d] -> (o [b,lq,h,d] f32 normalized,
    lse [b,h,lq] f32).  Offsets give global positions for causal masking
    (traced scalars are fine).  Fully-masked rows get o=0, lse=-inf."""
    b, lq, h, d = q.shape
    lk, hk = k.shape[1], k.shape[2]
    if hk != h:
        rep = h // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = q_off + jnp.arange(lq)
        kpos = k_off + jnp.arange(lk)
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)                                   # [b,h,lq]
    msafe = jnp.where(jnp.isneginf(m), 0.0, m)
    p = jnp.exp(s - msafe[..., None])                         # [b,h,lq,lk]
    if causal:
        p = jnp.where(jnp.isneginf(s), 0.0, p)
    l = jnp.sum(p, axis=-1)                                   # [b,h,lq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o = o / jnp.maximum(l, 1e-30)[..., None].swapaxes(1, 2)   # [b,lq,h,d]
    lse = jnp.where(l > 0, msafe + jnp.log(jnp.maximum(l, 1e-30)),
                    _NEG_INF)
    return o, lse


def _chunk_attn(q, k, v, causal: bool, q_off, k_off):
    """Dispatch: Pallas flash (TPU, static-eligible shapes) or jnp.
    The flash kernel path is only taken for offset patterns it encodes
    exactly: full (non-causal) chunks, or the diagonal chunk where
    q_off == k_off statically (ring step 0)."""
    b, lq, h, d = q.shape
    lk, hk = k.shape[1], k.shape[2]
    static_diag = (q_off is k_off)  # same traced value object => diagonal
    if _use_flash() and _flash_eligible(lq, lk, h, hk, d,
                                        causal and static_diag):
        if not causal or static_diag:
            from ..ops.pallas.flash_attention import _fwd, _pick_blocks
            bq, bk = _pick_blocks(lq, lk, d)
            o, lse = _fwd(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                          jnp.swapaxes(v, 1, 2),
                          causal=causal, bq=bq, bk=bk)
            return (jnp.swapaxes(o, 1, 2).astype(jnp.float32),
                    lse[..., 0])
    return _chunk_attn_jnp(q, k, v, causal, q_off, k_off)


def _merge(out, lse, o_i, lse_i):
    """Online-softmax merge of two normalized partials."""
    new_lse = jnp.logaddexp(lse, lse_i)
    w_prev = jnp.where(jnp.isneginf(new_lse), 0.0,
                       jnp.exp(lse - new_lse))
    w_new = jnp.where(jnp.isneginf(new_lse), 0.0,
                      jnp.exp(lse_i - new_lse))
    out = out * w_prev[..., None].swapaxes(1, 2) \
        + o_i * w_new[..., None].swapaxes(1, 2)
    return out, new_lse


def _ring_perm(n: int):
    return [(i, (i + 1) % n) for i in range(n)]


def _rotate(tree, axis_name: str, n: int):
    perm = _ring_perm(n)
    return jax.tree_util.tree_map(
        lambda x: lax.ppermute(x, axis_name, perm), tree)


# ---------------------------------------------------------------------------
# ring attention (manual over `axis_name`), ring-level custom vjp
# ---------------------------------------------------------------------------

def _ring_fwd_impl(q, k, v, axis_name: str, causal: bool):
    n = _compat_axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, lq, h, d = q.shape
    lk = k.shape[1]
    q_off = idx * lq
    out = jnp.zeros((b, lq, h, d), jnp.float32)
    lse = jnp.full((b, h, lq), _NEG_INF, jnp.float32)
    k_cur, v_cur = k, v
    for r in range(n):
        # chunk j = (idx - r) mod n is visiting; causal skips j > idx
        j = (idx - r) % n
        k_off = j * lk
        if r == 0:
            o_i, lse_i = _chunk_attn(q, k_cur, v_cur, causal, q_off, q_off)
            out, lse = _merge(out, lse, o_i, lse_i)
        else:
            def compute(args, k_off=k_off):
                kc, vc = args
                o_i, lse_i = _chunk_attn(q, kc, vc, False, q_off, k_off)
                return _merge(out, lse, o_i, lse_i)

            def skip(args):
                return out, lse

            if causal:
                out, lse = lax.cond(idx >= r, compute, skip, (k_cur, v_cur))
            else:
                out, lse = compute((k_cur, v_cur))
        if r != n - 1:
            k_cur, v_cur = _rotate((k_cur, v_cur), axis_name, n)
    return out.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def ring_attention_local(q, k, v, axis_name: str, causal: bool = True):
    """Local-chunk ring attention; call inside shard_map manual over
    ``axis_name``.  q [b, s/n, h, d]; k/v [b, s/n, hk, d] (GQA ok)."""
    out, _ = _ring_fwd_impl(q, k, v, axis_name, causal)
    return out


def _ring_fwd_rule(q, k, v, axis_name, causal):
    out, lse = _ring_fwd_impl(q, k, v, axis_name, causal)
    return out, (q, k, v, out, lse)


def _chunk_bwd_jnp(q, kc, vc, out, lse, do, causal, q_off, k_off,
                   delta=None):
    """Per-(Q-chunk, KV-chunk) backward with GLOBAL out/lse statistics
    — the FlashAttention-2 backward split, as f32 einsums (the CPU
    oracle; materializes the dense [b,h,lq,lk] score block).
    ``delta`` = precomputed rowsum(dO*O) [b,h,lq] f32 (hoisted out of
    the ring loop by the caller)."""
    b, lq, h, d = q.shape
    lk, hk = kc.shape[1], kc.shape[2]
    group = h // hk
    scale = 1.0 / math.sqrt(d)
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    if delta is None:
        delta = jnp.einsum("bqhd,bqhd->bhq", dof,
                           out.astype(jnp.float32))

    def repeat_kv(x):
        return jnp.repeat(x, group, axis=2) if group > 1 else x

    kcf = repeat_kv(kc.astype(jnp.float32))
    vcf = repeat_kv(vc.astype(jnp.float32))
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kcf,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = q_off + jnp.arange(lq)
        kpos = k_off + jnp.arange(lk)
        mask = (qpos[:, None] >= kpos[None, :])[None, None]
        s = jnp.where(mask, s, _NEG_INF)
    # p from the saved GLOBAL lse (rows with lse=-inf have no mass)
    lse_safe = jnp.where(jnp.isneginf(lse), 0.0, lse)
    p = jnp.exp(s - lse_safe[..., None])
    p = jnp.where(jnp.isneginf(s) | jnp.isneginf(lse)[..., None],
                  0.0, p)                                  # [b,h,q,k]
    dv_j = jnp.einsum("bhqk,bqhd->bkhd", p, dof)
    dp = jnp.einsum("bqhd,bkhd->bhqk", dof, vcf)
    ds = p * (dp - delta[..., None])
    dq_i = jnp.einsum("bhqk,bkhd->bqhd", ds, kcf) * scale
    dk_j = jnp.einsum("bhqk,bqhd->bkhd", ds, qf) * scale
    if group > 1:
        dk_j = dk_j.reshape(b, lk, hk, group, d).sum(axis=3)
        dv_j = dv_j.reshape(b, lk, hk, group, d).sum(axis=3)
    return dq_i, dk_j, dv_j


def _chunk_bwd(q, kc, vc, out, lse, do, diag: bool, q_off, k_off,
               delta=None):
    """Chunk-pair backward dispatch: the Pallas flash dq/dkv kernels on
    TPU (``diag`` = the causal diagonal block, else a full block with
    global statistics — O(lq·d) memory, never the dense score matrix),
    jnp einsums elsewhere.  Mirrors _chunk_attn's forward dispatch —
    round-5 closes VERDICT r4 Missing #4 (the cp backward used to pay
    the O(chunk²) f32 scores flash exists to avoid)."""
    b, lq, h, d = q.shape
    lk, hk = kc.shape[1], kc.shape[2]
    if _use_flash() and _flash_eligible(lq, lk, h, hk, d, diag):
        from ..ops.pallas.flash_attention import _bwd_impl, _pick_blocks
        bq, bk = _pick_blocks(lq, lk, d)
        lse8 = jnp.broadcast_to(lse[..., None], lse.shape + (8,))
        # f32 kernel outputs: the ring accumulates partials across
        # hops, so per-hop bf16 quantization would compound with sep
        dq_i, dk_j, dv_j = _bwd_impl(
            jnp.swapaxes(q, 1, 2), jnp.swapaxes(kc, 1, 2),
            jnp.swapaxes(vc, 1, 2), jnp.swapaxes(out, 1, 2), lse8,
            jnp.swapaxes(do, 1, 2), causal=diag, bq=bq, bk=bk,
            delta=delta, out_dtype=jnp.float32)
        return (jnp.swapaxes(dq_i, 1, 2), jnp.swapaxes(dk_j, 1, 2),
                jnp.swapaxes(dv_j, 1, 2))
    return _chunk_bwd_jnp(q, kc, vc, out, lse, do, diag, q_off, k_off,
                          delta)


def _ring_bwd_rule(axis_name, causal, res, do):
    q, k, v, out, lse = res
    n = _compat_axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, lq, h, d = q.shape
    lk, hk = k.shape[1], k.shape[2]
    q_off = idx * lq
    # delta = rowsum(dO*O) is hop-independent: compute once per ring
    delta = jnp.einsum("bqhd,bqhd->bhq", do.astype(jnp.float32),
                       out.astype(jnp.float32))

    def chunk_grads(kc, vc, k_off, diag=False):
        # off-diagonal hops run only when fully visible (idx >= r), so
        # they are FULL blocks (diag=False, no mask) — exactly the
        # pattern the flash backward kernels encode
        return _chunk_bwd(q, kc, vc, out, lse, do, diag and causal,
                          q_off, k_off, delta)

    dq = jnp.zeros((b, lq, h, d), jnp.float32)
    dk_acc = jnp.zeros((b, lk, hk, d), jnp.float32)
    dv_acc = jnp.zeros((b, lk, hk, d), jnp.float32)
    k_cur, v_cur = k, v
    for r in range(n):
        j = (idx - r) % n
        k_off = j * lk
        if r == 0:
            dq_i, dk_j, dv_j = chunk_grads(k_cur, v_cur, q_off,
                                           diag=True)
            dq = dq + dq_i
            dk_acc = dk_acc + dk_j
            dv_acc = dv_acc + dv_j
        else:
            def compute(args, k_off=k_off):
                kc, vc, dka, dva = args
                dq_i, dk_j, dv_j = chunk_grads(kc, vc, k_off)
                return dq + dq_i, dka + dk_j, dva + dv_j

            def skip(args):
                _, _, dka, dva = args
                return dq, dka, dva

            if causal:
                dq, dk_acc, dv_acc = lax.cond(
                    idx >= r, compute, skip, (k_cur, v_cur, dk_acc, dv_acc))
            else:
                dq, dk_acc, dv_acc = compute((k_cur, v_cur, dk_acc, dv_acc))
        # rotate K/V together with their traveling grad accumulators;
        # after the final hop each chunk's (dk, dv) is back home.  The
        # last hop ships only the accumulators — K/V are not consumed
        # again, and they dominate the hop payload for long context.
        if r != n - 1:
            k_cur, v_cur, dk_acc, dv_acc = _rotate(
                (k_cur, v_cur, dk_acc, dv_acc), axis_name, n)
        else:
            dk_acc, dv_acc = _rotate((dk_acc, dv_acc), axis_name, n)
    return (dq.astype(q.dtype), dk_acc.astype(k.dtype),
            dv_acc.astype(v.dtype))


ring_attention_local.defvjp(_ring_fwd_rule, _ring_bwd_rule)


# ---------------------------------------------------------------------------
# Ulysses (all_to_all heads<->seq), AD-native
# ---------------------------------------------------------------------------

def _local_full_attention(q, k, v, causal: bool):
    """Full-sequence attention on local arrays (flash on TPU, oracle
    elsewhere) — used after the Ulysses reshard."""
    b, s, h, d = q.shape
    hk = k.shape[2]
    if _use_flash() and _flash_eligible(s, k.shape[1], h, hk, d, causal):
        from ..ops.pallas.flash_attention import flash_attention_raw
        try:
            return flash_attention_raw(q, k, v, causal=causal)
        except NotImplementedError:
            pass
    from ..ops import _nn
    return _nn.scaled_dot_product_attention(q, k, v, is_causal=causal)


def ulysses_attention_local(q, k, v, axis_name: str, causal: bool = True):
    """Ulysses context parallelism; call inside shard_map manual over
    ``axis_name``.  q [b, s/n, h, d] with h % n == 0 (same for KV heads):
    all_to_all to [b, s, h/n, d], attend, all_to_all back."""
    qh = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1,
                        tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1,
                        tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1,
                        tiled=True)
    o = _local_full_attention(qh, kh, vh, causal)
    return lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


# ---------------------------------------------------------------------------
# global entry: shard_map wrapper over the hybrid mesh
# ---------------------------------------------------------------------------

def sep_attention_raw(q, k, v, causal: bool = True,
                      impl: Optional[str] = None, mesh=None):
    """Context-parallel attention on GLOBAL [B, S, H, D] arrays.

    Wraps ring/ulysses in ``shard_map`` manual over (batch axes, sep,
    mp-if-divisible); remaining mesh axes stay automatic.  Raises
    NotImplementedError when no sep axis is active or shapes don't
    divide — callers fall back to plain attention.
    """
    if mesh is None:
        from .auto_parallel import get_mesh
        pm = get_mesh()
        mesh = pm.mesh if pm is not None else None
    if mesh is None:
        raise NotImplementedError("no mesh — sep attention inactive")
    sep = mesh.shape.get("sep", 1)
    if sep <= 1:
        raise NotImplementedError("sep degree is 1")
    b, s, h, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    if s != sk:
        raise NotImplementedError("sep attention needs sq == sk "
                                  "(no KV-cache decode)")
    if s % sep:
        raise NotImplementedError(f"seq {s} not divisible by sep {sep}")

    batch_axes = tuple(a for a in ("dp", "sharding")
                       if mesh.shape.get(a, 1) > 1)
    if batch_axes and b % math.prod(mesh.shape[a] for a in batch_axes):
        batch_axes = ()
    mp = mesh.shape.get("mp", 1)
    use_mp = mp > 1 and h % mp == 0 and hk % mp == 0
    h_loc = h // mp if use_mp else h
    hk_loc = hk // mp if use_mp else hk

    if impl is None:
        impl = str(get_flag("sep_impl"))
    if impl == "auto":
        impl = "ulysses" if (h_loc % sep == 0 and hk_loc % sep == 0) \
            else "ring"
    if impl == "ulysses" and (h_loc % sep or hk_loc % sep):
        raise NotImplementedError(
            f"ulysses needs heads divisible by sep ({h_loc}/{hk_loc} "
            f"vs {sep})")

    manual = frozenset({"sep", *batch_axes,
                        *({"mp"} if use_mp else set())})
    bspec = batch_axes if batch_axes else None
    hspec = "mp" if use_mp else None
    spec = P(bspec, "sep", hspec, None)

    return _mapped(mesh, impl, causal, manual, spec)(q, k, v)


@functools.lru_cache(maxsize=64)
def _mapped(mesh, impl: str, causal: bool, manual: frozenset, spec):
    fn = {"ring": ring_attention_local,
          "ulysses": ulysses_attention_local}[impl]
    body = functools.partial(fn, axis_name="sep", causal=causal)
    mapped = _compat_shard_map(
        lambda q_, k_, v_: body(q_, k_, v_),
        mesh=mesh, axis_names=manual,
        in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
    # partial-manual shard_map only lowers under jit; this wrapper inlines
    # under an outer jit and makes eager calls (incl. jax.vjp tracing from
    # the eager-autograd tape) work with one cached compile
    return jax.jit(mapped)
