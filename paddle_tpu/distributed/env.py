"""Distributed environment facts.

Reference parity: paddle.distributed rank/world-size env (PADDLE_TRAINER_ID
/ PADDLE_TRAINERS_NUM set by launch).  On TPU: jax process index/count
(multi-host via jax.distributed) with the PADDLE_* env vars honored for
launch-tool compatibility.
"""
from __future__ import annotations

import os

import jax

_PARALLEL_ENV_READY = False


def init_parallel_env() -> bool:
    """paddle.distributed.init_parallel_env parity: join the multi-host
    runtime when the launch env is present.

    The launch controller (distributed/launch) seeds PADDLE_MASTER /
    PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM; this calls
    ``jax.distributed.initialize`` (jax's coordination service = the
    reference's TCPStore rendezvous, SURVEY.md §2.4) so every process
    sees the GLOBAL device set and one mesh spans all hosts.  No-op
    when single-process or already initialized.  Must run before first
    device use.  Returns True when a multi-process runtime is active.
    """
    global _PARALLEL_ENV_READY
    if _PARALLEL_ENV_READY:
        return True    # latched only after an actual initialize()
    n = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    master = os.environ.get("PADDLE_MASTER")
    if n > 1 and master:
        jax.distributed.initialize(
            coordinator_address=master, num_processes=n,
            process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
        _PARALLEL_ENV_READY = True
        return True
    # no launch env (single process, or a TPU pod slice where jax will
    # discover topology itself): not a joined runtime — do NOT latch,
    # so a later call made after the env is seeded can still join
    return False


def get_rank() -> int:
    if "PADDLE_TRAINER_ID" in os.environ:
        return int(os.environ["PADDLE_TRAINER_ID"])
    try:
        return jax.process_index()
    except RuntimeError:
        return 0


def get_world_size() -> int:
    if "PADDLE_TRAINERS_NUM" in os.environ:
        return int(os.environ["PADDLE_TRAINERS_NUM"])
    try:
        return jax.process_count()
    except RuntimeError:
        return 1
