"""Distributed environment facts.

Reference parity: paddle.distributed rank/world-size env (PADDLE_TRAINER_ID
/ PADDLE_TRAINERS_NUM set by launch).  On TPU: jax process index/count
(multi-host via jax.distributed) with the PADDLE_* env vars honored for
launch-tool compatibility.
"""
from __future__ import annotations

import os

import jax


def get_rank() -> int:
    if "PADDLE_TRAINER_ID" in os.environ:
        return int(os.environ["PADDLE_TRAINER_ID"])
    try:
        return jax.process_index()
    except RuntimeError:
        return 0


def get_world_size() -> int:
    if "PADDLE_TRAINERS_NUM" in os.environ:
        return int(os.environ["PADDLE_TRAINERS_NUM"])
    try:
        return jax.process_count()
    except RuntimeError:
        return 1
