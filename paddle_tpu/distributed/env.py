"""Distributed environment facts.

Reference parity: paddle.distributed rank/world-size env (PADDLE_TRAINER_ID
/ PADDLE_TRAINERS_NUM set by launch).  On TPU: jax process index/count
(multi-host via jax.distributed) with the PADDLE_* env vars honored for
launch-tool compatibility.
"""
from __future__ import annotations

import os

import jax

_PARALLEL_ENV_READY = False


def init_parallel_env() -> bool:
    """paddle.distributed.init_parallel_env parity: join the multi-host
    runtime when the launch env is present.

    The launch controller (distributed/launch) seeds PADDLE_MASTER /
    PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM; this calls
    ``jax.distributed.initialize`` (jax's coordination service = the
    reference's TCPStore rendezvous, SURVEY.md §2.4) so every process
    sees the GLOBAL device set and one mesh spans all hosts.  No-op
    when single-process or already initialized.  Must run before first
    device use.  Returns True when a multi-process runtime is active.
    """
    global _PARALLEL_ENV_READY
    if _PARALLEL_ENV_READY:
        return True    # latched only after an actual initialize()
    n = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    master = os.environ.get("PADDLE_MASTER")
    if n > 1 and master:
        jax.distributed.initialize(
            coordinator_address=master, num_processes=n,
            process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
        _PARALLEL_ENV_READY = True
        return True
    # no launch env (single process, or a TPU pod slice where jax will
    # discover topology itself): not a joined runtime — do NOT latch,
    # so a later call made after the env is seeded can still join
    return False


def get_rank() -> int:
    if "PADDLE_TRAINER_ID" in os.environ:
        return int(os.environ["PADDLE_TRAINER_ID"])
    try:
        return jax.process_index()
    except RuntimeError:
        return 0


def get_world_size() -> int:
    if "PADDLE_TRAINERS_NUM" in os.environ:
        return int(os.environ["PADDLE_TRAINERS_NUM"])
    try:
        return jax.process_count()
    except RuntimeError:
        return 1


class ParallelEnv:
    """paddle.distributed.ParallelEnv parity: rank/world-size view of
    the launch env."""

    @property
    def rank(self):
        return get_rank()

    @property
    def local_rank(self):
        # per-NODE rank when the launch controller exported it
        if "PADDLE_LOCAL_RANK" in os.environ:
            return int(os.environ["PADDLE_LOCAL_RANK"])
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    nranks = world_size

    @property
    def device_id(self):
        return int(os.environ.get("FLAGS_selected_tpus",
                                  os.environ.get("FLAGS_selected_gpus",
                                                 "0")).split(",")[0])

    @property
    def current_endpoint(self):
        eps = self.trainer_endpoints
        r = get_rank()
        return eps[r] if r < len(eps) else ""

    @property
    def trainer_endpoints(self):
        return [e for e in os.environ.get(
            "PADDLE_TRAINER_ENDPOINTS", "").split(",") if e]


def _spawn_worker(fn, rank, nprocs, master, args):
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    os.environ["PADDLE_MASTER"] = master
    fn(*args)


def spawn(func, args=(), nprocs=1, join=True, daemon=False, **options):
    """paddle.distributed.spawn: run ``func`` in ``nprocs`` processes
    with the launch env seeded (each worker's init_parallel_env joins
    one jax.distributed runtime — the TCPStore-rendezvous analog)."""
    import multiprocessing as mp
    import socket

    if nprocs <= 1:
        func(*args)
        return None
    master = options.get("master")
    holder = None
    if master is None:
        # hold the port until just before the workers launch to shrink
        # the reuse race; pass options['master'] to eliminate it
        holder = socket.socket()
        holder.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        holder.bind(("127.0.0.1", 0))
        master = f"127.0.0.1:{holder.getsockname()[1]}"
    ctx = mp.get_context("spawn")
    procs = [ctx.Process(target=_spawn_worker,
                         args=(func, r, nprocs, master, args),
                         daemon=daemon)
             for r in range(nprocs)]
    if holder is not None:
        holder.close()
    for p in procs:
        p.start()
    ctx_obj = MultiprocessContext(procs)
    if join:
        ctx_obj.join()
        return None
    return ctx_obj


class MultiprocessContext:
    """paddle.distributed.spawn(join=False) return value: .join() with
    exit-code propagation, .processes list."""

    def __init__(self, processes):
        self.processes = list(processes)

    def join(self, timeout=None):
        for p in self.processes:
            p.join(timeout)
        # a worker still alive after the timeout has exitcode None,
        # which the truthiness check below would read as success
        # (ADVICE r5 finding 4) — treat it as a timeout failure
        hung = [p.pid for p in self.processes if p.is_alive()]
        if hung:
            raise RuntimeError(
                f"spawn worker(s) still alive after join"
                f"(timeout={timeout}): pids {hung}")
        bad = [p.exitcode for p in self.processes if p.exitcode]
        if bad:
            raise RuntimeError(f"spawn worker(s) failed: {bad}")
        return True
