"""Expert-parallel dropless MoE: ragged all-to-all token exchange +
per-shard Pallas grouped matmul.

Reference parity: the reference runs its fused MoE kernels and the EP
all-to-all *together* — incubate moe_layer's alltoall dispatch feeding
the phi/kernels/fusion grouped expert GEMMs (SURVEY.md §2.3 EP row).
Round-3 of this build had the two halves separately; round-4 composed
them with a capacity-PADDED ``lax.all_to_all`` (each peer chunk padded
to a fixed per-peer capacity, overflow beyond it silently dropped).

Round-5 design (this file): the exchange is **ragged** —
``jax.lax.ragged_all_to_all`` moves exactly the routed rows, no padded
payload.  Per shard:

1. route local tokens (router weights replicated; the aux loss is
   reassembled EXACTLY from fold-``pmean``'d per-shard means, so it
   equals the dense path's global aux),
2. sort the (token, expert) slots by owner shard — the sorted rows ARE
   the send buffer (no per-peer padding slots),
3. all-gather the tiny per-peer count vector into the global count
   matrix ``C`` (n² ints over ICI), from which every shard derives the
   same exchange plan: send offsets/sizes, each chunk's landing offset
   in its receiver's buffer, and — when a receive bound ``R`` is set —
   the clamped matrix ``C_eff`` (sender-order prefix of each receiver
   column),
4. exchange rows + expert ids with ``ragged_all_to_all`` (rides ICI;
   payload = actual routed rows, not capacity padding),
5. run the dropless grouped-matmul SwiGLU on the received rows against
   the LOCAL expert shard (ops/pallas/grouped_matmul.py
   ``dropless_moe_ffn_rows``; Megatron row-parallel ``psum`` over
   ``mp`` when the FFN dim is tensor-sharded),
6. reverse-exchange the rows (transposed plan, landing back at each
   sender's unclamped chunk starts — undelivered slots stay zero and
   contribute nothing to the combine), and combine with the local
   top-k gates.

Capacity semantics (better than round-4's): ``capacity_factor`` bounds
each shard's TOTAL receive buffer at ``factor * s`` rows (``s`` = local
slots), not each per-peer chunk — drops happen only when a shard's
total routed load exceeds ``factor``× balanced, never because one
peer's chunk is skewed.  ``capacity_factor=None`` sizes the buffer at
the full global slot count: **zero drops at any router skew** (XLA
shapes are static, so strict droplessness must still allocate the
worst case — but the ragged exchange only ever MOVES the actual rows,
and the drop count is exact and observable either way; see
``return_drops`` and ``FLAGS_moe_log_drops``).

XLA:CPU has no ragged-all-to-all thunk (verified: "HLO opcode
`ragged-all-to-all` is not supported by XLA:CPU ThunkEmitter"), so on
CPU meshes (the 8-virtual-device test/dryrun platform) the SAME plan
drives a gather-based emulation with identical semantics; the real
primitive lowers on TPU.  ``tests/test_moe.py`` additionally checks
the plan algebra against a numpy model of the primitive's documented
contract, so the TPU path's offsets are covered without multi-chip
hardware.
"""
from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P
from ..compat import shard_map as _compat_shard_map
from ..compat import axis_size as _compat_axis_size

__all__ = ["moe_grouped_ep_raw", "expert_fold_axes",
           "ep_grouped_compatible", "EP_FOLD", "exchange_plan"]

# single source of the expert-dim fold order (this module loads lazily
# from MoELayer.forward, after nn.moe is fully imported)
from ..nn.moe import EP_AXES as EP_FOLD  # noqa: E402


def expert_fold_axes(mesh) -> Tuple[str, ...]:
    """Mesh axes (>1) the expert dim folds over, in fold order."""
    return tuple(a for a in EP_FOLD if mesh.shape.get(a, 1) > 1)


def ep_grouped_compatible(mesh, num_experts: int,
                          num_tokens: int) -> bool:
    """True when the grouped EP path can run: an active expert fold
    whose size divides both the expert count and the token count.  The
    ONE divisibility predicate shared by MoELayer._resolve_dispatch and
    the dryrun's forced-mode gate."""
    fold = expert_fold_axes(mesh)
    if not fold:
        return False
    n = int(np.prod([mesh.shape[a] for a in fold]))
    return n > 1 and num_experts % n == 0 and num_tokens % n == 0


def _fused_index(fold: Tuple[str, ...]):
    """Row-major linear index over the fold axes — matches both the
    PartitionSpec fold ordering and tuple-axis collectives.  The ONE
    source of the fused shard index (plan rows/columns and the
    emulation's buffer selection must agree on it)."""
    me = jnp.int32(0)
    for a in fold:
        me = me * _compat_axis_size(a) + lax.axis_index(a)
    return me


# ---------------------------------------------------------------------------
# Exchange plan: every shard derives the SAME plan from the global count
# matrix, so sender-side and receiver-side views always agree.
# ---------------------------------------------------------------------------

def exchange_plan(C, R: int):
    """From the global count matrix ``C`` ([n, n] int32, ``C[j, i]`` =
    rows shard j routes to shard i) and the receive bound ``R``, derive
    the clamped matrix ``C_eff`` (each receiver column keeps the
    sender-order prefix that fits in ``R``) and both directions' offset
    vectors, as functions of the caller's shard index ``me``:

    forward (tokens -> expert shards), for shard ``me``:
      - ``in_off[i]``   start of peer i's chunk in my sorted send rows
                        (UNCLAMPED cumsum — that is where the rows sit)
      - ``send_sz[i]``  rows actually delivered to peer i (clamped)
      - ``out_off[i]``  where my chunk starts in peer i's buffer
                        (= sum of earlier senders' delivered rows)
      - ``recv_sz[j]``  rows I receive from peer j

    reverse (processed rows -> back to their senders) is the transpose:
    chunk starts on the return side are the UNCLAMPED ``in_off`` of the
    original sender, so undelivered slots stay at the buffer fill.
    """
    n = C.shape[0]
    C = C.astype(jnp.int32)
    # receiver-column prefix clamp: sender j's chunk for receiver i is
    # cut to what fits after senders < j
    recv_cum = jnp.cumsum(C, axis=0) - C            # [n, n] excl. over j
    C_eff = jnp.clip(jnp.int32(R) - recv_cum, 0, C)
    send_start = jnp.cumsum(C, axis=1) - C          # [n, n] excl. over i
    out_start = jnp.cumsum(C_eff, axis=0) - C_eff   # [n, n] excl. over j
    return C_eff, send_start, out_start


def _ragged_a2a(operand, out_buf, in_off, send_sz, out_off, recv_sz,
                fold, use_primitive: bool):
    """One ragged exchange.  ``use_primitive`` lowers to the XLA
    ragged-all-to-all (TPU); otherwise an all-gather + gather emulation
    with identical semantics runs (XLA:CPU lacks the thunk).  Chunks
    may be non-contiguous in ``out_buf`` (reverse direction lands at
    unclamped starts); positions no chunk covers keep ``out_buf``'s
    fill values."""
    if use_primitive:
        return lax.ragged_all_to_all(
            operand, out_buf, in_off.astype(jnp.int32),
            send_sz.astype(jnp.int32), out_off.astype(jnp.int32),
            recv_sz.astype(jnp.int32), axis_name=fold)
    g_op = lax.all_gather(operand, fold)            # [n, S, ...]
    g_in = lax.all_gather(in_off, fold)             # [n, n]
    g_out = lax.all_gather(out_off, fold)           # [n, n]
    g_send = lax.all_gather(send_sz, fold)          # [n, n]
    # my column index == my fused index (row-major over fold — the same
    # ordering tuple-axis all_gather concatenates in)
    idx = _fused_index(fold)
    # receiver view of sender j's chunk for me: starts at g_out[j, idx]
    # locally, at g_in[j, idx] in j's buffer, size g_send[j, idx]
    starts = g_out[:, idx]                          # [n] chunk starts here
    sizes_ = g_send[:, idx]                         # [n] chunk sizes
    srcs = g_in[:, idx]                             # [n] starts at sender
    r = jnp.arange(out_buf.shape[0])
    # last chunk starting at or before r (zero-size chunks share starts
    # with their successor; 'right' picks the covering one)
    j_of_r = jnp.searchsorted(starts, r, side="right") - 1
    j_of_r = jnp.clip(j_of_r, 0, starts.shape[0] - 1)
    within = r - starts[j_of_r]
    valid = (within >= 0) & (within < sizes_[j_of_r])
    src_row = jnp.clip(srcs[j_of_r] + within, 0, operand.shape[0] - 1)
    picked = g_op[j_of_r, src_row]
    mask = valid.reshape((-1,) + (1,) * (operand.ndim - 1))
    return jnp.where(mask, picked, out_buf)


def _ep_local(x, router_w, wg, wu, wd, *, fold, sizes, k, balance_coef,
              z_coef, norm_topk, tm, interpret, recv_rows, use_mp,
              use_primitive):
    """Per-shard body (manual over ``fold`` + optionally ``mp``).
    x [T_l, H] local tokens; wg/wu [E_l, H, F(/mp)], wd [E_l, F(/mp), H]
    local experts.  Returns (out [T_l, H], aux scalar, dropped rows)."""
    from ..nn.moe import _assemble_aux, _router_parts
    from ..ops.pallas.grouped_matmul import dropless_moe_ffn_rows

    n = int(np.prod(sizes))
    e_l = wg.shape[0]
    t_l, h = x.shape
    me = _fused_index(fold)

    gate_vals, expert_idx, density, proxy, zsq = _router_parts(
        x, router_w, k=k, norm_topk=norm_topk)
    # exact global aux: per-shard token means pmean'd over the fold
    density = lax.pmean(density, fold)
    proxy = lax.pmean(proxy, fold)
    zsq = lax.pmean(zsq, fold)
    aux = _assemble_aux(density, proxy, zsq, balance_coef=balance_coef,
                        z_coef=z_coef)

    s = t_l * k
    flat_e = expert_idx.reshape(s)
    dshard = flat_e // e_l                              # owner shard
    order = jnp.argsort(dshard, stable=True)
    counts = jnp.bincount(dshard, length=n)

    # the sorted rows ARE the send buffer — no per-peer padding slots
    rows = x[order // k]                                # [s, H]
    ids = flat_e[order]                                 # [s]

    C = lax.all_gather(counts, fold)                    # [n, n]
    C_eff, send_start, out_start = exchange_plan(C, recv_rows)
    in_off = send_start[me]
    send_sz = C_eff[me]
    out_off = out_start[me]
    recv_sz = C_eff[:, me]

    recv_x = _ragged_a2a(rows, jnp.zeros((recv_rows, h), x.dtype),
                         in_off, send_sz, out_off, recv_sz, fold,
                         use_primitive)
    recv_e = _ragged_a2a(ids, jnp.full((recv_rows,), -1, ids.dtype),
                         in_off, send_sz, out_off, recv_sz, fold,
                         use_primitive)

    # ids < 0 mark empty buffer rows -> local id e_l (zero output)
    loc_e = jnp.where(recv_e >= 0, recv_e - me * e_l, e_l)
    y = dropless_moe_ffn_rows(recv_x, loc_e, wg, wu, wd, tm=tm,
                              interpret=interpret)
    if use_mp:
        y = lax.psum(y, "mp")                           # row-parallel F

    # reverse exchange: transposed plan; undelivered slots stay zero
    y_back = _ragged_a2a(y, jnp.zeros((s, h), y.dtype),
                         out_start[:, me], C_eff[:, me],
                         send_start[:, me], C_eff[me], fold,
                         use_primitive)
    y_flat = jnp.zeros((s, h), y_back.dtype).at[order].set(y_back)
    out = jnp.einsum("tk,tkh->th", gate_vals,
                     y_flat.reshape(t_l, k, h).astype(jnp.float32))
    dropped = jnp.sum(C) - jnp.sum(C_eff)               # exact, global
    return out.astype(x.dtype), aux, dropped


@functools.lru_cache(maxsize=64)
def _mapped_ep(mesh, fold, use_mp, k, balance_coef, z_coef, norm_topk,
               tm, interpret, recv_rows):
    sizes = tuple(mesh.shape[a] for a in fold)
    use_primitive = mesh.devices.flat[0].platform == "tpu"
    body = functools.partial(
        _ep_local, fold=fold, sizes=sizes, k=k,
        balance_coef=balance_coef, z_coef=z_coef, norm_topk=norm_topk,
        tm=tm, interpret=interpret, recv_rows=recv_rows, use_mp=use_mp,
        use_primitive=use_primitive)
    mp = "mp" if use_mp else None
    x_spec = P(fold, None)
    specs = (x_spec, P(None, None), P(fold, None, mp),
             P(fold, None, mp), P(fold, mp, None))
    mapped = _compat_shard_map(
        body, mesh=mesh, axis_names=frozenset(fold) | (
            {"mp"} if use_mp else set()),
        in_specs=specs, out_specs=(x_spec, P(), P()), check_vma=False)
    # partial-manual shard_map only lowers under jit; the jit wrapper
    # inlines under an outer jit and caches the eager compile
    return jax.jit(mapped)


def moe_grouped_ep_raw(x, router_w, wg, wu, wd, *, k, balance_coef,
                       z_coef, norm_topk, tm, interpret, mesh,
                       capacity_factor: Optional[float] = 2.0,
                       return_drops: bool = False):
    """Grouped MoE over GLOBAL arrays: x [T, H], router_w [H, E],
    wg/wu [E, H, F], wd [E, F, H] -> (out [T, H], aux[, dropped]).

    ``capacity_factor`` bounds each shard's TOTAL receive buffer at
    ``factor * s`` rows (s = local slots = T/n * k); drops happen only
    when a shard's whole routed load exceeds that — never from one
    skewed peer chunk.  ``None`` sizes the buffer at the global slot
    count: strictly dropless at any skew.  Either way the exchange
    payload is ragged (actual rows only) and ``dropped`` (returned when
    ``return_drops``; also see ``FLAGS_moe_log_drops``) counts exactly
    the rows the bound cut.

    Callers must pre-check :func:`ep_grouped_compatible` (MoELayer's
    dispatch resolution does); the NotImplementedErrors below are the
    backstop for direct raw-level misuse.
    """
    fold = expert_fold_axes(mesh)
    if not fold:
        raise NotImplementedError("no expert-parallel fold axis > 1")
    n = int(np.prod([mesh.shape[a] for a in fold]))
    t, _ = x.shape
    e = wg.shape[0]
    if e % n:
        raise NotImplementedError(f"{e} experts not divisible by "
                                  f"expert fold {n}")
    if t % n:
        raise NotImplementedError(f"{t} tokens not divisible by "
                                  f"expert fold {n}")
    mp = mesh.shape.get("mp", 1)
    f_dim = wg.shape[2]
    use_mp = mp > 1 and f_dim % mp == 0
    t_l = t // n
    s = t_l * k
    if capacity_factor is None:
        recv_rows = n * s                               # dropless
    else:
        recv_rows = min(n * s, max(8, int(math.ceil(
            capacity_factor * s))))
    fn = _mapped_ep(mesh, fold, use_mp, k, float(balance_coef),
                    float(z_coef), bool(norm_topk), tm, bool(interpret),
                    int(recv_rows))
    out, aux, dropped = fn(x, router_w, wg, wu, wd)
    if return_drops:
        return out, aux, dropped
    return out, aux
