"""Expert-parallel dropless MoE: all-to-all token exchange + per-shard
Pallas grouped matmul.

Reference parity: the reference runs its fused MoE kernels and the EP
all-to-all *together* — incubate moe_layer's alltoall dispatch feeding
the phi/kernels/fusion grouped expert GEMMs (SURVEY.md §2.3 EP row).
Round-3 of this build had the two halves separately: the dropless
grouped-matmul path ran single-chip only and sharded experts fell back
to the capacity-padded GShard einsums (VERDICT r3 Missing #1).  This
module composes them.

TPU-native design: ``shard_map`` manual over the expert fold axes
(``ep`` then the DeepSpeed-style (dp, sharding) folding, matching
nn.moe.EP_AXES) — per shard:

1. route local tokens (router weights replicated; the aux loss is
   reassembled EXACTLY from fold-``pmean``'d per-shard means, so it
   equals the dense path's global aux),
2. bucket slots by owner shard (``expert // E_local``) into a
   per-peer-capacity send buffer and exchange with ONE
   ``lax.all_to_all`` over the fused fold axis (rides ICI),
3. run the dropless grouped-matmul SwiGLU on the received rows against
   the LOCAL expert shard (ops/pallas/grouped_matmul.py
   ``dropless_moe_ffn_rows``; Megatron row-parallel ``psum`` over
   ``mp`` when the FFN dim is tensor-sharded),
4. all-to-all the rows back and combine with the local top-k gates.

Per-peer capacity defaults to ``capacity_factor=2.0`` — each shard's
receive buffer (and therefore its grouped-matmul FLOPs and all-to-all
payload) is ~2x the balanced load of ``slots/fold``, so EP genuinely
divides expert compute by the fold size; overflow beyond 2x the
balanced load is dropped (zero combine contribution), like the
reference's capacity knob.  ``capacity_factor=None`` (or any factor
>= fold) buys strict droplessness at the cost of every shard
buffering the full global slot count — right for parity tests and
small folds, wasteful at scale.
"""
from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

__all__ = ["moe_grouped_ep_raw", "expert_fold_axes",
           "ep_grouped_compatible", "EP_FOLD"]

# single source of the expert-dim fold order (this module loads lazily
# from MoELayer.forward, after nn.moe is fully imported)
from ..nn.moe import EP_AXES as EP_FOLD  # noqa: E402


def expert_fold_axes(mesh) -> Tuple[str, ...]:
    """Mesh axes (>1) the expert dim folds over, in fold order."""
    return tuple(a for a in EP_FOLD if mesh.shape.get(a, 1) > 1)


def ep_grouped_compatible(mesh, num_experts: int,
                          num_tokens: int) -> bool:
    """True when the grouped EP path can run: an active expert fold
    whose size divides both the expert count and the token count.  The
    ONE divisibility predicate shared by MoELayer._resolve_dispatch and
    the dryrun's forced-mode gate."""
    fold = expert_fold_axes(mesh)
    if not fold:
        return False
    n = int(np.prod([mesh.shape[a] for a in fold]))
    return n > 1 and num_experts % n == 0 and num_tokens % n == 0


def _fused_index(fold: Tuple[str, ...], sizes: Tuple[int, ...]):
    """Row-major linear index over the fold axes — matches both the
    PartitionSpec fold ordering and tuple-axis collectives."""
    me = jnp.int32(0)
    for a, sz in zip(fold, sizes):
        me = me * sz + lax.axis_index(a)
    return me


def _ep_local(x, router_w, wg, wu, wd, *, fold, sizes, k, balance_coef,
              z_coef, norm_topk, tm, interpret, cap, use_mp):
    """Per-shard body (manual over ``fold`` + optionally ``mp``).
    x [T_l, H] local tokens; wg/wu [E_l, H, F(/mp)], wd [E_l, F(/mp), H]
    local experts.  Returns (out [T_l, H], aux scalar)."""
    from ..nn.moe import _assemble_aux, _router_parts
    from ..ops.pallas.grouped_matmul import dropless_moe_ffn_rows

    n = int(np.prod(sizes))
    e_l = wg.shape[0]
    t_l, h = x.shape
    me = _fused_index(fold, sizes)

    gate_vals, expert_idx, density, proxy, zsq = _router_parts(
        x, router_w, k=k, norm_topk=norm_topk)
    # exact global aux: per-shard token means pmean'd over the fold
    density = lax.pmean(density, fold)
    proxy = lax.pmean(proxy, fold)
    zsq = lax.pmean(zsq, fold)
    aux = _assemble_aux(density, proxy, zsq, balance_coef=balance_coef,
                        z_coef=z_coef)

    s = t_l * k
    flat_e = expert_idx.reshape(s)
    dshard = flat_e // e_l                                  # owner shard
    order = jnp.argsort(dshard, stable=True)
    sorted_shard = dshard[order]
    counts = jnp.bincount(dshard, length=n)
    start = jnp.concatenate(
        [jnp.zeros(1, counts.dtype), jnp.cumsum(counts)[:-1]])
    rank = jnp.arange(s) - start[sorted_shard]
    ok = rank < cap                                         # capacity drop
    pos = jnp.where(ok, sorted_shard * cap + rank, n * cap)

    rows = x[order // k]                                    # [s, H]
    send_x = jnp.zeros((n * cap, h), x.dtype).at[pos].set(
        rows, mode="drop")
    send_e = jnp.full((n * cap,), -1, jnp.int32).at[pos].set(
        flat_e[order], mode="drop")

    recv_x = lax.all_to_all(send_x, fold, 0, 0, tiled=True)
    recv_e = lax.all_to_all(send_e, fold, 0, 0, tiled=True)

    # ids >= e_l mark empty buffer rows (zero output downstream)
    loc_e = jnp.where(recv_e >= 0, recv_e - me * e_l, e_l)
    y = dropless_moe_ffn_rows(recv_x, loc_e, wg, wu, wd, tm=tm,
                              interpret=interpret)
    if use_mp:
        y = lax.psum(y, "mp")                               # row-parallel F

    y_ret = lax.all_to_all(y, fold, 0, 0, tiled=True)
    pos_safe = jnp.minimum(pos, n * cap - 1)
    y_sorted = jnp.where(ok[:, None], y_ret[pos_safe], 0)
    y_flat = jnp.zeros((s, h), y_ret.dtype).at[order].set(y_sorted)
    out = jnp.einsum("tk,tkh->th", gate_vals,
                     y_flat.reshape(t_l, k, h).astype(jnp.float32))
    return out.astype(x.dtype), aux


@functools.lru_cache(maxsize=64)
def _mapped_ep(mesh, fold, use_mp, k, balance_coef, z_coef, norm_topk,
               tm, interpret, cap):
    sizes = tuple(mesh.shape[a] for a in fold)
    body = functools.partial(
        _ep_local, fold=fold, sizes=sizes, k=k,
        balance_coef=balance_coef, z_coef=z_coef, norm_topk=norm_topk,
        tm=tm, interpret=interpret, cap=cap, use_mp=use_mp)
    mp = "mp" if use_mp else None
    x_spec = P(fold, None)
    w_spec = P(None, None)
    specs = (x_spec, w_spec, P(fold, None, mp), P(fold, None, mp),
             P(fold, mp, None))
    mapped = jax.shard_map(
        body, mesh=mesh, axis_names=frozenset(fold) | (
            {"mp"} if use_mp else set()),
        in_specs=specs, out_specs=(x_spec, P()), check_vma=False)
    # partial-manual shard_map only lowers under jit; the jit wrapper
    # inlines under an outer jit and caches the eager compile
    return jax.jit(mapped)


def moe_grouped_ep_raw(x, router_w, wg, wu, wd, *, k, balance_coef,
                       z_coef, norm_topk, tm, interpret, mesh,
                       capacity_factor: Optional[float] = 2.0):
    """Grouped MoE over GLOBAL arrays: x [T, H], router_w [H, E],
    wg/wu [E, H, F], wd [E, F, H] -> (out [T, H], aux).

    ``capacity_factor`` bounds each shard's receive buffer at
    ``factor * slots / fold`` rows per peer (see module docstring);
    ``None`` means strictly dropless (full slot count per shard).

    Callers must pre-check :func:`ep_grouped_compatible` (MoELayer's
    dispatch resolution does); the NotImplementedErrors below are the
    backstop for direct raw-level misuse.
    """
    fold = expert_fold_axes(mesh)
    if not fold:
        raise NotImplementedError("no expert-parallel fold axis > 1")
    n = int(np.prod([mesh.shape[a] for a in fold]))
    t, _ = x.shape
    e = wg.shape[0]
    if e % n:
        raise NotImplementedError(f"{e} experts not divisible by "
                                  f"expert fold {n}")
    if t % n:
        raise NotImplementedError(f"{t} tokens not divisible by "
                                  f"expert fold {n}")
    mp = mesh.shape.get("mp", 1)
    f_dim = wg.shape[2]
    use_mp = mp > 1 and f_dim % mp == 0
    t_l = t // n
    s = t_l * k
    if capacity_factor is None:
        cap = s                                             # dropless
    else:
        cap = min(s, max(8, int(math.ceil(capacity_factor * s / n))))
    fn = _mapped_ep(mesh, fold, use_mp, k, float(balance_coef),
                    float(z_coef), bool(norm_topk), tm, bool(interpret),
                    int(cap))
    return fn(x, router_w, wg, wu, wd)
