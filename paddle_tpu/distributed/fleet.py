"""paddle.distributed.fleet — the hybrid-parallel entry point.

Reference parity: fleet/fleet.py + fleet/base — ``fleet.init(strategy)``
building the HybridCommunicateGroup, ``distributed_model``,
``distributed_optimizer``, rank/worker accessors.

TPU-native design: init builds ONE jax Mesh from the strategy's hybrid
degrees (topology.py) and sets it as the global auto-parallel mesh; model
and optimizer "wrapping" attach sharding metadata instead of comm hooks —
the compiled path (distributed/trainer.py ShardedTrainStep) consumes it
and GSPMD emits all communication.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

from ..common.errors import enforce
from . import env as dist_env
from .strategy import DistributedStrategy
from .topology import HybridCommunicateGroup

__all__ = ["init", "reset", "fleet", "DistributedStrategy",
           "distributed_model", "distributed_optimizer",
           "get_hybrid_communicate_group",
           "worker_num", "worker_index", "is_first_worker", "barrier_worker"]

_HCG: Optional[HybridCommunicateGroup] = None
_STRATEGY: Optional[DistributedStrategy] = None


def reset():
    """Tear down all fleet/mesh state so a new topology can be built —
    the single owner of 'what constitutes mesh state' (drivers and tests
    must use this instead of poking module globals)."""
    global _HCG, _STRATEGY
    _HCG = None
    _STRATEGY = None
    from . import auto_parallel as _ap
    _ap._GLOBAL_MESH = None
    from . import collective as _coll
    _coll._DEFAULT_GROUP = None


def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None, log_level="INFO",
         devices=None):
    """fleet.init — build the device mesh from strategy.hybrid_configs.

    ``devices`` overrides the mesh's device set (default
    ``jax.devices()``).  Detached-topology devices
    (jax.experimental.topologies.get_topology_desc) are accepted: the
    whole stack then LOWERS/COMPILES for that topology — AOT memory
    planning on hardware this host doesn't have — but nothing can
    execute (see tests/plan8b_aot_check.py)."""
    global _HCG, _STRATEGY
    # join the multi-host runtime first (no-op single-process): the mesh
    # below must span the GLOBAL device set
    dist_env.init_parallel_env()
    strategy = strategy or DistributedStrategy()
    _STRATEGY = strategy
    hybrid = strategy.hybrid
    n_needed = (hybrid.dp_degree * hybrid.mp_degree * hybrid.pp_degree *
                hybrid.sharding_degree * hybrid.sep_degree *
                hybrid.ep_degree)
    n_have = len(devices) if devices is not None else len(jax.devices())
    if n_needed == 1 and n_have > 1:
        # no explicit topology: default all devices to dp (reference
        # behavior: fleet defaults to pure DP over visible devices).
        # Persist into the strategy so get_strategy() agrees with the mesh.
        hybrid.dp_degree = n_have
        strategy.hybrid_configs["dp_degree"] = n_have
    _HCG = HybridCommunicateGroup(hybrid, devices=devices)
    from .auto_parallel import set_mesh
    set_mesh(_HCG.mesh)
    from .collective import _set_default_group
    _set_default_group(_HCG.get_data_parallel_group())
    return _HCG


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _HCG


def get_strategy() -> Optional[DistributedStrategy]:
    return _STRATEGY


def distributed_model(model):
    """Attach the hybrid topology to the model.  Under GSPMD no wrapper
    module is needed (no reducer/no pipeline runner objects); TP layers
    already carry shardings, and ShardedTrainStep consumes the plan.  A
    thin passthrough keeps the fleet API contract."""
    enforce(_HCG is not None, "fleet.init() first")
    model._hcg = _HCG
    return model


def distributed_optimizer(optimizer, strategy: Optional[DistributedStrategy] = None):
    enforce(_HCG is not None, "fleet.init() first")
    optimizer._hcg = _HCG
    return optimizer


def worker_num() -> int:
    return dist_env.get_world_size()


def worker_index() -> int:
    return dist_env.get_rank()


def is_first_worker() -> bool:
    return worker_index() == 0


def barrier_worker():
    from .collective import barrier
    barrier()


class _FleetFacade:
    """``paddle.distributed.fleet`` object-style access (fleet.init, ...)"""
    init = staticmethod(init)
    reset = staticmethod(reset)
    distributed_model = staticmethod(distributed_model)
    distributed_optimizer = staticmethod(distributed_optimizer)
    worker_num = staticmethod(worker_num)
    worker_index = staticmethod(worker_index)
    is_first_worker = staticmethod(is_first_worker)
    barrier_worker = staticmethod(barrier_worker)
    get_hybrid_communicate_group = staticmethod(get_hybrid_communicate_group)
    DistributedStrategy = DistributedStrategy


fleet = _FleetFacade()
