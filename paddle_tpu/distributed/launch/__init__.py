from .controller import Controller, LaunchConfig, free_port
from .main import launch, parse_args

__all__ = ["Controller", "LaunchConfig", "free_port", "launch",
           "parse_args"]
