"""Process controller for ``python -m paddle_tpu.distributed.launch``.

Reference parity: python/paddle/distributed/launch (SURVEY.md §1 L9,
§3.3) — the controller spawns N trainer processes per node, assigns
ranks, seeds the rendezvous env (PADDLE_MASTER / PADDLE_TRAINER_ID /
PADDLE_TRAINERS_NUM), streams per-worker logs, and (elastic mode,
SURVEY.md §5 failure-detection) relaunches the gang on worker failure so
training resumes from the latest checkpoint.

TPU-native design: the rendezvous the env seeds is consumed by
``jax.distributed.initialize`` (the TCPStore analog is jax's
coordination service; rank 0's address is the master).  One process per
host is the TPU norm — ``--nproc_per_node`` exists for CPU simulation
and multi-process-per-host setups.
"""
from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

__all__ = ["LaunchConfig", "Controller", "free_port"]


def free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class LaunchConfig:
    script: str = ""
    script_args: List[str] = field(default_factory=list)
    nnodes: int = 1
    node_rank: int = 0
    nproc_per_node: int = 1
    master: Optional[str] = None      # "host:port"; default localhost:rand
    log_dir: Optional[str] = None
    elastic_level: int = 0            # 0: fail fast; 1: relaunch gang
    max_restarts: int = 3
    env: Dict[str, str] = field(default_factory=dict)
    module: bool = False              # run script with -m


class Controller:
    """Spawns and supervises the local trainer gang."""

    def __init__(self, cfg: LaunchConfig):
        self.cfg = cfg
        if cfg.master is None:
            cfg.master = f"127.0.0.1:{free_port()}"
        self.procs: List[subprocess.Popen] = []
        self._logs = []

    # -- env per worker ------------------------------------------------------
    def _worker_env(self, local_rank: int) -> Dict[str, str]:
        cfg = self.cfg
        rank = cfg.node_rank * cfg.nproc_per_node + local_rank
        env = dict(os.environ)
        env.update(cfg.env)
        env.update({
            "PADDLE_MASTER": cfg.master,
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(cfg.nnodes * cfg.nproc_per_node),
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_LOCAL_SIZE": str(cfg.nproc_per_node),
            "PADDLE_NNODES": str(cfg.nnodes),
            # jax coordination service must not route via any proxy
            "NO_PROXY": env.get("NO_PROXY", "") + ",127.0.0.1,localhost",
            "no_proxy": env.get("no_proxy", "") + ",127.0.0.1,localhost",
        })
        return env

    # -- lifecycle -----------------------------------------------------------
    def _spawn_one(self, local_rank: int) -> subprocess.Popen:
        cfg = self.cfg
        cmd = [sys.executable]
        if cfg.module:
            cmd += ["-m", cfg.script]
        else:
            cmd += [cfg.script]
        cmd += list(cfg.script_args)
        stdout = stderr = None
        if cfg.log_dir:
            os.makedirs(cfg.log_dir, exist_ok=True)
            rank = cfg.node_rank * cfg.nproc_per_node + local_rank
            f = open(os.path.join(cfg.log_dir, f"workerlog.{rank}"), "ab")
            self._logs.append(f)
            stdout, stderr = f, subprocess.STDOUT
        return subprocess.Popen(cmd, env=self._worker_env(local_rank),
                                stdout=stdout, stderr=stderr)

    def start(self):
        self.procs = [self._spawn_one(i)
                      for i in range(self.cfg.nproc_per_node)]

    def stop(self, sig=signal.SIGTERM):
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.send_signal(sig)
                except OSError:
                    pass
        deadline = time.time() + 10
        for p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
        for f in self._logs:
            f.close()
        self._logs = []

    def _poll_gang(self) -> Optional[int]:
        """None while all running; else first non-zero exit code, or 0
        when every worker exited cleanly."""
        codes = [p.poll() for p in self.procs]
        for c in codes:
            if c is not None and c != 0:
                return c
        if all(c == 0 for c in codes):
            return 0
        return None

    def run(self) -> int:
        """Supervise until the gang exits.  Elastic level 1: on worker
        failure kill + relaunch the whole gang (fresh rendezvous port —
        ranks re-init) up to max_restarts times; recovery is
        checkpoint-based (the trainer script reloads its latest ckpt,
        reference elastic manager semantics)."""
        restarts = 0
        self.start()
        while True:
            code = self._poll_gang()
            if code is None:
                time.sleep(0.2)
                continue
            if code == 0:
                self.stop()
                return 0
            if self.cfg.elastic_level >= 1 and restarts < self.cfg.max_restarts:
                restarts += 1
                sys.stderr.write(
                    f"[launch] worker failed (exit {code}); relaunching "
                    f"gang (restart {restarts}/{self.cfg.max_restarts})\n")
                self.stop()
                # fresh coordinator port: the old coordination service
                # died with rank 0
                self.cfg.master = f"127.0.0.1:{free_port()}"
                self.start()
                continue
            self.stop()
            return code
