"""CLI argument parsing for paddle_tpu.distributed.launch (reference:
python/paddle/distributed/launch/main.py)."""
from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from .controller import Controller, LaunchConfig

__all__ = ["launch", "parse_args"]


def parse_args(argv: Optional[List[str]] = None) -> LaunchConfig:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="Launch a multi-process (multi-host) training job.")
    p.add_argument("--nnodes", type=int, default=1,
                   help="number of nodes (hosts)")
    p.add_argument("--node_rank", type=int, default=0,
                   help="this node's index in [0, nnodes)")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="trainer processes per node (TPU norm: 1/host)")
    p.add_argument("--master", type=str, default=None,
                   help="rank-0 coordinator host:port (required multi-node)")
    p.add_argument("--log_dir", type=str, default=None,
                   help="per-worker log directory (workerlog.N)")
    p.add_argument("--elastic_level", type=int, default=0,
                   help="0: fail fast; 1: relaunch gang on worker failure")
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("--module", action="store_true",
                   help="run the script as a python module (python -m)")
    p.add_argument("script", help="training script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    a = p.parse_args(argv)
    if a.nnodes > 1 and not a.master:
        p.error("--master host:port is required when nnodes > 1")
    return LaunchConfig(
        script=a.script, script_args=a.script_args, nnodes=a.nnodes,
        node_rank=a.node_rank, nproc_per_node=a.nproc_per_node,
        master=a.master, log_dir=a.log_dir, elastic_level=a.elastic_level,
        max_restarts=a.max_restarts, module=a.module)


def launch(argv: Optional[List[str]] = None) -> int:
    return Controller(parse_args(argv)).run()


if __name__ == "__main__":
    sys.exit(launch())
