"""Tensor-parallel (Megatron-style) layers + sequence-parallel utilities.

Reference parity: fleet/meta_parallel/parallel_layers/mp_layers.py —
``VocabParallelEmbedding``, ``ColumnParallelLinear``, ``RowParallelLinear``
— and fleet/utils/sequence_parallel_utils.py.

TPU-native design (SURVEY.md §2.3): these layers do NOT issue collectives.
They (1) annotate their weights with per-dim mesh axes (``dist_spec``)
and (2) add ``with_sharding_constraint`` hints on activations when
tracing.  The XLA SPMD partitioner then inserts exactly the
allgather/allreduce pattern Megatron hand-codes — identical math, zero
hand-written communication.  Outside a mesh/jit context they behave as
ordinary layers (single-device semantics), so the same model code runs
everywhere.
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ..common.errors import enforce
from ..nn import functional as F
from ..nn.common import Embedding, Linear
from ..nn.initializer import Normal, XavierNormal
from ..nn.layer import Layer
from ..tensor import Tensor, apply_op

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "ParallelCrossEntropy",
           "mark_as_sequence_parallel_parameter", "ScatterOp", "GatherOp",
           "sharding_constraint"]


def _mesh():
    from .fleet import get_hybrid_communicate_group
    hcg = get_hybrid_communicate_group()
    return hcg.mesh if hcg is not None else None


def sharding_constraint(x, *spec_entries):
    """Activation sharding hint — no-op outside tracing/mesh context."""
    mesh = _mesh()
    if mesh is None:
        return x
    val = x.value if isinstance(x, Tensor) else x
    if not isinstance(val, jax.core.Tracer):
        return x
    spec = PartitionSpec(*spec_entries)

    def _constrain(a):
        return jax.lax.with_sharding_constraint(a, NamedSharding(mesh, spec))
    _constrain.__name__ = "sharding_constraint"
    return apply_op(_constrain, x)


class ColumnParallelLinear(Layer):
    """Weight [in, out] sharded on OUT (columns) over the mp axis.

    gather_output=False leaves activations mp-sharded on the feature dim
    (fed to a RowParallelLinear), True re-replicates them.
    """

    def __init__(self, in_features: int, out_features: int,
                 weight_attr=None, has_bias: bool = True,
                 gather_output: bool = True, fuse_matmul_bias: bool = False,
                 mp_group=None, name: Optional[str] = None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.is_mp = True
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=None if weight_attr is not None
            else XavierNormal())
        self.weight.dist_spec = (None, "mp")
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.dist_spec = ("mp",)
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out = sharding_constraint(out, *([None] * (out.ndim - 1)), None)
        else:
            out = sharding_constraint(out, *([None] * (out.ndim - 1)), "mp")
        return out


class RowParallelLinear(Layer):
    """Weight [in, out] sharded on IN (rows) over the mp axis; the partial
    matmul results are summed by the partitioner (Megatron's forward
    allreduce — emitted automatically)."""

    def __init__(self, in_features: int, out_features: int,
                 weight_attr=None, has_bias: bool = True,
                 input_is_parallel: bool = False,
                 fuse_matmul_bias: bool = False, mp_group=None,
                 name: Optional[str] = None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.is_mp = True
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=None if weight_attr is not None
            else XavierNormal())
        self.weight.dist_spec = ("mp", None)
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            x = sharding_constraint(x, *([None] * (x.ndim - 1)), "mp")
        out = F.linear(x, self.weight, self.bias)
        return sharding_constraint(out, *([None] * (out.ndim - 1)), None)


class VocabParallelEmbedding(Layer):
    """Embedding weight [vocab, hidden] sharded on vocab over mp."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 weight_attr=None, mp_group=None, name: Optional[str] = None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=None if weight_attr is not None
            else Normal(0.0, 0.02))
        self.weight.dist_spec = ("mp", None)

    def forward(self, x):
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(Layer):
    """fleet parallel_cross_entropy: CE over mp-sharded logits.  GSPMD
    partitions the log-softmax reduction across the mp axis itself."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)


# -- sequence parallel (Megatron-SP) ----------------------------------------

def mark_as_sequence_parallel_parameter(parameter: Tensor):
    """fleet sequence_parallel_utils parity: under GSPMD the SP grad
    allreduce bookkeeping is emitted by the partitioner — pure no-op."""
    return parameter


class ScatterOp:
    """Scatter sequence dim across mp (enter an SP region)."""

    @staticmethod
    def apply(x):
        return sharding_constraint(x, None, "mp",
                                   *([None] * (x.ndim - 2)))


class GatherOp:
    """Gather sequence dim back (exit an SP region)."""

    @staticmethod
    def apply(x):
        return sharding_constraint(x, *([None] * x.ndim))
