"""Pipeline parallelism.

Reference parity: fleet/meta_parallel/parallel_layers/pp_layers.py
(LayerDesc, SharedLayerDesc, PipelineLayer — layer-list segmentation) and
fleet/meta_parallel/pipeline_parallel.py (PipelineParallel.train_batch:
python 1F1B microbatch loop over NCCL p2p, SURVEY.md §3.3).

TPU-native design: the reference's python-level schedule loop becomes ONE
compiled SPMD program — ``gpipe_spmd`` runs a GPipe-style circulating
pipeline inside ``jax.shard_map`` manual over ONLY the ``pp`` mesh axis
(dp/sharding/mp stay auto, so GSPMD still lays out data/tensor/FSDP
parallelism inside each stage).  Stage params are stacked on a leading
axis sharded over ``pp``; activations rotate between stages with
``lax.ppermute`` over ICI; backward is derived by jax.grad through the
loop (GPipe schedule: all-forward then reversed all-backward, remat per
stage via jax.checkpoint).  Bubble fraction = (S-1)/(M+S-1), same as
1F1B; 1F1B's memory advantage is recovered with stage remat instead.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..common.errors import enforce
from ..nn.layer import Layer
from ..nn.container import LayerList

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer", "gpipe_spmd"]


# ---------------------------------------------------------------------------
# The compiled SPMD pipeline engine
# ---------------------------------------------------------------------------

def _pvary(x, axis):
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, (axis,), to="varying")
    return jax.lax.pvary(x, (axis,))


@functools.lru_cache(maxsize=64)
def _jitted_pipeline(stage_fn: Callable, mesh, pp_axis: str,
                     n_params: int, n_extra: int, remat: bool):
    """Build + cache the jitted shard_map engine (keyed on a *stable*
    stage_fn object so eager loops don't re-trace every step)."""
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def inner(params_local, xm, *extra_local):
        locals_ = [p[0] for p in params_local]
        n_micro = xm.shape[0]
        stage = jax.lax.axis_index(pp_axis)
        nstage = jax.lax.axis_size(pp_axis)
        carry = _pvary(jnp.zeros(xm.shape[1:], xm.dtype), pp_axis)
        outs = _pvary(jnp.zeros(xm.shape, xm.dtype), pp_axis)

        def step(t, state):
            carry, outs = state
            feed = _pvary(xm[jnp.minimum(t, n_micro - 1)], pp_axis)
            inp = jnp.where(stage == 0, feed, carry)
            y = fn(locals_, inp, *extra_local)
            out_idx = jnp.maximum(t - (nstage - 1), 0)
            keep = jnp.logical_and(stage == nstage - 1,
                                   t - (nstage - 1) >= 0)
            upd = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(keep, y, outs[out_idx]), out_idx, 0)
            nxt = jax.lax.ppermute(
                y, pp_axis, [(i, (i + 1) % nstage) for i in range(nstage)])
            return nxt, upd

        carry, outs = jax.lax.fori_loop(
            0, n_micro + nstage - 1, step, (carry, outs))
        outs = jnp.where(stage == nstage - 1, outs, jnp.zeros_like(outs))
        return jax.lax.psum(outs, pp_axis)

    in_specs = (tuple(P(pp_axis) for _ in range(n_params)), P(),
                *(P() for _ in range(n_extra)))
    mapped = jax.shard_map(inner, mesh=mesh, axis_names={pp_axis},
                           in_specs=in_specs, out_specs=P())
    # jit wrapper: eager evaluation of checkpoint/scan inside shard_map is
    # unsupported; under an outer jit this inlines
    return jax.jit(mapped)


def gpipe_spmd(params: Sequence[jax.Array], x_micro: jax.Array,
               stage_fn: Callable, *extra,
               mesh, pp_axis: str = "pp", remat: bool = True):
    """Run ``stage_fn`` as a circulating SPMD pipeline.

    params:   arrays stacked [n_stages, ...] (pp-sharded on dim 0);
              n_stages must equal the ``pp_axis`` mesh size.
    x_micro:  [n_micro, micro_batch, ...] input microbatches (replicated
              over pp; may be sharded over data axes).
    stage_fn: (local_params_list, h, *extra) -> h, applied by every
              stage.  Pass a STABLE callable (module-level or cached) —
              the compiled engine is cached keyed on it.
    extra:    broadcast side inputs (e.g. rope tables), replicated.

    Returns [n_micro, micro_batch, ...] outputs of the final stage.
    """
    n_stages = params[0].shape[0]
    enforce(n_stages == mesh.shape[pp_axis],
            f"stacked stage dim {n_stages} != mesh '{pp_axis}' size "
            f"{mesh.shape[pp_axis]}")
    fn = _jitted_pipeline(stage_fn, mesh, pp_axis, len(params),
                          len(extra), remat)
    return fn(tuple(params), x_micro, *extra)


# ---------------------------------------------------------------------------
# Paddle-parity layer-list API
# ---------------------------------------------------------------------------

class LayerDesc:
    """Deferred layer constructor (fleet pp_layers.LayerDesc parity)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs
        enforce(issubclass(layer_cls, Layer) or callable(layer_cls),
                "LayerDesc needs a Layer subclass")

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Layer whose parameters are shared across stages (e.g. tied
    embedding/lm-head).  Under single-program SPMD the sharing is simply
    object identity — the first build is reused."""

    def __init__(self, key, layer_cls, *args, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """fleet.meta_parallel.PipelineLayer parity.

    Holds the full layer list (single-program SPMD: every process owns
    the whole model; stage placement is a sharding concern, not an
    ownership concern).  ``forward`` runs the stack sequentially — the
    semantics the reference's PipelineParallel produces.  The pipelined
    *execution* is the compiled path: models with a uniform decoder
    stack (e.g. LlamaForCausalLMPipe) lower it through gpipe_spmd.
    """

    def __init__(self, layers, num_stages: Optional[int] = None,
                 topology=None, loss_fn=None, seg_method="uniform",
                 recompute_interval: int = 0, **kwargs):
        super().__init__()
        self._loss_fn = loss_fn
        self._shared: dict = {}
        built: List[Layer] = []
        self.descs = list(layers)
        for d in self.descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    built.append(self._shared[d.layer_name])
                else:
                    lyr = d.build_layer()
                    self._shared[d.layer_name] = lyr
                    built.append(lyr)
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            else:
                enforce(isinstance(d, Layer),
                        "PipelineLayer accepts Layers or LayerDescs")
                built.append(d)
        self.run_function = LayerList(built)
        if num_stages is None and topology is not None:
            num_stages = topology.get_dim("pipe") if hasattr(
                topology, "get_dim") else 1
        self._num_stages = num_stages or 1
        self._segment()

    def _segment(self):
        n = len(self.run_function)
        s = self._num_stages
        base, extra = divmod(n, s)
        bounds = [0]
        for i in range(s):
            bounds.append(bounds[-1] + base + (1 if i < extra else 0))
        self.segment_parts = bounds

    def get_stage_layers(self, stage: int) -> List[Layer]:
        lo, hi = self.segment_parts[stage], self.segment_parts[stage + 1]
        return list(self.run_function[lo:hi])

    @property
    def num_stages(self) -> int:
        return self._num_stages

    def forward(self, x, *args, **kwargs):
        # side inputs (e.g. rope cos/sin) are forwarded to every layer —
        # dropping them silently diverged from the sequential-parity
        # contract (ADVICE.md round-1)
        for lyr in self.run_function:
            x = lyr(x, *args, **kwargs)
        return x
