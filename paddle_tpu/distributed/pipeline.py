"""Pipeline parallelism.

Reference parity: fleet/meta_parallel/parallel_layers/pp_layers.py
(LayerDesc, SharedLayerDesc, PipelineLayer — layer-list segmentation) and
fleet/meta_parallel/pipeline_parallel.py (PipelineParallel.train_batch:
python 1F1B microbatch loop over NCCL p2p, SURVEY.md §3.3).

TPU-native design: the reference's python-level schedule loop becomes ONE
compiled SPMD program — ``gpipe_spmd`` runs a GPipe-style circulating
pipeline inside ``jax.shard_map`` manual over ONLY the ``pp`` mesh axis
(dp/sharding/mp stay auto, so GSPMD still lays out data/tensor/FSDP
parallelism inside each stage).  Stage params are stacked on a leading
axis sharded over ``pp``; activations rotate between stages with
``lax.ppermute`` over ICI.

Two backward strategies:

* ``pipeline_train_1f1b`` (training default, n_virtual==1): a TRUE
  1F1B schedule — ONE fused loop interleaves each microbatch's
  backward with the forwards (B_s(m) fires at tick m + 2S-1-s, F_s(m)
  at m + s), holding stage inputs in a ring buffer of 2S slots.  Peak
  live activation memory is bounded by the in-flight microbatch count
  (∝ pp), NOT by n_micro — the reference 1F1B's memory bound
  (fleet PipelineParallel.train_batch), delivered as a jax.custom_vjp
  whose backward replays nothing: grads are accumulated inside the
  same loop via per-tick jax.vjp at the saved stage inputs.
* ``gpipe_spmd`` + jax.grad (eval / interleaved v>1): backward derived
  by AD through the loop (all-forward-then-all-backward), with stage
  remat; residual memory ∝ n_micro.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from ..compat import shard_map as _compat_shard_map
from ..compat import axis_size as _compat_axis_size

from ..common.errors import enforce
from ..nn.layer import Layer
from ..nn.container import LayerList

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer", "gpipe_spmd",
           "pipeline_train_1f1b"]


# ---------------------------------------------------------------------------
# The compiled SPMD pipeline engine
# ---------------------------------------------------------------------------

def _typeof(x):
    fn = getattr(jax, "typeof", None)
    return fn(x) if fn is not None else jax.core.get_aval(x)


def _pvary(x, axis):
    # no-op when already varying over this axis (pcast rejects that);
    # any OTHER ValueError (bad axis name etc.) must surface here, not
    # as an opaque vma mismatch deep in the scan
    aval = _typeof(x)
    if axis in getattr(aval, "vma", ()):
        return x
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, (axis,), to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, (axis,))
    return x   # pre-vma jax: no varying bookkeeping to maintain


def _mesh_platform(mesh) -> str:
    try:
        return list(mesh.devices.flat)[0].platform
    except Exception:
        return "cpu"


@functools.lru_cache(maxsize=64)
def _jitted_pipeline(stage_fn: Callable, mesh, pp_axis: str,
                     n_params: int, n_extra: int, remat: bool,
                     n_virtual: int, tail_fn: Optional[Callable] = None,
                     n_tail_params: int = 0, n_tail_idx: int = 0,
                     tail_cond: Optional[bool] = None):
    """Build + cache the jitted shard_map engine (keyed on a *stable*
    stage_fn object so eager loops don't re-trace every step).

    Schedule: circulating pipeline.  With ``n_virtual == 1`` this is
    GPipe (each device owns one contiguous chunk; microbatch m enters
    stage 0 at tick m).  With ``n_virtual = v > 1`` it is the
    interleaved / virtual-stage schedule (Megatron "virtual pipeline"):
    device d owns chunks d, d+S, …, d+(v-1)·S and microbatches cycle the
    ring v times in rounds of S, shrinking the fill bubble from
    (S-1)·T_stage to (S-1)·T_stage/v.

    Output contract — two modes:

    * no ``tail_fn``: each device returns its own [n_micro, …] buffer
      (only the last stage's is meaningful) with out_specs sharded over
      ``pp_axis`` — the caller slices the last stage's shard.
    * ``tail_fn`` (the training path): the loss head runs *inside* the
      pipeline on each completed microbatch (the reference computes the
      loss on the last stage — fleet PipelineParallel ``_loss_fn``) and
      only the accumulated scalars are psum'd over pp.  This removes
      the round-1 zero-fill + psum of the full [n_micro, batch, …]
      activation buffer AND never materializes whole-batch logits.
    """
    fn = jax.checkpoint(stage_fn) if remat else stage_fn
    tfn = (jax.checkpoint(tail_fn) if (remat and tail_fn is not None)
           else tail_fn)
    # cond-guard the loss tail on TPU; XLA:CPU keeps the masked path
    # (grad-of-cond-in-scan aborts there, jax 0.9).  Callers that never
    # differentiate through the loop (the 1F1B primal) force it on.
    if tail_cond is None:
        tail_cond = _mesh_platform(mesh) == "tpu"

    def inner(params_local, xm, *rest):
        extra_local = rest[:n_extra]
        tail_local = rest[n_extra:n_extra + n_tail_params]
        tail_idx = rest[n_extra + n_tail_params:]
        # local slab: [1, v, per_chunk, ...] -> [v, per_chunk, ...]
        locals_ = [p[0] for p in params_local]
        n_micro = xm.shape[0]
        stage = jax.lax.axis_index(pp_axis)
        nstage = _compat_axis_size(pp_axis)
        v = n_virtual
        rounds = -(-n_micro // nstage) if v > 1 else 1
        total = (rounds * v * nstage + nstage - 1) if v > 1 \
            else (n_micro + nstage - 1)
        carry = _pvary(jnp.zeros(xm.shape[1:], xm.dtype), pp_axis)
        xmv = _pvary(xm, pp_axis)   # feed index is stage-dependent
        if tfn is None:
            acc0 = _pvary(jnp.zeros(xm.shape, xm.dtype), pp_axis)
        else:
            shapes = jax.eval_shape(
                tail_fn, tail_local, xm[0], *(ti[0] for ti in tail_idx))
            acc0 = jax.tree_util.tree_map(
                lambda s: _pvary(jnp.zeros(s.shape, s.dtype), pp_axis),
                shapes)

        def step(t, state):
            carry, acc = state
            u = t - stage                     # device-local schedule tick
            if v > 1:
                uc = jnp.clip(u, 0, rounds * v * nstage - 1)
                r, uu = uc // (v * nstage), uc % (v * nstage)
                lap = uu // nstage
                m = r * nstage + uu % nstage  # microbatch index
            else:
                lap = jnp.zeros((), u.dtype)
                m = jnp.clip(u, 0, n_micro - 1)
            mc = jnp.minimum(m, n_micro - 1)
            feed = xmv[mc]
            inp = jnp.where((stage == 0) & (lap == 0), feed, carry)
            chunk = [jax.lax.dynamic_index_in_dim(p, lap, 0, False)
                     for p in locals_]
            y = fn(chunk, inp, *extra_local)
            keep = ((stage == nstage - 1) & (u >= 0) & (m < n_micro)
                    & (lap == v - 1))
            if tfn is None:
                acc = jax.lax.dynamic_update_index_in_dim(
                    acc, jnp.where(keep, y, acc[mc]), mc, 0)
            elif tail_cond:
                # TPU path: lax.cond skips the dead tail evaluations
                # (norm + lm-head matmul over the full vocab!) on every
                # stage/tick where keep is False — the round-2 "loss
                # tail runs on every stage every tick" waste
                tout = jax.lax.cond(
                    keep,
                    lambda: jax.tree_util.tree_map(
                        lambda o: _pvary(o, pp_axis),
                        tfn(tail_local, y, *(ti[mc] for ti in
                                             tail_idx))),
                    lambda: jax.tree_util.tree_map(
                        lambda a: jnp.zeros_like(a), acc))
                acc = jax.tree_util.tree_map(lambda a, o: a + o, acc,
                                             tout)
            else:
                # XLA:CPU fallback: the tail runs every tick on every
                # stage and is masked (SPMD lockstep) — grad-of-cond
                # inside scan inside shard_map aborts XLA:CPU (jax 0.9)
                tout = tfn(tail_local, y, *(ti[mc] for ti in tail_idx))
                acc = jax.tree_util.tree_map(
                    lambda a, o: a + jnp.where(keep, o, jnp.zeros_like(o)),
                    acc, tout)
            nxt = jax.lax.ppermute(
                y, pp_axis, [(i, (i + 1) % nstage) for i in range(nstage)])
            return nxt, acc

        carry, acc = jax.lax.fori_loop(0, total, step, (carry, acc0))
        if tfn is None:
            return acc[None]                 # [1, n_micro, ...] per stage
        # scalars (loss sums/counts): psum over pp is O(1) traffic
        return jax.tree_util.tree_map(
            lambda a: jax.lax.psum(a, pp_axis), acc)

    in_specs = (tuple(P(pp_axis) for _ in range(n_params)), P(),
                *(P() for _ in range(n_extra + n_tail_params + n_tail_idx)))
    out_specs = P() if tail_fn is not None else P(pp_axis)
    manual = ({pp_axis} if hasattr(jax, "shard_map")
              else set(mesh.axis_names))
    mapped = _compat_shard_map(inner, mesh=mesh, axis_names=manual,
                           in_specs=in_specs, out_specs=out_specs)
    # jit wrapper: eager evaluation of checkpoint/scan inside shard_map is
    # unsupported; under an outer jit this inlines
    return jax.jit(mapped)


def gpipe_spmd(params: Sequence[jax.Array], x_micro: jax.Array,
               stage_fn: Callable, *extra,
               mesh, pp_axis: str = "pp", remat: bool = True,
               n_virtual: int = 1, tail_fn: Optional[Callable] = None,
               tail_params: Sequence[jax.Array] = (),
               tail_indexed: Sequence[jax.Array] = (),
               tail_cond: Optional[bool] = None):
    """Run ``stage_fn`` as a circulating SPMD pipeline.

    params:   v==1: arrays stacked [n_chunks, per, ...]; v>1: the
              interleaved [S, v, per, ...] device-major layout (chunk
              l*S+d at [d, l] — device d's lap-l virtual stage), so
              pp shards dim 0 with no cross-shard relayout.
    x_micro:  [n_micro, micro_batch, ...] input microbatches (replicated
              over pp; may be sharded over data axes).
    stage_fn: (local_params_list, h, *extra) -> h, applied by every
              stage.  Pass a STABLE callable (module-level or cached) —
              the compiled engine is cached keyed on it.
    extra:    broadcast side inputs (e.g. rope tables), replicated.
    n_virtual: virtual stages per device (interleaved schedule).
    tail_fn:  optional (tail_params, y, *per_micro) -> pytree of arrays;
              runs on each completed microbatch at the last stage (loss
              head); results are summed over microbatches.  Must be a
              STABLE callable, like stage_fn.
    tail_params: side parameters for tail_fn (e.g. final norm + lm head
              weights), replicated over pp (mp/dp shardings still apply).
    tail_indexed: arrays with a leading [n_micro] dim, indexed per
              microbatch and passed to tail_fn (e.g. labels).

    Returns [n_micro, micro_batch, ...] outputs of the final stage, or
    the summed tail pytree when ``tail_fn`` is given.
    """
    nstage = mesh.shape[pp_axis]
    n_chunks = params[0].shape[0] if n_virtual == 1 \
        else params[0].shape[0] * params[0].shape[1]
    enforce(n_chunks == nstage * n_virtual,
            f"stacked chunk dims {tuple(params[0].shape)} != mesh "
            f"'{pp_axis}' size {nstage} * n_virtual {n_virtual}")
    # interleaved placement: stacks arrive ALREADY [S, v, per, ...]
    # (device-major storage — see models' pipe classes): dim 0 shards
    # over pp, dim 1 indexes the device's laps.  A global-chunk-order
    # [v*S, ...] layout would need a cross-shard relayout here (SPMD
    # involuntary full rematerialization of every stack, every step).
    # v==1 gains a singleton lap dim (free — dim 0 stays sharded) so
    # the engine slab is uniformly [S, v, per, ...].
    if n_virtual > 1:
        for p in params:
            enforce(p.shape[0] == nstage and p.shape[1] == n_virtual,
                    f"interleaved stacks must be [S={nstage}, "
                    f"v={n_virtual}, per, ...]; got {p.shape}")
        stacked = list(params)
    else:
        stacked = [p[:, None] for p in params]
    fn = _jitted_pipeline(stage_fn, mesh, pp_axis, len(params),
                          len(extra), remat, n_virtual, tail_fn,
                          len(tail_params), len(tail_indexed),
                          tail_cond)
    out = fn(tuple(stacked), x_micro, *extra, *tail_params, *tail_indexed)
    if tail_fn is not None:
        return out
    return out[nstage - 1]                   # last stage's buffer


# ---------------------------------------------------------------------------
# 1F1B: fused forward+backward schedule (training path, n_virtual == 1)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=64)
def _jitted_1f1b(stage_fn: Callable, tail_fn: Callable, mesh,
                 pp_axis: str, n_params: int, n_extra: int,
                 n_tail_params: int, n_tail_idx: int,
                 stash: bool = False, n_virtual: int = 1):
    """The fused 1F1B loop (fleet PipelineParallel.train_batch's
    schedule, compiled): at tick t, stage s runs forward on microbatch
    ``t - s`` and backward on microbatch ``t - (2S-1) + s``.  Stage
    inputs wait in a ring buffer of 2S slots (max in-flight is 2S-1 at
    stage 0), so peak activation memory is ∝ S in-flight microbatches
    — independent of n_micro.  Gradients come from per-tick jax.vjp at
    the saved inputs (no AD through the loop, so lax.cond may skip
    inactive ramp ticks and the per-stage branch on every backend).

    ``n_virtual = v > 1`` is the INTERLEAVED 1F1B (Megatron virtual
    pipeline, fleet's interleaved schedule): device d owns chunks
    d, d+S, …, d+(v-1)S; microbatches run in rounds of S per lap.
    Forward of chunk c = lap·S + d on microbatch m = r·S + j fires at
    tick t = r·vS + lap·S + j + d; backward mirrors it with delay
    D = vS at t = D + r·vS + (v-1-lap)·S + j + (S-1-d) — the mirror
    keeps every producer exactly one tick ahead of its consumer
    (chain gap 1 at the loss chunk, ring gap < 2vS everywhere, both
    provable from the algebra), so the ring needs 2vS CHUNK slots —
    each 1/v of a stage, i.e. the same total bytes as v=1's 2S stage
    slots: memory stays ∝ pp.  Fill+drain bubble shrinks from 2S-1
    stage-units (v=1) to S + (S-1)/v.

    ``stash=False`` (remat schedule): the ring holds stage INPUTS and
    every backward tick re-runs the stage forward inside jax.vjp —
    minimal memory (2S input slots), ~1 extra forward of FLOPs per
    microbatch.  ``stash=True`` (the reference 1F1B's memory/compute
    point — fleet PipelineParallel saves in-flight activations): the
    forward tick runs jax.vjp and the ring holds the VJP RESIDUALS
    (weight leaves are filtered by tracer identity and re-injected at
    backward, so parameters are never duplicated per slot); backward
    ticks apply the saved vjp — no recompute (measured 1.26x faster
    per microbatch-stage on v5e).  With ``n_virtual > 1`` the capture
    and rebuild run as ``lax.switch`` over per-lap STATIC chunk
    slices, so identity filtering still holds per branch.  Residual
    size per slot is whatever ``stage_fn``'s own checkpoint policy
    leaves saveable, so model-level recompute flags still control the
    memory/FLOPs trade inside a stage.  Rings are 2vS chunk slots —
    memory stays ∝ pp either way.

    Returns (loss_sum, count, grads_stacked, dxm, grads_tail) with the
    grads UNSCALED (cotangent 1.0 on loss_sum); the custom_vjp wrapper
    scales by the incoming cotangent and 1/count.
    """
    nstage = mesh.shape[pp_axis]
    # XLA:CPU aborts on lax.cond inside a loop inside shard_map (jax
    # 0.9) — fall back to computing both branches + select there; TPU
    # gets real conds (ramp ticks and the last-stage branch cost ~0)
    use_cond = _mesh_platform(mesh) == "tpu"

    def _branch(pred, true_fn, false_fn, operand):
        if use_cond:
            return jax.lax.cond(pred, true_fn, false_fn, operand)
        t = true_fn(operand)
        f = false_fn(operand)
        return jax.tree_util.tree_map(
            lambda a, b: jnp.where(pred, a, b), t, f)

    v = n_virtual

    def inner(params_local, xm, *rest):
        extra = rest[:n_extra]
        tail_params = rest[n_extra:n_extra + n_tail_params]
        tail_idx = rest[n_extra + n_tail_params:]
        # v==1: local slab [1, per, ...] -> [per, ...]
        # v>1:  local slab [1, v, per, ...] -> [v, per, ...] (lap dim)
        locals_ = [p[0] for p in params_local]
        n_micro = xm.shape[0]
        stage = jax.lax.axis_index(pp_axis)
        s_count = nstage
        rounds = -(-n_micro // s_count)             # ceil, v>1 rounds
        ring_n = 2 * v * s_count
        span = rounds * v * s_count                 # F-tick count (v>1)
        total = (n_micro + 2 * s_count - 1) if v == 1 \
            else (span + v * s_count + s_count - 1)
        is_last = stage == s_count - 1
        chunk_shapes = [tuple(p.shape[(2 if v > 1 else 1):])
                        for p in params_local]

        def fwd_fn(chunk, inp):
            return stage_fn(chunk, inp, *extra)

        def last_fn(chunk, inp, tailp, lbls):
            return tail_fn(tailp, stage_fn(chunk, inp, *extra), *lbls)

        act = jax.eval_shape(lambda x: x[0], xm)
        zero_act = _pvary(jnp.zeros(act.shape, act.dtype), pp_axis)
        xmv = _pvary(xm, pp_axis)
        tail_idx_v = tuple(_pvary(t, pp_axis) for t in tail_idx)
        # tail params must be VARYING here: a vjp wrt a replicated
        # (unvaried) input makes jax transpose-insert a psum over pp on
        # its cotangent at every tick — wrong (it mixes the other
        # stages' masked-out branch values) and a collective per tick.
        # Varying inputs keep cotangents device-local; the single psum
        # at the end does the cross-stage reduction.
        tail_params = tuple(_pvary(t, pp_axis) for t in tail_params)
        # per-lap STATIC chunk slices: stable tracer identities, so the
        # residual weight-leaf filter works per lap (for v>1 the laps
        # are lax.switch branches — each branch closes over its own
        # static chunk, never a dynamically-indexed copy)
        if v == 1:
            chunks_static = [locals_]
        else:
            chunks_static = [[p[l] for p in locals_] for l in range(v)]
        const_pools = [list(ch) + list(extra) for ch in chunks_static]
        const_pool = const_pools[0]
        box: dict = {}
        if stash:
            # trace-time probe: residual shapes + which leaves are just
            # re-reads of the (tick-invariant) weights/extras — those
            # are re-injected at backward instead of ring-buffered
            def _probe(ip):
                _, vjp = jax.vjp(lambda ch, i: stage_fn(ch, i, *extra),
                                 chunks_static[0], ip)
                flat, _ = jax.tree_util.tree_flatten(vjp)
                box["const_ix"] = [
                    next((j for j, c in enumerate(const_pool)
                          if l is c), -1) for l in flat]
                box["res_sd"] = [(tuple(l.shape), l.dtype)
                                 for l in flat]
                return 0

            # probe with zero_act (not the act template): its aval
            # carries the {pp} varying annotation the scan carries need
            jax.eval_shape(_probe, zero_act)
            const_ix = box["const_ix"]
            # identity filtering is heuristic (vjp residual leaves that
            # ARE the weight tracers) — if it matched nothing, the full
            # weight set would be ring-buffered 2S times per device.
            # Make that degradation loud instead of a silent HBM blowup.
            import numpy as _np
            stored_b = sum(
                int(_np.prod(sh)) * _np.dtype(dt).itemsize
                for (sh, dt), ci in zip(box["res_sd"], const_ix)
                if ci < 0)
            act_b = int(_np.prod(act.shape)) * _np.dtype(
                act.dtype).itemsize
            weight_b = sum(int(_np.prod(c.shape)) * _np.dtype(
                c.dtype).itemsize for c in locals_)
            if all(ci < 0 for ci in const_ix) and \
                    stored_b > 4 * act_b + weight_b:
                import warnings
                warnings.warn(
                    "1F1B stash: no vjp residual leaf matched a weight "
                    f"tracer; ring-buffering {stored_b >> 20} MiB per "
                    "slot (includes per-slot weight copies). Set "
                    "stash=False or simplify the stage fn.",
                    RuntimeWarning, stacklevel=2)
            ring0 = (
                tuple(_pvary(jnp.zeros((ring_n,) + sh, dt), pp_axis)
                      for (sh, dt), ci in zip(box["res_sd"], const_ix)
                      if ci < 0),
                _pvary(jnp.zeros((ring_n,) + act.shape, act.dtype),
                       pp_axis),                             # stage outs
            )
        else:
            ring0 = _pvary(jnp.zeros((ring_n,) + act.shape, act.dtype),
                           pp_axis)                          # stage inputs
        state = (
            zero_act,                                        # fwd carry
            zero_act,                                        # bwd carry
            ring0,
            tuple(_pvary(jnp.zeros(c.shape, jnp.float32), pp_axis)
                  for c in locals_),                         # param grads
            tuple(_pvary(jnp.zeros(t.shape, jnp.float32), pp_axis)
                  for t in tail_params),                     # tail grads
            _pvary(jnp.zeros(xm.shape, jnp.float32), pp_axis),  # dxm
            _pvary(jnp.zeros((), jnp.float32), pp_axis),     # loss sum
            _pvary(jnp.zeros((), jnp.float32), pp_axis),     # count
        )

        def step(t, st):
            fcarry, bcarry, ring, gp, gt, dxm, lsum, cnt = st

            # ---- forward ------------------------------------------------
            if v == 1:
                # F_s(m) at t = m + s
                mf = t - stage
                active_f = (mf >= 0) & (mf < n_micro)
                mfc = jnp.clip(mf, 0, n_micro - 1)
                slot_f = mfc % ring_n
                lap_f = jnp.zeros((), t.dtype)
                chunk_f = locals_
                feed_f = stage == 0
            else:
                # interleaved: F of chunk lap·S+d on microbatch r·S+j at
                # t = r·vS + lap·S + j + d  (device tick u = t - d)
                uf = t - stage
                ufc = jnp.clip(uf, 0, span - 1)
                r_f = ufc // (v * s_count)
                q_f = ufc % (v * s_count)
                lap_f = q_f // s_count
                mf = r_f * s_count + q_f % s_count
                active_f = (uf >= 0) & (uf < span) & (mf < n_micro)
                mfc = jnp.clip(mf, 0, n_micro - 1)
                slot_f = ufc % ring_n
                chunk_f = [jax.lax.dynamic_index_in_dim(p, lap_f, 0,
                                                        False)
                           for p in locals_]
                feed_f = (stage == 0) & (lap_f == 0)
            inp = jnp.where(feed_f, xmv[mfc], fcarry)

            if stash:
                def _capture(chunk):
                    """vjp-capture branch for one lap's static chunk:
                    returns (y, stored residual leaves)."""
                    def br(ip):
                        y, vjp = jax.vjp(
                            lambda ch, i: fwd_fn(ch, i), chunk, ip)
                        flat, td = jax.tree_util.tree_flatten(vjp)
                        box["td"] = td
                        return y, tuple(
                            l for l, ci in zip(flat, const_ix)
                            if ci < 0)
                    return br

                def do_f(rs):
                    res_rings, y_ring = rs
                    if v == 1:
                        y, stored = _capture(chunks_static[0])(inp)
                    else:
                        y, stored = jax.lax.switch(
                            lap_f, [_capture(ch)
                                    for ch in chunks_static], inp)
                    res_rings = tuple(
                        jax.lax.dynamic_update_index_in_dim(
                            r, v_, slot_f, 0)
                        for r, v_ in zip(res_rings, stored))
                    y_ring = jax.lax.dynamic_update_index_in_dim(
                        y_ring, y, slot_f, 0)
                    return y, (res_rings, y_ring)
            else:
                def do_f(ring):
                    y = fwd_fn(chunk_f, inp)
                    ring = jax.lax.dynamic_update_index_in_dim(
                        ring, inp, slot_f, 0)
                    return y, ring

            y, ring = _branch(
                active_f, do_f, lambda ring: (inp, ring), ring)

            # ---- backward ----------------------------------------------
            if v == 1:
                # B_s(m) at t = m + 2S-1-s
                mb = t - (2 * s_count - 1) + stage
                active_b = (mb >= 0) & (mb < n_micro)
                mbc = jnp.clip(mb, 0, n_micro - 1)
                slot_b = mbc % ring_n
                chunk_b = locals_
                lap_b = jnp.zeros((), t.dtype)
                is_last_chunk = is_last
            else:
                # mirror schedule with delay D = vS: B of chunk lap·S+d
                # at t = D + r·vS + (v-1-lap)·S + j + (S-1-d)
                ub = t - v * s_count - (s_count - 1 - stage)
                ubc = jnp.clip(ub, 0, span - 1)
                r_b = ubc // (v * s_count)
                q_b = ubc % (v * s_count)
                lap_b = v - 1 - q_b // s_count
                j_b = q_b % s_count
                mb = r_b * s_count + j_b
                active_b = (ub >= 0) & (ub < span) & (mb < n_micro)
                mbc = jnp.clip(mb, 0, n_micro - 1)
                # ring slot keyed on the F tick of the same (chunk, m)
                slot_b = (r_b * v * s_count + lap_b * s_count
                          + j_b) % ring_n
                chunk_b = [jax.lax.dynamic_index_in_dim(p, lap_b, 0,
                                                        False)
                           for p in locals_]
                is_last_chunk = is_last & (lap_b == v - 1)
            sinp = None if stash else ring[slot_b]

            def _apply_saved_vjp(ct):
                """Rebuild the forward tick's vjp from ring residuals +
                re-injected constant leaves and apply it (stash mode).
                For v>1 the constants are the BACKWARD lap's static
                chunk — selected with lax.switch so identities stay
                per-branch."""
                res_rings, _ = ring
                stored_b = [jax.lax.dynamic_index_in_dim(r, slot_b, 0,
                                                         False)
                            for r in res_rings]

                def _rebuild(pool):
                    def br(args):
                        stored, ct_ = args
                        it = iter(stored)
                        re_flat = [pool[ci] if ci >= 0 else next(it)
                                   for ci in const_ix]
                        vjp_saved = jax.tree_util.tree_unflatten(
                            box["td"], re_flat)
                        return vjp_saved(ct_)
                    return br

                if v == 1:
                    return _rebuild(const_pools[0])(
                        (tuple(stored_b), ct))
                return jax.lax.switch(
                    lap_b, [_rebuild(p) for p in const_pools],
                    (tuple(stored_b), ct))

            def seed(p, fill):
                ct = jnp.full(p.shape, fill, p.dtype)
                if pp_axis in getattr(_typeof(p), "vma", ()):
                    ct = _pvary(ct, pp_axis)
                return ct

            def bwd_last(_):
                lbls = tuple(ti[mbc] for ti in tail_idx_v)
                if stash:
                    y_saved = jax.lax.dynamic_index_in_dim(
                        ring[1], slot_b, 0, False)
                    (s_, c_), tvjp = jax.vjp(
                        lambda tp, yy: tail_fn(tp, yy, *lbls),
                        tuple(tail_params), y_saved)
                    dtp, dy = tvjp((seed(s_, 1.0), seed(c_, 0.0)))
                    dch, dip = _apply_saved_vjp(dy)
                else:
                    (s_, c_), vjp = jax.vjp(
                        lambda ch, ip, tp: last_fn(ch, ip, tp, lbls),
                        chunk_b, sinp, tuple(tail_params))
                    dch, dip, dtp = vjp((seed(s_, 1.0), seed(c_, 0.0)))
                # cotangents of replicated (unvaried) inputs come back
                # unvaried — align vma/pytree with the other branches
                dch = tuple(_pvary(g, pp_axis) for g in dch)
                dip = _pvary(dip, pp_axis)
                dtp = tuple(_pvary(g, pp_axis) for g in dtp)
                return (dch, dip, dtp,
                        _pvary(s_.astype(jnp.float32), pp_axis),
                        _pvary(c_.astype(jnp.float32), pp_axis))

            def bwd_mid(_):
                if stash:
                    dch, dip = _apply_saved_vjp(bcarry)
                else:
                    _, vjp = jax.vjp(
                        lambda ch, ip: fwd_fn(ch, ip), chunk_b, sinp)
                    dch, dip = vjp(bcarry)
                dch = tuple(_pvary(g, pp_axis) for g in dch)
                zt = tuple(_pvary(jnp.zeros(t.shape, t.dtype), pp_axis)
                           for t in tail_params)
                z = _pvary(jnp.zeros((), jnp.float32), pp_axis)
                return dch, _pvary(dip, pp_axis), zt, z, z

            def do_b(_):
                return _branch(is_last_chunk, bwd_last, bwd_mid, None)

            def skip_b(_):
                zc = tuple(_pvary(jnp.zeros(sh, p.dtype), pp_axis)
                           for sh, p in zip(chunk_shapes, locals_))
                zt = tuple(_pvary(jnp.zeros(t.shape, t.dtype), pp_axis)
                           for t in tail_params)
                z = _pvary(jnp.zeros((), jnp.float32), pp_axis)
                return zc, zero_act, zt, z, z

            dch, dip, dtp, ds, dc = _branch(active_b, do_b, skip_b,
                                            None)
            if v == 1:
                gp = tuple(g + d.astype(jnp.float32)
                           for g, d in zip(gp, dch))
            else:
                # scatter-add the chunk grad into its lap slot
                gp = tuple(
                    jax.lax.dynamic_update_index_in_dim(
                        g, jax.lax.dynamic_index_in_dim(g, lap_b, 0,
                                                        False)
                        + d.astype(jnp.float32), lap_b, 0)
                    for g, d in zip(gp, dch))
            gt = tuple(g + d.astype(jnp.float32)
                       for g, d in zip(gt, dtp))
            lsum = lsum + ds
            cnt = cnt + dc
            # stage 0's (lap 0's) dinp is this microbatch's input grad
            dxm = jnp.where(
                active_b & (stage == 0) & (lap_b == 0),
                jax.lax.dynamic_update_index_in_dim(
                    dxm, dip.astype(jnp.float32), mbc, 0),
                dxm)

            # ---- rotate: y forward, dinp backward ----------------------
            fcarry = jax.lax.ppermute(
                y, pp_axis,
                [(i, (i + 1) % s_count) for i in range(s_count)])
            bcarry = jax.lax.ppermute(
                dip.astype(act.dtype), pp_axis,
                [(i, (i - 1) % s_count) for i in range(s_count)])
            return fcarry, bcarry, ring, gp, gt, dxm, lsum, cnt

        _, _, _, gp, gt, dxm, lsum, cnt = jax.lax.fori_loop(
            0, total, step, state)
        lsum = jax.lax.psum(lsum, pp_axis)
        cnt = jax.lax.psum(cnt, pp_axis)
        dxm = jax.lax.psum(dxm, pp_axis)          # stage 0 contributed
        gt = tuple(jax.lax.psum(g, pp_axis) for g in gt)   # last stage
        gp = tuple(g[None] for g in gp)           # [1, per, ...]
        return lsum, cnt, gp, dxm, gt

    in_specs = (tuple(P(pp_axis) for _ in range(n_params)), P(),
                *(P() for _ in range(n_extra + n_tail_params
                                     + n_tail_idx)))
    out_specs = (P(), P(), tuple(P(pp_axis) for _ in range(n_params)),
                 P(), tuple(P() for _ in range(n_tail_params)))
    manual = ({pp_axis} if hasattr(jax, "shard_map")
              else set(mesh.axis_names))
    mapped = _compat_shard_map(inner, mesh=mesh, axis_names=manual,
                           in_specs=in_specs, out_specs=out_specs)
    return jax.jit(mapped)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 9, 10))
def pipeline_train_1f1b(stage_fn, tail_fn, mesh, pp_axis, stacked,
                        x_micro, extra, tail_params, tail_indexed,
                        stash: bool = False, n_virtual: int = 1):
    """Mean loss of the pipelined model+loss-head under the 1F1B
    schedule (interleaved when ``n_virtual > 1``).  ``tail_fn`` must
    return ``(loss_sum, valid_count)``; the result is
    Σloss_sum / max(Σcount, 1) over all microbatches.

    Differentiable via custom_vjp: under jax.grad the fwd rule runs the
    fused 1F1B loop ONCE, producing loss and all gradients together
    (ring buffers ⇒ activation memory ∝ pp, not n_micro); without grad,
    the plain forward pipeline runs (cond-guarded tail).
    stacked: v==1: tuple of [S, per_chunk, ...] arrays; v>1: the
    interleaved [S, v, per_chunk, ...] device-major layout (chunk
    l*S+d at [d, l]) — never global chunk order, so no cross-shard
    relayout happens.  ``stash``: ring-buffer VJP residuals so backward
    ticks skip the forward recompute (see _jitted_1f1b)."""
    loss_sum, count = gpipe_spmd(
        list(stacked), x_micro, stage_fn, *extra, mesh=mesh,
        pp_axis=pp_axis, n_virtual=n_virtual, tail_fn=tail_fn,
        tail_params=tuple(tail_params),
        tail_indexed=tuple(tail_indexed), tail_cond=True)
    return loss_sum / jnp.maximum(count, 1.0)


def _ptrain_1f1b_fwd(stage_fn, tail_fn, mesh, pp_axis, stacked, x_micro,
                     extra, tail_params, tail_indexed,
                     stash: bool = False, n_virtual: int = 1):
    eng = _jitted_1f1b(stage_fn, tail_fn, mesh, pp_axis, len(stacked),
                       len(extra), len(tail_params), len(tail_indexed),
                       stash, n_virtual)
    # v>1 stacks arrive already in [S, v, per, ...] engine layout;
    # gradients come back in the same layout — no relayout either way
    if n_virtual > 1:
        nstage = mesh.shape[pp_axis]
        for p in stacked:
            enforce(p.shape[0] == nstage and p.shape[1] == n_virtual,
                    f"interleaved stacks must be [S={nstage}, "
                    f"v={n_virtual}, per, ...]; got {p.shape}")
    lsum, cnt, gp, dxm, gt = eng(tuple(stacked), x_micro, *extra,
                                 *tail_params, *tail_indexed)
    denom = jnp.maximum(cnt, 1.0)
    loss = lsum / denom
    # cotangents must come back in the primal dtypes; scale-by-ct in
    # the bwd rule preserves each grad's dtype
    gp = tuple(g.astype(p.dtype) for g, p in zip(gp, stacked))
    dxm = dxm.astype(x_micro.dtype)
    gt = tuple(g.astype(t.dtype) for g, t in zip(gt, tail_params))
    return loss, (gp, dxm, gt, denom)


def _ptrain_1f1b_bwd(stage_fn, tail_fn, mesh, pp_axis, stash, n_virtual,
                     res, ct):
    gp, dxm, gt, denom = res
    scale = ct / denom
    dstacked = tuple((g * scale).astype(g.dtype) for g in gp)
    dx = (dxm * scale).astype(dxm.dtype)
    dtail = tuple((g * scale).astype(g.dtype) for g in gt)
    return dstacked, dx, None, dtail, None


pipeline_train_1f1b.defvjp(_ptrain_1f1b_fwd, _ptrain_1f1b_bwd)


# ---------------------------------------------------------------------------
# Paddle-parity layer-list API
# ---------------------------------------------------------------------------

def _balance_partition(costs: Sequence[int], s: int) -> List[int]:
    """Contiguous partition of ``costs`` into ``s`` parts minimizing the
    max part sum (classic DP; n and s are tiny — layer counts)."""
    n = len(costs)
    enforce(n >= s, f"cannot split {n} layers into {s} stages")
    prefix = [0]
    for c in costs:
        prefix.append(prefix[-1] + c)
    INF = float("inf")
    # best[k][i] = minimal max-part-sum splitting costs[:i] into k parts
    best = [[INF] * (n + 1) for _ in range(s + 1)]
    cut = [[0] * (n + 1) for _ in range(s + 1)]
    best[0][0] = 0.0
    for k in range(1, s + 1):
        for i in range(k, n + 1):
            for j in range(k - 1, i):
                val = max(best[k - 1][j], prefix[i] - prefix[j])
                if val < best[k][i]:
                    best[k][i] = val
                    cut[k][i] = j
    bounds = [n]
    k, i = s, n
    while k > 0:
        i = cut[k][i]
        bounds.append(i)
        k -= 1
    return list(reversed(bounds))

class LayerDesc:
    """Deferred layer constructor (fleet pp_layers.LayerDesc parity)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs
        enforce(issubclass(layer_cls, Layer) or callable(layer_cls),
                "LayerDesc needs a Layer subclass")

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Layer whose parameters are shared across stages (e.g. tied
    embedding/lm-head).  Under single-program SPMD the sharing is simply
    object identity — the first build is reused."""

    def __init__(self, key, layer_cls, *args, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """fleet.meta_parallel.PipelineLayer parity.

    Holds the full layer list (single-program SPMD: every process owns
    the whole model; stage placement is a sharding concern, not an
    ownership concern).  ``forward`` runs the stack sequentially — the
    semantics the reference's PipelineParallel produces.  The pipelined
    *execution* is the compiled path: models with a uniform decoder
    stack (e.g. LlamaForCausalLMPipe) lower it through gpipe_spmd.
    """

    def __init__(self, layers, num_stages: Optional[int] = None,
                 topology=None, loss_fn=None, seg_method="uniform",
                 recompute_interval: int = 0, **kwargs):
        super().__init__()
        self._loss_fn = loss_fn
        self._seg_method = seg_method
        self._shared: dict = {}
        built: List[Layer] = []
        self.descs = list(layers)
        for d in self.descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    built.append(self._shared[d.layer_name])
                else:
                    lyr = d.build_layer()
                    self._shared[d.layer_name] = lyr
                    built.append(lyr)
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            else:
                enforce(isinstance(d, Layer),
                        "PipelineLayer accepts Layers or LayerDescs")
                built.append(d)
        self.run_function = LayerList(built)
        if num_stages is None and topology is not None:
            num_stages = topology.get_dim("pipe") if hasattr(
                topology, "get_dim") else 1
        self._num_stages = num_stages or 1
        self._segment()

    def _segment(self):
        """Compute stage boundaries per ``seg_method`` (fleet
        PipelineLayer ``seg_method`` parity):

        - ``"uniform"``: equal layer counts per stage;
        - ``"layer:<Class>"``: stage boundaries only at occurrences of
          the named layer class (the reference's way of keeping e.g. a
          decoder block plus its surrounding glue on one stage);
        - ``"flops"``: balance per-stage cost using parameter count as
          the FLOPs proxy (for dense layers FLOPs ≈ 2·params·tokens, so
          param totals rank transformer blocks correctly).
        """
        n = len(self.run_function)
        s = self._num_stages
        method = self._seg_method or "uniform"
        if method == "uniform":
            base, extra = divmod(n, s)
            bounds = [0]
            for i in range(s):
                bounds.append(bounds[-1] + base + (1 if i < extra else 0))
        elif method.startswith("layer:"):
            name = method[len("layer:"):]
            marks = [i for i, lyr in enumerate(self.run_function)
                     if type(lyr).__name__ == name]
            enforce(len(marks) >= s,
                    f"seg_method '{method}': found {len(marks)} "
                    f"'{name}' layers < {s} stages")
            # first stage starts at 0; later stages begin at evenly
            # strided marker layers
            bounds = [0]
            base, extra = divmod(len(marks), s)
            idx = 0
            for i in range(s - 1):
                idx += base + (1 if i < extra else 0)
                bounds.append(marks[idx])
            bounds.append(n)
        elif method == "flops":
            costs = [max(1, sum(int(np.prod(p.shape))
                                for p in lyr.parameters()))
                     for lyr in self.run_function]
            bounds = _balance_partition(costs, s)
        else:
            enforce(False, f"unknown seg_method '{method}'")
        self.segment_parts = bounds

    def get_stage_layers(self, stage: int) -> List[Layer]:
        lo, hi = self.segment_parts[stage], self.segment_parts[stage + 1]
        return list(self.run_function[lo:hi])

    @property
    def num_stages(self) -> int:
        return self._num_stages

    def forward(self, x, *args, **kwargs):
        # side inputs (e.g. rope cos/sin) are forwarded to every layer —
        # dropping them silently diverged from the sequential-parity
        # contract (ADVICE.md round-1)
        for lyr in self.run_function:
            x = lyr(x, *args, **kwargs)
        return x
