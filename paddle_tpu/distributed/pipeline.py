"""Pipeline parallelism.

Reference parity: fleet/meta_parallel/parallel_layers/pp_layers.py
(LayerDesc, SharedLayerDesc, PipelineLayer — layer-list segmentation) and
fleet/meta_parallel/pipeline_parallel.py (PipelineParallel.train_batch:
python 1F1B microbatch loop over NCCL p2p, SURVEY.md §3.3).

TPU-native design: the reference's python-level schedule loop becomes ONE
compiled SPMD program — ``gpipe_spmd`` runs a GPipe-style circulating
pipeline inside ``jax.shard_map`` manual over ONLY the ``pp`` mesh axis
(dp/sharding/mp stay auto, so GSPMD still lays out data/tensor/FSDP
parallelism inside each stage).  Stage params are stacked on a leading
axis sharded over ``pp``; activations rotate between stages with
``lax.ppermute`` over ICI; backward is derived by jax.grad through the
loop (GPipe schedule: all-forward then reversed all-backward, remat per
stage via jax.checkpoint).  Bubble fraction = (S-1)/(M+S-1), same as
1F1B; 1F1B's memory advantage is recovered with stage remat instead.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..common.errors import enforce
from ..nn.layer import Layer
from ..nn.container import LayerList

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer", "gpipe_spmd"]


# ---------------------------------------------------------------------------
# The compiled SPMD pipeline engine
# ---------------------------------------------------------------------------

def _pvary(x, axis):
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, (axis,), to="varying")
    return jax.lax.pvary(x, (axis,))


@functools.lru_cache(maxsize=64)
def _jitted_pipeline(stage_fn: Callable, mesh, pp_axis: str,
                     n_params: int, n_extra: int, remat: bool,
                     n_virtual: int, tail_fn: Optional[Callable] = None,
                     n_tail_params: int = 0, n_tail_idx: int = 0):
    """Build + cache the jitted shard_map engine (keyed on a *stable*
    stage_fn object so eager loops don't re-trace every step).

    Schedule: circulating pipeline.  With ``n_virtual == 1`` this is
    GPipe (each device owns one contiguous chunk; microbatch m enters
    stage 0 at tick m).  With ``n_virtual = v > 1`` it is the
    interleaved / virtual-stage schedule (Megatron "virtual pipeline"):
    device d owns chunks d, d+S, …, d+(v-1)·S and microbatches cycle the
    ring v times in rounds of S, shrinking the fill bubble from
    (S-1)·T_stage to (S-1)·T_stage/v.

    Output contract — two modes:

    * no ``tail_fn``: each device returns its own [n_micro, …] buffer
      (only the last stage's is meaningful) with out_specs sharded over
      ``pp_axis`` — the caller slices the last stage's shard.
    * ``tail_fn`` (the training path): the loss head runs *inside* the
      pipeline on each completed microbatch (the reference computes the
      loss on the last stage — fleet PipelineParallel ``_loss_fn``) and
      only the accumulated scalars are psum'd over pp.  This removes
      the round-1 zero-fill + psum of the full [n_micro, batch, …]
      activation buffer AND never materializes whole-batch logits.
    """
    fn = jax.checkpoint(stage_fn) if remat else stage_fn
    tfn = (jax.checkpoint(tail_fn) if (remat and tail_fn is not None)
           else tail_fn)

    def inner(params_local, xm, *rest):
        extra_local = rest[:n_extra]
        tail_local = rest[n_extra:n_extra + n_tail_params]
        tail_idx = rest[n_extra + n_tail_params:]
        # local slab: [1, v, per_chunk, ...] -> [v, per_chunk, ...]
        locals_ = [p[0] for p in params_local]
        n_micro = xm.shape[0]
        stage = jax.lax.axis_index(pp_axis)
        nstage = jax.lax.axis_size(pp_axis)
        v = n_virtual
        rounds = -(-n_micro // nstage) if v > 1 else 1
        total = (rounds * v * nstage + nstage - 1) if v > 1 \
            else (n_micro + nstage - 1)
        carry = _pvary(jnp.zeros(xm.shape[1:], xm.dtype), pp_axis)
        xmv = _pvary(xm, pp_axis)   # feed index is stage-dependent
        if tfn is None:
            acc0 = _pvary(jnp.zeros(xm.shape, xm.dtype), pp_axis)
        else:
            shapes = jax.eval_shape(
                tail_fn, tail_local, xm[0], *(ti[0] for ti in tail_idx))
            acc0 = jax.tree_util.tree_map(
                lambda s: _pvary(jnp.zeros(s.shape, s.dtype), pp_axis),
                shapes)

        def step(t, state):
            carry, acc = state
            u = t - stage                     # device-local schedule tick
            if v > 1:
                uc = jnp.clip(u, 0, rounds * v * nstage - 1)
                r, uu = uc // (v * nstage), uc % (v * nstage)
                lap = uu // nstage
                m = r * nstage + uu % nstage  # microbatch index
            else:
                lap = jnp.zeros((), u.dtype)
                m = jnp.clip(u, 0, n_micro - 1)
            mc = jnp.minimum(m, n_micro - 1)
            feed = xmv[mc]
            inp = jnp.where((stage == 0) & (lap == 0), feed, carry)
            chunk = [jax.lax.dynamic_index_in_dim(p, lap, 0, False)
                     for p in locals_]
            y = fn(chunk, inp, *extra_local)
            keep = ((stage == nstage - 1) & (u >= 0) & (m < n_micro)
                    & (lap == v - 1))
            if tfn is None:
                acc = jax.lax.dynamic_update_index_in_dim(
                    acc, jnp.where(keep, y, acc[mc]), mc, 0)
            else:
                # the tail runs every tick on every stage and is masked
                # (SPMD lockstep).  A lax.cond would skip the dead
                # evaluations, but grad-of-cond inside scan inside
                # shard_map aborts XLA:CPU (jax 0.9) — and the masked
                # work rides ticks where non-final stages would
                # otherwise idle at the next ppermute barrier anyway.
                tout = tfn(tail_local, y, *(ti[mc] for ti in tail_idx))
                acc = jax.tree_util.tree_map(
                    lambda a, o: a + jnp.where(keep, o, jnp.zeros_like(o)),
                    acc, tout)
            nxt = jax.lax.ppermute(
                y, pp_axis, [(i, (i + 1) % nstage) for i in range(nstage)])
            return nxt, acc

        carry, acc = jax.lax.fori_loop(0, total, step, (carry, acc0))
        if tfn is None:
            return acc[None]                 # [1, n_micro, ...] per stage
        # scalars (loss sums/counts): psum over pp is O(1) traffic
        return jax.tree_util.tree_map(
            lambda a: jax.lax.psum(a, pp_axis), acc)

    in_specs = (tuple(P(pp_axis) for _ in range(n_params)), P(),
                *(P() for _ in range(n_extra + n_tail_params + n_tail_idx)))
    out_specs = P() if tail_fn is not None else P(pp_axis)
    mapped = jax.shard_map(inner, mesh=mesh, axis_names={pp_axis},
                           in_specs=in_specs, out_specs=out_specs)
    # jit wrapper: eager evaluation of checkpoint/scan inside shard_map is
    # unsupported; under an outer jit this inlines
    return jax.jit(mapped)


def gpipe_spmd(params: Sequence[jax.Array], x_micro: jax.Array,
               stage_fn: Callable, *extra,
               mesh, pp_axis: str = "pp", remat: bool = True,
               n_virtual: int = 1, tail_fn: Optional[Callable] = None,
               tail_params: Sequence[jax.Array] = (),
               tail_indexed: Sequence[jax.Array] = ()):
    """Run ``stage_fn`` as a circulating SPMD pipeline.

    params:   arrays stacked [n_chunks, ...] in global chunk order,
              where n_chunks = pp_size * n_virtual; chunk l*S+d is
              placed on device d as its lap-l virtual stage.
    x_micro:  [n_micro, micro_batch, ...] input microbatches (replicated
              over pp; may be sharded over data axes).
    stage_fn: (local_params_list, h, *extra) -> h, applied by every
              stage.  Pass a STABLE callable (module-level or cached) —
              the compiled engine is cached keyed on it.
    extra:    broadcast side inputs (e.g. rope tables), replicated.
    n_virtual: virtual stages per device (interleaved schedule).
    tail_fn:  optional (tail_params, y, *per_micro) -> pytree of arrays;
              runs on each completed microbatch at the last stage (loss
              head); results are summed over microbatches.  Must be a
              STABLE callable, like stage_fn.
    tail_params: side parameters for tail_fn (e.g. final norm + lm head
              weights), replicated over pp (mp/dp shardings still apply).
    tail_indexed: arrays with a leading [n_micro] dim, indexed per
              microbatch and passed to tail_fn (e.g. labels).

    Returns [n_micro, micro_batch, ...] outputs of the final stage, or
    the summed tail pytree when ``tail_fn`` is given.
    """
    nstage = mesh.shape[pp_axis]
    n_chunks = params[0].shape[0]
    enforce(n_chunks == nstage * n_virtual,
            f"stacked chunk dim {n_chunks} != mesh '{pp_axis}' size "
            f"{nstage} * n_virtual {n_virtual}")
    # interleaved placement: global chunk order [v*S, ...] -> [S, v, ...]
    # so dim 0 shards over pp and dim 1 indexes the device's laps
    stacked = []
    for p in params:
        q = p.reshape((n_virtual, nstage) + p.shape[1:])
        stacked.append(jnp.swapaxes(q, 0, 1))
    fn = _jitted_pipeline(stage_fn, mesh, pp_axis, len(params),
                          len(extra), remat, n_virtual, tail_fn,
                          len(tail_params), len(tail_indexed))
    out = fn(tuple(stacked), x_micro, *extra, *tail_params, *tail_indexed)
    if tail_fn is not None:
        return out
    return out[nstage - 1]                   # last stage's buffer


# ---------------------------------------------------------------------------
# Paddle-parity layer-list API
# ---------------------------------------------------------------------------

def _balance_partition(costs: Sequence[int], s: int) -> List[int]:
    """Contiguous partition of ``costs`` into ``s`` parts minimizing the
    max part sum (classic DP; n and s are tiny — layer counts)."""
    n = len(costs)
    enforce(n >= s, f"cannot split {n} layers into {s} stages")
    prefix = [0]
    for c in costs:
        prefix.append(prefix[-1] + c)
    INF = float("inf")
    # best[k][i] = minimal max-part-sum splitting costs[:i] into k parts
    best = [[INF] * (n + 1) for _ in range(s + 1)]
    cut = [[0] * (n + 1) for _ in range(s + 1)]
    best[0][0] = 0.0
    for k in range(1, s + 1):
        for i in range(k, n + 1):
            for j in range(k - 1, i):
                val = max(best[k - 1][j], prefix[i] - prefix[j])
                if val < best[k][i]:
                    best[k][i] = val
                    cut[k][i] = j
    bounds = [n]
    k, i = s, n
    while k > 0:
        i = cut[k][i]
        bounds.append(i)
        k -= 1
    return list(reversed(bounds))

class LayerDesc:
    """Deferred layer constructor (fleet pp_layers.LayerDesc parity)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs
        enforce(issubclass(layer_cls, Layer) or callable(layer_cls),
                "LayerDesc needs a Layer subclass")

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Layer whose parameters are shared across stages (e.g. tied
    embedding/lm-head).  Under single-program SPMD the sharing is simply
    object identity — the first build is reused."""

    def __init__(self, key, layer_cls, *args, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """fleet.meta_parallel.PipelineLayer parity.

    Holds the full layer list (single-program SPMD: every process owns
    the whole model; stage placement is a sharding concern, not an
    ownership concern).  ``forward`` runs the stack sequentially — the
    semantics the reference's PipelineParallel produces.  The pipelined
    *execution* is the compiled path: models with a uniform decoder
    stack (e.g. LlamaForCausalLMPipe) lower it through gpipe_spmd.
    """

    def __init__(self, layers, num_stages: Optional[int] = None,
                 topology=None, loss_fn=None, seg_method="uniform",
                 recompute_interval: int = 0, **kwargs):
        super().__init__()
        self._loss_fn = loss_fn
        self._seg_method = seg_method
        self._shared: dict = {}
        built: List[Layer] = []
        self.descs = list(layers)
        for d in self.descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    built.append(self._shared[d.layer_name])
                else:
                    lyr = d.build_layer()
                    self._shared[d.layer_name] = lyr
                    built.append(lyr)
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            else:
                enforce(isinstance(d, Layer),
                        "PipelineLayer accepts Layers or LayerDescs")
                built.append(d)
        self.run_function = LayerList(built)
        if num_stages is None and topology is not None:
            num_stages = topology.get_dim("pipe") if hasattr(
                topology, "get_dim") else 1
        self._num_stages = num_stages or 1
        self._segment()

    def _segment(self):
        """Compute stage boundaries per ``seg_method`` (fleet
        PipelineLayer ``seg_method`` parity):

        - ``"uniform"``: equal layer counts per stage;
        - ``"layer:<Class>"``: stage boundaries only at occurrences of
          the named layer class (the reference's way of keeping e.g. a
          decoder block plus its surrounding glue on one stage);
        - ``"flops"``: balance per-stage cost using parameter count as
          the FLOPs proxy (for dense layers FLOPs ≈ 2·params·tokens, so
          param totals rank transformer blocks correctly).
        """
        n = len(self.run_function)
        s = self._num_stages
        method = self._seg_method or "uniform"
        if method == "uniform":
            base, extra = divmod(n, s)
            bounds = [0]
            for i in range(s):
                bounds.append(bounds[-1] + base + (1 if i < extra else 0))
        elif method.startswith("layer:"):
            name = method[len("layer:"):]
            marks = [i for i, lyr in enumerate(self.run_function)
                     if type(lyr).__name__ == name]
            enforce(len(marks) >= s,
                    f"seg_method '{method}': found {len(marks)} "
                    f"'{name}' layers < {s} stages")
            # first stage starts at 0; later stages begin at evenly
            # strided marker layers
            bounds = [0]
            base, extra = divmod(len(marks), s)
            idx = 0
            for i in range(s - 1):
                idx += base + (1 if i < extra else 0)
                bounds.append(marks[idx])
            bounds.append(n)
        elif method == "flops":
            costs = [max(1, sum(int(np.prod(p.shape))
                                for p in lyr.parameters()))
                     for lyr in self.run_function]
            bounds = _balance_partition(costs, s)
        else:
            enforce(False, f"unknown seg_method '{method}'")
        self.segment_parts = bounds

    def get_stage_layers(self, stage: int) -> List[Layer]:
        lo, hi = self.segment_parts[stage], self.segment_parts[stage + 1]
        return list(self.run_function[lo:hi])

    @property
    def num_stages(self) -> int:
        return self._num_stages

    def forward(self, x, *args, **kwargs):
        # side inputs (e.g. rope cos/sin) are forwarded to every layer —
        # dropping them silently diverged from the sequential-parity
        # contract (ADVICE.md round-1)
        for lyr in self.run_function:
            x = lyr(x, *args, **kwargs)
        return x
