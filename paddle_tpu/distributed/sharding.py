"""Sharding (ZeRO 1-2-3) planning.

Reference parity: fleet/meta_parallel/sharding/group_sharded_stage{1,2,3}
+ group_sharded_optimizer_stage2 (param/grad/optimizer-state sharding with
allgather-on-demand and reduce-scatter hooks).

TPU-native design (SURVEY.md §2.3): stages become STATIC sharding specs —
  stage 1/2: params replicated over the ``sharding`` axis, optimizer
             moments sharded (grad reduce-scatter is what the partitioner
             emits for sharded-moment updates — stage-2 behavior falls
             out of XLA's scheduling);
  stage 3:   params themselves sharded over ``sharding`` (FSDP); XLA
             inserts the allgather-before-use / discard-after (and
             overlaps them), replacing GroupShardedStage3's python hooks.
The planner combines these with TP specs carried by ``dist_spec`` on
parameters (parallel_layers.py).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["ShardingPlan", "plan_param_spec", "group_sharded_parallel",
           "TPShardings"]


class TPShardings:
    """Hashable tensor-parallel sharding plan for the serving engine.

    Carried as a STATIC jit argument by the serving programs
    (engine.py): one distinct ``TPShardings`` per mesh shape hashes to
    one trace, so the one-compile-per-program invariant becomes
    one-compile-per-mesh-shape.  ``Mesh`` itself is hashable, which is
    what makes this safe to put in ``static_argnames``.

    ``constrain(x, dim)`` applies ``with_sharding_constraint`` with the
    tp axis on ``dim`` (``None`` = fully replicated); ``put(x, dim)``
    commits a host array the same way at init time.
    """

    __slots__ = ("mesh", "axis")

    def __init__(self, mesh: Mesh, axis: str = "tp"):
        self.mesh = mesh
        self.axis = axis

    @property
    def tp(self) -> int:
        return _axis_size(self.mesh, self.axis)

    def _sharding(self, ndim: int, dim: Optional[int]):
        from .. import compat
        spec = [None] * ndim
        if dim is not None:
            spec[dim] = self.axis
        return compat.named_sharding(self.mesh, *spec)

    def constrain(self, x, dim: Optional[int] = None):
        from .. import compat
        return compat.with_sharding_constraint(
            x, self._sharding(x.ndim, dim))

    def put(self, x, dim: Optional[int] = None):
        x = jax.numpy.asarray(x)
        return jax.device_put(x, self._sharding(x.ndim, dim))

    def __hash__(self):
        return hash((self.mesh, self.axis))

    def __eq__(self, other):
        return (isinstance(other, TPShardings)
                and self.mesh == other.mesh and self.axis == other.axis)

    def __repr__(self):
        return f"TPShardings(tp={self.tp}, axis={self.axis!r})"


def _axis_size(mesh: Mesh, name: str) -> int:
    return mesh.shape.get(name, 1)


def _shardable_dim(shape: Tuple[int, ...], size: int,
                   taken: Tuple[Optional[object], ...]) -> Optional[int]:
    """Largest dim divisible by ``size`` that is not already sharded."""
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if taken[i] is None and shape[i] % size == 0 and shape[i] >= size:
            return i
    return None


def plan_param_spec(param, mesh: Mesh, stage: int,
                    fsdp_axis: str = "sharding") -> PartitionSpec:
    """Combine the param's TP ``dist_spec`` with the ZeRO stage policy."""
    base = list(getattr(param, "dist_spec", None) or
                (None,) * param.ndim)
    base += [None] * (param.ndim - len(base))
    # drop annotated axes the dim cannot divide over (e.g. 4 experts on
    # an 8-wide ep fold) — replicate instead of failing at device_put
    for i, entry in enumerate(base):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        keep = []
        size = 1
        for a in axes:
            if a not in mesh.axis_names:   # e.g. ep on a non-MoE mesh
                continue
            a_sz = _axis_size(mesh, a)
            if param.shape[i] % (size * a_sz) == 0:
                keep.append(a)
                size *= a_sz
        base[i] = tuple(keep) if len(keep) > 1 else (
            keep[0] if keep else None)
    if stage >= 3 and _axis_size(mesh, fsdp_axis) > 1 \
            and fsdp_axis not in jax.tree_util.tree_leaves(base):
        shape = tuple(param.shape)
        dim = _shardable_dim(shape, _axis_size(mesh, fsdp_axis), tuple(base))
        if dim is not None:
            base[dim] = (base[dim], fsdp_axis) if base[dim] is not None \
                else fsdp_axis
    return PartitionSpec(*base)


def _slot_spec(param_spec: PartitionSpec, param_shape, mesh: Mesh,
               stage: int, fsdp_axis: str = "sharding") -> PartitionSpec:
    """Optimizer-moment sharding: same as the param, plus (stage 1/2) the
    sharding axis even when the param is replicated."""
    base = list(param_spec) + [None] * (len(param_shape) - len(param_spec))
    if stage >= 1 and _axis_size(mesh, fsdp_axis) > 1 \
            and fsdp_axis not in jax.tree_util.tree_leaves(base):
        dim = _shardable_dim(tuple(param_shape),
                             _axis_size(mesh, fsdp_axis), tuple(base))
        if dim is not None:
            base[dim] = (base[dim], fsdp_axis) if base[dim] is not None \
                else fsdp_axis
    return PartitionSpec(*base)


class ShardingPlan:
    """Computes NamedShardings for the full train state of a model."""

    def __init__(self, model, mesh: Mesh, stage: int = 1,
                 fsdp_axis: str = "sharding",
                 data_axes: Tuple[str, ...] = ("dp", "sharding")):
        self.model = model
        self.mesh = mesh
        self.stage = stage
        self.fsdp_axis = fsdp_axis
        self.data_axes = data_axes
        self.param_specs: Dict[str, PartitionSpec] = {}
        self.slot_specs: Dict[str, PartitionSpec] = {}
        for name, p in model.named_parameters():
            spec = plan_param_spec(p, mesh, stage, fsdp_axis)
            self.param_specs[name] = spec
            self.slot_specs[name] = _slot_spec(spec, p.shape, mesh, stage,
                                               fsdp_axis)

    # -- shardings for the CompiledTrainStep state pytree -------------------
    def state_shardings(self, state):
        mesh = self.mesh

        def param_shard(name):
            return NamedSharding(mesh, self.param_specs[name])

        params_s = {k: param_shard(k) for k in state["params"]}
        slots_s = {}
        for k, slots in state["opt"]["slots"].items():
            spec = self.slot_specs.get(k, PartitionSpec())
            slots_s[k] = {s: NamedSharding(mesh, spec) for s in slots}
        return {"params": params_s,
                "opt": {"slots": slots_s,
                        "step": NamedSharding(mesh, PartitionSpec())}}

    def batch_sharding(self, ndim: int = 2) -> NamedSharding:
        """Global batch sharded over the data axes on dim 0."""
        axes = tuple(a for a in self.data_axes
                     if _axis_size(self.mesh, a) > 1)
        spec = PartitionSpec(axes if axes else None,
                             *([None] * (ndim - 1)))
        return NamedSharding(self.mesh, spec)

    def place_state(self, state):
        """device_put the whole state tree onto the mesh per plan."""
        sh = self.state_shardings(state)
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, s), state, sh,
            is_leaf=lambda x: isinstance(x, jax.Array) or isinstance(
                x, (np.ndarray,)))

    def shard_batch(self, batch):
        def put(a):
            a = np.asarray(a) if not isinstance(a, jax.Array) else a
            return jax.device_put(a, self.batch_sharding(a.ndim))
        return jax.tree_util.tree_map(put, batch)


def group_sharded_parallel(model, optimizer, level: str = "os_g",
                           scaler=None, group=None, offload=False,
                           sync_buffers=False, buffer_max_size=2 ** 23,
                           segment_size=2 ** 20, sync_comm=False):
    """paddle.distributed.sharding.group_sharded_parallel parity:
    level 'os' = stage1, 'os_g' = stage2, 'p_g_os' = stage3.
    Returns (model, optimizer, scaler) with the plan attached; the
    compiled path reads ``model._sharding_stage``."""
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}[level]
    model._sharding_stage = stage
    optimizer._sharding_stage = stage
    return model, optimizer, scaler
