"""paddle.distributed.TCPStore — framework-level rendezvous KV store.

Reference parity: phi/core/distributed/store/tcp_store (SURVEY.md §2.4):
rank 0 hosts the server, all ranks are clients; set/get(blocking)/add/
wait/delete + barrier built on add.  The native backend is
core/csrc/tcp_store.cpp; a pure-python server/client speaking the SAME
wire protocol is the no-toolchain fallback (so mixed native/python
gangs interoperate).
"""
from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Dict, Optional

from ..common.errors import enforce
from ..core import load_native

__all__ = ["TCPStore"]

_SET, _GET, _ADD, _WAIT, _DEL, _CHECK = range(6)
_TIMEOUT_SENTINEL = (1 << 64) - 1


# ---------------------------------------------------------------------------
# pure-python server (wire-compatible with tcp_store.cpp)
# ---------------------------------------------------------------------------

class _PyServer:
    def __init__(self, host: str, port: int):
        self._kv: Dict[bytes, bytes] = {}
        self._cond = threading.Condition()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host or "0.0.0.0", port))
        self._sock.listen(128)
        self.port = self._sock.getsockname()[1]
        self._stop = False
        self._threads = []
        t = threading.Thread(target=self._accept, daemon=True)
        t.start()
        self._threads.append(t)

    def _accept(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    @staticmethod
    def _read_n(conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def _serve(self, conn):
        try:
            while not self._stop:
                hdr = self._read_n(conn, 5)
                if hdr is None:
                    return
                op, klen = struct.unpack("<BI", hdr)
                key = self._read_n(conn, klen) if klen else b""
                arg = struct.unpack("<Q", self._read_n(conn, 8))[0]
                payload = b""
                if op == _SET:
                    val = self._read_n(conn, arg) if arg else b""
                    with self._cond:
                        self._kv[key] = val
                        self._cond.notify_all()
                elif op in (_GET, _WAIT):
                    deadline = None if arg == 0 else \
                        time.monotonic() + arg / 1000.0
                    with self._cond:
                        while key not in self._kv and not self._stop:
                            left = None if deadline is None else \
                                deadline - time.monotonic()
                            if left is not None and left <= 0:
                                break
                            self._cond.wait(timeout=left)
                        if key not in self._kv:
                            conn.sendall(
                                struct.pack("<Q", _TIMEOUT_SENTINEL))
                            continue
                        payload = self._kv[key] if op == _GET else b""
                elif op == _ADD:
                    delta = struct.unpack("<q", struct.pack("<Q", arg))[0]
                    with self._cond:
                        raw = self._kv.get(key, b"\0" * 8)
                        # non-counter value -> start from 0 (native
                        # server semantics; wire compat)
                        if len(raw) != 8:
                            raw = b"\0" * 8
                        cur = struct.unpack("<q", raw)[0]
                        cur += delta
                        self._kv[key] = struct.pack("<q", cur)
                        payload = self._kv[key]
                        self._cond.notify_all()
                elif op == _DEL:
                    with self._cond:
                        self._kv.pop(key, None)
                elif op == _CHECK:
                    with self._cond:
                        payload = b"1" if key in self._kv else b"0"
                conn.sendall(struct.pack("<Q", len(payload)) + payload)
        except OSError:
            pass
        finally:
            conn.close()

    def stop(self):
        self._stop = True
        with self._cond:
            self._cond.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass


class _PyClient:
    def __init__(self, host, port, timeout_s):
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=timeout_s)
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(None)
        self._lock = threading.Lock()

    def _req(self, op, key: bytes, arg: int, val: bytes = b"") -> bytes:
        with self._lock:
            msg = struct.pack("<BI", op, len(key)) + key + \
                struct.pack("<Q", arg & ((1 << 64) - 1)) + \
                (val if op == _SET else b"")
            self._sock.sendall(msg)
            raw = _PyServer._read_n(self._sock, 8)
            enforce(raw is not None, "TCPStore connection lost")
            (length,) = struct.unpack("<Q", raw)
            if length == _TIMEOUT_SENTINEL:
                raise TimeoutError(f"TCPStore wait timed out on {key!r}")
            return _PyServer._read_n(self._sock, length) if length else b""

    def close(self):
        self._sock.close()


class _NativeClient:
    def __init__(self, lib, host, port, timeout_s):
        self._lib = lib
        self._fd = lib.tcp_store_connect(host.encode(), port,
                                         int(timeout_s * 1000))
        enforce(self._fd >= 0, f"TCPStore connect to {host}:{port} failed")
        self._lock = threading.Lock()

    def _req(self, op, key: bytes, arg: int, val: bytes = b"") -> bytes:
        import ctypes
        lib = self._lib
        with self._lock:
            if op == _SET:
                rc = lib.tcp_store_set(self._fd, key, len(key), val,
                                       len(val))
                enforce(rc == 0, "TCPStore set failed")
                return b""
            if op == _GET:
                out = ctypes.POINTER(ctypes.c_char)()
                olen = ctypes.c_uint64()
                rc = lib.tcp_store_get(self._fd, key, len(key), arg,
                                       ctypes.byref(out),
                                       ctypes.byref(olen))
                if rc == -2:
                    raise TimeoutError(f"TCPStore get timeout {key!r}")
                enforce(rc == 0, "TCPStore get failed")
                data = ctypes.string_at(out, olen.value) \
                    if olen.value else b""
                if olen.value:
                    lib.tcp_store_free(out)
                return data
            if op == _ADD:
                res = ctypes.c_int64()
                rc = lib.tcp_store_add(self._fd, key, len(key), arg,
                                       ctypes.byref(res))
                enforce(rc == 0, "TCPStore add failed")
                return struct.pack("<q", res.value)
            if op == _WAIT:
                rc = lib.tcp_store_wait(self._fd, key, len(key), arg)
                if rc == -2:
                    raise TimeoutError(f"TCPStore wait timeout {key!r}")
                enforce(rc == 0, "TCPStore wait failed")
                return b""
            if op == _DEL:
                lib.tcp_store_delete(self._fd, key, len(key))
                return b""
            if op == _CHECK:
                ex = ctypes.c_int()
                rc = lib.tcp_store_check(self._fd, key, len(key),
                                         ctypes.byref(ex))
                enforce(rc == 0, "TCPStore check failed")
                return b"1" if ex.value else b"0"
        raise ValueError(op)

    def close(self):
        self._lib.tcp_store_close(self._fd)


class TCPStore:
    """paddle.distributed.TCPStore(host, port, world_size, is_master,
    timeout) parity."""

    def __init__(self, host: str, port: int, world_size: int = 1,
                 is_master: bool = False, timeout: float = 300.0):
        self.host, self.world_size = host, world_size
        self._server = None
        self._native_server = None
        lib = load_native()
        if is_master:
            if lib is not None:
                import ctypes
                out_port = ctypes.c_int()
                h = lib.tcp_store_server_start(host.encode(), port,
                                               ctypes.byref(out_port))
                enforce(h, f"TCPStore bind {host}:{port} failed")
                self._native_server = h
                port = out_port.value
            else:
                self._server = _PyServer(host, port)
                port = self._server.port
        self.port = port
        if lib is not None:
            self._client = _NativeClient(lib, host, port, timeout)
        else:
            self._client = _PyClient(host, port, timeout)

    # -- API ------------------------------------------------------------------
    def set(self, key: str, value) -> None:
        if isinstance(value, str):
            value = value.encode()
        self._client._req(_SET, key.encode(), len(value), bytes(value))

    def get(self, key: str, timeout_ms: int = 0) -> bytes:
        return self._client._req(_GET, key.encode(), timeout_ms)

    def add(self, key: str, delta: int) -> int:
        out = self._client._req(_ADD, key.encode(), int(delta))
        return struct.unpack("<q", out)[0]

    def wait(self, keys, timeout_ms: int = 0) -> None:
        if isinstance(keys, str):
            keys = [keys]
        for k in keys:
            self._client._req(_WAIT, k.encode(), timeout_ms)

    def delete_key(self, key: str) -> None:
        self._client._req(_DEL, key.encode(), 0)

    def check(self, key: str) -> bool:
        return self._client._req(_CHECK, key.encode(), 0) == b"1"

    def barrier(self, name: str = "_barrier", timeout_ms: int = 60000):
        """All world_size ranks arrive, then proceed.  Reusable: each
        world_size-full round of arrivals forms an epoch with its own
        release key (a single '/go' key would make every later barrier
        a no-op)."""
        n = self.add(f"{name}/count", 1)
        epoch = (n - 1) // self.world_size
        if n % self.world_size == 0:
            self.set(f"{name}/go{epoch}", b"1")
        self.wait(f"{name}/go{epoch}", timeout_ms)

    def __del__(self):
        try:
            self._client.close()
            if self._server is not None:
                self._server.stop()
            if self._native_server is not None:
                load_native().tcp_store_server_stop(self._native_server)
        except Exception:
            pass
