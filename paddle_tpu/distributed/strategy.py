"""DistributedStrategy.

Reference parity: paddle.distributed.fleet.DistributedStrategy
(fleet/base/distributed_strategy.py backed by distributed_strategy.proto)
— the knob tree for hybrid parallelism.  Here: a typed dataclass tree
(SURVEY.md §5 config-system mapping) with the same field names used by
the reference's LLM recipes.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

__all__ = ["DistributedStrategy", "HybridConfig", "ShardingConfig",
           "RecomputeConfig", "AmpConfig"]


@dataclass
class HybridConfig:
    dp_degree: int = 1
    mp_degree: int = 1
    pp_degree: int = 1
    sharding_degree: int = 1
    sep_degree: int = 1          # sequence/context parallel axis
    ep_degree: int = 1           # expert parallel (MoE)


@dataclass
class ShardingConfig:
    sharding_degree: int = 1
    stage: int = 1               # ZeRO stage 1/2/3


@dataclass
class RecomputeConfig:
    enable: bool = False
    checkpoints: Optional[list] = None


@dataclass
class AmpConfig:
    enable: bool = False
    dtype: str = "bfloat16"
    level: str = "O2"


class DistributedStrategy:
    def __init__(self):
        self.hybrid_configs: Dict[str, Any] = {}
        self._hybrid = HybridConfig()
        self.sharding = False
        self.sharding_configs = ShardingConfig()
        self.recompute = False
        self.recompute_configs = RecomputeConfig()
        self.amp = False
        self.amp_configs = AmpConfig()
        self.pipeline_configs: Dict[str, Any] = {"accumulate_steps": 1,
                                                 "micro_batch_size": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1}
        self.find_unused_parameters = False

    @property
    def hybrid(self) -> HybridConfig:
        # hybrid_configs dict (recipe style) overrides the dataclass
        h = HybridConfig()
        for k, v in self.hybrid_configs.items():
            if hasattr(h, k):
                setattr(h, k, int(v))
        return h

    def __repr__(self):
        h = self.hybrid
        return (f"DistributedStrategy(dp={h.dp_degree}, mp={h.mp_degree}, "
                f"pp={h.pp_degree}, sharding={h.sharding_degree}, "
                f"sep={h.sep_degree}, ep={h.ep_degree})")
