"""Hybrid-parallel topology → one jax device Mesh.

Reference parity: fleet/base/topology.py ``HybridCommunicateGroup`` — the
cartesian [dp, pp, sharding, mp, sep] process topology with one NCCL ring
per axis per coordinate.

TPU-native design (SURVEY.md §2.3): ALL axes live in ONE
``jax.sharding.Mesh`` with named axes ``(dp, sharding, sep, mp)``(+ep
aliased onto sharding×sep as in DeepSpeed-MoE, pp as leading axis for the
stage loop).  There are no per-axis communicators to manage — GSPMD emits
the collectives from shardings; the group accessors below return
axis-name handles usable in shard_map/PartitionSpec, keeping the fleet
API shape.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from ..common.errors import enforce
from .strategy import HybridConfig

__all__ = ["HybridCommunicateGroup", "CommGroup", "build_mesh",
           "serving_mesh"]

AXES = ("pp", "dp", "sharding", "ep", "sep", "mp")


def serving_mesh(tp: int, axis: str = "tp",
                 devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh for tensor-parallel serving (`LLMEngine(mesh=...)`).

    Takes the first ``tp`` devices — on CPU these are the virtual
    devices created by ``--xla_force_host_platform_device_count``, on
    TPU a single ICI-adjacent prefix of the default device order."""
    devices = list(devices if devices is not None else jax.devices())
    enforce(tp >= 1, f"tp degree must be >= 1, got {tp}")
    enforce(tp <= len(devices),
            f"serving mesh tp={tp} needs {tp} devices, have {len(devices)}")
    return Mesh(np.array(devices[:tp]), (axis,))


def build_mesh(hybrid: HybridConfig, devices: Optional[Sequence] = None
               ) -> Mesh:
    """Mesh with axis order (pp, dp, sharding, ep, sep, mp) — the
    reference's topology order plus a dedicated expert-parallel axis,
    which also places mp on the innermost (fastest-ICI) axis, matching
    TPU torus locality best practice (scaling-book recipe: innermost
    mesh dim ↔ highest-bandwidth links).  The ep axis sits next to
    sharding so the MoE all-to-all rides the same ICI neighborhood as
    the ZeRO collectives."""
    devices = list(devices if devices is not None else jax.devices())
    shape = (hybrid.pp_degree, hybrid.dp_degree, hybrid.sharding_degree,
             hybrid.ep_degree, hybrid.sep_degree, hybrid.mp_degree)
    n = int(np.prod(shape))
    enforce(n <= len(devices),
            f"topology {shape} needs {n} devices, have {len(devices)}")
    dev_array = np.array(devices[:n]).reshape(shape)
    return Mesh(dev_array, AXES)


class CommGroup:
    """Axis-handle standing in for the reference's ProcessGroup: carries
    the mesh + axis names; collectives inside shard_map reference
    ``group.axis_name``."""

    def __init__(self, mesh: Mesh, axis_names: Tuple[str, ...]):
        self.mesh = mesh
        self.axis_names = axis_names if isinstance(axis_names, tuple) \
            else (axis_names,)

    @property
    def axis_name(self):
        return self.axis_names[0] if len(self.axis_names) == 1 \
            else self.axis_names

    @property
    def nranks(self) -> int:
        return int(np.prod([self.mesh.shape[a] for a in self.axis_names]))

    world_size = nranks

    @property
    def rank(self) -> int:
        return 0  # single-controller SPMD: rank is resolved inside shard_map

    def __repr__(self):
        return f"CommGroup(axes={self.axis_names}, nranks={self.nranks})"


class HybridCommunicateGroup:
    def __init__(self, hybrid: HybridConfig,
                 devices: Optional[Sequence] = None):
        self._hybrid = hybrid
        self.mesh = build_mesh(hybrid, devices)
        self.global_mesh = self.mesh

    # -- degrees (fleet API names) ------------------------------------------
    def get_data_parallel_world_size(self) -> int:
        return self._hybrid.dp_degree

    def get_model_parallel_world_size(self) -> int:
        return self._hybrid.mp_degree

    def get_pipe_parallel_world_size(self) -> int:
        return self._hybrid.pp_degree

    def get_sharding_parallel_world_size(self) -> int:
        return self._hybrid.sharding_degree

    def get_sep_parallel_world_size(self) -> int:
        return self._hybrid.sep_degree

    def get_expert_parallel_world_size(self) -> int:
        return self._hybrid.ep_degree

    # -- groups --------------------------------------------------------------
    def get_data_parallel_group(self) -> CommGroup:
        return CommGroup(self.mesh, ("dp",))

    def get_model_parallel_group(self) -> CommGroup:
        return CommGroup(self.mesh, ("mp",))

    def get_pipe_parallel_group(self) -> CommGroup:
        return CommGroup(self.mesh, ("pp",))

    def get_sharding_parallel_group(self) -> CommGroup:
        return CommGroup(self.mesh, ("sharding",))

    def get_sep_parallel_group(self) -> CommGroup:
        return CommGroup(self.mesh, ("sep",))

    def get_expert_parallel_group(self) -> CommGroup:
        if self._hybrid.ep_degree > 1:
            return CommGroup(self.mesh, ("ep",))
        # EP reuses dp×sharding capacity (DeepSpeed-MoE style folding)
        return CommGroup(self.mesh, ("dp", "sharding"))

    def get_check_parallel_group(self) -> CommGroup:
        return CommGroup(self.mesh, AXES)

    # batch/replica axes used for data sharding in the compiled path
    def data_axes(self) -> Tuple[str, ...]:
        return ("dp", "sharding")

    def topology(self):
        return self.mesh.shape
