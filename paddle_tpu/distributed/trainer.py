"""ShardedTrainStep — the hybrid-parallel compiled training step.

Reference parity: the whole fleet hybrid-parallel runtime path
(SURVEY.md §3.3): DataParallel reducer + GroupSharded stages + mp layer
collectives + grad-clip cross-group allreduces, fused here into ONE
pjit'd XLA program whose communication is emitted by the SPMD
partitioner over the mesh (the TPU-native replacement for the python
1F1B/NCCL orchestration loop).

Usage:
    fleet.init(strategy)                       # builds the mesh
    step = ShardedTrainStep(model, loss_fn, opt, stage=2)
    loss = step(batch)                         # batch: numpy/jax pytree
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..common.errors import enforce
from ..jit.train import CompiledTrainStep, _to_arrays
from ..nn.layer import Layer
from ..optimizer.optimizer import Optimizer
from .fleet import get_hybrid_communicate_group, get_strategy
from .sharding import ShardingPlan

__all__ = ["ShardedTrainStep"]


class ShardedTrainStep(CompiledTrainStep):
    def __init__(self, model: Layer, loss_fn: Callable, optimizer: Optimizer,
                 stage: Optional[int] = None, seed: int = 0,
                 donate: bool = True, fused_step: bool = True,
                 grad_bucket_mb: float = 4.0):
        hcg = get_hybrid_communicate_group()
        enforce(hcg is not None, "fleet.init() before ShardedTrainStep")
        self.mesh = hcg.mesh
        if stage is None:
            stage = getattr(model, "_sharding_stage", None)
            if stage is None:
                strat = get_strategy()
                stage = strat.sharding_configs.stage if (strat and
                                                         strat.sharding) else 1
        super().__init__(model, loss_fn, optimizer, seed=seed, donate=donate,
                         fused_step=fused_step)
        # packing flat per-dtype update buffers would concatenate leaves
        # with DIFFERENT shardings (stage>=2 shards moments/params) and
        # force a GSPMD gather — the sharded fused path keeps per-leaf
        # updates (same fused math, collectives stay where GSPMD put them)
        self._fused_pack_small = False
        # bucketed data-parallel gradient reduction (see _sync_grads);
        # 0 disables
        self._bucket_bytes = int(grad_bucket_mb * 2**20)
        self._bucket_plan: Optional[List[List[int]]] = None
        self.plan = ShardingPlan(model, self.mesh, stage=stage)
        # place initial state onto the mesh
        self.state = jax.tree_util.tree_map(
            jax.device_put, self.state, self.plan.state_shardings(self.state))

    # -- bucketed gradient collectives ---------------------------------------
    def grad_buckets(self) -> List[List[int]]:
        """The static bucket plan: a list of buckets, each a list of
        indices into the flattened params/grads tree.  Only FULLY
        REPLICATED grads participate — those are the data-parallel
        gradients whose cross-replica sum needs an all-reduce; sharded
        (TP/FSDP) grads are already local to their shard and pass
        through untouched.  Leaves pack into a bucket in flatten order
        while they share a dtype and the running size stays within the
        budget; a single leaf larger than the whole budget gets a
        bucket of its own."""
        if self._bucket_plan is not None:
            return self._bucket_plan
        shardings = self.plan.state_shardings(self.state)["params"]
        flat_p = jax.tree_util.tree_leaves(self.state["params"])
        flat_sh = jax.tree_util.tree_leaves(shardings)
        plan: List[List[int]] = []
        cur: List[int] = []
        cur_bytes = 0
        cur_dt = None
        for i, (p, sh) in enumerate(zip(flat_p, flat_sh)):
            if not getattr(sh, "is_fully_replicated", False):
                continue
            nbytes = p.size * p.dtype.itemsize
            if nbytes >= self._bucket_bytes:
                plan.append([i])        # giant leaf: its own bucket
                continue
            if cur and (cur_dt != p.dtype
                        or cur_bytes + nbytes > self._bucket_bytes):
                plan.append(cur)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_dt = p.dtype
            cur_bytes += nbytes
        if cur:
            plan.append(cur)
        self._bucket_plan = plan
        return plan

    def _sync_grads(self, grads):
        """Bucketed data-parallel gradient reduction — the GSPMD analog
        of DDP gradient bucketing.  Each bucket's replicated grads are
        packed into one flat vector and pinned replicated with ONE
        with_sharding_constraint, so the partitioner emits one fused
        all-reduce per size-bounded bucket instead of one tiny
        collective per leaf (or one giant one after the whole
        backward).  Every bucket depends only on its own leaves, so its
        reduce is issued as soon as backward has produced them and
        XLA's latency-hiding scheduler overlaps it with the remaining
        backward compute.  Values are untouched (concat → constraint →
        split is an identity), so this composes bit-identically with
        both the fused and the reference update paths."""
        if not self._bucket_bytes:
            return grads
        from jax.sharding import NamedSharding, PartitionSpec
        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        repl = NamedSharding(self.mesh, PartitionSpec())
        for bucket in self.grad_buckets():
            if len(bucket) == 1:
                i = bucket[0]
                flat_g[i] = jax.lax.with_sharding_constraint(flat_g[i],
                                                             repl)
                continue
            vec = jnp.concatenate([flat_g[i].reshape(-1) for i in bucket])
            vec = jax.lax.with_sharding_constraint(vec, repl)
            off = 0
            for i in bucket:
                n = flat_g[i].size
                flat_g[i] = vec[off:off + n].reshape(flat_g[i].shape)
                off += n
        return jax.tree_util.tree_unflatten(treedef, flat_g)

    def _build(self):
        # same fused step as the parent, jitted with explicit state
        # shardings so donation + placement are stable; batch/lr/key
        # shardings are propagated by XLA
        from ..jit.train import _maybe_enable_debug_nans
        _maybe_enable_debug_nans()
        shardings = self.plan.state_shardings(self.state)
        self._step_fn = jax.jit(
            self._make_step(),
            in_shardings=(shardings, None, None, None),
            out_shardings=(shardings, None),
            donate_argnums=(0,) if self._donate else ())
        from ..observability import introspection as _insp
        _insp.get_compile_watch().register_program(self._program_name)

    _program_name = "train.sharded_step"

    def __call__(self, batch):
        if self._step_fn is None:
            self._build()
        self._key, sub = jax.random.split(self._key)
        lr = self.optimizer.get_lr()
        batch = self.plan.shard_batch(_to_arrays(batch))
        # same tracing + StepTimer contract as the parent: one span
        # per step, fence on the sharded outputs so multi-chip async
        # dispatch can't flatter step time
        from ..observability import health as _health
        from ..observability import introspection as _insp
        from ..observability import tracing as _tracing
        span = _tracing.span("train.compiled_step")
        span.set_attr("step", self._step_count)
        span.set_attr("sharded", True)
        with _health.goodput_region(
                "productive_step" if self._compiled_once
                else "compile"):
            if self._timer is not None:
                self._timer.start()
            self.state, loss = _insp.watched_call(
                self._program_name, self._step_fn,
                self.state, batch, sub, lr)
            if self._grad_norm_tap:
                loss, self.last_grad_norm = loss
            if self._timer is not None:
                self._timer.stop(fence=(self.state, loss))
        self._compiled_once = True
        span.end()
        # same resumable-state contract as the parent: the update count
        # must tick here too or a sharded run's checkpoint lies about
        # its position
        self._step_count += 1
        sched = self.optimizer._lr_scheduler
        if sched is not None:
            sched.step()
        return loss
