"""ShardedTrainStep — the hybrid-parallel compiled training step.

Reference parity: the whole fleet hybrid-parallel runtime path
(SURVEY.md §3.3): DataParallel reducer + GroupSharded stages + mp layer
collectives + grad-clip cross-group allreduces, fused here into ONE
pjit'd XLA program whose communication is emitted by the SPMD
partitioner over the mesh (the TPU-native replacement for the python
1F1B/NCCL orchestration loop).

Usage:
    fleet.init(strategy)                       # builds the mesh
    step = ShardedTrainStep(model, loss_fn, opt, stage=2)
    loss = step(batch)                         # batch: numpy/jax pytree
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from ..common.errors import enforce
from ..jit.train import CompiledTrainStep, _to_arrays
from ..nn.layer import Layer
from ..optimizer.optimizer import Optimizer
from .fleet import get_hybrid_communicate_group, get_strategy
from .sharding import ShardingPlan

__all__ = ["ShardedTrainStep"]


class ShardedTrainStep(CompiledTrainStep):
    def __init__(self, model: Layer, loss_fn: Callable, optimizer: Optimizer,
                 stage: Optional[int] = None, seed: int = 0,
                 donate: bool = True):
        hcg = get_hybrid_communicate_group()
        enforce(hcg is not None, "fleet.init() before ShardedTrainStep")
        self.mesh = hcg.mesh
        if stage is None:
            stage = getattr(model, "_sharding_stage", None)
            if stage is None:
                strat = get_strategy()
                stage = strat.sharding_configs.stage if (strat and
                                                         strat.sharding) else 1
        super().__init__(model, loss_fn, optimizer, seed=seed, donate=donate)
        self.plan = ShardingPlan(model, self.mesh, stage=stage)
        # place initial state onto the mesh
        self.state = jax.tree_util.tree_map(
            jax.device_put, self.state, self.plan.state_shardings(self.state))

    def _build(self):
        # same fused step as the parent, jitted with explicit state
        # shardings so donation + placement are stable; batch/lr/key
        # shardings are propagated by XLA
        from ..jit.train import _maybe_enable_debug_nans
        _maybe_enable_debug_nans()
        shardings = self.plan.state_shardings(self.state)
        self._step_fn = jax.jit(
            self._make_step(),
            in_shardings=(shardings, None, None, None),
            out_shardings=(shardings, None),
            donate_argnums=(0,) if self._donate else ())

    def __call__(self, batch):
        if self._step_fn is None:
            self._build()
        self._key, sub = jax.random.split(self._key)
        lr = self.optimizer.get_lr()
        batch = self.plan.shard_batch(_to_arrays(batch))
        # same StepTimer contract as the parent: fence on the sharded
        # outputs so multi-chip async dispatch can't flatter step time
        if self._timer is not None:
            self._timer.start()
        self.state, loss = self._step_fn(self.state, batch, sub, lr)
        if self._timer is not None:
            self._timer.stop(fence=(self.state, loss))
        # same resumable-state contract as the parent: the update count
        # must tick here too or a sharded run's checkpoint lies about
        # its position
        self._step_count += 1
        sched = self.optimizer._lr_scheduler
        if sched is not None:
            sched.step()
        return loss
