"""paddle.distribution — probability distributions.

Reference parity: python/paddle/distribution (Distribution base,
Normal/Uniform/Categorical/Bernoulli/..., kl_divergence registry).
TPU-native: densities are jnp expressions on the tape (differentiable
through log_prob — the RL/VAE use cases), sampling uses the framework
RNG stream so ``paddle.seed`` governs reproducibility.
"""
from __future__ import annotations

import math
import numpy as np

from .common.errors import enforce
from .tensor import Tensor, apply_op, to_tensor

__all__ = ["Distribution", "Normal", "Uniform", "Categorical",
           "Bernoulli", "Exponential", "Gumbel", "Laplace", "LogNormal",
           "kl_divergence", "register_kl"]


def _key():
    from .ops.random import split_key
    return split_key()


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from . import ops
        return ops.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = to_tensor(loc, dtype="float32") \
            if not isinstance(loc, Tensor) else loc
        self.scale = to_tensor(scale, dtype="float32") \
            if not isinstance(scale, Tensor) else scale
        super().__init__(tuple(np.broadcast_shapes(self.loc.shape,
                                                   self.scale.shape)))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape

        def raw(loc, scale):
            return loc + scale * jax.random.normal(key, shp)
        return apply_op(raw, self.loc, self.scale)

    rsample = sample

    def log_prob(self, value):
        def raw(v, loc, scale):
            import jax.numpy as jnp
            var = scale ** 2
            return -((v - loc) ** 2) / (2 * var) - jnp.log(scale) \
                - 0.5 * math.log(2 * math.pi)
        return apply_op(raw, value, self.loc, self.scale)

    def entropy(self):
        def raw(scale):
            import jax.numpy as jnp
            return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(scale)
        return apply_op(raw, self.scale)

    def kl_divergence(self, other: "Normal"):
        def raw(l1, s1, l2, s2):
            import jax.numpy as jnp
            var_ratio = (s1 / s2) ** 2
            t1 = ((l1 - l2) / s2) ** 2
            return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
        return apply_op(raw, self.loc, self.scale, other.loc, other.scale)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = to_tensor(low, dtype="float32") \
            if not isinstance(low, Tensor) else low
        self.high = to_tensor(high, dtype="float32") \
            if not isinstance(high, Tensor) else high
        super().__init__(tuple(np.broadcast_shapes(self.low.shape,
                                                   self.high.shape)))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape

        def raw(low, high):
            return jax.random.uniform(key, shp, minval=low, maxval=high)
        return apply_op(raw, self.low, self.high)

    def log_prob(self, value):
        def raw(v, low, high):
            import jax.numpy as jnp
            inside = (v >= low) & (v < high)
            return jnp.where(inside, -jnp.log(high - low), -jnp.inf)
        return apply_op(raw, value, self.low, self.high)

    def entropy(self):
        def raw(low, high):
            import jax.numpy as jnp
            return jnp.log(high - low)
        return apply_op(raw, self.low, self.high)


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = logits if isinstance(logits, Tensor) \
            else to_tensor(logits, dtype="float32")
        super().__init__(tuple(self.logits.shape[:-1]))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape

        def raw(logits):
            return jax.random.categorical(key, logits, shape=shp)
        return apply_op(raw, self.logits)

    def log_prob(self, value):
        def raw(logits, v):
            import jax
            import jax.numpy as jnp
            logp = jax.nn.log_softmax(logits, axis=-1)
            v = v.astype(jnp.int32)
            if logp.ndim == 1:       # scalar batch: broadcast over value
                logp = jnp.broadcast_to(
                    logp, tuple(v.shape) + logp.shape[-1:])
            return jnp.take_along_axis(logp, v[..., None],
                                       axis=-1)[..., 0]
        return apply_op(raw, self.logits, value)

    def probs(self):
        def raw(logits):
            import jax
            return jax.nn.softmax(logits, axis=-1)
        return apply_op(raw, self.logits)

    def entropy(self):
        def raw(logits):
            import jax
            import jax.numpy as jnp
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.sum(jnp.exp(logp) * logp, axis=-1)
        return apply_op(raw, self.logits)


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = probs if isinstance(probs, Tensor) \
            else to_tensor(probs, dtype="float32")
        super().__init__(tuple(self.probs_.shape))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape

        def raw(p):
            return jax.random.bernoulli(key, p, shape=shp).astype(
                p.dtype)
        return apply_op(raw, self.probs_)

    def log_prob(self, value):
        def raw(p, v):
            import jax.numpy as jnp
            eps = 1e-7
            p = jnp.clip(p, eps, 1 - eps)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
        return apply_op(raw, self.probs_, value)

    def entropy(self):
        def raw(p):
            import jax.numpy as jnp
            eps = 1e-7
            p = jnp.clip(p, eps, 1 - eps)
            return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))
        return apply_op(raw, self.probs_)


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = rate if isinstance(rate, Tensor) \
            else to_tensor(rate, dtype="float32")
        super().__init__(tuple(self.rate.shape))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape

        def raw(rate):
            return jax.random.exponential(key, shp) / rate
        return apply_op(raw, self.rate)

    def log_prob(self, value):
        def raw(rate, v):
            import jax.numpy as jnp
            # support check: density is zero (log -inf) below 0
            return jnp.where(v >= 0, jnp.log(rate) - rate * v, -jnp.inf)
        return apply_op(raw, self.rate, value)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = to_tensor(loc, dtype="float32") \
            if not isinstance(loc, Tensor) else loc
        self.scale = to_tensor(scale, dtype="float32") \
            if not isinstance(scale, Tensor) else scale
        super().__init__(tuple(np.broadcast_shapes(self.loc.shape,
                                                   self.scale.shape)))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape

        def raw(loc, scale):
            return loc + scale * jax.random.gumbel(key, shp)
        return apply_op(raw, self.loc, self.scale)

    def log_prob(self, value):
        def raw(v, loc, scale):
            import jax.numpy as jnp
            z = (v - loc) / scale
            return -(z + jnp.exp(-z)) - jnp.log(scale)
        return apply_op(raw, value, self.loc, self.scale)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = to_tensor(loc, dtype="float32") \
            if not isinstance(loc, Tensor) else loc
        self.scale = to_tensor(scale, dtype="float32") \
            if not isinstance(scale, Tensor) else scale
        super().__init__(tuple(np.broadcast_shapes(self.loc.shape,
                                                   self.scale.shape)))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape

        def raw(loc, scale):
            return loc + scale * jax.random.laplace(key, shp)
        return apply_op(raw, self.loc, self.scale)

    def log_prob(self, value):
        def raw(v, loc, scale):
            import jax.numpy as jnp
            return -jnp.abs(v - loc) / scale - jnp.log(2 * scale)
        return apply_op(raw, value, self.loc, self.scale)


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self._normal = Normal(loc, scale)
        super().__init__(self._normal.batch_shape)

    def sample(self, shape=()):
        from . import ops
        return ops.exp(self._normal.sample(shape))

    def log_prob(self, value):
        from . import ops
        logv = ops.log(value)
        return self._normal.log_prob(logv) - logv


# -- KL registry --------------------------------------------------------------

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p: Distribution, q: Distribution):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is not None:
        return fn(p, q)
    own = getattr(p, "kl_divergence", None)
    enforce(own is not None and isinstance(q, type(p)),
            f"no KL registered for ({type(p).__name__}, "
            f"{type(q).__name__})")
    return own(q)


@register_kl(Categorical, Categorical)
def _kl_cat(p: Categorical, q: Categorical):
    def raw(lp, lq):
        import jax
        import jax.numpy as jnp
        a = jax.nn.log_softmax(lp, axis=-1)
        b = jax.nn.log_softmax(lq, axis=-1)
        return jnp.sum(jnp.exp(a) * (a - b), axis=-1)
    return apply_op(raw, p.logits, q.logits)
