"""paddle.distribution — probability distributions.

Reference parity: python/paddle/distribution (Distribution base,
Normal/Uniform/Categorical/Bernoulli/..., kl_divergence registry).
TPU-native: densities are jnp expressions on the tape (differentiable
through log_prob — the RL/VAE use cases), sampling uses the framework
RNG stream so ``paddle.seed`` governs reproducibility.
"""
from __future__ import annotations

import math
import numpy as np

from .common.errors import enforce
from .tensor import Tensor, apply_op, to_tensor

__all__ = ["Distribution", "Normal", "Uniform", "Categorical",
           "Bernoulli", "Exponential", "Gumbel", "Laplace", "LogNormal",
           "kl_divergence", "register_kl"]


def _key():
    from .ops.random import split_key
    return split_key()


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from . import ops
        return ops.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = to_tensor(loc, dtype="float32") \
            if not isinstance(loc, Tensor) else loc
        self.scale = to_tensor(scale, dtype="float32") \
            if not isinstance(scale, Tensor) else scale
        super().__init__(tuple(np.broadcast_shapes(self.loc.shape,
                                                   self.scale.shape)))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape

        def raw(loc, scale):
            return loc + scale * jax.random.normal(key, shp)
        return apply_op(raw, self.loc, self.scale)

    rsample = sample

    def log_prob(self, value):
        def raw(v, loc, scale):
            import jax.numpy as jnp
            var = scale ** 2
            return -((v - loc) ** 2) / (2 * var) - jnp.log(scale) \
                - 0.5 * math.log(2 * math.pi)
        return apply_op(raw, value, self.loc, self.scale)

    def entropy(self):
        def raw(scale):
            import jax.numpy as jnp
            return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(scale)
        return apply_op(raw, self.scale)

    def kl_divergence(self, other: "Normal"):
        def raw(l1, s1, l2, s2):
            import jax.numpy as jnp
            var_ratio = (s1 / s2) ** 2
            t1 = ((l1 - l2) / s2) ** 2
            return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
        return apply_op(raw, self.loc, self.scale, other.loc, other.scale)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = to_tensor(low, dtype="float32") \
            if not isinstance(low, Tensor) else low
        self.high = to_tensor(high, dtype="float32") \
            if not isinstance(high, Tensor) else high
        super().__init__(tuple(np.broadcast_shapes(self.low.shape,
                                                   self.high.shape)))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape

        def raw(low, high):
            return jax.random.uniform(key, shp, minval=low, maxval=high)
        return apply_op(raw, self.low, self.high)

    def log_prob(self, value):
        def raw(v, low, high):
            import jax.numpy as jnp
            inside = (v >= low) & (v < high)
            return jnp.where(inside, -jnp.log(high - low), -jnp.inf)
        return apply_op(raw, value, self.low, self.high)

    def entropy(self):
        def raw(low, high):
            import jax.numpy as jnp
            return jnp.log(high - low)
        return apply_op(raw, self.low, self.high)


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = logits if isinstance(logits, Tensor) \
            else to_tensor(logits, dtype="float32")
        super().__init__(tuple(self.logits.shape[:-1]))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape

        def raw(logits):
            return jax.random.categorical(key, logits, shape=shp)
        return apply_op(raw, self.logits)

    def log_prob(self, value):
        def raw(logits, v):
            import jax
            import jax.numpy as jnp
            logp = jax.nn.log_softmax(logits, axis=-1)
            v = v.astype(jnp.int32)
            if logp.ndim == 1:       # scalar batch: broadcast over value
                logp = jnp.broadcast_to(
                    logp, tuple(v.shape) + logp.shape[-1:])
            return jnp.take_along_axis(logp, v[..., None],
                                       axis=-1)[..., 0]
        return apply_op(raw, self.logits, value)

    def probs(self):
        def raw(logits):
            import jax
            return jax.nn.softmax(logits, axis=-1)
        return apply_op(raw, self.logits)

    def entropy(self):
        def raw(logits):
            import jax
            import jax.numpy as jnp
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.sum(jnp.exp(logp) * logp, axis=-1)
        return apply_op(raw, self.logits)


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = probs if isinstance(probs, Tensor) \
            else to_tensor(probs, dtype="float32")
        super().__init__(tuple(self.probs_.shape))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape

        def raw(p):
            return jax.random.bernoulli(key, p, shape=shp).astype(
                p.dtype)
        return apply_op(raw, self.probs_)

    def log_prob(self, value):
        def raw(p, v):
            import jax.numpy as jnp
            eps = 1e-7
            p = jnp.clip(p, eps, 1 - eps)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
        return apply_op(raw, self.probs_, value)

    def entropy(self):
        def raw(p):
            import jax.numpy as jnp
            eps = 1e-7
            p = jnp.clip(p, eps, 1 - eps)
            return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))
        return apply_op(raw, self.probs_)


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = rate if isinstance(rate, Tensor) \
            else to_tensor(rate, dtype="float32")
        super().__init__(tuple(self.rate.shape))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape

        def raw(rate):
            return jax.random.exponential(key, shp) / rate
        return apply_op(raw, self.rate)

    def log_prob(self, value):
        def raw(rate, v):
            import jax.numpy as jnp
            # support check: density is zero (log -inf) below 0
            return jnp.where(v >= 0, jnp.log(rate) - rate * v, -jnp.inf)
        return apply_op(raw, self.rate, value)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = to_tensor(loc, dtype="float32") \
            if not isinstance(loc, Tensor) else loc
        self.scale = to_tensor(scale, dtype="float32") \
            if not isinstance(scale, Tensor) else scale
        super().__init__(tuple(np.broadcast_shapes(self.loc.shape,
                                                   self.scale.shape)))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape

        def raw(loc, scale):
            return loc + scale * jax.random.gumbel(key, shp)
        return apply_op(raw, self.loc, self.scale)

    def log_prob(self, value):
        def raw(v, loc, scale):
            import jax.numpy as jnp
            z = (v - loc) / scale
            return -(z + jnp.exp(-z)) - jnp.log(scale)
        return apply_op(raw, value, self.loc, self.scale)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = to_tensor(loc, dtype="float32") \
            if not isinstance(loc, Tensor) else loc
        self.scale = to_tensor(scale, dtype="float32") \
            if not isinstance(scale, Tensor) else scale
        super().__init__(tuple(np.broadcast_shapes(self.loc.shape,
                                                   self.scale.shape)))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape

        def raw(loc, scale):
            return loc + scale * jax.random.laplace(key, shp)
        return apply_op(raw, self.loc, self.scale)

    def log_prob(self, value):
        def raw(v, loc, scale):
            import jax.numpy as jnp
            return -jnp.abs(v - loc) / scale - jnp.log(2 * scale)
        return apply_op(raw, value, self.loc, self.scale)


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self._normal = Normal(loc, scale)
        super().__init__(self._normal.batch_shape)

    def sample(self, shape=()):
        from . import ops
        return ops.exp(self._normal.sample(shape))

    def log_prob(self, value):
        from . import ops
        logv = ops.log(value)
        return self._normal.log_prob(logv) - logv


# -- KL registry --------------------------------------------------------------

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p: Distribution, q: Distribution):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is not None:
        return fn(p, q)
    own = getattr(p, "kl_divergence", None)
    enforce(own is not None and isinstance(q, type(p)),
            f"no KL registered for ({type(p).__name__}, "
            f"{type(q).__name__})")
    return own(q)


@register_kl(Categorical, Categorical)
def _kl_cat(p: Categorical, q: Categorical):
    def raw(lp, lq):
        import jax
        import jax.numpy as jnp
        a = jax.nn.log_softmax(lp, axis=-1)
        b = jax.nn.log_softmax(lq, axis=-1)
        return jnp.sum(jnp.exp(a) * (a - b), axis=-1)
    return apply_op(raw, p.logits, q.logits)


# ---------------------------------------------------------------------------
# round-5 batch: the remaining reference distribution zoo
# ---------------------------------------------------------------------------

def _t(v):
    return to_tensor(v, dtype="float32") if not isinstance(v, Tensor) \
        else v


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha, self.beta = _t(alpha), _t(beta)
        super().__init__(tuple(np.broadcast_shapes(self.alpha.shape,
                                                   self.beta.shape)))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape
        return apply_op(
            lambda a, b: jax.random.beta(key, a, b, shp),
            self.alpha, self.beta)

    def log_prob(self, value):
        def raw(v, a, b):
            import jax.scipy.special as jss
            import jax.numpy as jnp
            return ((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
                    - jss.betaln(a, b))
        return apply_op(raw, value, self.alpha, self.beta)

    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    def entropy(self):
        def raw(a, b):
            import jax.scipy.special as jss
            return (jss.betaln(a, b) - (a - 1) * jss.digamma(a)
                    - (b - 1) * jss.digamma(b)
                    + (a + b - 2) * jss.digamma(a + b))
        return apply_op(raw, self.alpha, self.beta)


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration, self.rate = _t(concentration), _t(rate)
        super().__init__(tuple(np.broadcast_shapes(
            self.concentration.shape, self.rate.shape)))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape
        return apply_op(
            lambda c, r: jax.random.gamma(key, c, shp) / r,
            self.concentration, self.rate)

    def log_prob(self, value):
        def raw(v, c, r):
            import jax.scipy.special as jss
            import jax.numpy as jnp
            return (c * jnp.log(r) + (c - 1) * jnp.log(v) - r * v
                    - jss.gammaln(c))
        return apply_op(raw, value, self.concentration, self.rate)

    def mean(self):
        return self.concentration / self.rate

    def entropy(self):
        def raw(c, r):
            import jax.scipy.special as jss
            import jax.numpy as jnp
            return (c - jnp.log(r) + jss.gammaln(c)
                    + (1 - c) * jss.digamma(c))
        return apply_op(raw, self.concentration, self.rate)


class Chi2(Gamma):
    def __init__(self, df, name=None):
        df = _t(df)
        super().__init__(df * 0.5, to_tensor(0.5))
        self.df = df


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _t(concentration)
        super().__init__(tuple(self.concentration.shape[:-1]),
                         tuple(self.concentration.shape[-1:]))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape
        return apply_op(
            lambda c: jax.random.dirichlet(key, c, shp), self.concentration)

    def log_prob(self, value):
        def raw(v, c):
            import jax.scipy.special as jss
            import jax.numpy as jnp
            return (jnp.sum((c - 1) * jnp.log(v), -1)
                    + jss.gammaln(jnp.sum(c, -1))
                    - jnp.sum(jss.gammaln(c), -1))
        return apply_op(raw, value, self.concentration)

    def mean(self):
        from . import ops
        s = ops.sum(self.concentration, axis=-1, keepdim=True)
        return self.concentration / s


class Geometric(Distribution):
    """P(X=k) = (1-p)^(k-1) p, k = 1, 2, ... — the reference's
    trials-until-first-success convention (mean 1/p), NOT the torch
    failures-before-success shift (ADVICE r5 finding 1)."""

    def __init__(self, probs, name=None):
        self.probs = _t(probs)
        super().__init__(tuple(self.probs.shape))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape

        def raw(p):
            import jax.numpy as jnp
            u = jax.random.uniform(key, shp, minval=1e-7, maxval=1.0)
            return jnp.floor(jnp.log(u) / jnp.log1p(-p)) + 1.0
        return apply_op(raw, self.probs)

    def log_prob(self, value):
        def raw(v, p):
            import jax.numpy as jnp
            return (v - 1.0) * jnp.log1p(-p) + jnp.log(p)
        return apply_op(raw, value, self.probs)

    def mean(self):
        return 1.0 / self.probs


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(tuple(self.rate.shape))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape
        return apply_op(
            lambda r: jax.random.poisson(key, r, shp).astype("float32"),
            self.rate)

    def log_prob(self, value):
        def raw(v, r):
            import jax.scipy.special as jss
            import jax.numpy as jnp
            return v * jnp.log(r) - r - jss.gammaln(v + 1)
        return apply_op(raw, value, self.rate)

    def mean(self):
        return self.rate


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count, self.probs = _t(total_count), _t(probs)
        super().__init__(tuple(np.broadcast_shapes(
            self.total_count.shape, self.probs.shape)))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape
        return apply_op(
            lambda n, p: jax.random.binomial(key, n, p, shape=shp),
            self.total_count, self.probs)

    def log_prob(self, value):
        def raw(v, n, p):
            import jax.scipy.special as jss
            import jax.numpy as jnp
            comb = (jss.gammaln(n + 1) - jss.gammaln(v + 1)
                    - jss.gammaln(n - v + 1))
            return comb + v * jnp.log(p) + (n - v) * jnp.log1p(-p)
        return apply_op(raw, value, self.total_count, self.probs)

    def mean(self):
        return self.total_count * self.probs


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _t(probs)
        super().__init__(tuple(self.probs.shape[:-1]),
                         tuple(self.probs.shape[-1:]))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape

        def raw(p):
            import jax.numpy as jnp
            k = p.shape[-1]
            draws = jax.random.categorical(
                key, jnp.log(p), shape=shp + (self.total_count,))
            return jax.nn.one_hot(draws, k).sum(-2)
        return apply_op(raw, self.probs)

    def log_prob(self, value):
        def raw(v, p):
            import jax.scipy.special as jss
            import jax.numpy as jnp
            n = jnp.sum(v, -1)
            return (jss.gammaln(n + 1) - jnp.sum(jss.gammaln(v + 1), -1)
                    + jnp.sum(v * jnp.log(p), -1))
        return apply_op(raw, value, self.probs)


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df, self.loc, self.scale = _t(df), _t(loc), _t(scale)
        super().__init__(tuple(np.broadcast_shapes(
            self.df.shape, self.loc.shape, self.scale.shape)))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape
        return apply_op(
            lambda d, l, s: l + s * jax.random.t(key, d, shp),
            self.df, self.loc, self.scale)

    def log_prob(self, value):
        def raw(v, d, l, s):
            import jax.scipy.special as jss
            import jax.numpy as jnp
            z = (v - l) / s
            return (jss.gammaln((d + 1) / 2) - jss.gammaln(d / 2)
                    - 0.5 * jnp.log(d * math.pi) - jnp.log(s)
                    - (d + 1) / 2 * jnp.log1p(z * z / d))
        return apply_op(raw, value, self.df, self.loc, self.scale)


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc, self.scale = _t(loc), _t(scale)
        super().__init__(tuple(np.broadcast_shapes(self.loc.shape,
                                                   self.scale.shape)))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape
        return apply_op(
            lambda l, s: l + s * jax.random.cauchy(key, shp),
            self.loc, self.scale)

    def log_prob(self, value):
        def raw(v, l, s):
            import jax.numpy as jnp
            z = (v - l) / s
            return -jnp.log(math.pi * s * (1 + z * z))
        return apply_op(raw, value, self.loc, self.scale)

    def entropy(self):
        def raw(s):
            import jax.numpy as jnp
            return jnp.log(4 * math.pi * s)
        return apply_op(raw, self.scale)


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, scale_tril=None,
                 name=None):
        enforce((covariance_matrix is None) != (scale_tril is None),
                "give exactly one of covariance_matrix / scale_tril")
        self.loc = _t(loc)
        if scale_tril is not None:
            self.scale_tril = _t(scale_tril)
        else:
            cov = _t(covariance_matrix)
            from . import ops
            self.scale_tril = ops.cholesky(cov)
        super().__init__(tuple(self.loc.shape[:-1]),
                         tuple(self.loc.shape[-1:]))

    def sample(self, shape=()):
        import jax
        key = _key()
        shp = tuple(shape) + self.batch_shape

        def raw(l, L):
            import jax.numpy as jnp
            d = l.shape[-1]
            eps = jax.random.normal(key, shp + (d,))
            return l + jnp.einsum("...ij,...j->...i", L, eps)
        return apply_op(raw, self.loc, self.scale_tril)

    def log_prob(self, value):
        def raw(v, l, L):
            import jax.numpy as jnp
            import jax.scipy.linalg as jsl
            d = l.shape[-1]
            diff = v - l
            sol = jsl.solve_triangular(L, diff[..., None], lower=True)
            maha = jnp.sum(jnp.square(sol[..., 0]), -1)
            logdet = jnp.sum(jnp.log(jnp.diagonal(L, axis1=-2,
                                                  axis2=-1)), -1)
            return -0.5 * (d * math.log(2 * math.pi) + maha) - logdet
        return apply_op(raw, value, self.loc, self.scale_tril)

    def mean(self):
        return self.loc


@register_kl(Beta, Beta)
def _kl_beta(p: Beta, q: Beta):
    def raw(a1, b1, a2, b2):
        import jax.scipy.special as jss
        return (jss.betaln(a2, b2) - jss.betaln(a1, b1)
                + (a1 - a2) * jss.digamma(a1)
                + (b1 - b2) * jss.digamma(b1)
                + (a2 - a1 + b2 - b1) * jss.digamma(a1 + b1))
    return apply_op(raw, p.alpha, p.beta, q.alpha, q.beta)


@register_kl(Gamma, Gamma)
def _kl_gamma(p: Gamma, q: Gamma):
    def raw(c1, r1, c2, r2):
        import jax.scipy.special as jss
        import jax.numpy as jnp
        return ((c1 - c2) * jss.digamma(c1) - jss.gammaln(c1)
                + jss.gammaln(c2) + c2 * (jnp.log(r1) - jnp.log(r2))
                + c1 * (r2 - r1) / r1)
    return apply_op(raw, p.concentration, p.rate, q.concentration,
                    q.rate)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p: Dirichlet, q: Dirichlet):
    def raw(c1, c2):
        import jax.scipy.special as jss
        import jax.numpy as jnp
        s1 = jnp.sum(c1, -1)
        return (jss.gammaln(s1) - jnp.sum(jss.gammaln(c1), -1)
                - jss.gammaln(jnp.sum(c2, -1))
                + jnp.sum(jss.gammaln(c2), -1)
                + jnp.sum((c1 - c2) * (jss.digamma(c1)
                                       - jss.digamma(s1)[..., None]), -1))
    return apply_op(raw, p.concentration, q.concentration)


__all__ += ["Beta", "Gamma", "Chi2", "Dirichlet", "Geometric", "Poisson",
            "Binomial", "Multinomial", "StudentT", "Cauchy",
            "MultivariateNormal"]
