"""paddle.fft — discrete Fourier transform family.

Reference parity: python/paddle/fft.py (phi fft kernels).  TPU-native:
jnp.fft lowers to the XLA FFT HLO (TPU has a dedicated FFT
implementation); norm-mode semantics follow paddle/numpy ("backward" |
"ortho" | "forward").
"""
from __future__ import annotations

import jax.numpy as jnp

from .tensor import apply_op

__all__ = ["fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
           "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
           "hfft", "ihfft", "fftfreq", "rfftfreq", "fftshift",
           "ifftshift"]


def _named(jfn, fn):
    # raw_fn.__name__ keys AMP lists, nan-check reports, and static
    # Program.to_string — an anonymous lambda defeats all three
    fn.__name__ = jfn.__name__
    return fn


def _wrap1(jfn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply_op(
            _named(jfn, lambda a: jfn(a, n=n, axis=axis, norm=norm)), x)
    op.__name__ = jfn.__name__
    return op


def _wrapn(jfn, default_axes=None):
    def op(x, s=None, axes=default_axes, norm="backward", name=None):
        return apply_op(
            _named(jfn, lambda a: jfn(a, s=s, axes=axes, norm=norm)), x)
    op.__name__ = jfn.__name__
    return op


fft = _wrap1(jnp.fft.fft)
ifft = _wrap1(jnp.fft.ifft)
rfft = _wrap1(jnp.fft.rfft)
irfft = _wrap1(jnp.fft.irfft)
hfft = _wrap1(jnp.fft.hfft)
ihfft = _wrap1(jnp.fft.ihfft)

fftn = _wrapn(jnp.fft.fftn)
ifftn = _wrapn(jnp.fft.ifftn)
rfftn = _wrapn(jnp.fft.rfftn)
irfftn = _wrapn(jnp.fft.irfftn)


fft2 = _wrapn(jnp.fft.fft2, default_axes=(-2, -1))
ifft2 = _wrapn(jnp.fft.ifft2, default_axes=(-2, -1))
rfft2 = _wrapn(jnp.fft.rfft2, default_axes=(-2, -1))
irfft2 = _wrapn(jnp.fft.irfft2, default_axes=(-2, -1))


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .tensor import Tensor
    out = jnp.fft.fftfreq(int(n), d=float(d))
    return Tensor(out if dtype is None else out.astype(dtype))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .tensor import Tensor
    out = jnp.fft.rfftfreq(int(n), d=float(d))
    return Tensor(out if dtype is None else out.astype(dtype))


def fftshift(x, axes=None, name=None):
    return apply_op(_named(jnp.fft.fftshift,
        lambda a: jnp.fft.fftshift(a, axes=axes)), x)


def ifftshift(x, axes=None, name=None):
    return apply_op(_named(jnp.fft.ifftshift,
        lambda a: jnp.fft.ifftshift(a, axes=axes)), x)
