from . import io
