"""paddle.save / paddle.load.

Reference parity: python/paddle/framework/io.py — pickle-based state_dict
persistence (.pdparams/.pdopt).  Tensors are converted to numpy on save
and restored as Tensors on load; nested dicts/lists/tuples round-trip.
The sharded/distributed checkpoint path (orbax/tensorstore) lives in
paddle_tpu.distributed.checkpoint — this is the single-host format.
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from ..tensor import Tensor

__all__ = ["save", "load"]

_PROTOCOL = 4


def _to_saveable(obj: Any) -> Any:
    if isinstance(obj, Tensor):
        return {"__paddle_tpu_tensor__": True,
                "data": np.asarray(obj.value),
                "stop_gradient": obj.stop_gradient,
                "name": obj.name}
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_saveable(v) for v in obj)
    return obj


def _from_saveable(obj: Any) -> Any:
    if isinstance(obj, dict):
        if obj.get("__paddle_tpu_tensor__"):
            t = Tensor(obj["data"], stop_gradient=obj.get("stop_gradient",
                                                          True))
            t.name = obj.get("name")
            return t
        return {k: _from_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_from_saveable(v) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = _PROTOCOL, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path: str, **configs) -> Any:
    with open(path, "rb") as f:
        return _from_saveable(pickle.load(f))
