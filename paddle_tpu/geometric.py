"""paddle.geometric — graph message-passing ops.

Reference parity: python/paddle/geometric (send_u_recv / send_ue_recv /
send_uv message passing, segment reductions) over phi graph kernels.

TPU-native design: every op is a gather along edge indices + an XLA
scatter-reduce (``jax.ops.segment_*``) — the exact lowering GNN
libraries use on TPU, where sorted-segment reductions beat the
reference's atomics-based CUDA scatter kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .ops.api import tensorize

__all__ = ["send_u_recv", "send_ue_recv", "send_uv", "segment_sum",
           "segment_mean", "segment_max", "segment_min"]


def _seg_reduce(data, ids, pool_type, num):
    ids = ids.astype(jnp.int32)
    if pool_type == "sum":
        return jax.ops.segment_sum(data, ids, num_segments=num)
    if pool_type == "mean":
        s = jax.ops.segment_sum(data, ids, num_segments=num)
        cnt = jax.ops.segment_sum(jnp.ones_like(ids, data.dtype), ids,
                                  num_segments=num)
        shape = (num,) + (1,) * (data.ndim - 1)
        return s / jnp.maximum(cnt.reshape(shape), 1.0)
    if pool_type in ("max", "min"):
        fn = jax.ops.segment_max if pool_type == "max" \
            else jax.ops.segment_min
        out = fn(data, ids, num_segments=num)
        # paddle fills untouched rows with 0, not the reduction
        # identity.  Detect empties via a segment COUNT, not
        # isfinite(out): integer data's identity is iinfo min/max
        # (finite), and float data may legitimately hold +/-inf
        # (ADVICE r5 finding 3).
        cnt = jax.ops.segment_sum(jnp.ones_like(ids), ids,
                                  num_segments=num)
        empty = (cnt == 0).reshape((num,) + (1,) * (data.ndim - 1))
        return jnp.where(empty, jnp.zeros((), data.dtype), out)
    raise ValueError(f"unknown pool_type {pool_type}")


def _message(xs, ys, message_op):
    if message_op == "add":
        return xs + ys
    if message_op == "sub":
        return xs - ys
    if message_op == "mul":
        return xs * ys
    if message_op == "div":
        return xs / ys
    raise ValueError(f"unknown message_op {message_op}")


def _send_u_recv_raw(x, src_index, dst_index, reduce_op="sum",
                     out_size=None):
    """Gather x at src edges, reduce into dst nodes."""
    num = int(out_size) if out_size is not None else x.shape[0]
    return _seg_reduce(x[src_index], dst_index, reduce_op, num)


def _send_ue_recv_raw(x, y, src_index, dst_index, message_op="add",
                      reduce_op="sum", out_size=None):
    """Combine node features x[src] with edge features y, reduce to dst."""
    num = int(out_size) if out_size is not None else x.shape[0]
    xs = x[src_index]
    ys = y
    if ys.ndim < xs.ndim:
        ys = ys.reshape(ys.shape + (1,) * (xs.ndim - ys.ndim))
    return _seg_reduce(_message(xs, ys, message_op), dst_index,
                       reduce_op, num)


def _send_uv_raw(x, y, src_index, dst_index, message_op="add"):
    """Per-edge message from both endpoints (no reduction)."""
    return _message(x[src_index], y[dst_index], message_op)


def _num_segments(segment_ids):
    """paddle's segment ops size the output max(ids)+1 — inherently
    data-dependent, so it cannot be traced.  Erroring beats silently
    returning a different shape under jit; the jit-safe spelling is
    send_u_recv(..., out_size=N)."""
    if isinstance(segment_ids, jax.core.Tracer):
        raise NotImplementedError(
            "paddle.geometric.segment_* output size is max(ids)+1 — "
            "data-dependent, so not jit-traceable; use "
            "send_u_recv(x, ids, ids, reduce_op, out_size=N) for a "
            "static output size under jit")
    return int(jax.device_get(segment_ids).max()) + 1


def _segment_sum_raw(data, segment_ids):
    return _seg_reduce(data, segment_ids, "sum", _num_segments(segment_ids))


def _segment_mean_raw(data, segment_ids):
    return _seg_reduce(data, segment_ids, "mean",
                       _num_segments(segment_ids))


def _segment_max_raw(data, segment_ids):
    return _seg_reduce(data, segment_ids, "max", _num_segments(segment_ids))


def _segment_min_raw(data, segment_ids):
    return _seg_reduce(data, segment_ids, "min", _num_segments(segment_ids))


send_u_recv = tensorize(_send_u_recv_raw)
send_ue_recv = tensorize(_send_ue_recv_raw)
send_uv = tensorize(_send_uv_raw)
segment_sum = tensorize(_segment_sum_raw)
segment_mean = tensorize(_segment_mean_raw)
segment_max = tensorize(_segment_max_raw)
segment_min = tensorize(_segment_min_raw)
