from . import callbacks
from .callbacks import (Callback, EarlyStopping, LRScheduler,
                        ModelCheckpoint, ProgBarLogger, VisualDL)
from .model import Model

__all__ = ["Model", "callbacks", "Callback", "ProgBarLogger",
           "ModelCheckpoint", "EarlyStopping", "LRScheduler", "VisualDL"]
