"""hapi callbacks (reference: python/paddle/hapi/callbacks.py —
Callback/CallbackList, ProgBarLogger, ModelCheckpoint, EarlyStopping,
LRScheduler, VisualDL).  Pure-python training-loop hooks; nothing here
touches the compiled step."""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

import numpy as np

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "EarlyStopping", "LRScheduler", "VisualDL", "config_callbacks"]


class Callback:
    """Base class; subclass and override the hooks you need."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    # train
    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    # eval
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    # predict
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Step/epoch console logging (reference ProgBarLogger; TPU note:
    values printed are already device_get'd scalars — logging never
    blocks the async dispatch queue more than the step already did)."""

    def __init__(self, log_freq: int = 1, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.time()
        if self.verbose and self.params.get("epochs"):
            print(f"Epoch {epoch + 1}/{self.params['epochs']}")

    def _fmt(self, logs):
        return " - ".join(f"{k}: {np.asarray(v).item():.4f}"
                          if isinstance(v, (int, float, np.number))
                          or np.ndim(v) == 0 else f"{k}: {v}"
                          for k, v in (logs or {}).items())

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and step % self.log_freq == 0:
            steps = self.params.get("steps")
            print(f"step {step}/{steps or '?'} - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            print(f"Epoch {epoch + 1} done ({dt:.1f}s) - {self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq: int = 1, save_dir: Optional[str] = None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor: str = "loss", mode: str = "auto",
                 patience: int = 0, verbose: int = 1, min_delta: float = 0,
                 baseline: Optional[float] = None,
                 save_best_model: bool = True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.stopped_epoch = 0
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = (self.baseline if self.baseline is not None else
                     (-np.inf if self.mode == "max" else np.inf))

    def _better(self, cur):
        if self.mode == "max":
            return cur > self.best + self.min_delta
        return cur < self.best - self.min_delta

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(np.asarray(cur).reshape(-1)[0])
        if self._better(cur):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.params.get("save_dir"):
                self.model.save(
                    os.path.join(self.params["save_dir"], "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping: no {self.monitor} improvement "
                          f"in {self.patience} evals")


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (by_step or by_epoch)."""

    def __init__(self, by_step: bool = True, by_epoch: bool = False):
        super().__init__()
        assert by_step != by_epoch, "exactly one of by_step/by_epoch"
        self.by_step = by_step

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return getattr(opt, "_lr_scheduler", None) if opt else None

    # NOTE: CompiledTrainStep already steps the scheduler per call; this
    # callback only drives the by_epoch policy (per-step would
    # double-step).
    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s is not None and not self.by_step:
            s.step()


class VisualDL(Callback):
    """Scalar logging to the visualdl-shaped writer (paddle.callbacks
    .VisualDL parity over paddle_tpu.visualdl.LogWriter)."""

    def __init__(self, log_dir: str = "./log"):
        super().__init__()
        self.log_dir = log_dir
        self._writer = None
        self._step = 0

    def _w(self):
        if self._writer is None:
            from ..visualdl import LogWriter
            self._writer = LogWriter(self.log_dir)
        return self._writer

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        for k, v in (logs or {}).items():
            try:
                self._w().add_scalar(f"train/{k}",
                                     float(np.asarray(v).reshape(-1)[0]),
                                     self._step)
            except (TypeError, ValueError):
                pass

    def on_eval_end(self, logs=None):
        for k, v in (logs or {}).items():
            try:
                self._w().add_scalar(f"eval/{k}",
                                     float(np.asarray(v).reshape(-1)[0]),
                                     self._step)
            except (TypeError, ValueError):
                pass

    def on_train_end(self, logs=None):
        if self._writer is not None:
            self._writer.close()


def config_callbacks(callbacks=None, model=None, batch_size=None,
                     epochs=None, steps=None, verbose=2, log_freq=10,
                     save_dir=None, save_freq=1, metrics=None
                     ) -> CallbackList:
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks):
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq=save_freq,
                                       save_dir=save_dir)]
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({"batch_size": batch_size, "epochs": epochs,
                    "steps": steps, "verbose": verbose,
                    "metrics": metrics or [], "save_dir": save_dir})
    return lst
