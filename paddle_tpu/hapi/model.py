"""paddle.Model — the hapi high-level trainer.

Reference parity: python/paddle/hapi/model.py (SURVEY.md §2.2 hapi row):
``Model(network).prepare(optimizer, loss, metrics)`` then
``fit/evaluate/predict/save/load`` with the callbacks protocol.

TPU-native design: ``fit`` drives ONE compiled XLA step
(jit/train.CompiledTrainStep — fwd+bwd+clip+update fused, params live on
device) instead of the reference's per-op dygraph loop; eval/predict are
compile-once jitted forwards.  Metrics consume per-batch predictions on
host, matching paddle.metric semantics.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from ..common.errors import enforce
from ..io.dataloader import DataLoader
from ..metric import Metric
from ..nn.layer import Layer
from ..tensor import Tensor
from .callbacks import config_callbacks

__all__ = ["Model"]


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _to_host(x):
    import jax
    return np.asarray(jax.device_get(x))


class Model:
    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._train_step = None
        self._eval_jits = {}
        self._pending_opt_state = None
        self._accum_grads = None
        self._last_train_preds = None
        self._in_fit = False
        self.stop_training = False

    # -- setup ---------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, fused_step: bool = True,
                grad_norm_tap: bool = False):
        # fused_step: run the compiled step's optimizer update through
        # the fused clip+update path (jit/train.py; bit-identical to
        # False, which keeps the per-leaf reference loop for debugging)
        self._fused_step = bool(fused_step)
        # grad_norm_tap: the compiled step also returns the f32 global
        # grad norm, which fit feeds to the AnomalySentinel alongside
        # the loss — exploding gradients trip a step before the loss
        # spike.  Off by default (the extra step output can move XLA
        # fusion boundaries by an ulp, which parity tests pin).
        self._grad_norm_tap = bool(grad_norm_tap)
        self._optimizer = optimizer
        if loss is not None:
            enforce(callable(loss), "loss must be callable (a Layer or fn)")
        self._loss = loss
        # re-preparing drops any compiled step: optimizer/loss/metrics
        # are baked into it (incl. the has_aux choice), so a stale step
        # would silently ignore the new configuration.  If the
        # OPTIMIZER object is unchanged, its accumulated state
        # (moments, loaded via load()) carries over into the rebuilt
        # step — silently resetting it was ADVICE r3 (e.g. a metrics
        # tweak mid-training zeroing Adam moments)
        if self._train_step is not None:
            # trained params live in the step's donated state — push
            # them back into the Layer FIRST, else the rebuilt step
            # restarts from stale weights (with warm moments, worse)
            self._train_step.sync_to_model()
            if optimizer is not None and optimizer is getattr(
                    self._train_step, "optimizer", None):
                self._pending_opt_state = self._train_step.state.get(
                    "opt")
            else:
                self._pending_opt_state = None
            self._train_step = None
        self._metrics = _as_list(metrics)
        for m in self._metrics:
            enforce(isinstance(m, Metric),
                    f"metrics must be paddle_tpu.metric.Metric, got "
                    f"{type(m)}")
        return self

    def _loss_fn(self, net, batch):
        ins, labs = batch["inputs"], batch["labels"]
        out = net(*ins)
        outs = _as_list(out)
        return self._loss(*(outs + list(labs)))

    def _loss_fn_aux(self, net, batch):
        """Fused-step variant returning (loss, predictions): train
        metrics then come from the SAME pre-update forward as the loss
        (paddle parity) instead of a second post-update eval pass."""
        ins, labs = batch["inputs"], batch["labels"]
        out = net(*ins)
        outs = _as_list(out)
        return self._loss(*(outs + list(labs))), tuple(outs)

    def _ensure_train_step(self):
        if self._train_step is None:
            enforce(self._optimizer is not None and self._loss is not None,
                    "call prepare(optimizer=..., loss=...) before training")
            from ..jit.train import CompiledTrainStep
            self.network.train()
            # with metrics configured, the fused step also returns the
            # training forward's predictions (has_aux) so per-batch
            # train metrics cost no extra forward
            fused = getattr(self, "_fused_step", True)
            tap = getattr(self, "_grad_norm_tap", False)
            if self._metrics:
                self._train_step = CompiledTrainStep(
                    self.network, self._loss_fn_aux, self._optimizer,
                    has_aux=True, fused_step=fused, grad_norm_tap=tap)
            else:
                self._train_step = CompiledTrainStep(
                    self.network, self._loss_fn, self._optimizer,
                    fused_step=fused, grad_norm_tap=tap)
            if self._pending_opt_state is not None:
                self._train_step.state["opt"] = self._pending_opt_state
                self._pending_opt_state = None
        return self._train_step

    def _params(self):
        """Current params pytree: the train step's device state when it
        exists, else the network's own."""
        if self._train_step is not None:
            return self._train_step.state["params"]
        return self.network.raw_state_dict()

    def _run_eval(self, name: str, fn: Callable, batch):
        """Compile-once jitted forward independent of the train step —
        predict/evaluate must work with no optimizer (inference-only
        Model, paddle parity) and never allocate optimizer state.  The
        network is traced in eval mode (dropout off, BN running stats)."""
        import jax

        jitted = self._eval_jits.get(name)
        if jitted is None:
            from ..jit.train import traced_forward
            net = self.network

            def run(params, batch, key):
                return traced_forward(net, fn, params, batch, key)

            jitted = jax.jit(run)
            self._eval_jits[name] = jitted
        import jax.numpy as jnp
        was_training = self.network.training
        self.network.eval()
        try:
            batch_arr = jax.tree_util.tree_map(
                lambda x: x.value if isinstance(x, Tensor) else jnp.asarray(x),
                batch, is_leaf=lambda x: isinstance(x, Tensor))
            return jitted(self._params(), batch_arr, jax.random.key(0))
        finally:
            if was_training:
                self.network.train()

    # -- batch-level API ------------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        step = self._ensure_train_step()
        batch = {"inputs": tuple(_as_list(inputs)),
                 "labels": tuple(_as_list(labels))}
        if update and self._accum_grads is None:
            out = step(batch)                  # fused fast path
            if step._has_aux:
                loss, preds = out
                # stash for fit's metrics pass only — direct
                # train_batch callers must not pin a logits buffer
                self._last_train_preds = preds if self._in_fit else None
                return [_to_host(loss)]
            self._last_train_preds = None
            return [_to_host(out)]
        # paddle update=False semantics: accumulate grads, defer update
        self._last_train_preds = None   # no fused-forward preds here
        import jax
        loss, grads = step.grad_step(batch)
        if self._accum_grads is None:
            self._accum_grads = grads
        else:
            self._accum_grads = jax.tree_util.tree_map(
                lambda a, g: a + g, self._accum_grads, grads)
        if update:
            step.apply_grads(self._accum_grads)
            self._accum_grads = None
        return [_to_host(loss)]

    def _eval_fn(self, net, batch):
        ins, labs = batch["inputs"], batch["labels"]
        out = net(*ins)
        outs = _as_list(out)
        res = {"preds": outs}
        if self._loss is not None and labs:
            res["loss"] = self._loss(*(outs + list(labs)))
        return res

    def _predict_fn(self, net, batch):
        return _as_list(net(*batch["inputs"]))

    def eval_batch(self, inputs, labels=None):
        batch = {"inputs": tuple(_as_list(inputs)),
                 "labels": tuple(_as_list(labels))}
        return self._run_eval("eval", self._eval_fn, batch)

    def predict_batch(self, inputs):
        batch = {"inputs": tuple(_as_list(inputs)), "labels": ()}
        return [_to_host(p)
                for p in self._run_eval("predict", self._predict_fn, batch)]

    # -- loops ---------------------------------------------------------------
    def _loader(self, data, batch_size, shuffle, num_workers,
                drop_last=False):
        from ..io.dataloader import CheckpointableLoader
        if data is None or isinstance(data, (DataLoader,
                                             CheckpointableLoader)):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          num_workers=num_workers, drop_last=drop_last)

    @staticmethod
    def _split_batch(batch):
        """DataLoader batches arrive as [x] or [x, y] (or a longer list:
        the LAST item is the label, paddle's single-label convention)."""
        items = list(batch) if isinstance(batch, (list, tuple)) else [batch]
        if len(items) == 1:
            return items, []
        return items[:-1], items[-1:]

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, checkpoint_dir=None, save_steps=None,
            auto_resume=True):
        """Train.  Crash-safe checkpointing: with ``checkpoint_dir`` set
        (a path, or a configured ``distributed.CheckpointManager`` for
        async saves / custom retention), the FULL training state —
        params, optimizer state, RNG stream, LR-scheduler and loader
        position — is checkpointed every ``save_steps`` optimizer steps
        (atomic commit: a kill mid-save never leaves a torn checkpoint)
        and at train end; ``auto_resume=True`` restores the latest valid
        checkpoint before the first step and continues exactly where the
        interrupted run stopped.  Pass the data as a
        ``CheckpointableLoader`` for mid-epoch exactness (the loader
        position rides in the checkpoint and the resumed loss trajectory
        is bit-identical to an uninterrupted run); with a plain loader,
        resume restarts the interrupted epoch from its first batch.  If
        ``manager.install_preemption_hook()`` was armed, a SIGTERM saves
        and stops cleanly after the in-flight step."""
        loader = self._loader(train_data, batch_size, shuffle, num_workers,
                              drop_last=drop_last)
        enforce(loader is not None, "fit needs train_data")
        step = self._ensure_train_step()

        manager = None
        start_epoch = 0
        global_step = 0
        last_saved = -1
        loader_ckptable = hasattr(loader, "state_dict") and \
            hasattr(loader, "set_state_dict")
        # goodput accounting: one window per fit() call — a resumed
        # run books its checkpoint restore as restart_replay (the
        # badput a kill actually cost), a fresh run shows zero there
        from ..observability import health as _health
        _health.get_health().goodput.start()
        if checkpoint_dir is not None:
            from ..distributed.ckpt_manager import CheckpointManager
            manager = checkpoint_dir if isinstance(
                checkpoint_dir, CheckpointManager) else \
                CheckpointManager(str(checkpoint_dir))
            if auto_resume:
                import time as _time
                _t0 = _time.monotonic()
                restored = manager.restore(step)
                if restored is not None:
                    rstep, extra = restored
                    extra = extra or {}
                    global_step = int(extra.get("global_step", rstep))
                    start_epoch = int(extra.get("epoch", 0))
                    lstate = extra.get("loader")
                    if lstate is not None and loader_ckptable:
                        loader.set_state_dict(lstate)
                    # booked only when a checkpoint actually replayed:
                    # a fresh run's no-op restore probe isn't badput
                    _health.get_health().goodput.add(
                        "restart_replay", _time.monotonic() - _t0)

        def ckpt_extra(epoch):
            lstate = loader.state_dict() if loader_ckptable else None
            # the epoch to RESUME INTO: the loader's cursor epoch when
            # it is checkpointable (it already advanced past an epoch
            # boundary), else the current one (replayed from batch 0)
            return {"global_step": global_step,
                    "epoch": int(lstate["epoch"]) if lstate is not None
                    else epoch,
                    "loader": lstate}
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                steps=steps, batch_size=batch_size,
                                verbose=verbose, log_freq=log_freq,
                                save_dir=save_dir,
                                save_freq=save_freq,
                                metrics=[n for m in self._metrics
                                         for n in _as_list(m.name())])
        # per-step telemetry: wall time (block_until_ready fenced),
        # tokens/s, MFU — into the metrics registry, and mirrored to
        # the VisualDL callback's writer when one is configured
        from .callbacks import VisualDL
        from ..observability import StepTimer
        from ..observability import tracing as _tracing
        vdl = next((c for c in cbks.callbacks
                    if isinstance(c, VisualDL)), None)
        timer = StepTimer(prefix="train",
                          writer=vdl._w() if vdl is not None else None)
        step.attach_timer(timer)

        def traced_batches(ldr):
            # one "train.data_load" span per batch FETCH (host input
            # pipeline time, distinct from the compiled-step span the
            # train step emits) — the NULL_SPAN singleton when tracing
            # is off, so the loop shape costs nothing
            it = iter(ldr)
            while True:
                with _tracing.span("train.data_load"), \
                        _health.goodput_region("data_stall"):
                    try:
                        batch = next(it)
                    except StopIteration:
                        return
                yield batch
        self.stop_training = False
        cbks.on_train_begin()
        logs = {}
        self._in_fit = True
        cur_epoch = start_epoch
        for epoch in range(start_epoch, epochs):
            cur_epoch = epoch
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            for step_i, batch in enumerate(traced_batches(loader)):
                cbks.on_train_batch_begin(step_i)
                ins, labs = self._split_batch(batch)
                if ins:
                    # tokens/s convention: elements of the first input
                    # ([B, S] ids for an LM = real tokens); np.shape
                    # reads .shape — no host copy of the batch
                    timer.tokens_per_step = int(
                        np.prod(np.shape(ins[0]))) or None
                logs = {"loss": self.train_batch(ins, labs)[0]}
                # anomaly sentinel: NaN/Inf or an EWMA spike in the
                # step loss trips the configured policy (warn /
                # skip_step / halt) and dumps the flight recorder.
                # With prepare(grad_norm_tap=True) the fused step also
                # surfaces its f32 global grad norm, so an exploding
                # gradient trips a step BEFORE the loss spike.
                _sentinel_vals = {
                    "loss": float(np.asarray(logs["loss"]).ravel()[0])}
                _gn = getattr(self._train_step, "last_grad_norm", None)
                if _gn is not None:
                    _sentinel_vals["grad_norm"] = float(
                        np.asarray(_gn).ravel()[0])
                _act = _health.get_health().sentinel_check(
                    step=global_step, **_sentinel_vals)
                if _act == "halt":
                    self.stop_training = True
                _skip_metrics = _act == "skip_step"
                if timer.flops_per_step is None and \
                        timer.peak_flops is not None:
                    # first step compiled the program: one AOT lowering
                    # prices the step for the MFU gauge (skipped when
                    # the host has no known peak — CPU runs)
                    timer.flops_per_step = step.step_flops(
                        {"inputs": tuple(_as_list(ins)),
                         "labels": tuple(_as_list(labs))})
                    if timer.flops_per_step is None:
                        timer.peak_flops = None   # don't retry per step
                if self._metrics and not _skip_metrics:
                    preds = self._last_train_preds
                    self._last_train_preds = None  # consume: don't pin
                    if preds is not None:
                        # pre-update predictions from the SAME forward
                        # as the loss (paddle semantics, zero extra cost)
                        mlogs = self._update_metrics(
                            {"preds": [Tensor(p) for p in preds]},
                            _as_list(labs))
                    else:
                        # grad-accumulation path: fall back to an eval
                        # forward (post-update, documented drift)
                        ev = self.eval_batch(ins, labs)
                        mlogs = self._update_metrics(ev, _as_list(labs))
                    mlogs.pop("loss", None)
                    logs.update(mlogs)
                cbks.on_train_batch_end(step_i, logs)
                global_step += 1
                if manager is not None:
                    preempted = getattr(manager, "preempted", False)
                    if preempted or (save_steps and
                                     global_step % save_steps == 0):
                        manager.save(step, global_step,
                                     extra_state=ckpt_extra(epoch))
                        last_saved = global_step
                    if preempted:
                        # SIGTERM landed: state is on disk, exit the
                        # loop cleanly before the scheduler's SIGKILL
                        self.stop_training = True
                if self.stop_training:
                    break
            cbks.on_epoch_end(epoch, logs)
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_data, batch_size=batch_size,
                              verbose=verbose, num_workers=num_workers,
                              callbacks=cbks)
        if manager is not None:
            # final checkpoint (unless the last save already covers this
            # step), then drain the async queue so a background-writer
            # failure surfaces HERE, not at interpreter exit
            if global_step > 0 and last_saved != global_step:
                # a stopped run resumes INTO the interrupted epoch; a
                # completed one records `epochs` so resume is a no-op
                manager.save(step, global_step, extra_state=ckpt_extra(
                    cur_epoch if self.stop_training else epochs))
            manager.wait()
        cbks.on_train_end(logs)
        _health.get_health().goodput.stop()
        # the VisualDL callback closed its writer above — detach the
        # timer so later direct train_batch calls can't write into it
        step.attach_timer(None)
        self._in_fit = False
        self._last_train_preds = None
        return self

    def _update_metrics(self, ev, labs):
        out = {}
        if "loss" in ev:
            out["loss"] = _to_host(ev["loss"])
        preds = ev["preds"]
        for m in self._metrics:
            r = m.compute(*(list(preds) + [Tensor(l) if not isinstance(
                l, Tensor) else l for l in labs]))
            # default compute() passes through an args tuple; update
            # takes them positionally (paddle Metric protocol)
            vals = _as_list(m.update(*[_to_host(x) for x in _as_list(r)]))
            out.update(dict(zip(_as_list(m.name()), vals)))
        return out

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = self._loader(eval_data, batch_size, False, num_workers)
        from .callbacks import CallbackList
        cbks = callbacks if isinstance(callbacks, CallbackList) else \
            config_callbacks(callbacks, model=self, verbose=verbose,
                             log_freq=log_freq,
                             metrics=[n for m in self._metrics
                                      for n in _as_list(m.name())])
        cbks.on_eval_begin()
        for m in self._metrics:
            m.reset()
        losses = []
        for step_i, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step_i)
            ins, labs = self._split_batch(batch)
            ev = self.eval_batch(ins, labs)
            logs = self._update_metrics(ev, labs)
            if "loss" in logs:
                losses.append(float(np.asarray(logs["loss"])))
            cbks.on_eval_batch_end(step_i, logs)
        result = {}
        if losses:
            result["loss"] = float(np.mean(losses))
        for m in self._metrics:
            vals = _as_list(m.accumulate())
            result.update(dict(zip(_as_list(m.name()), vals)))
        cbks.on_eval_end(result)
        return result

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._loader(test_data, batch_size, False, num_workers)
        cbks = config_callbacks(callbacks, model=self, verbose=0)
        cbks.on_predict_begin()
        outs: List[List[np.ndarray]] = []
        for step_i, batch in enumerate(loader):
            cbks.on_predict_batch_begin(step_i)
            ins, _ = self._split_batch(batch)
            preds = self.predict_batch(ins)
            outs.append(preds)
            cbks.on_predict_batch_end(step_i)
        cbks.on_predict_end()
        n_out = len(outs[0]) if outs else 0
        grouped = [[b[i] for b in outs] for i in range(n_out)]
        if stack_outputs:
            grouped = [np.concatenate(g, axis=0) for g in grouped]
        return grouped

    # -- persistence ----------------------------------------------------------
    def save(self, path: str, training: bool = True):
        """path.pdparams (+ path.pdopt when training=True), paddle layout."""
        from ..framework.io import save
        self._sync_from_step()
        save(self.network.state_dict(), path + ".pdparams")
        if training and self._train_step is not None:
            save(self._train_step.state["opt"], path + ".pdopt")

    def load(self, path: str, skip_mismatch: bool = False, reset_optimizer=False):
        from ..framework.io import load
        state = load(path + ".pdparams")
        skipped = False
        if skip_mismatch:
            cur = self.network.state_dict()
            kept = {k: v for k, v in state.items()
                    if k in cur and tuple(np.shape(v)) ==
                    tuple(cur[k].shape)}
            skipped = len(kept) != len(state)
            state = kept
        self.network.set_state_dict(state)
        import os
        opt_state = None
        # a checkpoint whose params were partially skipped has optimizer
        # slots shaped for the OLD params — restoring them would crash
        # deep inside the first jitted update
        if not reset_optimizer and not skipped and \
                os.path.exists(path + ".pdopt"):
            opt_state = load(path + ".pdopt")
        if self._train_step is not None:
            self._train_step.sync_from_model()
            if opt_state is not None:
                self._train_step.state["opt"] = opt_state
        else:
            # train step is built lazily: apply on first _ensure_train_step
            self._pending_opt_state = opt_state
        return self

    def _sync_from_step(self):
        if self._train_step is not None:
            self._train_step.sync_to_model()

    def parameters(self, *a, **k):
        return self.network.parameters(*a, **k)

    def summary(self, input_size=None, dtype=None):
        total = 0
        lines = []
        for name, p in self.network.named_parameters():
            n = int(np.prod(p.shape)) if p.shape else 1
            total += n
            lines.append(f"  {name:50s} {str(tuple(p.shape)):20s} {n}")
        out = "\n".join(["-" * 80] + lines +
                        ["-" * 80, f"Total params: {total}"])
        print(out)
        return {"total_params": total}
