"""paddle.hub — load models from hubconf.py entrypoints.

Reference parity: python/paddle/hapi/hub.py (hub.list / hub.help /
hub.load over a ``hubconf.py`` protocol).  The local-dir source works
fully; remote github/gitee sources need network egress and raise a
clear error instead of half-working (this environment is air-gapped;
the protocol — entrypoints are callables in hubconf.py, `dependencies`
is an optional requirements list — is identical)."""
from __future__ import annotations

import importlib.util
import os
import sys

from .common.errors import enforce

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, _HUBCONF)
    enforce(os.path.isfile(path), f"no {_HUBCONF} in {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
    return mod


def _resolve(repo_dir: str, source: str):
    enforce(source in ("local", "github", "gitee"),
            f"unknown hub source {source!r}")
    if source != "local":
        raise NotImplementedError(
            "paddle.hub remote sources need network egress; clone the "
            "repo and use source='local' with its path")
    return repo_dir


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    """Entrypoint names exported by the repo's hubconf.py."""
    mod = _load_hubconf(_resolve(repo_dir, source))
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")
            and n != "dependencies"]


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    mod = _load_hubconf(_resolve(repo_dir, source))
    enforce(hasattr(mod, model), f"no entrypoint {model!r}")
    return getattr(mod, model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    """Call the named hubconf entrypoint with **kwargs."""
    mod = _load_hubconf(_resolve(repo_dir, source))
    enforce(hasattr(mod, model), f"no entrypoint {model!r}")
    return getattr(mod, model)(**kwargs)
