"""paddle.incubate namespace parity (MoE et al., SURVEY.md §1 L7)."""
from . import distributed  # noqa: F401
from . import nn  # noqa: F401
