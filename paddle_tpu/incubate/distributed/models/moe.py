"""paddle.incubate.distributed.models.moe parity surface."""
from ....nn.moe import ExpertFFN, MoELayer, TopKGate  # noqa: F401

__all__ = ["MoELayer", "TopKGate", "ExpertFFN"]
