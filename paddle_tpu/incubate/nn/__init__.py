"""paddle.incubate.nn — fused layer surface.

Reference parity: python/paddle/incubate/nn (FusedTransformerEncoderLayer,
FusedMultiHeadAttention, FusedFeedForward over phi fusion kernels).
TPU-native: "fused" is XLA's job — these classes keep the incubate
constructor signatures and route to the standard layers, whose attention
already dispatches to the Pallas flash kernel; XLA fuses the rest.
"""
from __future__ import annotations

from ...nn.layer import Layer
from ...nn.transformer import MultiHeadAttention, TransformerEncoderLayer
from . import functional

__all__ = ["FusedTransformerEncoderLayer", "FusedMultiHeadAttention",
           "FusedFeedForward", "functional"]


class FusedTransformerEncoderLayer(TransformerEncoderLayer):
    """incubate.nn.FusedTransformerEncoderLayer signature over the
    standard encoder layer (XLA performs the fusions the reference's
    hand-written kernels provide)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__(d_model, nhead, dim_feedforward,
                         dropout=dropout_rate, activation=activation,
                         attn_dropout=attn_dropout_rate,
                         act_dropout=act_dropout_rate,
                         normalize_before=normalize_before,
                         weight_attr=weight_attr, bias_attr=bias_attr)


class FusedMultiHeadAttention(Layer):
    """incubate.nn.FusedMultiHeadAttention: (pre|post)-LN + MHA +
    dropout + residual, the reference's fused block structure, over the
    standard MHA whose attention takes the flash path."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 weight_attr=None, bias_attr=None, epsilon=1e-5):
        super().__init__()
        from ...nn.common import Dropout
        from ...nn.norm import LayerNorm
        self.normalize_before = normalize_before
        self.attn = MultiHeadAttention(
            embed_dim, num_heads, dropout=attn_dropout_rate, kdim=kdim,
            vdim=vdim, need_weights=need_weights,
            weight_attr=weight_attr, bias_attr=bias_attr)
        self.norm = LayerNorm(embed_dim, epsilon=epsilon)
        self.dropout = Dropout(dropout_rate)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        residual = query
        if self.normalize_before:
            query = self.norm(query)
        out = self.attn(query, key, value, attn_mask, cache)
        # MHA returns (out, cache) only for the incremental Cache type;
        # StaticCache (and no cache) return the bare tensor
        returned_cache = None
        if cache is not None and not isinstance(
                cache, MultiHeadAttention.StaticCache):
            out, returned_cache = out
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.norm(out)
        return out if returned_cache is None else (out, returned_cache)


class FusedFeedForward(Layer):
    """incubate.nn.FusedFeedForward: linear -> act -> dropout -> linear
    (+ residual/LayerNorm per normalize_before)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 activation="relu", act_dropout_rate=None,
                 normalize_before=False, weight_attr=None,
                 bias_attr=None, epsilon=1e-5):
        super().__init__()
        from ...nn.common import Dropout, Linear
        from ...nn.norm import LayerNorm
        from ...nn.transformer import _get_activation
        act_dropout_rate = dropout_rate if act_dropout_rate is None \
            else act_dropout_rate
        self.normalize_before = normalize_before
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.dropout1 = Dropout(act_dropout_rate)
        self.dropout2 = Dropout(dropout_rate)
        self.norm = LayerNorm(d_model, epsilon=epsilon)
        self.activation = _get_activation(activation)

    def forward(self, src):
        residual = src
        if self.normalize_before:
            src = self.norm(src)
        src = self.dropout1(self.activation(self.linear1(src)))
        src = residual + self.dropout2(self.linear2(src))
        if not self.normalize_before:
            src = self.norm(src)
        return src
