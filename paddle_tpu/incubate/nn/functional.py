"""paddle.incubate.nn.functional — fused functional surface.

The reference's fused phi kernels map to the framework's existing fused
paths (flash attention, chunked linear+CE) or to compositions XLA fuses.
"""
from __future__ import annotations

from ...nn import functional as F
from ...ops.api import fused_linear  # noqa: F401
from ...ops.api import fused_linear_cross_entropy  # noqa: F401

__all__ = ["fused_linear", "fused_linear_cross_entropy",
           "fused_multi_head_attention", "fused_feedforward",
           "fused_rms_norm", "fused_layer_norm", "swiglu",
           "fused_rotary_position_embedding"]


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5,
                               qkv_bias=None, linear_bias=None,
                               cache_kv=None, attn_mask=None,
                               dropout_rate=0.0,
                               attn_dropout_rate=0.0,
                               ln_epsilon=1e-5, training=True,
                               mode="upscale_in_train", ring_id=-1,
                               add_residual=True, num_heads=None):
    """Reference fused_multi_head_attention signature (the common
    subset): qkv_weight [3, num_heads, head_dim, embed_dim] packed (the
    reference layout — num_heads comes from the weight); attention runs
    the flash path.  cache_kv (incremental decode) is not ported here —
    use nn.MultiHeadAttention's cache API or the generation engine."""
    from ...common.errors import enforce
    from ... import ops as P

    enforce(cache_kv is None,
            "fused_multi_head_attention: cache_kv is not supported — "
            "use nn.MultiHeadAttention's Cache API or "
            "inference.LLMEngine for incremental decode")
    enforce(ring_id == -1,
            "fused_multi_head_attention: ring_id (tensor-parallel "
            "allreduce) is not supported here — use the Megatron "
            "parallel layers, whose collectives GSPMD emits")
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, x.shape[-1], pre_ln_scale, pre_ln_bias,
                         pre_ln_epsilon)
    b, s, d = x.shape
    if getattr(qkv_weight, "ndim", None) == 4:
        _, nh, hd, _ = qkv_weight.shape      # reference packed layout
    else:
        enforce(num_heads is not None,
                "pass num_heads (or a 4-D [3, heads, head_dim, embed] "
                "qkv_weight it can be read from)")
        nh = num_heads
        hd = d // nh
    qkv = P.matmul(P.reshape(x, [b * s, d]),
                   P.reshape(qkv_weight, [3 * d, d]).T)
    if qkv_bias is not None:
        qkv = qkv + P.reshape(qkv_bias, [-1])
    q, k, v = P.split(P.reshape(qkv, [b, s, 3, d]), 3, axis=2)

    def heads(t):
        return P.reshape(t, [b, s, nh, hd])
    out = F.scaled_dot_product_attention(
        heads(q), heads(k), heads(v), attn_mask=attn_mask,
        dropout_p=attn_dropout_rate if training else 0.0,
        training=training)
    out = P.matmul(P.reshape(out, [b, s, d]), linear_weight)
    if linear_bias is not None:
        out = out + linear_bias
    if dropout_rate and training:
        out = F.dropout(out, dropout_rate, training=training, mode=mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, out.shape[-1], ln_scale, ln_bias,
                           ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight,
                      linear1_bias=None, linear2_bias=None,
                      ln1_scale=None, ln1_bias=None, ln2_scale=None,
                      ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True):
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, x.shape[-1], ln1_scale, ln1_bias,
                         ln1_epsilon)
    from ...nn.transformer import _get_activation
    h = F.linear(x, linear1_weight, linear1_bias)
    h = _get_activation(activation)(h)
    if dropout1_rate and training:
        h = F.dropout(h, dropout1_rate, training=training)
    h = F.linear(h, linear2_weight, linear2_bias)
    if dropout2_rate and training:
        h = F.dropout(h, dropout2_rate, training=training)
    out = residual + h
    if not pre_layer_norm:
        out = F.layer_norm(out, out.shape[-1], ln2_scale, ln2_bias,
                           ln2_epsilon)
    return out


def fused_rms_norm(x, scale, epsilon=1e-6):
    return F.rms_norm(x, scale, epsilon=epsilon)


def fused_layer_norm(x, scale=None, bias=None, epsilon=1e-5):
    return F.layer_norm(x, x.shape[-1], scale, bias, epsilon)


def swiglu(x, y=None):
    """incubate swiglu: silu(x) * y (y defaults to the second half)."""
    from ... import ops as P
    if y is None:
        x, y = P.split(x, 2, axis=-1)
    return F.silu(x) * y


def fused_rotary_position_embedding(q, k=None, v=None, sin=None,
                                    cos=None, position_ids=None,
                                    use_neox_rotary_style=True):
    """incubate fused_rotary_position_embedding: rotate q/k(/v)
    [B, S, H, D] by cos/sin [1, S, 1, D] (XLA fuses the mul/roll chain
    — the 'fused' of the reference's CUDA kernel comes free here).
    Neox style rotates halves; the non-neox style rotates interleaved
    even/odd lanes."""
    from ...models.llama import _rope_cos_sin, apply_rotary_pos_emb
    from ... import ops as P
    from ...tensor import to_tensor as _tt
    import numpy as _np

    if cos is None or sin is None:
        # the llama rope table (single source for layout/theta handling)
        emb = _rope_cos_sin(q.shape[1], q.shape[-1], 10000.0)
        cos = _tt(_np.cos(emb))
        sin = _tt(_np.sin(emb))
    else:
        # paddle passes [1, S, 1, D]; the rope core wants [S, D]
        if len(cos.shape) == 4:
            cos = P.reshape(cos, [cos.shape[1], cos.shape[3]])
            sin = P.reshape(sin, [sin.shape[1], sin.shape[3]])
    if position_ids is not None:
        # PER-ROW positions: gather [B, S, D] angles and rotate inline
        # (the shared rope core takes one [S, D] table for the batch)
        cos_b = cos[position_ids]                  # [B, S, D]
        sin_b = sin[position_ids]

        def rope_rows(x):
            def raw(xv, cv, sv):
                import jax.numpy as jnp
                if use_neox_rotary_style:
                    h = xv.shape[-1] // 2
                    rot = jnp.concatenate([-xv[..., h:], xv[..., :h]], -1)
                else:
                    h = cv.shape[-1] // 2
                    cv = jnp.repeat(cv[..., :h], 2, axis=-1)
                    sv = jnp.repeat(sv[..., :h], 2, axis=-1)
                    even = xv[..., 0::2]
                    odd = xv[..., 1::2]
                    rot = jnp.stack([-odd, even], -1).reshape(xv.shape)
                cf = cv[:, :, None, :].astype(jnp.float32)
                sf = sv[:, :, None, :].astype(jnp.float32)
                xf = xv.astype(jnp.float32)
                return (xf * cf + rot.astype(jnp.float32) * sf).astype(
                    xv.dtype)
            from ...tensor import apply_op as _ap
            return _ap(raw, x, cos_b, sin_b)

        return tuple(None if x is None else rope_rows(x)
                     for x in (q, k, v))
    # rotate in PAIRS: apply_rotary_pos_emb does two tensors per call,
    # so a (q, k, v) batch costs 2 calls, not 3 doubled ones
    present = [i for i, x in enumerate((q, k, v)) if x is not None]
    tensors = [q, k, v]
    out = [None, None, None]
    i = 0
    while i < len(present):
        ia = present[i]
        if i + 1 < len(present):
            ib = present[i + 1]
            out[ia], out[ib] = apply_rotary_pos_emb(
                tensors[ia], tensors[ib], cos, sin,
                interleaved=not use_neox_rotary_style)
            i += 2
        else:
            out[ia], _ = apply_rotary_pos_emb(
                tensors[ia], tensors[ia], cos, sin,
                interleaved=not use_neox_rotary_style)
            i += 1
    return tuple(out)
