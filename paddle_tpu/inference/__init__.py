from .predictor import Config, PredictorTensor, Predictor, create_predictor
from .paged_cache import PagedKVCache

__all__ = ["Config", "Predictor", "PredictorTensor", "create_predictor",
           "PagedKVCache"]
