from .predictor import Config, PredictorTensor, Predictor, create_predictor
from .paged_cache import PagedKVCache
from .engine import GenRequest, LLMEngine
from .sampling import sample_logits, split_step, window_keys

__all__ = ["Config", "Predictor", "PredictorTensor", "create_predictor",
           "PagedKVCache", "LLMEngine", "GenRequest",
           "sample_logits", "split_step", "window_keys"]
