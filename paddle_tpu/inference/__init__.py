from .predictor import Config, PredictorTensor, Predictor, create_predictor
from .paged_cache import PagedKVCache
from .backbone import BackboneSpec, register_backbone, resolve_backbone
from .moe_dispatch import MoEArch, moe_ffn
from .engine import GenRequest, LLMEngine
from .sampling import sample_logits, split_step, window_keys

__all__ = ["Config", "Predictor", "PredictorTensor", "create_predictor",
           "PagedKVCache", "LLMEngine", "GenRequest",
           "BackboneSpec", "register_backbone", "resolve_backbone",
           "MoEArch", "moe_ffn",
           "sample_logits", "split_step", "window_keys"]
