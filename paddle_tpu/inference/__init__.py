from .predictor import Config, PredictorTensor, Predictor, create_predictor
from .paged_cache import PagedKVCache
from .engine import GenRequest, LLMEngine

__all__ = ["Config", "Predictor", "PredictorTensor", "create_predictor",
           "PagedKVCache", "LLMEngine", "GenRequest"]
