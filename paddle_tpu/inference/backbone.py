"""Model-backbone adapter seam for :class:`~.engine.LLMEngine`.

The engine used to read ``model.llama.*`` attributes directly, so any
model that was not literally a ``LlamaForCausalLM`` died with a bare
``AttributeError`` deep inside ``__init__``.  This module is the
reviewable seam that replaced those hardwired reads: a
:class:`BackboneSpec` names everything the serving programs consume —
the decoder layer list, the final norm, the embedding/head weights, the
rope buffers, and (for MoE families) the router geometry — and a small
predicate registry resolves a model instance to its spec by DUCK
TYPING, never by class identity, so converted/quantized wrappers keep
working as long as the attribute shape survives.

Two backbones register here:

- ``llama`` — ``LlamaForCausalLM``-shaped models (``model.llama.*``),
  the original engine contract, byte-identical programs.
- ``qwen2_moe`` — ``Qwen2MoeForCausalLM``/DeepSeekMoE-shaped models
  (top-level ``layers`` whose ``mlp`` is a shared-expert MoE layer).
  The spec additionally carries the router geometry the engine folds
  into its static MoE arch (see inference/moe_dispatch.py).

Unsupported models get ONE clear error listing what would make them
servable, instead of the old attribute crash.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional

from ..common.errors import enforce

__all__ = ["BackboneSpec", "register_backbone", "resolve_backbone"]


@dataclass
class BackboneSpec:
    """Everything LLMEngine reads off a model, named once.

    ``moe`` is ``None`` for dense-FFN backbones; for MoE backbones it
    is the router geometry dict (num_experts, top_k, norm_topk,
    capacity_factor, shared, shared_gate) the engine freezes into its
    static dispatch arch and its capsule fingerprint."""
    arch: str
    config: Any
    layers: List[Any]
    norm: Any
    embed_tokens: Any
    lm_head: Optional[Any]
    rope_cos: Any
    rope_sin: Any
    attn_bias: bool = False
    moe: Optional[dict] = None


# ordered (arch, predicate, builder) triples — first predicate match
# wins, so register more specific shapes before more general ones
_REGISTRY: List[tuple] = []


def register_backbone(arch: str, predicate: Callable[[Any], bool],
                      builder: Callable[[Any], "BackboneSpec"]):
    """Register a servable model family: ``predicate(model)`` decides
    membership by duck typing, ``builder(model)`` produces the spec.
    Later registrations of the same ``arch`` replace the earlier one
    (tests swap in instrumented builders)."""
    global _REGISTRY
    _REGISTRY = [(a, p, b) for (a, p, b) in _REGISTRY if a != arch]
    _REGISTRY.append((arch, predicate, builder))


def resolve_backbone(model) -> BackboneSpec:
    """Resolve ``model`` to its BackboneSpec, or raise ONE clear error
    naming the supported families."""
    for arch, pred, build in _REGISTRY:
        try:
            matched = bool(pred(model))
        except Exception:
            matched = False
        if matched:
            return build(model)
    supported = ", ".join(a for a, _, _ in _REGISTRY)
    raise ValueError(
        f"LLMEngine cannot serve {type(model).__name__}: no registered "
        f"backbone matches it (supported: {supported}).  A servable "
        f"model exposes either a ``.llama`` submodule (Llama family) "
        f"or top-level ``layers``/``norm``/``embed_tokens``/``rope_*`` "
        f"with a shared-expert MoE ``mlp`` (Qwen2-MoE/DeepSeekMoE "
        f"family); register new families with "
        f"inference.backbone.register_backbone().")


# -- llama ------------------------------------------------------------------

def _is_llama(model) -> bool:
    return hasattr(model, "llama") and hasattr(model.llama, "layers")


def _build_llama(model) -> BackboneSpec:
    lm = model.llama
    layers = list(lm.layers)
    enforce(layers, "model.llama.layers is empty")
    # the dense serving programs carry no qkv bias arrays; a biased
    # Llama checkpoint would silently drop its biases (wrong tokens),
    # so refuse it loudly — the Qwen2-MoE path is the biased one
    enforce(layers[0].self_attn.q_proj.bias is None,
            "Llama backbone with attention biases is not servable by "
            "the dense engine path (the stacked programs carry no "
            "bias arrays); biased attention serves via the MoE "
            "backbone family")
    return BackboneSpec(
        arch="llama", config=model.config, layers=layers,
        norm=lm.norm, embed_tokens=lm.embed_tokens,
        lm_head=model.lm_head, rope_cos=lm.rope_cos,
        rope_sin=lm.rope_sin, attn_bias=False, moe=None)


# -- qwen2-moe / deepseek-moe ----------------------------------------------

def _is_qwen2_moe(model) -> bool:
    if hasattr(model, "llama") or not hasattr(model, "layers"):
        return False
    layers = list(model.layers)
    if not layers:
        return False
    mlp = getattr(layers[0], "mlp", None)
    gate = getattr(mlp, "gate", None)
    return (hasattr(model, "norm") and hasattr(model, "embed_tokens")
            and hasattr(model, "rope_cos")
            and hasattr(mlp, "experts")
            and hasattr(gate, "num_experts") and hasattr(gate, "k"))


def _build_qwen2_moe(model) -> BackboneSpec:
    layers = list(model.layers)
    g0, m0 = layers[0].mlp.gate, layers[0].mlp
    for l in layers[1:]:
        g, m = l.mlp.gate, l.mlp
        enforce(g.num_experts == g0.num_experts and g.k == g0.k
                and g.norm_topk_prob == g0.norm_topk_prob
                and (m.shared_gate is None) == (m0.shared_gate is None)
                and (m.shared_expert_gate is None)
                == (m0.shared_expert_gate is None),
                "MoE serving needs one router/shared-expert geometry "
                "across all decoder layers (the dispatch arch is one "
                "static jit argument)")
    attn_bias = layers[0].self_attn.q_proj.bias is not None
    return BackboneSpec(
        arch="qwen2_moe", config=model.config, layers=layers,
        norm=model.norm, embed_tokens=model.embed_tokens,
        lm_head=model.lm_head, rope_cos=model.rope_cos,
        rope_sin=model.rope_sin, attn_bias=attn_bias,
        moe={"num_experts": int(g0.num_experts), "top_k": int(g0.k),
             "norm_topk": bool(g0.norm_topk_prob),
             "capacity_factor": float(g0.capacity_factor),
             "shared": m0.shared_gate is not None,
             "shared_gate": m0.shared_expert_gate is not None})


register_backbone("llama", _is_llama, _build_llama)
register_backbone("qwen2_moe", _is_qwen2_moe, _build_qwen2_moe)
