"""LLMEngine — continuous-batching serving loop over the paged KV cache.

Reference parity: the reference's serving story (AnalysisPredictor +
PaddleNLP's llm serving loops); kernel blueprint per PAPERS.md ragged
paged attention.  TPU-native design: requests of ragged lengths share
one physical page pool; each engine step decodes ONE token for every
active request as a single jitted program — a lax.scan over the stacked
decoder layers whose attention is the Pallas ragged-paged kernel and
whose K/V append is a vectorized page scatter.  Host-side work per step
is only page-table bookkeeping (allocate/extend/release).  Admission
(add_request) prefills through the model's standard cache path and
bulk-writes the prompt K/V into the request's pages.

The dense jitted ``generate()`` remains the single-tenant fast path;
this engine is the multi-tenant path where requests join and leave
between steps (continuous batching).

Serving-shape discipline: admission runs prompts through page-size
**chunks** of ONE compiled prefill program (each chunk fills exactly
one KV page in-graph, and its queries attend over the sequence's
pages so far under a position mask), so a mixed-length request stream
costs a single prefill compile total — no length buckets at all.
``prefill_compiles()`` / ``decode_compiles()`` expose the jit cache
sizes so ops can assert the no-recompile property.

Quantized serving (the quantization subsystem's engine knobs):
``kv_dtype="int8"`` stores the paged KV pools int8 with per-token
scales — the Pallas decode kernel streams int8 pages and dequantizes
in VMEM, roughly halving decode HBM traffic and doubling page capacity
per chip vs fp16.  ``weight_dtype="int8"`` runs the decoder matmuls
against int8 weights (per-output-channel absmax scales folded into the
matmul outputs); models already converted by
``paddle_tpu.quantization.quantize_model`` are picked up as-is.  Both
knobs keep the no-recompile property: the quantized programs' shapes
are still fixed by the engine geometry alone.

Preemption (``suspend``/``resume``): a request can be evicted from the
decode batch mid-generation — its KV pages swap into the paged cache's
bounded host pool (``swap_pool_pages=``) and its slot frees — and
later re-admitted.  Resume restores the pages host-side (swap-in) or,
when the pool could not hold them or the entry was dropped, REPLAYS
the prompt through the same chunked-prefill program and the
already-generated tokens through the same compiled decode program —
either way the request continues with bit-identical tokens to an
unpreempted run (greedy decoding; the sampling strategy's key stream
is global per step, so preemption reshuffles it by construction) and
no new prefill compilations.

Ragged unified step (``unified_step=True``, default): ``step()``
dispatches ONE compiled mixed-batch program (``_paged_mixed_step``)
that packs every active decode slot (compacted host-side — retired
slots cost nothing) plus up to ``prefill_token_budget`` tokens of
pending ``begin_request`` prefill chunks.  Descriptors are traced
scalars, so ``mixed_compiles() == 1`` across arbitrary batch mixes,
and a long prompt no longer stalls in-flight decodes (ROADMAP open
item 2).  ``add_request`` remains the synchronous admission path;
tokens are bit-identical between the unified and split programs
(greedy decoding).

On-device decode windows (``scan_decode=True``, default): a
``steps_per_sync > 1`` pure-decode window runs as ONE compiled
``lax.while_loop`` program — attend (ragged Pallas kernel, pools
aliased in place), sample, KV-append, token feed-back chained
in-graph — syncing the host only at the window boundary, with early
exit once every row has hit EOS or its budget (per-row emitted counts
come back so the host merge stays exact).  Window lengths bucket to
powers of two (one compile per bucket, declared to the CompileWatch
at construction); the per-step body IS the single-step program's
body and the key sequence is the same ``inference.sampling``
``split_step`` chain, so tokens are bit-identical to host-chained
dispatch on every path — plain, int8 KV, prefix hits,
preempt→resume, migration.

Automatic prefix caching (``enable_prefix_caching=``, default on):
admission looks up the longest cached page-aligned prefix of the
prompt in the paged cache's chain-hash index, maps those pages into
the new slot's table (host-side only), and runs the chunked prefill
over the uncached tail — shared system prompts / few-shot templates
prefill ONCE and cost one set of pages however many requests carry
them.  Sharing is page-table indirection only: the prefill/decode
programs are unchanged, so ``prefill_compiles() == 1`` still holds.

MoE serving (Qwen2-MoE/DeepSeekMoE backbones): the model resolves
through the backbone seam (inference/backbone.py) instead of the old
hardwired ``model.llama.*`` reads, and every serving program gains a
static ``arch`` argument — ``None`` keeps the Llama trace byte
identical; an :class:`~.moe_dispatch.MoEArch` switches the decoder
FFN to the top-k routed + shared-expert path (inference/
moe_dispatch.py): ONE grouped matmul dispatch per projection per
layer over the sorted dropless layout, or the dense per-row
reference (``moe_dispatch="dense"``), bit-identical on CPU.  Routing
descriptors are traced data, so every one-compile invariant above
survives; the programs additionally return per-layer-per-expert
routed-token counts feeding the ``llm_engine_expert_tokens_total``
observability plane.  Capacity-factor dispatch (``moe_dropless=
False``) drops per page-group deterministically across the
split/unified/scanned paths (the unified planner packs whole page
chunks in that mode); decode rows are singleton groups and never
drop.
"""
from __future__ import annotations

import functools
import itertools
import time
from typing import Dict, List, Optional

import numpy as np

from ..common.errors import enforce
from ..observability import get_registry
from ..observability import capsule as _capsule
from ..observability import health as _health
from ..observability import introspection as _insp
from ..observability import tracing as _tracing
from ..profiler import RecordEvent
from . import sampling as _sampling
from .paged_cache import PagedKVCache

__all__ = ["LLMEngine", "GenRequest"]

_ENGINE_IDS = itertools.count()

# serving-latency bucket ladders (seconds): TTFT spans prefill compiles
# and multi-chunk prompts; TPOT is per decoded token
_TTFT_BUCKETS = (.01, .025, .05, .1, .25, .5, 1.0, 2.5, 5.0, 10.0,
                 30.0, 60.0)
_TPOT_BUCKETS = (.0005, .001, .0025, .005, .01, .025, .05, .1, .25,
                 .5, 1.0)
# accepted-draft-length ladder (speculative windows): covers k up to
# 32; fixed so engines with different spec_k share the family
_SPEC_LEN_BUCKETS = (0., 1., 2., 3., 4., 5., 6., 7., 8., 12., 16.,
                     24., 32.)


class GenRequest:
    def __init__(self, rid, prompt_ids, max_new_tokens, eos_token_id):
        self.rid = rid
        self.prompt = list(prompt_ids)
        self.max_new = max_new_tokens
        self.eos = eos_token_id
        self.out: List[int] = []
        self.slot: Optional[int] = None
        self.done = False
        self.cancelled = False
        # preemption: suspended requests hold no slot or device pages,
        # only (maybe) a host swap-pool entry
        self.suspended = False
        self.swap_handle: Optional[int] = None
        # unified-step chunked admission (begin_request): next prompt
        # position to prefill, and the submit time TTFT measures from
        self.pf_pos = 0
        self.t_submit: Optional[float] = None
        # speculative decoding: the request's DRAFT KV slot in the
        # engine's second paged cache — attached lazily at its first
        # speculative window, released on retire/suspend/abort
        self.draft_slot: Optional[int] = None


def _wout(w) -> int:
    """Output width of a stacked weight — fp array [.., in, out] or
    weight-only-int8 (values, scale) pair."""
    return w[0].shape[-1] if isinstance(w, tuple) else w.shape[-1]


def _mm(x, w):
    """x @ w for fp or weight-only-int8 stacked weights.  The int8
    scale is per-OUTPUT-channel, so it folds into the matmul result —
    the MXU pass consumes the int8 weight upcast in registers, never a
    materialized fp copy."""
    import jax.numpy as jnp
    if isinstance(w, tuple):
        qw, sc = w
        return jnp.matmul(x, qw.astype(x.dtype)) * sc.astype(x.dtype)
    return jnp.matmul(x, w)


def _tpc(x, shardings, dim=None):
    """Tensor-parallel sharding constraint: shard ``dim`` over the tp
    axis (``None`` = fully replicated) when the engine carries a mesh,
    identity otherwise — so the no-mesh trace is byte-identical to the
    pre-sharding programs.

    The placement discipline that keeps tp=N BIT-IDENTICAL to tp=1 on
    greedy: only OUTPUT axes are ever sharded (head axes, MLP hidden,
    the o/down projections' H outputs), and every contraction input is
    constrained REPLICATED first.  A contraction over a sharded axis
    would lower to partial-sum + psum — a cross-device float reduction
    whose order differs from the single-device dot — while gathering
    the inputs (all-gather moves bits, never adds floats) keeps every
    matmul's reduction on one device in one order."""
    if shardings is None:
        return x
    return shardings.constrain(x, dim)


@functools.partial(
    __import__("jax").jit,
    static_argnames=("eps", "kvh", "head_dim", "transpose_head",
                     "shardings", "arch"),
    donate_argnames=("k_pages", "v_pages", "k_scales", "v_scales"))
def _paged_prefill_chunk(stack, norm_w, head_w, embed_w, rope,
                         k_pages, v_pages, k_scales, v_scales,
                         ids, table, prev_len,
                         page_slot, last_in_chunk, *, eps: float,
                         kvh: int, head_dim: int,
                         transpose_head: bool = False,
                         shardings=None, arch=None):
    """CHUNKED ragged prefill (round 5): process ``ids`` [C] — one
    page-sized chunk of ONE prompt — against the paged cache.  Each
    chunk's K/V fill exactly one page (C == page_size), written with a
    whole-page dynamic_update_slice (the efficient TPU case — no
    per-row scatter), and the chunk's queries attend over ALL of the
    sequence's pages so far via an additive position mask.

    ONE XLA program serves every prompt length and every chunk index
    (prev_len/page_slot/last_in_chunk are traced scalars; the page
    gather spans the static per-sequence page budget), so admission
    stops compiling per length bucket entirely — `prefill_compiles()`
    is 1 for any request mix (VERDICT r4 Missing #5: the
    bucketed-dense prefill's power-of-two compiles).  The attention
    cost per chunk is C × max_len instead of C × len; prefill is
    matmul-dominated so the overhead is the (cheap) attention term
    only.  (The ``table`` must keep its static per-engine width —
    trimming it per prompt would re-introduce per-shape compiles.)

    ``k_scales``/``v_scales`` ([L, KVH, n_pages, P] f32, or None for
    fp pools) switch the cache write to int8: the chunk's K/V rows
    quantize per token before the page dus, and the page gather
    dequantizes for the chunk's (matmul-dominated) attention.

    ids [C] int32 (end-padded on the final chunk); table [maxp] this
    sequence's page table; prev_len tokens already prefilled;
    page_slot the pool index this chunk writes; last_in_chunk =
    clamp(plen-1 - chunk_base, 0, C-1) (the row whose logits matter
    on the final chunk).  Returns (logits [V], k_pages', v_pages',
    k_scales', v_scales') — plus per-layer expert counts [L, E] when
    ``arch`` is an MoE dispatch config (static; None = dense Llama
    FFN, byte-identical to the pre-MoE trace).  MoE routing masks the
    end-padding rows (``> last_in_chunk``) out of the dispatch and
    counts; the chunk is one capacity page-group.
    """
    import jax
    import jax.numpy as jnp

    from ..ops import _nn
    from ..quantization.ops import quantize_rows_raw
    from ..runtime.device import is_compiled_with_tpu

    cos_t, sin_t = rope
    c = ids.shape[0]
    maxp = table.shape[0]
    page = c                                  # C == page_size
    s_kv = maxp * page
    x = jnp.take(embed_w, ids, axis=0)        # [C, H]
    cos = jax.lax.dynamic_slice(cos_t, (prev_len, 0),
                                (c, cos_t.shape[1]))[None, :, None, :]
    sin = jax.lax.dynamic_slice(sin_t, (prev_len, 0),
                                (c, sin_t.shape[1]))[None, :, None, :]

    from ..models.llama import _rotate_half as rotate_half

    # additive visibility mask over the gathered pages: chunk row r
    # (global position prev_len + r) sees kv positions <= prev_len + r
    kvpos = jnp.arange(s_kv)
    allow = kvpos[None, :] <= prev_len + jnp.arange(c)[:, None]
    amask = jnp.where(allow, 0.0, -1e30).astype(jnp.float32)

    def attend(q, k_full, v_full):
        # q [C, NH, D], k/v_full [S_kv, KVH, D]
        if is_compiled_with_tpu():
            from ..ops.pallas.flash_attention import flash_attention_raw
            try:
                return flash_attention_raw(
                    q[None], k_full[None], v_full[None], causal=False,
                    mask=amask[None, None])[0]
            except NotImplementedError:
                pass
        g = q.shape[1] // kvh
        qg = q.reshape(c, kvh, g, head_dim)
        sc = jnp.einsum("qhgd,khd->hgqk", qg.astype(jnp.float32),
                        k_full.astype(jnp.float32))
        sc = sc / jnp.sqrt(jnp.float32(head_dim)) + amask[None, None]
        p = jax.nn.softmax(sc, axis=-1)
        o = jnp.einsum("hgqk,khd->qhgd", p,
                       v_full.astype(jnp.float32))
        return o.reshape(c, q.shape[1], head_dim).astype(q.dtype)

    if arch is not None:
        from .moe_dispatch import moe_ffn
        # MoE routing sees only the chunk's REAL rows; the chunk is
        # one capacity page-group starting at row 0
        moe_live = jnp.arange(c) <= last_in_chunk
        moe_group = jnp.zeros(c, jnp.int32)

    def layer(carry, xs):
        hcur = carry
        lp, kp, vp, ksp, vsp = xs             # params + per-layer pools
        if arch is None:
            iln, qw, kw, vw, ow, pln, gw, uw, dw = lp
            qb = kb = vb = None
        else:
            (iln, qw, qb, kw, kb, vw, vb, ow, pln, rw, egw, euw, edw,
             sgw, suw, sdw, seg) = lp
        hn = _nn.rms_norm(hcur, iln, epsilon=eps)
        nh = _wout(qw) // head_dim
        qx, kx, vx = _mm(hn, qw), _mm(hn, kw), _mm(hn, vw)
        if arch is not None and arch.attn_bias:
            qx, kx, vx = qx + qb, kx + kb, vx + vb
        q = _tpc(qx.reshape(c, nh, head_dim), shardings, 1)
        k = _tpc(kx.reshape(c, kvh, head_dim), shardings, 1)
        v = _tpc(vx.reshape(c, kvh, head_dim), shardings, 1)
        qf, kf = q.astype(jnp.float32)[None], k.astype(jnp.float32)[None]
        q = (qf * cos + rotate_half(qf) * sin)[0].astype(q.dtype)
        k = (kf * cos + rotate_half(kf) * sin)[0].astype(k.dtype)
        if ksp is None:
            # whole-page write: [C, KVH, D] -> [KVH, 1, C(=P), D] block
            kblk = jnp.swapaxes(k, 0, 1)[:, None].astype(kp.dtype)
            vblk = jnp.swapaxes(v, 0, 1)[:, None].astype(vp.dtype)
            kp = jax.lax.dynamic_update_slice(kp, kblk,
                                              (0, page_slot, 0, 0))
            vp = jax.lax.dynamic_update_slice(vp, vblk,
                                              (0, page_slot, 0, 0))
            # gather this sequence's pages (chunk included — written)
            k_full = kp[:, table].reshape(kvh, s_kv, head_dim)
            v_full = vp[:, table].reshape(kvh, s_kv, head_dim)
        else:
            # int8 pools: quantize the chunk's rows (per-token absmax)
            # before the page write; the gather dequantizes
            kq8, ksc = quantize_rows_raw(k)   # [C, KVH, D], [C, KVH]
            vq8, vsc = quantize_rows_raw(v)
            kp = jax.lax.dynamic_update_slice(
                kp, jnp.swapaxes(kq8, 0, 1)[:, None],
                (0, page_slot, 0, 0))
            vp = jax.lax.dynamic_update_slice(
                vp, jnp.swapaxes(vq8, 0, 1)[:, None],
                (0, page_slot, 0, 0))
            ksp = jax.lax.dynamic_update_slice(
                ksp, jnp.swapaxes(ksc, 0, 1)[:, None].astype(ksp.dtype),
                (0, page_slot, 0))
            vsp = jax.lax.dynamic_update_slice(
                vsp, jnp.swapaxes(vsc, 0, 1)[:, None].astype(vsp.dtype),
                (0, page_slot, 0))
            k_full = (kp[:, table].astype(jnp.float32)
                      * ksp[:, table][..., None]).reshape(kvh, s_kv,
                                                          head_dim)
            v_full = (vp[:, table].astype(jnp.float32)
                      * vsp[:, table][..., None]).reshape(kvh, s_kv,
                                                          head_dim)
        attn = _tpc(attend(q, jnp.swapaxes(k_full, 0, 1),
                           jnp.swapaxes(v_full, 0, 1)), shardings, 1)
        # gather the head-sharded attention rows BEFORE the o_proj
        # contraction, and the hidden-sharded ff before down_proj —
        # the bit-exactness discipline (see _tpc)
        hcur = _tpc(hcur + _mm(_tpc(attn.reshape(c, nh * head_dim),
                                    shardings), ow), shardings)
        hn = _nn.rms_norm(hcur, pln, epsilon=eps)
        if arch is None:
            ff = _tpc(_nn.silu(_mm(hn, gw)) * _mm(hn, uw), shardings, 1)
            return (_tpc(hcur + _mm(_tpc(ff, shardings), dw),
                         shardings), (kp, vp, ksp, vsp))
        ff, cnt = moe_ffn(hn, (rw, egw, euw, edw, sgw, suw, sdw, seg),
                          arch, moe_live, moe_group)
        return (_tpc(hcur + ff, shardings), (kp, vp, ksp, vsp, cnt))

    if arch is None:
        x, (k_pages, v_pages, k_scales, v_scales) = jax.lax.scan(
            layer, x,
            (tuple(stack), k_pages, v_pages, k_scales, v_scales))
    else:
        x, (k_pages, v_pages, k_scales, v_scales, counts) = \
            jax.lax.scan(
                layer, x,
                (tuple(stack), k_pages, v_pages, k_scales, v_scales))
    x = _nn.rms_norm(x, norm_w, epsilon=eps)
    xl = jnp.take(x, last_in_chunk, axis=0)   # [H]
    logits = _tpc(jnp.matmul(xl, head_w.T) if transpose_head
                  else _mm(xl, head_w), shardings)
    if arch is None:
        return logits, k_pages, v_pages, k_scales, v_scales
    return logits, k_pages, v_pages, k_scales, v_scales, counts


def _decode_one_token_fn(stack, norm_w, head_w, embed_w, rope, tables,
                         *, eps, kvh, head_dim, transpose_head,
                         strategy, top_k, top_p, temperature,
                         draw_base=None, shardings=None, arch=None,
                         live=None, collect_probs=False):
    """Build the one-token decode body shared by ``_paged_decode_step``
    (fixed-length window) and ``_paged_decode_window`` (the early-exit
    scanned window).  ONE definition of the per-step math — embed,
    rope, fused append+attend, sample, ``split_step`` key chain — is
    what makes the two programs bit-identical step for step.

    ``draw_base`` (traced int32 scalar) offsets the per-row sampling
    fold: row i draws with ``fold_row(sub, draw_base + i)`` — the live
    engine always passes 0 (row i folds i), capsule replay passes the
    CAPTURED row so a request replayed in row 0 re-draws its original
    stream (see inference/sampling.py).  Unused by greedy.
    ``shardings`` threads the tensor-parallel constraints (see _tpc).
    ``collect_probs`` (static) makes the body return ``(carry,
    probs [B, V])`` — the post-filter sampling distribution of this
    step (``filtered_probs``), the draft-side q surface speculative
    decoding's rejection acceptance consumes.

    carry: (tokens [B], positions [B], lens [B], k_pages, v_pages,
    k_scales, v_scales, key) → the same tuple one step later, with the
    sampled token in slot 0.  With an MoE ``arch`` the carry gains a
    trailing ``counts_acc`` [L, E] int32 accumulator and ``live`` [B]
    (from the WINDOW-START lens — pad rows stay masked for the whole
    window) gates which rows route; decode rows are singleton capacity
    groups, so ``group_start=None`` (never drop — top-k experts are
    distinct).
    """
    import jax
    import jax.numpy as jnp

    from ..ops import _nn
    from ..ops.pallas.paged_attention import (
        paged_decode_append_attend_raw,
        paged_decode_append_attend_reference)
    from ..runtime.device import is_compiled_with_tpu
    from ..models.llama import _rotate_half as rotate_half
    from .sampling import sample_logits, split_step

    cos_t, sin_t = rope                       # [maxpos, D]

    # ONE fused kernel appends this step's K/V and attends over them —
    # the separate XLA paged_write rewrote the whole pool per step on
    # TPU (round-3 serving bottleneck; see paged_attention.py).  The
    # _raw form: this body is traced INSIDE an already-jitted program,
    # often inside its scan/while loop.
    append_attend = paged_decode_append_attend_raw \
        if is_compiled_with_tpu() else paged_decode_append_attend_reference

    if arch is not None:
        from .moe_dispatch import moe_ffn

    def one_token(carry):
        if arch is None:
            (tokens, positions, lens, k_pages, v_pages, k_scales,
             v_scales, key) = carry
        else:
            (tokens, positions, lens, k_pages, v_pages, k_scales,
             v_scales, key, counts_acc) = carry
        b = tokens.shape[0]
        x = jnp.take(embed_w, tokens, axis=0)  # [B, H]
        cos = jnp.take(cos_t, positions, axis=0)[:, None, :]  # [B,1,D]
        sin = jnp.take(sin_t, positions, axis=0)[:, None, :]

        def layer(carry, xs):
            hcur = carry
            lp, kp, vp, ksp, vsp = xs          # per-layer params + pools
            if arch is None:
                iln, qw, kw, vw, ow, pln, gw, uw, dw = lp
                qb = kb = vb = None
            else:
                (iln, qw, qb, kw, kb, vw, vb, ow, pln, rw, egw, euw,
                 edw, sgw, suw, sdw, seg) = lp
            hn = _nn.rms_norm(hcur, iln, epsilon=eps)
            nh = _wout(qw) // head_dim
            qx, kx, vx = _mm(hn, qw), _mm(hn, kw), _mm(hn, vw)
            if arch is not None and arch.attn_bias:
                qx, kx, vx = qx + qb, kx + kb, vx + vb
            q = _tpc(qx.reshape(b, nh, head_dim), shardings, 1)
            k = _tpc(kx.reshape(b, kvh, head_dim), shardings, 1)
            v = _tpc(vx.reshape(b, kvh, head_dim), shardings, 1)
            qf = q.astype(jnp.float32)
            kf = k.astype(jnp.float32)
            q = (qf * cos + rotate_half(qf) * sin).astype(q.dtype)
            k = (kf * cos + rotate_half(kf) * sin).astype(k.dtype)
            if ksp is None:
                attn, kp, vp = append_attend(q, kp, vp, k, v, tables,
                                             lens)
            else:
                # int8 pools ride the same fused kernel with their
                # per-token scale rows ([KVH, n_pages, 1, P] views)
                attn, kp, vp, ks4, vs4 = append_attend(
                    q, kp, vp, k, v, tables, lens,
                    ksp[:, :, None, :], vsp[:, :, None, :])
                ksp = ks4.reshape(ksp.shape)
                vsp = vs4.reshape(vsp.shape)
            attn = _tpc(attn, shardings, 1)
            hcur = _tpc(hcur + _mm(
                _tpc(attn.reshape(b, nh * head_dim), shardings), ow),
                shardings)
            hn = _nn.rms_norm(hcur, pln, epsilon=eps)
            if arch is None:
                ff = _tpc(_nn.silu(_mm(hn, gw)) * _mm(hn, uw),
                          shardings, 1)
                return (_tpc(hcur + _mm(_tpc(ff, shardings), dw),
                             shardings), (kp, vp, ksp, vsp))
            ff, cnt = moe_ffn(hn, (rw, egw, euw, edw, sgw, suw, sdw,
                                   seg), arch, live)
            return (_tpc(hcur + ff, shardings),
                    (kp, vp, ksp, vsp, cnt))

        if arch is None:
            x, (k_pages, v_pages, k_scales, v_scales) = jax.lax.scan(
                layer, x, (tuple(stack), k_pages, v_pages, k_scales,
                           v_scales))
        else:
            x, (k_pages, v_pages, k_scales, v_scales, cnts) = \
                jax.lax.scan(
                    layer, x, (tuple(stack), k_pages, v_pages,
                               k_scales, v_scales))
        x = _nn.rms_norm(x, norm_w, epsilon=eps)
        logits = _tpc(jnp.matmul(x, head_w.T) if transpose_head
                      else _mm(x, head_w), shardings)
        key, sub = split_step(key)
        row_ids = None if strategy == "greedy_search" else \
            draw_base + jnp.arange(b, dtype=jnp.int32)
        nxt, _ = sample_logits(logits, sub, strategy=strategy,
                               top_k=top_k, top_p=top_p,
                               temperature=temperature,
                               row_ids=row_ids)
        if arch is None:
            out = (nxt, positions + 1, lens + 1, k_pages, v_pages,
                   k_scales, v_scales, key)
        else:
            out = (nxt, positions + 1, lens + 1, k_pages, v_pages,
                   k_scales, v_scales, key, counts_acc + cnts)
        if collect_probs:
            from ..nn.generation import filtered_probs
            return out, filtered_probs(
                logits, strategy=strategy, top_k=top_k, top_p=top_p,
                temperature=temperature)
        return out

    return one_token


@functools.partial(
    __import__("jax").jit,
    static_argnames=("eps", "kvh", "head_dim", "transpose_head",
                     "strategy", "top_k", "top_p", "temperature",
                     "n_steps", "shardings", "arch"),
    donate_argnames=("k_pages", "v_pages", "k_scales", "v_scales"))
def _paged_decode_step(stack, norm_w, head_w, embed_w, rope,
                       k_pages, v_pages, k_scales, v_scales,
                       tokens, positions, tables, lens,
                       key, draw_base=0, *, eps: float, kvh: int,
                       head_dim: int,
                       transpose_head: bool = False,
                       strategy: str = "greedy_search", top_k: int = 0,
                       top_p: float = 1.0, temperature: float = 1.0,
                       n_steps: int = 1, shardings=None, arch=None):
    """``n_steps`` decode tokens for every active sequence as ONE XLA
    program (multi-step scheduling: the host syncs — EOS checks,
    admission — every n_steps tokens, so dispatch latency amortizes
    over n_steps; page capacity for all n_steps is pre-allocated by the
    caller).

    stack: 9 arrays [L, ...] (decoder weights, _decoder_layer_raw
    order; weight-only-int8 entries are (values, scale) pairs) — or 17
    with an MoE ``arch`` (see LLMEngine.__init__); k/v_pages
    [L, KVH, n_pages, P, D]; k/v_scales [L, KVH, n_pages, P] f32
    per-token dequant scales for int8 pools (None for fp); tokens [B]
    int32; positions [B] (= current lengths); tables [B, maxp]; lens
    [B].  Returns (tokens [n_steps, B], k_pages', v_pages', k_scales',
    v_scales') — plus a trailing routed-token counts [L, E] int32 when
    ``arch`` is an MoE (pad rows, lens == 0, route nowhere).
    """
    import jax
    import jax.numpy as jnp

    live = None if arch is None else lens > 0
    one_token = _decode_one_token_fn(
        stack, norm_w, head_w, embed_w, rope, tables,
        eps=eps, kvh=kvh, head_dim=head_dim,
        transpose_head=transpose_head, strategy=strategy, top_k=top_k,
        top_p=top_p, temperature=temperature, draw_base=draw_base,
        shardings=shardings, arch=arch, live=live)

    carry0 = (tokens, positions, lens, k_pages, v_pages, k_scales,
              v_scales, key)
    if arch is not None:
        carry0 = carry0 + (jnp.zeros(
            (stack[0].shape[0], arch.num_experts), jnp.int32),)

    if n_steps == 1:
        out = one_token(carry0)
        (nxt, _, _, k_pages, v_pages, k_scales, v_scales, _) = out[:8]
        if arch is None:
            return nxt[None], k_pages, v_pages, k_scales, v_scales
        return (nxt[None], k_pages, v_pages, k_scales, v_scales,
                out[8])

    def body(carry, _):
        carry = one_token(carry)
        return carry, carry[0]

    (final, toks) = jax.lax.scan(body, carry0, None, length=n_steps)
    (_, _, _, k_pages, v_pages, k_scales, v_scales, _) = final[:8]
    if arch is None:
        return toks, k_pages, v_pages, k_scales, v_scales
    return toks, k_pages, v_pages, k_scales, v_scales, final[8]


@functools.partial(
    __import__("jax").jit,
    static_argnames=("eps", "kvh", "head_dim", "transpose_head",
                     "strategy", "top_k", "top_p", "temperature",
                     "n_steps", "shardings", "arch"),
    donate_argnames=("k_pages", "v_pages", "k_scales", "v_scales"))
def _paged_decode_window(stack, norm_w, head_w, embed_w, rope,
                         k_pages, v_pages, k_scales, v_scales,
                         tokens, positions, tables, lens, key,
                         draw_base, eos_ids, budgets, n_live, *,
                         eps: float, kvh: int, head_dim: int,
                         transpose_head: bool = False,
                         strategy: str = "greedy_search", top_k: int = 0,
                         top_p: float = 1.0, temperature: float = 1.0,
                         n_steps: int = 2, shardings=None, arch=None):
    """The split path's ON-DEVICE decode window with EARLY EXIT: up to
    ``n_steps`` tokens per dispatch (same per-step body as
    ``_paged_decode_step`` — ``_decode_one_token_fn`` — so the token
    stream is bit-identical), but a ``lax.while_loop`` stops as soon as
    every live row has hit its EOS (``eos_ids``, −1 = none) or emitted
    its remaining budget (``budgets`` = max_new − len(out) at window
    start).  The host merge loop discards a finished row's surplus
    tokens either way, so exiting early changes NOTHING observable —
    it just stops paying for steps no row needs.  Like the host path,
    rows keep computing (and appending into soon-released pages) while
    ANY row still runs: per-row masking would change nothing and cost
    a select on every tensor.

    eos_ids/budgets [B] int32 (pad rows: −1 / 1); ``n_live`` the count
    of real rows (traced — the compiled shape stays one per n_steps
    bucket).  Returns (tokens [n_steps, B] — rows ≥ steps_done are
    zero-filled, the host must slice with steps_done —, emitted [B]
    int32 per-row delivered-token counts, steps_done, k_pages',
    v_pages', k_scales', v_scales') — plus a trailing routed-token
    counts [L, E] int32 with an MoE ``arch`` (rows with window-start
    ``lens == 0`` route nowhere for the whole window).
    """
    import jax
    import jax.numpy as jnp

    moe_live = None if arch is None else lens > 0
    one_token = _decode_one_token_fn(
        stack, norm_w, head_w, embed_w, rope, tables,
        eps=eps, kvh=kvh, head_dim=head_dim,
        transpose_head=transpose_head, strategy=strategy, top_k=top_k,
        top_p=top_p, temperature=temperature, draw_base=draw_base,
        shardings=shardings, arch=arch, live=moe_live)

    b = tokens.shape[0]
    live = jnp.arange(b) < n_live
    state0 = (tokens, positions, lens, k_pages, v_pages, k_scales,
              v_scales, key)
    if arch is not None:
        state0 = state0 + (jnp.zeros(
            (stack[0].shape[0], arch.num_experts), jnp.int32),)
    toks0 = jnp.zeros((n_steps, b), jnp.int32)
    carry0 = (jnp.zeros((), jnp.int32), state0, toks0,
              jnp.logical_not(live), jnp.zeros(b, jnp.int32))

    def cond(carry):
        si, _, _, done, _ = carry
        return jnp.logical_and(si < n_steps,
                               jnp.logical_not(jnp.all(done)))

    def body(carry):
        si, state, toks, done, emitted = carry
        state = one_token(state)
        nxt = state[0].astype(jnp.int32)
        toks = jax.lax.dynamic_update_slice(toks, nxt[None], (si, 0))
        # mirror the host merge EXACTLY: a row emits while not done;
        # it retires on EOS or on filling its budget (the window never
        # exceeds the smallest budget, so budget exhaustion can only
        # land on the window's last step — but the same test keeps the
        # invariant local instead of trusting the caller)
        fresh = jnp.logical_not(done)
        emitted = emitted + fresh.astype(jnp.int32)
        hit_eos = jnp.logical_and(eos_ids >= 0, nxt == eos_ids)
        done = jnp.logical_or(
            done, jnp.logical_and(fresh, jnp.logical_or(
                hit_eos, emitted >= budgets)))
        return (si + 1, state, toks, done, emitted)

    si, state, toks, done, emitted = jax.lax.while_loop(
        cond, body, carry0)
    (_, _, _, k_pages, v_pages, k_scales, v_scales, _) = state[:8]
    if arch is None:
        return (toks, emitted, si, k_pages, v_pages, k_scales,
                v_scales)
    return (toks, emitted, si, k_pages, v_pages, k_scales, v_scales,
            state[8])


def _mixed_forward(stack, norm_w, head_w, embed_w, rope,
                   k_pages, v_pages, k_scales, v_scales,
                   ids, positions, row_tables,
                   q_start, q_len, kv_len, desc_tables,
                   desc_of_row, off_of_row, key, draw_base=0, *,
                   eps: float, kvh: int, head_dim: int,
                   transpose_head: bool = False,
                   strategy: str = "greedy_search", top_k: int = 0,
                   top_p: float = 1.0, temperature: float = 1.0,
                   shardings=None, arch=None, return_probs=False):
    """Un-jitted body of ``_paged_mixed_step`` — ALSO the per-step body
    of ``_paged_mixed_window``'s on-device loop, which is what makes
    the scanned window bit-identical to host-chained dispatch: the two
    paths trace the very same ops in the very same order (see
    ``_paged_mixed_step`` for the argument contract).  With an MoE
    ``arch`` the return gains a trailing routed-token counts [L, E]:
    rows past their descriptor's ``q_len`` (padding) route nowhere,
    and each descriptor is one capacity page-group (``group_start =
    q_start[desc_of_row]``) so split-path prefill chunks rank
    identically."""
    import jax
    import jax.numpy as jnp

    from ..ops import _nn
    from ..ops.pallas.paged_attention import (
        ragged_paged_append_attend_raw,
        ragged_paged_append_attend_reference)
    from ..runtime.device import is_compiled_with_tpu

    cos_t, sin_t = rope
    t = ids.shape[0]

    from ..models.llama import _rotate_half as rotate_half
    from .sampling import sample_logits, split_step

    x = jnp.take(embed_w, ids, axis=0)             # [T, H]
    cos = jnp.take(cos_t, positions, axis=0)[:, None, :]   # [T, 1, D]
    sin = jnp.take(sin_t, positions, axis=0)[:, None, :]
    on_tpu = is_compiled_with_tpu()
    if arch is not None:
        from .moe_dispatch import moe_ffn
        moe_live = off_of_row < jnp.take(q_len, desc_of_row)
        moe_group = jnp.take(q_start, desc_of_row)

    def layer(carry, xs):
        hcur = carry
        lp, kp, vp, ksp, vsp = xs              # per-layer params + pools
        if arch is None:
            iln, qw, kw, vw, ow, pln, gw, uw, dw = lp
            qb = kb = vb = None
        else:
            (iln, qw, qb, kw, kb, vw, vb, ow, pln, rw, egw, euw,
             edw, sgw, suw, sdw, seg) = lp
        hn = _nn.rms_norm(hcur, iln, epsilon=eps)
        nh = _wout(qw) // head_dim
        qx, kx, vx = _mm(hn, qw), _mm(hn, kw), _mm(hn, vw)
        if arch is not None and arch.attn_bias:
            qx, kx, vx = qx + qb, kx + kb, vx + vb
        q = _tpc(qx.reshape(t, nh, head_dim), shardings, 1)
        k = _tpc(kx.reshape(t, kvh, head_dim), shardings, 1)
        v = _tpc(vx.reshape(t, kvh, head_dim), shardings, 1)
        qf = q.astype(jnp.float32)
        kf = k.astype(jnp.float32)
        q = (qf * cos + rotate_half(qf) * sin).astype(q.dtype)
        k = (kf * cos + rotate_half(kf) * sin).astype(k.dtype)
        if on_tpu:
            # ragged kernel: per-descriptor [P, H, D] output blocks,
            # gathered back to the flat row order
            if ksp is None:
                blocks, kp, vp = ragged_paged_append_attend_raw(
                    q, kp, vp, k, v, q_start, q_len, kv_len,
                    desc_tables)
            else:
                blocks, kp, vp, ks4, vs4 = \
                    ragged_paged_append_attend_raw(
                        q, kp, vp, k, v, q_start, q_len, kv_len,
                        desc_tables, ksp[:, :, None, :],
                        vsp[:, :, None, :])
                ksp = ks4.reshape(ksp.shape)
                vsp = vs4.reshape(vsp.shape)
            attn = blocks[desc_of_row, off_of_row]          # [T, NH, D]
        elif ksp is None:
            attn, kp, vp = ragged_paged_append_attend_reference(
                q, kp, vp, k, v, positions, row_tables)
        else:
            attn, kp, vp, ks4, vs4 = \
                ragged_paged_append_attend_reference(
                    q, kp, vp, k, v, positions, row_tables,
                    ksp[:, :, None, :], vsp[:, :, None, :])
            ksp = ks4.reshape(ksp.shape)
            vsp = vs4.reshape(vsp.shape)
        attn = _tpc(attn, shardings, 1)
        hcur = _tpc(hcur + _mm(
            _tpc(attn.reshape(t, nh * head_dim), shardings), ow),
            shardings)
        hn = _nn.rms_norm(hcur, pln, epsilon=eps)
        if arch is None:
            ff = _tpc(_nn.silu(_mm(hn, gw)) * _mm(hn, uw),
                      shardings, 1)
            return (_tpc(hcur + _mm(_tpc(ff, shardings), dw),
                         shardings), (kp, vp, ksp, vsp))
        ff, cnt = moe_ffn(hn, (rw, egw, euw, edw, sgw, suw, sdw, seg),
                          arch, moe_live, moe_group)
        return (_tpc(hcur + ff, shardings), (kp, vp, ksp, vsp, cnt))

    if arch is None:
        x, (k_pages, v_pages, k_scales, v_scales) = jax.lax.scan(
            layer, x,
            (tuple(stack), k_pages, v_pages, k_scales, v_scales))
    else:
        x, (k_pages, v_pages, k_scales, v_scales, cnts) = jax.lax.scan(
            layer, x,
            (tuple(stack), k_pages, v_pages, k_scales, v_scales))
    x = _nn.rms_norm(x, norm_w, epsilon=eps)
    logits = _tpc(jnp.matmul(x, head_w.T) if transpose_head
                  else _mm(x, head_w), shardings)
    key, sub = split_step(key)
    row_ids = None if strategy == "greedy_search" else \
        draw_base + jnp.arange(t, dtype=jnp.int32)
    nxt, _ = sample_logits(logits, sub, strategy=strategy,
                           top_k=top_k, top_p=top_p,
                           temperature=temperature, row_ids=row_ids)
    if arch is None:
        out = (nxt, k_pages, v_pages, k_scales, v_scales, key)
    else:
        out = (nxt, k_pages, v_pages, k_scales, v_scales, key, cnts)
    if return_probs:
        # static flag (speculative verify, sampled mode): append the
        # per-row post-filter target distribution — the p surface the
        # rejection acceptance consumes — WITHOUT touching the default
        # trace (greedy speculative verify reuses the plain program)
        from ..nn.generation import filtered_probs
        return out + (filtered_probs(
            logits, strategy=strategy, top_k=top_k, top_p=top_p,
            temperature=temperature),)
    return out


@functools.partial(
    __import__("jax").jit,
    static_argnames=("eps", "kvh", "head_dim", "transpose_head",
                     "strategy", "top_k", "top_p", "temperature",
                     "shardings", "arch", "return_probs"),
    donate_argnames=("k_pages", "v_pages", "k_scales", "v_scales"))
def _paged_mixed_step(stack, norm_w, head_w, embed_w, rope,
                      k_pages, v_pages, k_scales, v_scales,
                      ids, positions, row_tables,
                      q_start, q_len, kv_len, desc_tables,
                      desc_of_row, off_of_row, key, draw_base=0, *,
                      eps: float, kvh: int, head_dim: int,
                      transpose_head: bool = False,
                      strategy: str = "greedy_search", top_k: int = 0,
                      top_p: float = 1.0, temperature: float = 1.0,
                      shardings=None, arch=None, return_probs=False):
    """ONE compiled program for the whole MIXED prefill+decode batch
    (the ragged unified step): a flat token batch of T rows — every
    active decode slot contributes 1 row, each pending prefill chunk
    up to page_size rows — runs the full decoder once, appending every
    row's K/V at its own position and attending each row over its own
    sequence's pages under the causal mask ``kv_pos <= position``.

    All batch-mix information is TRACED data (row ids/positions/tables
    and the per-descriptor (q_start, q_len, kv_len) scalars the TPU
    kernel prefetches), so one XLA program serves every interleaving —
    ``mixed_compiles() == 1`` however prefill chunks and decode slots
    mix.  On TPU the attention+append is the ragged Pallas kernel
    (descriptor outputs gathered back to flat rows via the host-built
    (desc_of_row, off_of_row) map); on CPU it is the per-row jnp
    mirror, bit-compatible with the split prefill/decode programs.

    ids/positions [T] int32 (position = the row's kv length before its
    own append); row_tables [T, maxp]; q_start/q_len/kv_len [S] with
    ``q_len == 0`` marking unused descriptors; desc_tables [S, maxp].
    Dead padding rows carry position 0 and the all-zero table — their
    writes land in the reserved pad page.  Returns (next_token [T],
    k_pages', v_pages', k_scales', v_scales', key') — the key chains
    across host-driven multi-token windows.  With an MoE ``arch`` the
    return gains a trailing routed-token counts [L, E]."""
    return _mixed_forward(
        stack, norm_w, head_w, embed_w, rope,
        k_pages, v_pages, k_scales, v_scales,
        ids, positions, row_tables, q_start, q_len, kv_len,
        desc_tables, desc_of_row, off_of_row, key, draw_base,
        eps=eps, kvh=kvh, head_dim=head_dim,
        transpose_head=transpose_head, strategy=strategy,
        top_k=top_k, top_p=top_p, temperature=temperature,
        shardings=shardings, arch=arch, return_probs=return_probs)


@functools.partial(
    __import__("jax").jit,
    static_argnames=("eps", "kvh", "head_dim", "transpose_head",
                     "strategy", "top_k", "top_p", "temperature",
                     "n_steps", "shardings", "arch"),
    donate_argnames=("k_pages", "v_pages", "k_scales", "v_scales"))
def _paged_mixed_window(stack, norm_w, head_w, embed_w, rope,
                        k_pages, v_pages, k_scales, v_scales,
                        ids, positions, row_tables,
                        q_start, q_len, kv_len, desc_tables,
                        desc_of_row, off_of_row, key, draw_base,
                        eos_ids, budgets, n_rows, *,
                        eps: float, kvh: int, head_dim: int,
                        transpose_head: bool = False,
                        strategy: str = "greedy_search", top_k: int = 0,
                        top_p: float = 1.0, temperature: float = 1.0,
                        n_steps: int = 2, shardings=None, arch=None):
    """The unified path's ON-DEVICE decode window: up to ``n_steps``
    pure-decode steps of ``_mixed_forward`` — attend+append (the
    ragged kernel, aliases intact), sample, feed-back — chained in a
    ``lax.while_loop`` so the whole window is ONE dispatch, with EARLY
    EXIT once every live row has retired (its EOS ``eos_ids[i]``, −1
    for none, or its remaining budget ``budgets[i]``).  The in-graph
    feedback is exactly the host chain: row < n_rows gets its sampled
    token as the next input with position/kv_len bumped — including
    already-retired rows, whose surplus tokens the host merge discards
    just as it does on the host-chained path (computing them keeps the
    two paths op-identical; their appends land in pages that release
    at retirement).  The key chains through ``split_step`` inside the
    graph — the same sequence the host-chained window derives.

    Only pure-decode windows dispatch here (the caller forces
    ``nsteps == 1`` whenever prefill chunks are packed), so q_len is
    constant 1 for live rows across the loop.  Returns
    (tokens [n_steps, T] — step rows ≥ steps_done zero-filled —,
    emitted [T] per-row delivered counts, steps_done, k_pages',
    v_pages', k_scales', v_scales', key') — plus a trailing
    routed-token counts [L, E] with an MoE ``arch`` (accumulated over
    the whole window, retired rows included, exactly like the
    host-chained path's per-step accumulation)."""
    import jax
    import jax.numpy as jnp

    t = ids.shape[0]
    live = jnp.arange(t) < n_rows
    toks0 = jnp.zeros((n_steps, t), jnp.int32)
    state0 = (ids, positions, kv_len, k_pages, v_pages, k_scales,
              v_scales, key)
    if arch is not None:
        state0 = state0 + (jnp.zeros(
            (stack[0].shape[0], arch.num_experts), jnp.int32),)
    carry0 = (jnp.zeros((), jnp.int32), state0, toks0,
              jnp.logical_not(live), jnp.zeros(t, jnp.int32))

    def cond(carry):
        si, _, _, done, _ = carry
        return jnp.logical_and(si < n_steps,
                               jnp.logical_not(jnp.all(done)))

    def body(carry):
        si, state, toks, done, emitted = carry
        (ids, positions, kv_len, k_pages, v_pages, k_scales, v_scales,
         key) = state[:8]
        cacc = state[8] if arch is not None else None
        res = _mixed_forward(
            stack, norm_w, head_w, embed_w, rope,
            k_pages, v_pages, k_scales, v_scales,
            ids, positions, row_tables, q_start, q_len, kv_len,
            desc_tables, desc_of_row, off_of_row, key, draw_base,
            eps=eps, kvh=kvh, head_dim=head_dim,
            transpose_head=transpose_head, strategy=strategy,
            top_k=top_k, top_p=top_p, temperature=temperature,
            shardings=shardings, arch=arch)
        (nxt, k_pages, v_pages, k_scales, v_scales, key) = res[:6]
        nxt = nxt.astype(jnp.int32)
        toks = jax.lax.dynamic_update_slice(toks, nxt[None], (si, 0))
        fresh = jnp.logical_not(done)
        emitted = emitted + fresh.astype(jnp.int32)
        hit_eos = jnp.logical_and(eos_ids >= 0, nxt == eos_ids)
        done = jnp.logical_or(
            done, jnp.logical_and(fresh, jnp.logical_or(
                hit_eos, emitted >= budgets)))
        # the host-chained feedback, in-graph: live rows advance, pad
        # rows keep position 0 / the pad table
        ids = jnp.where(live, nxt, ids)
        positions = jnp.where(live, positions + 1, positions)
        kv_len = jnp.where(live, kv_len + 1, kv_len)
        state = (ids, positions, kv_len, k_pages, v_pages, k_scales,
                 v_scales, key)
        if arch is not None:
            state = state + (cacc + res[6],)
        return (si + 1, state, toks, done, emitted)

    si, state, toks, done, emitted = jax.lax.while_loop(
        cond, body, carry0)
    (_, _, _, k_pages, v_pages, k_scales, v_scales, key) = state[:8]
    if arch is None:
        return (toks, emitted, si, k_pages, v_pages, k_scales,
                v_scales, key)
    return (toks, emitted, si, k_pages, v_pages, k_scales, v_scales,
            key, state[8])


class LLMEngine:
    """Continuous batching for backbone-registered models (Llama and
    Qwen2-MoE/DeepSeekMoE families; see inference/backbone.py)."""

    def __init__(self, model, max_seqs: int = 8, max_len: int = 2048,
                 page_size: int = 128, n_pages: Optional[int] = None,
                 dtype=np.float32, decode_strategy: str = "greedy_search",
                 top_k: int = 0, top_p: float = 1.0,
                 temperature: float = 1.0, seed: int = 0,
                 steps_per_sync: int = 1,
                 kv_dtype: Optional[str] = None,
                 weight_dtype: Optional[str] = None,
                 enable_metrics: bool = True,
                 enable_prefix_caching: bool = True,
                 swap_pool_pages: Optional[int] = None,
                 unified_step: bool = True,
                 prefill_token_budget: Optional[int] = None,
                 scan_decode: bool = True,
                 mesh=None, tp_axis: str = "tp",
                 moe_dispatch: str = "grouped",
                 moe_dropless: bool = True,
                 moe_capacity_factor: Optional[float] = None,
                 draft_model=None, spec_k: int = 4):
        import math

        import jax
        import jax.numpy as jnp

        from ..quantization.layers import QuantizedLinear
        from ..quantization.ops import quantize_absmax_raw
        from .backbone import resolve_backbone
        from .moe_dispatch import MoEArch

        enforce(decode_strategy in ("greedy_search", "sampling"),
                f"unsupported decode_strategy {decode_strategy!r}")
        enforce(steps_per_sync >= 1, "steps_per_sync must be >= 1")
        enforce(kv_dtype in (None, "int8", "float32", "bfloat16",
                             "float16"),
                f"unsupported kv_dtype {kv_dtype!r}")
        enforce(weight_dtype in (None, "int8"),
                f"unsupported weight_dtype {weight_dtype!r}")
        enforce(moe_dispatch in ("grouped", "dense"),
                f"unsupported moe_dispatch {moe_dispatch!r}")
        self.steps_per_sync = steps_per_sync
        # on-device decode windows: steps_per_sync > 1 windows run as
        # ONE compiled while_loop program (attend → sample → KV-append
        # chained in-graph, early exit when every row retires) instead
        # of host-chained single-token dispatches.  Bit-identical by
        # construction — the window program's step body IS the
        # single-step program's body.  False restores host chaining
        # (debugging / A-B benches).
        self.scan_decode = bool(scan_decode)
        self.last_window_steps = 0
        self.decode_strategy = decode_strategy
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.temperature = float(temperature)
        self._key = jax.random.PRNGKey(seed)
        self.model = model
        self.max_seqs = max_seqs
        self.max_len = max_len
        self.kv_dtype = kv_dtype
        self.weight_dtype = weight_dtype
        self.enable_prefix_caching = bool(enable_prefix_caching)
        # ragged unified step: ONE compiled program serves every mixed
        # prefill+decode batch.  The STATIC prefill-token budget sizes
        # the flat batch (T = max_seqs + budget rows); the runtime
        # budget (``prefill_token_budget`` attribute) can be lowered
        # per step — e.g. by a scheduler's decode-latency SLO loop —
        # without recompiling, since T never changes.
        self.unified_step = bool(unified_step)
        self._pf_budget_static = int(prefill_token_budget) \
            if prefill_token_budget is not None else page_size
        enforce(self._pf_budget_static >= 1,
                "prefill_token_budget must be >= 1")
        self.prefill_token_budget = self._pf_budget_static
        self._prefilling: List[GenRequest] = []
        # host-side prefix-cache stats (kept even with metrics off —
        # the bench and tests read them directly)
        self.prefix_stats = {"hit_tokens": 0, "miss_tokens": 0,
                             "shared_pages": 0, "hit_requests": 0,
                             "miss_requests": 0}
        # the backbone seam: resolve the model family by duck typing
        # (llama / qwen2_moe; see inference/backbone.py) instead of
        # the old hardwired ``model.llama.*`` reads
        spec = resolve_backbone(model)
        self._backbone = spec
        c = spec.config
        self.eps = c.rms_norm_eps
        self.kvh = c.num_key_value_heads
        self.head_dim = c.hidden_size // c.num_attention_heads
        layers = spec.layers
        # freeze the MoE router geometry into ONE hashable static jit
        # argument — None keeps every Llama program trace byte
        # identical to the pre-seam engine
        self._arch = None
        if spec.moe is not None:
            m = spec.moe
            cf = float(moe_capacity_factor
                       if moe_capacity_factor is not None
                       else m["capacity_factor"])
            # capacity-factor mode: per-page-group per-expert slot cap
            # (a group = one prefill page chunk of page_size rows;
            # decode rows are singleton groups and never drop)
            cap = 0 if moe_dropless else max(
                int(math.ceil(m["top_k"] * page_size * cf
                              / m["num_experts"])), 1)
            self._arch = MoEArch(
                num_experts=int(m["num_experts"]),
                top_k=int(m["top_k"]), norm_topk=bool(m["norm_topk"]),
                capacity=cap, shared=bool(m["shared"]),
                shared_gate=bool(m["shared_gate"]),
                attn_bias=bool(spec.attn_bias),
                dispatch=moe_dispatch)
            if cap and unified_step:
                # capacity ranks are defined per page-group, so the
                # unified planner packs WHOLE page chunks in this
                # mode — the static budget must fit one
                enforce(self._pf_budget_static >= page_size,
                        "capacity-factor MoE with unified_step needs "
                        f"prefill_token_budget >= page_size "
                        f"({page_size}) — the planner packs whole "
                        "page chunks so capacity ranks match the "
                        "split path")
        # tensor-parallel serving (``mesh=``): attention heads and MLP
        # hidden shard over the ``tp_axis`` of the given 1-D mesh
        # (distributed.topology.serving_mesh builds one); the paged KV
        # pools shard on their KV-head axis so each chip holds
        # num_kv_heads/tp heads of EVERY page.  The plan is a hashable
        # static jit arg — one extra trace per mesh shape, zero when
        # mesh is None (the constraints vanish and the programs are
        # the single-chip ones byte for byte).
        self._shardings = None
        if mesh is not None:
            from ..distributed.sharding import TPShardings
            self._shardings = TPShardings(mesh, tp_axis)
            tp = self._shardings.tp
            nh = c.num_attention_heads
            enforce(tp >= 1 and mesh.shape.get(tp_axis) is not None,
                    f"mesh has no {tp_axis!r} axis: {mesh!r}")
            enforce(self.kvh % tp == 0,
                    f"tp={tp} must divide num_key_value_heads "
                    f"({self.kvh}) — each shard holds whole KV heads")
            enforce(nh % tp == 0,
                    f"tp={tp} must divide num_attention_heads ({nh})")
        if n_pages is None:
            n_pages = max_seqs * (max_len // page_size) + 1
        if kv_dtype not in (None, "int8"):
            dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
                     "float16": jnp.float16}[kv_dtype]
        # host swap pool for preemption: default as many pages as the
        # device pool (host DRAM is cheap next to HBM; 0 disables swap
        # and makes every resume recompute)
        if swap_pool_pages is None:
            swap_pool_pages = n_pages
        self.cache = PagedKVCache(
            n_pages=n_pages, page_size=page_size, n_kv_heads=self.kvh,
            head_dim=self.head_dim, max_seqs=max_seqs, max_len=max_len,
            dtype=dtype, num_layers=len(layers),
            kv_dtype="int8" if kv_dtype == "int8" else None,
            swap_pool_pages=swap_pool_pages,
            shardings=self._shardings)

        def stackp(get):
            return jnp.stack([get(l).value for l in layers])

        def stackw(get):
            """Stack one projection across layers: fp array, or
            (int8 values, f32 scales) when the model's Linears were
            quantize_model'd or weight_dtype='int8' asks for it."""
            mods = [get(l) for l in layers]
            if any(isinstance(m, QuantizedLinear) for m in mods):
                enforce(all(isinstance(m, QuantizedLinear)
                            for m in mods),
                        "mixed fp/int8 Linears across decoder layers")
                return (jnp.stack([m.qweight.value for m in mods]),
                        jnp.stack([m.weight_scale.value
                                   for m in mods]))
            ws = jnp.stack([m.weight.value for m in mods])
            if weight_dtype == "int8":
                # per-(layer, out-channel) absmax over the in axis
                return quantize_absmax_raw(ws, axis=1)
            return ws

        if self._arch is None:
            self._stack = (
                stackp(lambda l: l.input_layernorm.weight),
                stackw(lambda l: l.self_attn.q_proj),
                stackw(lambda l: l.self_attn.k_proj),
                stackw(lambda l: l.self_attn.v_proj),
                stackw(lambda l: l.self_attn.o_proj),
                stackp(lambda l: l.post_attention_layernorm.weight),
                stackw(lambda l: l.mlp.gate_proj),
                stackw(lambda l: l.mlp.up_proj),
                stackw(lambda l: l.mlp.down_proj),
            )
        else:
            # MoE stack: 17 per-layer entries.  Attention biases and
            # shared-expert weights that a given config lacks are
            # stacked as [L, 1, 1] zero placeholders — the static arch
            # flags skip their use, and the fixed pytree keeps ONE
            # program signature per geometry.
            zed = jnp.zeros((len(layers), 1, 1), jnp.float32)

            def stackb(get):
                bs = [get(l) for l in layers]
                if bs[0] is None:
                    enforce(all(b is None for b in bs),
                            "mixed biased/bias-free attention across "
                            "decoder layers")
                    return zed
                return jnp.stack([b.value for b in bs])

            def stacke(get, axis):
                """Stack one expert projection [L, E, in, out]; int8
                quantizes per-(layer, expert, out-channel) over the
                contraction ``axis``."""
                ws = jnp.stack([get(l) for l in layers])
                if weight_dtype == "int8":
                    return quantize_absmax_raw(ws, axis=axis)
                return ws

            def stacksh(get):
                mods = [get(l) for l in layers]
                if mods[0] is None:
                    return zed
                return stackw(lambda l: get(l))

            self._stack = (
                stackp(lambda l: l.input_layernorm.weight),
                stackw(lambda l: l.self_attn.q_proj),
                stackb(lambda l: l.self_attn.q_proj.bias),
                stackw(lambda l: l.self_attn.k_proj),
                stackb(lambda l: l.self_attn.k_proj.bias),
                stackw(lambda l: l.self_attn.v_proj),
                stackb(lambda l: l.self_attn.v_proj.bias),
                stackw(lambda l: l.self_attn.o_proj),
                stackp(lambda l: l.post_attention_layernorm.weight),
                # router stays fp — its softmax drives routing and is
                # tiny ([H, E]); expert stacks ride the absmax path
                stackp(lambda l: l.mlp.gate.weight),
                stacke(lambda l: l.mlp.experts.gate_w.value, 2),
                stacke(lambda l: l.mlp.experts.up_w.value, 2),
                stacke(lambda l: l.mlp.experts.down_w.value, 2),
                stacksh(lambda l: l.mlp.shared_gate),
                stacksh(lambda l: getattr(l.mlp, "shared_up", None)),
                stacksh(lambda l: getattr(l.mlp, "shared_down", None)),
                stacksh(lambda l: l.mlp.shared_expert_gate),
            )
        self._norm_w = spec.norm.weight.value
        # tied embeddings: keep the [V, H] weight and transpose in-graph
        # (an eager .T would hold a duplicate of the full vocab matrix)
        self._tied = spec.lm_head is None
        if self._tied:
            self._head_w = spec.embed_tokens.weight.value
        elif isinstance(spec.lm_head, QuantizedLinear):
            self._head_w = (spec.lm_head.qweight.value,
                            spec.lm_head.weight_scale.value)
        elif weight_dtype == "int8":
            self._head_w = quantize_absmax_raw(
                spec.lm_head.weight.value, axis=0)
        else:
            self._head_w = spec.lm_head.weight.value
        self._embed_w = spec.embed_tokens.weight.value
        rope = np.asarray(spec.rope_cos.value), \
            np.asarray(spec.rope_sin.value)
        self._rope = (jnp.asarray(rope[0]), jnp.asarray(rope[1]))
        # the chunked prefill slices a FULL page of rope rows at the
        # last chunk's base; pad the tables to a page multiple so
        # dynamic_slice never clamps the start (clamping would rotate
        # the prompt tail by wrong angles when max_position_embeddings
        # is not a page multiple).  The padded rows back padding ids
        # only — real positions stay < max_position_embeddings by the
        # admission limit check.
        maxpos = rope[0].shape[0]
        pad_to = -(-max(maxpos, page_size) // page_size) * page_size
        if pad_to != maxpos:
            padr = ((0, pad_to - maxpos), (0, 0))
            self._rope_prefill = (
                jnp.asarray(np.pad(rope[0], padr)),
                jnp.asarray(np.pad(rope[1], padr)))
        else:
            self._rope_prefill = self._rope

        if self._shardings is not None:
            # commit every program input up front: projection weights
            # shard on their OUTPUT axis (int8 (values, scales) pairs
            # travel together), everything that feeds a contraction or
            # a norm stays replicated — the device_put placements and
            # the in-graph _tpc constraints are the same plan, so
            # GSPMD never has to guess (a guessed partial-sum would
            # break tp=1 vs tp=N bit-identity).
            sh = self._shardings

            def _put(w, dim):
                if isinstance(w, tuple):
                    return tuple(_put(a, dim) for a in w)
                d = dim if dim is not None and \
                    w.shape[dim] % sh.tp == 0 else None
                return sh.put(w, d)

            if self._arch is None:
                # stack order: iln, qw, kw, vw, ow, pln, gw, uw, dw —
                # layernorm weights (0, 5) replicate, projections
                # shard on the last (output) axis
                rep = (0, 5)
            else:
                # MoE stack: layernorms (0, 8) and the whole FFN tail
                # (router, expert stacks, shared expert; 9..16)
                # replicate — expert parallelism over the mesh is the
                # carried ROADMAP item; attention projections and
                # biases still shard on their output axis (zed
                # placeholders fall back to replicated via the
                # divisibility check in _put)
                rep = (0, 8) + tuple(range(9, 17))
            self._stack = tuple(
                _put(w, None if i in rep else -1)
                for i, w in enumerate(self._stack))
            self._norm_w = _put(self._norm_w, None)
            self._embed_w = _put(self._embed_w, None)
            if self._tied:
                self._head_w = self._embed_w
            else:
                self._head_w = _put(self._head_w, None)
            same_rope = self._rope_prefill is self._rope
            self._rope = _put(self._rope, None)
            self._rope_prefill = self._rope if same_rope \
                else _put(self._rope_prefill, None)

        self.requests: Dict[object, GenRequest] = {}
        self._active: List[GenRequest] = []
        # host-side per-expert load accounting (kept even with metrics
        # off — metrics_snapshot()/statusz and the bench read it):
        # routed-slot counts per (layer, expert) plus the running
        # capacity-drop total (always 0 dropless)
        if self._arch is not None:
            self._moe_counts = np.zeros(
                (len(layers), self._arch.num_experts), np.int64)
            self._moe_dropped = 0
        self._init_metrics(enable_metrics)
        # compile-watch registration: this engine's three jit entry
        # points and their warmup allowances (the split decode program
        # legitimately compiles one power-of-two window bucket per
        # size, bit_length of steps_per_sync of them; prefill and the
        # unified mixed step are strictly one-program per geometry).
        # A no-op off one global read when the watch is disabled.
        cw = _insp.get_compile_watch()
        cw.register_program("engine.prefill_chunk")
        cw.register_program("engine.decode_step",
                            expected=int(steps_per_sync).bit_length())
        cw.register_program("engine.mixed_step")
        # scanned windows: one program per power-of-two window bucket
        # {2, 4, ..., 2^floor(log2(steps_per_sync))} — the n_steps==1
        # window degenerates to the plain step program above, so the
        # bucket count is bit_length − 1 and ``mixed_compiles()`` stays
        # bounded by DECLARED allowances (a recompile past them is an
        # anomaly the watch flags)
        wb = max(int(steps_per_sync).bit_length() - 1, 0)
        if self.scan_decode and wb:
            cw.register_program(
                "engine.mixed_window" if self.unified_step
                else "engine.decode_window", expected=wb)
        # the paged KV pool (device pages + host swap) as a first-class
        # /memz row; weakly held so a released engine frees its pages
        _insp.register_memory_consumer(
            f"kv_cache:{self.engine_id}", self.cache)
        # request-capsule config fingerprint: everything a replay needs
        # to decide "same engine config" — cheap dict built once, the
        # model hash is a config hash (never a weight sync)
        self._capsule_fp = {
            "engine": self.engine_id,
            "model_hash": _capsule.model_fingerprint(model),
            "kv_dtype": kv_dtype, "weight_dtype": weight_dtype,
            "page_size": page_size, "n_pages": int(n_pages),
            "max_seqs": max_seqs, "max_len": max_len,
            "steps_per_sync": steps_per_sync,
            "unified_step": self.unified_step,
            "scan_decode": self.scan_decode,
            "decode_strategy": decode_strategy,
            "top_k": self.top_k, "top_p": self.top_p,
            "temperature": self.temperature, "seed": seed,
            "prefix_caching": self.enable_prefix_caching,
            # deliberately NOT token-affecting (capsule._TOKEN_AFFECTING):
            # tp=1 and tp=N streams are bit-identical by construction,
            # so cross-tp replay is allowed — and asserted in tests
            "tp": self._shardings.tp if self._shardings else 1,
            # TOKEN-AFFECTING router geometry (a tampered router config
            # must refuse replay); dispatch mode is deliberately
            # absent — grouped and dense are bit-identical like tp
            "moe": None if self._arch is None else {
                "num_experts": self._arch.num_experts,
                "top_k": self._arch.top_k,
                "norm_topk": self._arch.norm_topk,
                "dropless": self._arch.capacity == 0,
                "capacity": self._arch.capacity,
                "shared": self._arch.shared,
                "shared_gate": self._arch.shared_gate,
            },
            # TOKEN-AFFECTING speculative geometry (filled by
            # _init_spec): a changed draft model / k / acceptance mode
            # must refuse replay via fingerprint_mismatch.  None for
            # plain engines — greedy speculative streams are
            # bit-identical to plain decode, but SAMPLED acceptance
            # draws depend on the draft's q, so the conservative
            # contract covers both modes.
            "spec": None,
        }
        self._spec = None
        if draft_model is not None:
            self._init_spec(draft_model, spec_k, dtype, page_size,
                            weight_dtype)

    def config_fingerprint(self) -> dict:
        """This engine's capsule config fingerprint (copy)."""
        return dict(self._capsule_fp)

    # -- speculative decoding --------------------------------------------------
    def _init_spec(self, draft_model, spec_k: int, dtype, page_size: int,
                   weight_dtype):
        """Attach a DRAFT backbone for speculative decoding: its
        weights stack into the same serving pytrees as the target's
        (dense order — MoE drafts are refused; drafts are small), its
        KV rides a second ``PagedKVCache`` with the draft's geometry,
        and per-request draft slots attach LAZILY at the first
        speculative window (one hook covers admission, deferred
        prefill, resume — both restore paths — and import; suspend /
        abort / retire just release).  The draft always runs
        REPLICATED (``shardings=None``): tp shards the target, whose
        verify dispatch dominates — and greedy acceptance never
        depends on draft numerics, only on how often it matches.

        Compile surface, declared: one extra ``engine.prefill_chunk``
        trace (draft geometry), two ``engine.spec_draft`` traces
        (propose ``n_steps=spec_k`` + 1-step catch-up), one
        ``engine.spec_verify`` trace (the ragged mixed program at the
        static ``T_spec = max_seqs * (spec_k + 1)`` bucket — runtime k
        stays traced data, so churning k never recompiles)."""
        import jax.numpy as jnp

        from ..quantization.layers import QuantizedLinear
        from ..quantization.ops import quantize_absmax_raw
        from .backbone import resolve_backbone

        enforce(spec_k >= 1, "spec_k must be >= 1")
        dspec = resolve_backbone(draft_model)
        enforce(dspec.moe is None,
                "speculative draft must be a dense backbone "
                "(MoE drafts defeat the point of a small draft)")
        c, dc = self._backbone.config, dspec.config
        enforce(dc.vocab_size == c.vocab_size,
                f"draft vocab ({dc.vocab_size}) must match target "
                f"vocab ({c.vocab_size})")
        d_maxpos = int(np.asarray(dspec.rope_cos.value).shape[0])
        t_maxpos = int(np.asarray(self._backbone.rope_cos.value).shape[0])
        enforce(d_maxpos >= min(self.max_len, t_maxpos),
                f"draft max_position_embeddings ({d_maxpos}) too short "
                f"for the engine's sequence limit "
                f"({min(self.max_len, t_maxpos)})")
        self.spec_k = int(spec_k)
        self._spec_mode = "greedy" \
            if self.decode_strategy == "greedy_search" else "rejection"
        layers = dspec.layers

        def stackp(get):
            return jnp.stack([get(l).value for l in layers])

        def stackw(get):
            mods = [get(l) for l in layers]
            if any(isinstance(m, QuantizedLinear) for m in mods):
                enforce(all(isinstance(m, QuantizedLinear)
                            for m in mods),
                        "mixed fp/int8 Linears across draft layers")
                return (jnp.stack([m.qweight.value for m in mods]),
                        jnp.stack([m.weight_scale.value
                                   for m in mods]))
            ws = jnp.stack([m.weight.value for m in mods])
            if weight_dtype == "int8":
                return quantize_absmax_raw(ws, axis=1)
            return ws

        d_stack = (
            stackp(lambda l: l.input_layernorm.weight),
            stackw(lambda l: l.self_attn.q_proj),
            stackw(lambda l: l.self_attn.k_proj),
            stackw(lambda l: l.self_attn.v_proj),
            stackw(lambda l: l.self_attn.o_proj),
            stackp(lambda l: l.post_attention_layernorm.weight),
            stackw(lambda l: l.mlp.gate_proj),
            stackw(lambda l: l.mlp.up_proj),
            stackw(lambda l: l.mlp.down_proj),
        )
        d_tied = dspec.lm_head is None
        if d_tied:
            d_head = dspec.embed_tokens.weight.value
        elif isinstance(dspec.lm_head, QuantizedLinear):
            d_head = (dspec.lm_head.qweight.value,
                      dspec.lm_head.weight_scale.value)
        elif weight_dtype == "int8":
            d_head = quantize_absmax_raw(
                dspec.lm_head.weight.value, axis=0)
        else:
            d_head = dspec.lm_head.weight.value
        rope = (np.asarray(dspec.rope_cos.value),
                np.asarray(dspec.rope_sin.value))
        d_rope = (jnp.asarray(rope[0]), jnp.asarray(rope[1]))
        pad_to = -(-max(d_maxpos, page_size) // page_size) * page_size
        if pad_to != d_maxpos:
            padr = ((0, pad_to - d_maxpos), (0, 0))
            d_rope_prefill = (jnp.asarray(np.pad(rope[0], padr)),
                              jnp.asarray(np.pad(rope[1], padr)))
        else:
            d_rope_prefill = d_rope
        # draft KV pool: the draft's geometry, full slot capacity (no
        # prefix sharing thins it like the target's), no swap pool —
        # suspended drafts are cheaper to RECOMPUTE than to swap
        self._spec_cache = PagedKVCache(
            n_pages=self.max_seqs * (self.max_len // page_size) + 1,
            page_size=page_size,
            n_kv_heads=dc.num_key_value_heads,
            head_dim=dc.hidden_size // dc.num_attention_heads,
            max_seqs=self.max_seqs, max_len=self.max_len, dtype=dtype,
            num_layers=len(layers),
            kv_dtype="int8" if self.kv_dtype == "int8" else None,
            swap_pool_pages=0, shardings=None)
        self._spec = {
            "stack": d_stack, "norm_w": dspec.norm.weight.value,
            "head_w": d_head, "embed_w": dspec.embed_tokens.weight.value,
            "rope": d_rope, "rope_prefill": d_rope_prefill,
            "tied": d_tied, "eps": dc.rms_norm_eps,
            "kvh": dc.num_key_value_heads,
            "head_dim": dc.hidden_size // dc.num_attention_heads,
        }
        # host-side acceptance accounting (kept even with metrics off —
        # metrics_snapshot()/statusz/the bench read it directly):
        # ``accepted`` counts surviving DRAFT tokens only; the bonus /
        # correction token rides ``delivered``
        self.spec_stats = {"windows": 0, "proposed": 0, "accepted": 0,
                           "delivered": 0}
        cw = _insp.get_compile_watch()
        cw.register_program("engine.prefill_chunk")  # draft geometry
        cw.register_program("engine.spec_draft", expected=2)
        cw.register_program("engine.spec_verify")
        _insp.register_memory_consumer(
            f"kv_cache_draft:{self.engine_id}", self._spec_cache)
        self._capsule_fp["spec"] = {
            "draft_hash": _capsule.model_fingerprint(draft_model),
            "k": self.spec_k, "mode": self._spec_mode}
        if self._metrics is not None:
            reg = get_registry()
            lbl = ("engine",)
            eid = self.engine_id
            self._metrics["spec_proposed"] = reg.counter(
                "llm_engine_spec_proposed_total",
                "Draft tokens proposed to speculative verify "
                "windows.", lbl).labels(eid)
            self._metrics["spec_accepted"] = reg.counter(
                "llm_engine_spec_accepted_total",
                "Draft tokens accepted by the target (bonus/"
                "correction tokens excluded).", lbl).labels(eid)
            self._metrics["spec_rate"] = reg.gauge(
                "llm_engine_spec_acceptance_rate",
                "Cumulative accepted/proposed draft-token ratio.",
                lbl).labels(eid)
            # fixed ladder (NOT spec_k-derived): the registry enforces
            # one bucket set per metric name process-wide, and
            # engines with different k must share it
            self._metrics["spec_len"] = reg.histogram(
                "llm_engine_spec_accepted_len",
                "Accepted draft tokens per sequence per speculative "
                "window.", lbl,
                buckets=_SPEC_LEN_BUCKETS).labels(eid)

    # -- metrics ---------------------------------------------------------------
    def _init_metrics(self, enabled: bool):
        """Per-engine children in the global registry (label
        engine=<id>), so concurrent engines scrape apart.  Recording is
        a handful of host float-adds per step WINDOW (never per token:
        TPOT uses the weighted observe), which is what keeps the bench
        overhead row inside its <=2% budget."""
        self.engine_id = str(next(_ENGINE_IDS))
        self._metrics = None
        if not enabled:
            return
        reg = get_registry()
        lbl = ("engine",)
        eid = self.engine_id
        self._metrics = {
            "ttft": reg.histogram(
                "llm_engine_ttft_seconds",
                "Time to first token: add_request() entry to the "
                "prefill-produced token (includes any compile).",
                lbl, buckets=_TTFT_BUCKETS).labels(eid),
            "tpot": reg.histogram(
                "llm_engine_tpot_seconds",
                "Per-token decode latency: step() window wall time / "
                "tokens in the window.", lbl,
                buckets=_TPOT_BUCKETS).labels(eid),
            "prompt_tokens": reg.counter(
                "llm_engine_prompt_tokens_total",
                "Prompt tokens admitted.", lbl).labels(eid),
            "generated_tokens": reg.counter(
                "llm_engine_generated_tokens_total",
                "Tokens returned to requests (prefill token "
                "included).", lbl).labels(eid),
            "requests": reg.counter(
                "llm_engine_requests_total",
                "Requests admitted.", lbl).labels(eid),
            "aborted": reg.counter(
                "llm_engine_aborted_total",
                "Requests cancelled via abort() before finishing "
                "(suspended requests included — their swap entry is "
                "dropped).", lbl).labels(eid),
            "suspended": reg.counter(
                "llm_engine_suspended_total",
                "Requests preempted out of the decode batch "
                "(suspend()).", lbl).labels(eid),
            "resumed": reg.counter(
                "llm_engine_resumed_total",
                "Suspended requests re-admitted, by restore path "
                "(swap_in: host pages copied back; recompute: prompt "
                "+ generated tokens replayed).", ("engine", "path")),
            "migrated_out": reg.counter(
                "llm_engine_migrated_out_total",
                "Suspended requests exported as migration packages "
                "(export_request) — they now belong to another "
                "engine.", lbl).labels(eid),
            "migrated_in": reg.counter(
                "llm_engine_migrated_in_total",
                "Migration packages adopted (import_request) — they "
                "resume here via resume().", lbl).labels(eid),
            "queue_depth": reg.gauge(
                "llm_engine_queue_depth",
                "Requests active in the decode batch.", lbl).labels(eid),
            "occupancy": reg.gauge(
                "llm_engine_batch_occupancy",
                "Active requests / max_seqs in the last decode "
                "window.", lbl).labels(eid),
            "prefix_hit_tokens": reg.counter(
                "llm_engine_prefix_hit_tokens_total",
                "Prompt tokens served from cached prefix pages (no "
                "prefill compute).", lbl).labels(eid),
            "prefix_miss_tokens": reg.counter(
                "llm_engine_prefix_miss_tokens_total",
                "Prompt tokens that ran chunked prefill.",
                lbl).labels(eid),
            "prefix_shared_pages": reg.counter(
                "llm_engine_prefix_shared_pages_total",
                "Cached pages mapped read-shared into admitted "
                "slots.", lbl).labels(eid),
            "prefix_hit_rate": reg.gauge(
                "llm_engine_prefix_cache_hit_rate",
                "Cumulative cached / total prompt tokens (0 when "
                "prefix caching is off or nothing admitted).",
                lbl).labels(eid),
            "mixed_decode_slots": reg.gauge(
                "llm_engine_mixed_batch_decode_slots",
                "Decode rows packed into the last unified mixed "
                "step.", lbl).labels(eid),
            "mixed_prefill_tokens": reg.gauge(
                "llm_engine_mixed_batch_prefill_tokens",
                "Prefill-chunk tokens packed into the last unified "
                "mixed step (interleave ratio = this / (this + decode "
                "slots)).", lbl).labels(eid),
        }
        if self._arch is not None:
            # MoE serving observability: the per-(layer, expert) load
            # counter family plus the imbalance SLO gauge (max/mean
            # per-expert load over all layers — 1.0 is perfect
            # balance, E means one expert takes everything)
            self._metrics["expert_tokens"] = reg.counter(
                "llm_engine_expert_tokens_total",
                "Routed token-slots kept per (layer, expert) — "
                "capacity-dropped slots are excluded (see "
                "llm_engine_expert_dropped_tokens_total).",
                ("engine", "layer", "expert"))
            self._metrics["expert_dropped"] = reg.counter(
                "llm_engine_expert_dropped_tokens_total",
                "Routed token-slots dropped by the capacity factor "
                "(always 0 dropless).", lbl).labels(eid)
            self._metrics["expert_imbalance"] = reg.gauge(
                "llm_engine_expert_imbalance",
                "Max/mean cumulative per-expert routed load across "
                "layers (the MoE balance SLO; 1.0 = uniform).",
                lbl).labels(eid)
        # compile-count gauges are process-global (the jit caches are),
        # unlabeled: any drift past 1 means a recompile regression —
        # alarm on it instead of diagnosing a silent latency cliff
        self._metrics["prefill_compiles"] = reg.gauge(
            "llm_engine_prefill_compiles",
            "Distinct compiled prefill programs (expected: 1).")
        self._metrics["decode_compiles"] = reg.gauge(
            "llm_engine_decode_compiles",
            "Distinct compiled decode programs (expected: ~1, at most "
            "log2(steps_per_sync) window buckets).")
        self._metrics["mixed_compiles"] = reg.gauge(
            "llm_engine_mixed_compiles",
            "Distinct compiled unified mixed-step programs "
            "(expected: 1 per engine geometry, plus one scanned "
            "mixed-window program per power-of-two window bucket).")
        self._metrics["window_compiles"] = reg.gauge(
            "llm_engine_window_compiles",
            "Distinct compiled on-device decode-window programs "
            "(expected: at most log2(steps_per_sync) power-of-two "
            "buckets; 0 with scan_decode off).")

    def _record_compiles(self):
        m = self._metrics
        m["prefill_compiles"].set(self.prefill_compiles())
        m["decode_compiles"].set(self.decode_compiles())
        m["mixed_compiles"].set(self.mixed_compiles())
        m["window_compiles"].set(self.window_compiles())

    def _note_expert_counts(self, counts, routed_slots: int):
        """Fold one MoE dispatch's routed-token counts ([L, E] device
        int32) into the host accounting and the registry.
        ``routed_slots`` is the number of live (row, top-k) slots the
        dispatch routed PER LAYER — kept + capacity-dropped — so the
        drop total is ``routed_slots·L − counts.sum()`` (identically 0
        dropless).  One device_get per dispatch WINDOW, never per
        token, same budget discipline as the latency metrics."""
        import jax

        cnt = np.asarray(jax.device_get(counts), np.int64)
        self._moe_counts += cnt
        dropped = int(routed_slots) * cnt.shape[0] - int(cnt.sum())
        self._moe_dropped += dropped
        if self._metrics is not None:
            fam = self._metrics["expert_tokens"]
            eid = self.engine_id
            for l, e in zip(*np.nonzero(cnt)):
                fam.labels(eid, str(l), str(e)).inc(int(cnt[l, e]))
            if dropped:
                self._metrics["expert_dropped"].inc(dropped)
            tot = self._moe_counts.sum(axis=0).astype(np.float64)
            if tot.sum() > 0:
                self._metrics["expert_imbalance"].set(
                    float(tot.max() / tot.mean()))

    # -- prefill / replay internals --------------------------------------------
    def _prefill_seq(self, slot, seq, start_chunk: int):
        """Run the single compiled chunked-prefill program over
        ``seq`` in ``slot``, starting at chunk ``start_chunk`` (earlier
        chunks' pages are already written — the prefix-cache-hit
        path).  Returns the last real token's logits row.  Shared by
        admission and the recompute-resume replay: both go through the
        SAME jit entry, so ``prefill_compiles() == 1`` holds across
        preemption too."""
        import jax.numpy as jnp

        P = self.cache.page_size
        plen = len(seq)
        table = np.asarray(self.cache.page_table[slot])
        logits = None
        for ci in range(start_chunk, -(-plen // P)):
            base = ci * P
            chunk = np.zeros(P, np.int32)
            real = min(P, plen - base)
            chunk[:real] = np.asarray(seq[base:base + real], np.int32)
            # per-chunk span (nests under the active admit/prefill
            # span); one object per PAGE of prompt, never per token —
            # and the shared NULL_SPAN when tracing is off
            chunk_span = _tracing.span("engine.prefill_chunk")
            chunk_span.set_attr("chunk", ci).set_attr("tokens", real)
            out = _insp.watched_call(
                "engine.prefill_chunk", _paged_prefill_chunk,
                self._stack, self._norm_w, self._head_w,
                self._embed_w, self._rope_prefill,
                self.cache.k_pages, self.cache.v_pages,
                self.cache.k_scales, self.cache.v_scales,
                jnp.asarray(chunk),
                jnp.asarray(table), jnp.int32(base),
                jnp.int32(int(table[ci])),
                jnp.int32(min(plen - 1 - base, P - 1)),
                eps=self.eps, kvh=self.kvh,
                head_dim=self.head_dim,
                transpose_head=self._tied,
                shardings=self._shardings, arch=self._arch)
            if self._arch is not None:
                self._note_expert_counts(
                    out[-1], real * self._arch.top_k)
                out = out[:-1]
            (logits, self.cache.k_pages, self.cache.v_pages,
             self.cache.k_scales, self.cache.v_scales) = out
            chunk_span.end()
        return logits

    def _replay_decode(self, slot, toks):
        """Recompute-resume tail: re-append the KV of already-generated
        ``toks`` through the SAME compiled decode program the original
        run used, ignoring its sampled outputs and never touching the
        engine's sampling key (an unpreempted run's key stream must
        stay reproducible).  Greedy replay re-derives the recorded
        tokens inside multi-step windows (bit-identical logits ⇒ same
        argmax), so it reuses the power-of-two window programs;
        sampling replay forces 1-token windows so the RECORDED token —
        not a fresh draw — feeds every step."""
        import jax
        import jax.numpy as jnp

        key = jax.random.PRNGKey(0)            # unused by greedy
        pad = self.max_seqs - 1
        padt = np.zeros((pad,) + self.cache.page_table.shape[1:],
                        np.int32)
        i = 0
        while i < len(toks):
            nsteps = min(self.steps_per_sync, len(toks) - i)
            if self.decode_strategy != "greedy_search":
                nsteps = 1
            while nsteps & (nsteps - 1):
                nsteps &= nsteps - 1
            self.cache.extend(slot, nsteps)
            tokens = np.array([toks[i]] + [0] * pad, np.int32)
            lens = np.concatenate([self.cache.seq_lens[[slot]],
                                   np.zeros(pad, np.int32)])
            tables = np.concatenate(
                [self.cache.page_table[[slot]], padt])
            out = _insp.watched_call(
                "engine.decode_step", _paged_decode_step,
                self._stack, self._norm_w, self._head_w,
                self._embed_w, self._rope, self.cache.k_pages,
                self.cache.v_pages, self.cache.k_scales,
                self.cache.v_scales, jnp.asarray(tokens),
                jnp.asarray(lens, np.int32), jnp.asarray(tables),
                jnp.asarray(lens, np.int32), key, jnp.int32(0),
                eps=self.eps, kvh=self.kvh,
                head_dim=self.head_dim,
                transpose_head=self._tied,
                strategy=self.decode_strategy,
                top_k=self.top_k, top_p=self.top_p,
                temperature=self.temperature, n_steps=nsteps,
                shardings=self._shardings, arch=self._arch)
            if self._arch is not None:
                self._note_expert_counts(
                    out[-1], self._arch.top_k * nsteps)
                out = out[:-1]
            (_, self.cache.k_pages, self.cache.v_pages,
             self.cache.k_scales, self.cache.v_scales) = out
            self.cache.advance([slot], nsteps)
            i += nsteps

    # -- speculative window internals ------------------------------------------
    def _spec_prefill(self, dslot, seq):
        """Chunked prefill of ``seq`` into DRAFT slot ``dslot`` —
        ``_prefill_seq``'s mirror over the draft weights and cache
        (replicated, dense ``arch=None``).  Rides the same
        ``engine.prefill_chunk`` watch point; its one extra trace
        (draft geometry) is declared at ``_init_spec``."""
        import jax.numpy as jnp

        sp = self._spec
        dcache = self._spec_cache
        P = dcache.page_size
        plen = len(seq)
        table = np.asarray(dcache.page_table[dslot])
        for ci in range(-(-plen // P)):
            base = ci * P
            chunk = np.zeros(P, np.int32)
            real = min(P, plen - base)
            chunk[:real] = np.asarray(seq[base:base + real], np.int32)
            out = _insp.watched_call(
                "engine.prefill_chunk", _paged_prefill_chunk,
                sp["stack"], sp["norm_w"], sp["head_w"],
                sp["embed_w"], sp["rope_prefill"],
                dcache.k_pages, dcache.v_pages,
                dcache.k_scales, dcache.v_scales,
                jnp.asarray(chunk), jnp.asarray(table),
                jnp.int32(base), jnp.int32(int(table[ci])),
                jnp.int32(min(plen - 1 - base, P - 1)),
                eps=sp["eps"], kvh=sp["kvh"],
                head_dim=sp["head_dim"], transpose_head=sp["tied"],
                shardings=None, arch=None)
            (_, dcache.k_pages, dcache.v_pages, dcache.k_scales,
             dcache.v_scales) = out
        dcache.set_len(dslot, plen)

    def _spec_attach(self, req):
        """Lazily attach the request's DRAFT KV slot at its first
        speculative window: allocate the full page reservation on the
        draft cache and chunk-prefill ``prompt + out[:-1]`` — the
        draft mirror of the target's window-start state (KV through
        position ``cur - 1``, next input ``out[-1]``).  ONE hook
        covers every way a request reaches decode — admission,
        deferred prefill, resume via either restore path, import —
        because all of them land in ``_step_spec`` with a bare
        ``draft_slot``; retire / suspend / abort just release."""
        seq = list(req.prompt) + req.out[:-1]
        req.draft_slot = self._spec_cache.allocate(
            len(req.prompt) + req.max_new)
        self._spec_prefill(req.draft_slot, seq)

    def _spec_release(self, req):
        """Drop the request's draft slot (retire / suspend / abort /
        capsule-replay scratch).  Guarded no-op when the request never
        reached a speculative window — the lazy attach means plain
        interludes and first-token retires hold no draft state."""
        if self._spec is not None and req.draft_slot is not None:
            self._spec_cache.release(req.draft_slot)
            req.draft_slot = None

    def _spec_window(self, rows, sub, k_run):
        """One speculative window over ``rows`` (dicts with the
        request's target ``slot``, ``dslot``, ``last`` input token,
        ``cur`` KV length, full token ``seq`` and draw-id ``row``):
        draft catch-up + propose, ONE ragged target verify, accept,
        and the advance/rollback bookkeeping on BOTH caches.  Returns
        ``[(delivered_tokens, n_accepted)]`` aligned with ``rows`` and
        touches no request state — capsule replay re-invokes it with a
        single scratch row, which is why draws key off ``row`` (the
        CAPTURED batch index) and never off packing position.

        ``sub`` is the window's engine-key fork; ``spec_window_keys``
        derives the draft / accept / resample roots from it, so the
        engine key stream is identical to a plain window's and the
        capsule's per-window key fingerprint replays either kind.

        ``k_run`` (<= ``spec_k``) is the runtime draft length — TRACED
        data in both programs: propose always runs the static
        ``spec_k`` steps (overrun rows land in reserved pages or the
        pad page and are never attended), verify always dispatches the
        static ``T_spec = max_seqs * (spec_k + 1)`` bucket with
        ``q_len`` descriptors carving out the live ``k_run + 1`` rows
        — so churning ``k_run`` never recompiles."""
        import jax
        import jax.numpy as jnp

        from . import speculative as _spec_mod

        sp = self._spec
        dcache = self._spec_cache
        sampled = self._spec_mode == "rejection"
        draft_root, accept_root, resample_root = \
            _sampling.spec_window_keys(sub)
        B = self.max_seqs
        maxp_d = dcache.page_table.shape[1]

        # -- draft catch-up: teacher-force the draft level with the
        # target (deficit 1 after a fully-accepted window — the bonus
        # token's KV was never drafted — or more after plain-decode
        # interludes), one 1-step program dispatch per deficit level;
        # rows already level ride along as len-0 pad rows
        while True:
            lag = [r for r in rows
                   if int(dcache.seq_lens[r["dslot"]]) < r["cur"]]
            if not lag:
                break
            ids = np.zeros(B, np.int32)
            pos = np.zeros(B, np.int32)
            tabs = np.zeros((B, maxp_d), np.int32)
            lens = np.zeros(B, np.int32)
            dslots = []
            for j, r in enumerate(lag):
                dl = int(dcache.seq_lens[r["dslot"]])
                dcache.extend(r["dslot"], 1)
                ids[j] = r["seq"][dl]
                pos[j] = dl
                tabs[j] = dcache.page_table[r["dslot"]]
                lens[j] = dl
                dslots.append(r["dslot"])
            res = _insp.watched_call(
                "engine.spec_draft", _spec_mod._paged_draft_propose,
                sp["stack"], sp["norm_w"], sp["head_w"],
                sp["embed_w"], sp["rope"],
                dcache.k_pages, dcache.v_pages,
                dcache.k_scales, dcache.v_scales,
                jnp.asarray(ids), jnp.asarray(pos),
                jnp.asarray(tabs), jnp.asarray(lens),
                jax.random.PRNGKey(0), jnp.int32(0),
                eps=sp["eps"], kvh=sp["kvh"],
                head_dim=sp["head_dim"], transpose_head=sp["tied"],
                n_steps=1, collect_probs=False, shardings=None)
            (_, dcache.k_pages, dcache.v_pages, dcache.k_scales,
             dcache.v_scales) = res
            dcache.advance(dslots, 1)

        # -- propose: spec_k free-running draft tokens per row as ONE
        # program; the host advances only k_run (overrun rows are
        # garbage-by-construction: within the slot's reservation they
        # sit above the length watermark, past it the zero table
        # entries land them in pad page 0)
        ids = np.zeros(B, np.int32)
        pos = np.zeros(B, np.int32)
        tabs = np.zeros((B, maxp_d), np.int32)
        lens = np.zeros(B, np.int32)
        dslots = [r["dslot"] for r in rows]
        for j, r in enumerate(rows):
            dcache.extend(r["dslot"], k_run)
            ids[j] = r["last"]
            pos[j] = r["cur"]
            tabs[j] = dcache.page_table[r["dslot"]]
            lens[j] = r["cur"]
        db = rows[0]["row"] if len(rows) == 1 else 0
        res = _insp.watched_call(
            "engine.spec_draft", _spec_mod._paged_draft_propose,
            sp["stack"], sp["norm_w"], sp["head_w"], sp["embed_w"],
            sp["rope"], dcache.k_pages, dcache.v_pages,
            dcache.k_scales, dcache.v_scales,
            jnp.asarray(ids), jnp.asarray(pos), jnp.asarray(tabs),
            jnp.asarray(lens), draft_root, jnp.int32(db),
            eps=sp["eps"], kvh=sp["kvh"], head_dim=sp["head_dim"],
            transpose_head=sp["tied"], strategy=self.decode_strategy,
            top_k=self.top_k, top_p=self.top_p,
            temperature=self.temperature, n_steps=self.spec_k,
            collect_probs=sampled, shardings=None)
        if sampled:
            (toks_d, dcache.k_pages, dcache.v_pages, dcache.k_scales,
             dcache.v_scales, q_all) = res
            q_all = np.asarray(jax.device_get(q_all), np.float64)
        else:
            (toks_d, dcache.k_pages, dcache.v_pages, dcache.k_scales,
             dcache.v_scales) = res
        toks_d = np.asarray(jax.device_get(toks_d))  # [spec_k, B]
        dcache.advance(dslots, k_run)

        # -- verify: ONE ragged mixed dispatch scores every row's
        # whole draft window — k_run + 1 rows [last, d_1..d_k] per
        # sequence, descriptors split at page boundaries for the TPU
        # kernel's ``kv_len % P + q_len <= P`` contract (descriptor
        # index = the segment's first flat row, so live descriptors
        # never collide with pad rows' self-descriptors)
        P = self.cache.page_size
        maxp = self.cache.page_table.shape[1]
        T = self.max_seqs * (self.spec_k + 1)
        kw = k_run + 1
        v_ids = np.zeros(T, np.int32)
        positions = np.zeros(T, np.int32)
        row_tables = np.zeros((T, maxp), np.int32)
        q_start = np.zeros(T, np.int32)
        q_len = np.zeros(T, np.int32)
        kv_len = np.zeros(T, np.int32)
        desc_tables = np.zeros((T, maxp), np.int32)
        desc_of_row = np.arange(T, dtype=np.int32)
        off_of_row = np.zeros(T, np.int32)
        slots = [r["slot"] for r in rows]
        for i, r in enumerate(rows):
            self.cache.extend(r["slot"], kw)
            tbl = self.cache.page_table[r["slot"]]
            r0 = i * kw
            v_ids[r0] = r["last"]
            v_ids[r0 + 1:r0 + kw] = toks_d[:k_run, i]
            positions[r0:r0 + kw] = np.arange(r["cur"],
                                              r["cur"] + kw)
            row_tables[r0:r0 + kw] = tbl
            s = 0
            while s < kw:
                pos0 = r["cur"] + s
                seg = min(kw - s, P - pos0 % P)
                d = r0 + s
                q_start[d] = r0 + s
                q_len[d] = seg
                kv_len[d] = pos0
                desc_tables[d] = tbl
                desc_of_row[r0 + s:r0 + s + seg] = d
                off_of_row[r0 + s:r0 + s + seg] = np.arange(seg)
                s += seg
        res = _insp.watched_call(
            "engine.spec_verify", _paged_mixed_step,
            self._stack, self._norm_w, self._head_w, self._embed_w,
            self._rope, self.cache.k_pages, self.cache.v_pages,
            self.cache.k_scales, self.cache.v_scales,
            jnp.asarray(v_ids), jnp.asarray(positions),
            jnp.asarray(row_tables), jnp.asarray(q_start),
            jnp.asarray(q_len), jnp.asarray(kv_len),
            jnp.asarray(desc_tables), jnp.asarray(desc_of_row),
            jnp.asarray(off_of_row), sub, jnp.int32(0),
            eps=self.eps, kvh=self.kvh, head_dim=self.head_dim,
            transpose_head=self._tied, strategy=self.decode_strategy,
            top_k=self.top_k, top_p=self.top_p,
            temperature=self.temperature, shardings=self._shardings,
            arch=self._arch, return_probs=sampled)
        (nxt, self.cache.k_pages, self.cache.v_pages,
         self.cache.k_scales, self.cache.v_scales, _) = res[:6]
        if self._arch is not None:
            self._note_expert_counts(
                res[6], len(rows) * kw * self._arch.top_k)
        if sampled:
            p_all = np.asarray(jax.device_get(res[-1]), np.float64)
        nxt = np.asarray(jax.device_get(nxt))
        self.cache.advance(slots, kw)

        # -- accept + rejected-suffix rollback on both caches: the
        # target keeps rows for [last, d_1..d_a] (the delivered
        # correction/bonus token's KV appends next window); the draft
        # keeps [last, d_1..d_{a-1}] when a < k_run (mirror level
        # cur + a + 1) and stays one short after full acceptance —
        # next window's catch-up teacher-forces d_k
        out = []
        for i, r in enumerate(rows):
            r0 = i * kw
            if sampled:
                toks, a = _spec_mod.rejection_accept(
                    toks_d[:k_run, i], q_all[:k_run, i],
                    p_all[r0:r0 + kw], accept_root, resample_root,
                    r["row"])
            else:
                toks, a = _spec_mod.greedy_accept(
                    toks_d[:k_run, i], nxt[r0:r0 + kw])
            self.cache.rollback(r["slot"], k_run - a)
            if a < k_run:
                dcache.rollback(r["dslot"], k_run - a - 1)
            out.append((toks, a))
        return out

    def _step_spec(self) -> Dict[object, List[int]]:
        """The speculative decode window: draft-propose ``k_run``
        tokens per active request, verify them all in ONE ragged
        target dispatch, deliver the accepted prefix plus the
        correction/bonus token.  Greedy acceptance is BIT-IDENTICAL to
        plain decode (the verify rows' argmaxes ARE the plain stream);
        rejection acceptance preserves the target's post-filter
        sampling distribution for any draft.  Windows with pending
        prefill fall back to the plain unified step — chunked prefill
        interleaving is that path's job, and plain greedy windows are
        the same token stream anyway; drafts catch back up at the next
        speculative window."""
        import jax

        if self._prefilling:
            return self._step_mixed()
        if not self._active:
            return {}
        batch = list(self._active)
        for req in batch:
            if req.draft_slot is None:
                self._spec_attach(req)
        # runtime draft length: never draft past the tightest budget
        # (the window delivers at most k_run + 1 <= remaining + 1
        # tokens; the merge loop truncates the last one exactly like a
        # plain multi-step window)
        k_run = min([self.spec_k] +
                    [r.max_new - len(r.out) for r in batch])
        k_run = max(k_run, 1)
        self._key, sub = jax.random.split(self._key)
        rows = [{"slot": r.slot, "dslot": r.draft_slot,
                 "last": r.out[-1],
                 "cur": len(r.prompt) + len(r.out) - 1,
                 "seq": list(r.prompt) + r.out, "row": i}
                for i, r in enumerate(batch)]
        t_win = time.perf_counter()
        span = _tracing.span("engine.spec_window")
        span.set_attr("rows", len(batch))
        span.set_attr("k_run", k_run)
        try:
            with RecordEvent("llm_engine.decode"):
                results = self._spec_window(rows, sub, k_run)
        finally:
            span.end()
        dt_win = time.perf_counter() - t_win

        out = {}
        accepted = {}
        for i, req in enumerate(batch):
            toks, _a = results[i]
            accepted[req.rid] = int(_a)
            new_toks = []
            for tok in toks:
                if req.done:
                    break
                req.out.append(tok)
                new_toks.append(tok)
                if (req.eos is not None and tok == req.eos) or \
                        len(req.out) >= req.max_new:
                    req.done = True
                    self.cache.release(req.slot)
                    self._spec_release(req)
                    self._active.remove(req)
            if new_toks:
                out[req.rid] = new_toks
        delivered = max((len(v) for v in out.values()), default=0)
        self.last_window_steps = delivered

        n_prop = len(batch) * k_run
        n_acc = sum(a for (_, a) in results)
        st = self.spec_stats
        st["windows"] += 1
        st["proposed"] += n_prop
        st["accepted"] += n_acc
        st["delivered"] += sum(len(v) for v in out.values())

        cs = _capsule.get_capsule_store()
        if cs.enabled and out:
            cs.on_window(out, _sampling.key_fingerprint(sub),
                         k_run + 1, delivered, "spec_window",
                         rows={r.rid: i for i, r in enumerate(batch)},
                         accepted=accepted)
        # TPOT counts only DELIVERED tokens: dt_win amortizes over the
        # window's real payoff, so a low-acceptance draft shows up as
        # WORSE per-token latency, not phantom throughput (proposed-
        # but-rejected tokens never touch the histogram or the AIMD
        # SLO window)
        if delivered:
            _health.get_health().observe_tpot(dt_win / delivered,
                                              n=delivered)
        if self._metrics is not None:
            m = self._metrics
            if delivered:
                m["tpot"].observe(dt_win / delivered, n=delivered)
            m["generated_tokens"].inc(
                sum(len(v) for v in out.values()))
            m["queue_depth"].set(len(self._active))
            m["occupancy"].set(len(batch) / self.max_seqs)
            m["spec_proposed"].inc(n_prop)
            m["spec_accepted"].inc(n_acc)
            if st["proposed"]:
                m["spec_rate"].set(st["accepted"] / st["proposed"])
            for _, a in results:
                m["spec_len"].observe(float(a))
            self._record_compiles()
        return out

    # -- admission -------------------------------------------------------------
    def add_request(self, rid, prompt_ids, max_new_tokens: int = 64,
                    eos_token_id: Optional[int] = None):
        """Prefill the prompt into pages; the request joins the decode
        batch at the next step().

        The prompt runs through page-size CHUNKS of one compiled
        program (each chunk fills exactly one page in-graph), so a
        mixed-length request stream costs ONE prefill compile total
        (assert with ``prefill_compiles()``) — round 2 recompiled per
        prompt, round 4 per power-of-two bucket.

        Automatic prefix caching (on by default): the longest cached
        page-aligned prefix of the prompt is mapped into the slot's
        page table WITHOUT touching the device, and the chunk loop
        runs only over the uncached tail — same compiled program, it
        just starts at a later chunk, so ``prefill_compiles() == 1``
        survives.  The cacheable prefix is capped strictly below the
        prompt length: the chunk holding the last prompt token always
        recomputes (into a private page), which is what produces the
        first-token logits even when the whole prompt is cached."""
        import jax
        import jax.numpy as jnp

        t_admit = time.perf_counter()
        enforce(rid not in self.requests, f"duplicate request id {rid!r}")
        enforce(max_new_tokens >= 1, "max_new_tokens must be >= 1")
        req = GenRequest(rid, prompt_ids, max_new_tokens, eos_token_id)
        plen = len(req.prompt)
        enforce(plen >= 1, "empty prompt")
        total = plen + max_new_tokens
        limit = min(self.max_len,
                    self.model.config.max_position_embeddings)
        enforce(total <= limit,
                f"prompt ({plen}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the engine/model limit "
                f"{limit}")
        P = self.cache.page_size
        cached, shared_pages = 0, []
        if self.enable_prefix_caching:
            # cap at the last page boundary STRICTLY below plen so the
            # final chunk (the one whose logits seed decoding) always
            # runs — shared pages stay immutable, logits stay real
            cacheable = ((plen - 1) // P) * P
            cached, shared_pages = self.cache.lookup_prefix(
                req.prompt[:cacheable])
        req.slot = self.cache.allocate(total, shared_pages=shared_pages)

        # CHUNKED ragged prefill (round 5): page-size chunks, each one
        # filling exactly one page in-graph — ONE compiled program for
        # any prompt-length mix (prefill_compiles() == 1), vs the r4
        # power-of-two buckets (one compile per bucket).  Cached-prefix
        # chunks are skipped: their pages are already written.
        try:
            with RecordEvent("llm_engine.prefill"):
                logits = self._prefill_seq(req.slot, req.prompt,
                                           cached // P)
                self.cache.set_len(req.slot, plen)
                if self.enable_prefix_caching:
                    # publish this prompt's full pages (the just-
                    # prefilled ones included) for future requests
                    self.cache.register_prefix(
                        req.slot, req.prompt, upto=(plen // P) * P)

                self._key, sub = jax.random.split(self._key)
                from ..nn.generation import sample_logits
                # row_ids=[0]: the synchronous first token draws as
                # row 0 — exactly what anchored capsule replay re-folds
                first_tok, _ = sample_logits(
                    logits[None], sub, strategy=self.decode_strategy,
                    top_k=self.top_k, top_p=self.top_p,
                    temperature=self.temperature,
                    row_ids=np.zeros(1, np.int32))
                first = int(np.asarray(first_tok)[0])
        except BaseException:
            # chunked prefill / sampling failed: the slot (and its
            # page references) must not leak — release, then re-raise
            self.cache.release(req.slot)
            raise
        req.out.append(first)
        self.requests[rid] = req
        st = self.prefix_stats
        st["hit_tokens"] += cached
        st["miss_tokens"] += plen - cached
        st["shared_pages"] += len(shared_pages)
        st["hit_requests" if cached else "miss_requests"] += 1
        # capsule capture (one global read; no-op on the NULL store):
        # the admission subkey IS the key anchor — replay re-samples
        # the first token with exactly these words
        cs = _capsule.get_capsule_store()
        if cs.enabled:
            cs.begin(rid, prompt=list(req.prompt),
                     max_new=req.max_new, eos=req.eos,
                     fingerprint=self._capsule_fp,
                     key_anchor=_sampling.key_fingerprint(sub),
                     prefix={"hit_tokens": int(cached),
                             "shared_pages": len(shared_pages)},
                     tokens=[first])
        # the int() above synced the device: TTFT is honest
        ttft = time.perf_counter() - t_admit
        _health.get_health().observe_ttft(ttft)
        if self._metrics is not None:
            m = self._metrics
            m["ttft"].observe(ttft)
            m["prompt_tokens"].inc(plen)
            m["generated_tokens"].inc(1)
            m["requests"].inc()
            m["prefix_hit_tokens"].inc(cached)
            m["prefix_miss_tokens"].inc(plen - cached)
            m["prefix_shared_pages"].inc(len(shared_pages))
            seen = st["hit_tokens"] + st["miss_tokens"]
            m["prefix_hit_rate"].set(st["hit_tokens"] / seen
                                     if seen else 0.0)
            self._record_compiles()
        # the prefill-produced token counts toward the limits too
        if (req.eos is not None and first == req.eos) or \
                req.max_new <= 1:
            req.done = True
            self.cache.release(req.slot)
        else:
            self._active.append(req)
        if self._metrics is not None:
            self._metrics["queue_depth"].set(len(self._active))
        return rid

    def begin_request(self, rid, prompt_ids, max_new_tokens: int = 64,
                      eos_token_id: Optional[int] = None):
        """DEFERRED admission for the ragged unified step: reserve the
        slot and page budget now, but run the prompt's prefill inside
        subsequent ``step()`` calls — page-sized chunks ride the same
        mixed-batch dispatch as every ongoing decode, up to the
        per-step ``prefill_token_budget``, so a long prompt never
        stalls in-flight decodes (the chunk-level-admission half of
        the head-of-line fix; ``add_request`` remains the synchronous
        prefill-then-join path).  The first token arrives in a later
        ``step()`` return value, exactly like every other token.
        Prefix caching applies as in ``add_request``: cached pages map
        in host-side and the chunk stream starts at the first uncached
        position."""
        enforce(self.unified_step,
                "begin_request requires unified_step=True (the split-"
                "program engine admits synchronously via add_request)")
        enforce(rid not in self.requests, f"duplicate request id {rid!r}")
        enforce(max_new_tokens >= 1, "max_new_tokens must be >= 1")
        req = GenRequest(rid, prompt_ids, max_new_tokens, eos_token_id)
        plen = len(req.prompt)
        enforce(plen >= 1, "empty prompt")
        total = plen + max_new_tokens
        limit = min(self.max_len,
                    self.model.config.max_position_embeddings)
        enforce(total <= limit,
                f"prompt ({plen}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the engine/model limit "
                f"{limit}")
        P = self.cache.page_size
        cached, shared_pages = 0, []
        if self.enable_prefix_caching:
            cacheable = ((plen - 1) // P) * P
            cached, shared_pages = self.cache.lookup_prefix(
                req.prompt[:cacheable])
        req.slot = self.cache.allocate(total, shared_pages=shared_pages)
        req.pf_pos = cached
        req.t_submit = time.perf_counter()
        self.requests[rid] = req
        self._prefilling.append(req)
        # capsule capture: no key anchor on the deferred path — the
        # first token arrives inside a later mixed window, whose key
        # the window record carries like any other step's
        cs = _capsule.get_capsule_store()
        if cs.enabled:
            cs.begin(rid, prompt=list(req.prompt),
                     max_new=req.max_new, eos=req.eos,
                     fingerprint=self._capsule_fp, key_anchor=None,
                     prefix={"hit_tokens": int(cached),
                             "shared_pages": len(shared_pages)},
                     tokens=[])
        st = self.prefix_stats
        st["hit_tokens"] += cached
        st["miss_tokens"] += plen - cached
        st["shared_pages"] += len(shared_pages)
        st["hit_requests" if cached else "miss_requests"] += 1
        if self._metrics is not None:
            m = self._metrics
            m["prompt_tokens"].inc(plen)
            m["requests"].inc()
            m["prefix_hit_tokens"].inc(cached)
            m["prefix_miss_tokens"].inc(plen - cached)
            m["prefix_shared_pages"].inc(len(shared_pages))
            seen = st["hit_tokens"] + st["miss_tokens"]
            m["prefix_hit_rate"].set(st["hit_tokens"] / seen
                                     if seen else 0.0)
        return rid

    # -- decode loop -----------------------------------------------------------
    def step(self) -> Dict[object, List[int]]:
        """One serving step: returns {request_id: [new tokens]} and
        retires finished requests (streaming callers see every
        intermediate token).

        With ``unified_step=True`` (default) this is the RAGGED MIXED
        step: one compiled program packs every active decode slot plus
        up to ``prefill_token_budget`` tokens of pending
        ``begin_request`` prefill chunks — prefill rides alongside
        decode instead of stalling it.  Tokens are bit-identical to
        the split-program path (greedy decoding; the per-row programs
        agree op for op).  With ``unified_step=False`` the original
        split decode-only dispatch runs (``_paged_decode_step``).

        A ``draft_model`` engine routes pure-decode windows through
        the speculative path (``_step_spec``): greedy streams stay
        bit-identical to plain decode, sampled streams stay
        distributionally exact — only the tokens-per-dispatch ratio
        changes."""
        if self._spec is not None:
            return self._step_spec()
        if self.unified_step:
            return self._step_mixed()
        return self._step_split()

    def _step_split(self) -> Dict[object, List[int]]:
        """Decode up to ``steps_per_sync`` tokens for every active
        request in one device dispatch.  The host only
        syncs (EOS checks, admission window) once per call, so over a
        high-latency dispatch path (remote PJRT) throughput scales with
        steps_per_sync; the window never exceeds any request's
        remaining token budget, so page capacity is exact.  With
        ``scan_decode`` (default) multi-step windows run the early-exit
        ``_paged_decode_window`` while_loop program; otherwise the
        fixed-length ``_paged_decode_step`` scan."""
        import jax
        import jax.numpy as jnp

        if not self._active:
            return {}
        batch = list(self._active)
        n = len(batch)
        nsteps = min([self.steps_per_sync] +
                     [r.max_new - len(r.out) for r in batch])
        nsteps = max(nsteps, 1)
        # bucket the window to a power of two so ragged remaining
        # budgets compile at most log2(steps_per_sync) decode programs
        # (n_steps is a static jit arg), not one per distinct tail
        while nsteps & (nsteps - 1):
            nsteps &= nsteps - 1
        # pad to max_seqs: continuous batching must keep ONE compiled
        # shape as requests join/leave (dummy rows write into the
        # reserved pad page 0 with len 0 and are discarded)
        pad = self.max_seqs - n
        slots = np.array([r.slot for r in batch])
        tokens = np.array([r.out[-1] for r in batch] + [0] * pad,
                          np.int32)
        for s in slots:
            self.cache.extend(int(s), nsteps)
        lens = np.concatenate([self.cache.seq_lens[slots],
                               np.zeros(pad, np.int32)])
        tables = np.concatenate(
            [self.cache.page_table[slots],
             np.zeros((pad,) + self.cache.page_table.shape[1:],
                      np.int32)])

        self._key, sub = jax.random.split(self._key)
        t_win = time.perf_counter()
        with RecordEvent("llm_engine.decode"):
            if self.scan_decode and nsteps > 1:
                # on-device window: one while_loop program runs the
                # whole window, exiting early once every row retired
                # (EOS/budget tracked in-graph — same predicate as the
                # merge loop below)
                eos_ids = np.full(self.max_seqs, -1, np.int32)
                budgets = np.ones(self.max_seqs, np.int32)
                for i, r in enumerate(batch):
                    if r.eos is not None:
                        eos_ids[i] = r.eos
                    budgets[i] = r.max_new - len(r.out)
                res = _insp.watched_call(
                    "engine.decode_window", _paged_decode_window,
                    self._stack, self._norm_w, self._head_w,
                    self._embed_w, self._rope, self.cache.k_pages,
                    self.cache.v_pages, self.cache.k_scales,
                    self.cache.v_scales, jnp.asarray(tokens),
                    jnp.asarray(lens, np.int32),
                    jnp.asarray(tables),
                    jnp.asarray(lens, np.int32), sub,
                    jnp.int32(0),
                    jnp.asarray(eos_ids), jnp.asarray(budgets),
                    jnp.int32(n),
                    eps=self.eps, kvh=self.kvh,
                    head_dim=self.head_dim,
                    transpose_head=self._tied,
                    strategy=self.decode_strategy,
                    top_k=self.top_k, top_p=self.top_p,
                    temperature=self.temperature, n_steps=nsteps,
                    shardings=self._shardings, arch=self._arch)
                (toks, _, steps_d, self.cache.k_pages,
                 self.cache.v_pages, self.cache.k_scales,
                 self.cache.v_scales) = res[:7]
                steps_done = int(jax.device_get(steps_d))
                if self._arch is not None:
                    self._note_expert_counts(
                        res[7], n * self._arch.top_k * steps_done)
            else:
                res = _insp.watched_call(
                    "engine.decode_step", _paged_decode_step,
                    self._stack, self._norm_w, self._head_w,
                    self._embed_w, self._rope, self.cache.k_pages,
                    self.cache.v_pages, self.cache.k_scales,
                    self.cache.v_scales, jnp.asarray(tokens),
                    jnp.asarray(lens, np.int32),
                    jnp.asarray(tables),
                    jnp.asarray(lens, np.int32), sub,
                    jnp.int32(0),
                    eps=self.eps, kvh=self.kvh,
                    head_dim=self.head_dim,
                    transpose_head=self._tied,
                    strategy=self.decode_strategy,
                    top_k=self.top_k, top_p=self.top_p,
                    temperature=self.temperature, n_steps=nsteps,
                    shardings=self._shardings, arch=self._arch)
                (toks, self.cache.k_pages, self.cache.v_pages,
                 self.cache.k_scales, self.cache.v_scales) = res[:5]
                if self._arch is not None:
                    self._note_expert_counts(
                        res[5], n * self._arch.top_k * nsteps)
                steps_done = nsteps
            self.cache.advance(slots, steps_done)
            # [steps_done, n]
            toks = np.asarray(jax.device_get(toks))[:steps_done, :n]
        dt_win = time.perf_counter() - t_win
        self.last_window_steps = steps_done

        # contract (ADVICE r3): with steps_per_sync > 1 a window emits
        # up to nsteps tokens per request — return the LIST of new
        # tokens per rid so streaming callers never lose intermediates
        out = {}
        for i, req in enumerate(batch):
            new_toks = []
            for j in range(steps_done):
                if req.done:
                    break
                tok = int(toks[j, i])
                req.out.append(tok)
                new_toks.append(tok)
                if (req.eos is not None and tok == req.eos) or \
                        len(req.out) >= req.max_new:
                    req.done = True
                    self.cache.release(req.slot)
                    self._spec_release(req)
                    self._active.remove(req)
            if new_toks:
                out[req.rid] = new_toks
        # capsule capture: one window record per captured rid — the
        # forked window key anchors the in-window split_step chain, so
        # replay reproduces the draws key for key
        cs = _capsule.get_capsule_store()
        if cs.enabled and out:
            cs.on_window(out, _sampling.key_fingerprint(sub), nsteps,
                         steps_done,
                         "decode_window"
                         if self.scan_decode and nsteps > 1
                         else "decode_step",
                         rows={r.rid: i for i, r in enumerate(batch)})
        # TPOT counts only tokens actually DELIVERED to a stream: a
        # request that retired mid-window stops contributing positions
        # (the fixed window-boundary over-count), and the window's
        # per-token wall time is wall / steps actually run
        delivered = max((len(v) for v in out.values()), default=0)
        if delivered:
            _health.get_health().observe_tpot(dt_win / steps_done,
                                              n=delivered)
        if self._metrics is not None:
            m = self._metrics
            # ONE weighted observe per window: value is the wall time a
            # stream waits per token, count advances by the window's
            # DELIVERED token positions — O(1) recording however long
            # the window
            if delivered:
                m["tpot"].observe(dt_win / steps_done, n=delivered)
            m["generated_tokens"].inc(
                sum(len(v) for v in out.values()))
            m["queue_depth"].set(len(self._active))
            m["occupancy"].set(n / self.max_seqs)
            self._record_compiles()
        return out

    def _step_mixed(self) -> Dict[object, List[int]]:
        """The ragged unified step: ONE ``_paged_mixed_step`` dispatch
        carries every active decode slot (1 row each — the slot→row
        map is compacted host-side, no padded dead slots) plus pending
        prefill chunks packed FIFO up to the runtime
        ``prefill_token_budget`` (chunks never cross page boundaries,
        so one request may contribute several descriptors).  When no
        prefill is pending, the ``steps_per_sync`` window dispatches
        ONCE as the on-device ``_paged_mixed_window`` program
        (scan_decode, power-of-two buckets, early exit) or — with
        ``scan_decode=False`` — as host-chained single-token
        dispatches of the mixed program; both orders are bit-identical
        by construction."""
        import jax
        import jax.numpy as jnp

        if not self._active and not self._prefilling:
            return {}
        P = self.cache.page_size
        maxp = self.cache.page_table.shape[1]
        t_cap = self.max_seqs + self._pf_budget_static
        batch = list(self._active)
        n = len(batch)

        # prefill plan: (req, pos, chunk_len, first_row, descriptor).
        # The runtime budget is clamped to the static one (T is fixed)
        # and floored at 1 when only prefill is pending — a zero
        # budget must not livelock has_work().
        budget = max(0, min(int(self.prefill_token_budget),
                            self._pf_budget_static))
        if not batch and budget == 0:
            budget = min(P, self._pf_budget_static)
        # capacity-factor MoE defines its drop ranks per page-group =
        # page chunk, so the planner must pack WHOLE chunks (a split
        # chunk would rank differently than the split prefill path);
        # floor the runtime budget to one chunk when only prefill is
        # pending so a low budget can't livelock has_work()
        whole_chunks = self._arch is not None and \
            self._arch.capacity > 0
        if whole_chunks and not batch:
            budget = max(budget, min(P, self._pf_budget_static))
        plan = []
        finishing = []                        # (req, last_row)
        cursor, desc_i, used = n, n, 0
        stop = False
        for req in self._prefilling:
            plen = len(req.prompt)
            pos = req.pf_pos
            while pos < plen and used < budget:
                chunk = min(P - pos % P, plen - pos)
                if whole_chunks and used + chunk > budget:
                    stop = True
                    break
                cl = min(chunk, budget - used)
                plan.append((req, pos, cl, cursor, desc_i))
                pos += cl
                cursor += cl
                used += cl
                desc_i += 1
            if pos >= plen:
                finishing.append((req, cursor - 1))
            if stop or used >= budget:
                break
        if not batch and not plan:
            return {}

        if plan or n == 0:
            nsteps = 1
        else:
            nsteps = min([self.steps_per_sync] +
                         [r.max_new - len(r.out) for r in batch])
            nsteps = max(nsteps, 1)
            while nsteps & (nsteps - 1):
                nsteps &= nsteps - 1
        slots = np.array([r.slot for r in batch], np.int64)
        for r in batch:
            self.cache.extend(r.slot, nsteps)

        ids = np.zeros(t_cap, np.int32)
        positions = np.zeros(t_cap, np.int32)
        row_tables = np.zeros((t_cap, maxp), np.int32)
        q_start = np.zeros(t_cap, np.int32)
        q_len = np.zeros(t_cap, np.int32)
        kv_len = np.zeros(t_cap, np.int32)
        desc_tables = np.zeros((t_cap, maxp), np.int32)
        # padding rows point at their own (q_len == 0) descriptor,
        # whose kernel output block is zeroed — never garbage
        desc_of_row = np.arange(t_cap, dtype=np.int32)
        off_of_row = np.zeros(t_cap, np.int32)
        if n:
            ids[:n] = [r.out[-1] for r in batch]
            lens = self.cache.seq_lens[slots]
            positions[:n] = lens
            row_tables[:n] = self.cache.page_table[slots]
            q_start[:n] = np.arange(n)
            q_len[:n] = 1
            kv_len[:n] = lens
            desc_tables[:n] = row_tables[:n]
        for req, pos, cl, row0, d in plan:
            tbl = self.cache.page_table[req.slot]
            ids[row0:row0 + cl] = req.prompt[pos:pos + cl]
            positions[row0:row0 + cl] = np.arange(pos, pos + cl)
            row_tables[row0:row0 + cl] = tbl
            q_start[d] = row0
            q_len[d] = cl
            kv_len[d] = pos
            desc_tables[d] = tbl
            desc_of_row[row0:row0 + cl] = d
            off_of_row[row0:row0 + cl] = np.arange(cl)

        self._key, sub = jax.random.split(self._key)
        key = sub
        toks_all = []
        steps_done = nsteps
        t_win = time.perf_counter()
        span = _tracing.span("engine.mixed_step")
        span.set_attr("decode_slots", n)
        span.set_attr("prefill_tokens", int(used))
        span.set_attr("nsteps", nsteps)
        try:
            with RecordEvent("llm_engine.decode"):
                if self.scan_decode and nsteps > 1:
                    # ON-DEVICE window (pure decode by construction —
                    # prefill plans force nsteps == 1): the whole
                    # attend → sample → append chain runs as one
                    # while_loop program that exits as soon as every
                    # row has retired, syncing the host once
                    eos_ids = np.full(t_cap, -1, np.int32)
                    budgets = np.ones(t_cap, np.int32)
                    for i, r in enumerate(batch):
                        if r.eos is not None:
                            eos_ids[i] = r.eos
                        budgets[i] = r.max_new - len(r.out)
                    res = _insp.watched_call(
                        "engine.mixed_window", _paged_mixed_window,
                        self._stack, self._norm_w, self._head_w,
                        self._embed_w, self._rope,
                        self.cache.k_pages, self.cache.v_pages,
                        self.cache.k_scales, self.cache.v_scales,
                        jnp.asarray(ids), jnp.asarray(positions),
                        jnp.asarray(row_tables),
                        jnp.asarray(q_start), jnp.asarray(q_len),
                        jnp.asarray(kv_len),
                        jnp.asarray(desc_tables),
                        jnp.asarray(desc_of_row),
                        jnp.asarray(off_of_row), key,
                        jnp.int32(0),
                        jnp.asarray(eos_ids),
                        jnp.asarray(budgets), jnp.int32(n),
                        eps=self.eps, kvh=self.kvh,
                        head_dim=self.head_dim,
                        transpose_head=self._tied,
                        strategy=self.decode_strategy,
                        top_k=self.top_k, top_p=self.top_p,
                        temperature=self.temperature,
                        n_steps=nsteps,
                        shardings=self._shardings, arch=self._arch)
                    (toks_d, _, steps_d, self.cache.k_pages,
                     self.cache.v_pages, self.cache.k_scales,
                     self.cache.v_scales, key) = res[:8]
                    steps_done = int(jax.device_get(steps_d))
                    if self._arch is not None:
                        self._note_expert_counts(
                            res[8],
                            n * self._arch.top_k * steps_done)
                    toks_np = np.asarray(jax.device_get(toks_d))
                    toks_all = [toks_np[j] for j in range(steps_done)]
                    if n:
                        self.cache.advance(slots, steps_done)
                else:
                    for si in range(nsteps):
                        res = _insp.watched_call(
                            "engine.mixed_step", _paged_mixed_step,
                            self._stack, self._norm_w,
                            self._head_w, self._embed_w,
                            self._rope,
                            self.cache.k_pages, self.cache.v_pages,
                            self.cache.k_scales,
                            self.cache.v_scales,
                            jnp.asarray(ids),
                            jnp.asarray(positions),
                            jnp.asarray(row_tables),
                            jnp.asarray(q_start),
                            jnp.asarray(q_len),
                            jnp.asarray(kv_len),
                            jnp.asarray(desc_tables),
                            jnp.asarray(desc_of_row),
                            jnp.asarray(off_of_row), key,
                            jnp.int32(0),
                            eps=self.eps, kvh=self.kvh,
                            head_dim=self.head_dim,
                            transpose_head=self._tied,
                            strategy=self.decode_strategy,
                            top_k=self.top_k, top_p=self.top_p,
                            temperature=self.temperature,
                            shardings=self._shardings,
                            arch=self._arch)
                        (nxt, self.cache.k_pages, self.cache.v_pages,
                         self.cache.k_scales, self.cache.v_scales,
                         key) = res[:6]
                        if self._arch is not None:
                            # live rows this dispatch: n decode slots
                            # + the packed prefill tokens (used == 0
                            # past the first step — multi-step windows
                            # are pure decode)
                            self._note_expert_counts(
                                res[6],
                                (n + (used if si == 0 else 0))
                                * self._arch.top_k)
                        nxt = np.asarray(jax.device_get(nxt))
                        toks_all.append(nxt)
                        if n:
                            self.cache.advance(slots, 1)
                        if si + 1 < nsteps:
                            # host-chained window (pure decode): feed
                            # each slot's sampled token back as the
                            # next input
                            ids[:n] = nxt[:n]
                            positions[:n] += 1
                            kv_len[:n] += 1
        finally:
            span.set_attr("steps_done", steps_done)
            span.end()
        dt_win = time.perf_counter() - t_win
        self.last_window_steps = steps_done

        out = {}
        for i, req in enumerate(batch):
            new_toks = []
            for j in range(steps_done):
                if req.done:
                    break
                tok = int(toks_all[j][i])
                req.out.append(tok)
                new_toks.append(tok)
                if (req.eos is not None and tok == req.eos) or \
                        len(req.out) >= req.max_new:
                    req.done = True
                    self.cache.release(req.slot)
                    self._spec_release(req)
                    self._active.remove(req)
            if new_toks:
                out[req.rid] = new_toks
        # decode tokens DELIVERED this window (prefill-completing first
        # tokens are TTFT, appended to `out` below, never TPOT)
        delivered = max((len(v) for v in out.values()), default=0)

        # prefill bookkeeping AFTER the dispatch succeeded — a raise
        # above leaves every pf_pos where it was (no token lost)
        for req, pos, cl, row0, d in plan:
            req.pf_pos = pos + cl
        for req, last_row in finishing:
            first = int(toks_all[0][last_row])
            plen = len(req.prompt)
            self.cache.set_len(req.slot, plen)
            if self.enable_prefix_caching:
                self.cache.register_prefix(req.slot, req.prompt,
                                           upto=(plen // P) * P)
            req.out.append(first)
            self._prefilling.remove(req)
            out[req.rid] = [first]
            if req.t_submit is not None:
                ttft = time.perf_counter() - req.t_submit
                _health.get_health().observe_ttft(ttft)
                if self._metrics is not None:
                    self._metrics["ttft"].observe(ttft)
            if (req.eos is not None and first == req.eos) or \
                    req.max_new <= 1:
                req.done = True
                self.cache.release(req.slot)
                self._spec_release(req)
            else:
                self._active.append(req)
        # capsule capture after the finishing loop, so prefill-
        # completing first tokens ride the same window record as the
        # decode tokens (the forked key `sub` anchors the whole
        # window's split_step chain, host-chained or scanned)
        cs = _capsule.get_capsule_store()
        if cs.enabled and out:
            # per-rid draw rows: decode slots are rows 0..n-1 in batch
            # order; a prefill-finishing first token drew at its chunk's
            # last flat row — recorded so stochastic replay can re-fold
            # the exact draw id whatever slot the request decoded in
            rows = {r.rid: i for i, r in enumerate(batch)}
            for req, last_row in finishing:
                rows[req.rid] = int(last_row)
            cs.on_window(out, _sampling.key_fingerprint(sub), nsteps,
                         steps_done,
                         "mixed_window"
                         if self.scan_decode and nsteps > 1
                         else "mixed_step", rows=rows)
        # TPOT over-count fix: only DELIVERED decode positions advance
        # the histogram / SLO window — a window whose requests all
        # finished early contributes its real token count, not nsteps;
        # pure-prefill steps contribute nothing (their latency is TTFT)
        if delivered:
            _health.get_health().observe_tpot(dt_win / steps_done,
                                              n=delivered)
        if self._metrics is not None:
            m = self._metrics
            if delivered:
                m["tpot"].observe(dt_win / steps_done, n=delivered)
            m["generated_tokens"].inc(
                sum(len(v) for v in out.values()))
            m["queue_depth"].set(len(self._active))
            m["occupancy"].set(n / self.max_seqs)
            m["mixed_decode_slots"].set(n)
            m["mixed_prefill_tokens"].set(used)
            self._record_compiles()
        return out

    def has_work(self) -> bool:
        return bool(self._active or self._prefilling)

    # -- admission-control introspection ---------------------------------------
    def free_slots(self) -> int:
        """Sequence slots available for admission right now.  Paired
        with ``cache.free_pages()`` this lets a scheduler decide
        admission WITHOUT try/except on the OOM raise: a request fits
        iff ``free_slots() >= 1`` and ``cache.free_pages() >=
        ceil((len(prompt) + max_new_tokens) / page_size)`` (the engine
        reserves the full page budget at admission, so a request that
        admits can always decode to its budget)."""
        return self.cache.free_slot_count()

    def capacity(self) -> tuple:
        """ATOMIC admission snapshot: ``(free_slots, free_pages)`` in
        one call.  Invariant (the scheduler relies on it): every
        capacity-mutating engine operation — ``add_request``,
        ``step``, ``abort``, ``suspend``, ``resume`` — runs under the
        scheduler's lock on the stepping thread, so a snapshot taken
        inside that lock stays exact until the admission decision acts
        on it.  Reading ``free_slots()`` and ``cache.free_pages()``
        as two separate calls invites drift the moment anything (a
        preemption, a retirement) frees capacity between them —
        admission must use this helper."""
        return self.cache.free_slot_count(), self.cache.free_pages()

    def suspended_count(self) -> int:
        """Live requests currently preempted out of the decode batch
        (they hold no slot or device pages)."""
        return sum(1 for r in self.requests.values()
                   if r.suspended and not r.done)

    # -- preemption ------------------------------------------------------------
    def suspend(self, rid) -> bool:
        """Preempt an ACTIVE request: capture its generated-so-far
        tokens (they stay on the request record), swap its KV pages
        into the cache's host pool (or just release them when the pool
        is full — resume then recomputes), and free its slot.  The
        freed slot + pages are the point: a higher-priority request
        can admit into them NOW.  Returns True when the swap path is
        armed, False when resume will recompute.  Suspended requests
        still ``result()``-raise like active ones and can be
        ``abort()``-ed (their swap entry is dropped)."""
        enforce(rid in self.requests,
                f"unknown request id {rid!r} (never admitted to this "
                f"engine)")
        req = self.requests[rid]
        enforce(not req.done, f"request {rid!r} already retired")
        enforce(not req.suspended, f"request {rid!r} already suspended")
        if req in self._prefilling:
            # mid-prefill preemptee (begin_request, prefill not done):
            # its partial KV is cheaper to recompute than to swap —
            # release the pages outright; resume restarts the chunk
            # stream (prefix-cache hits still skip cached pages)
            self._prefilling.remove(req)
            with _tracing.span("engine.swap_out") as sp:
                self.cache.release(req.slot)
                req.swap_handle = None
                sp.set_attr("rid", str(rid))
                sp.set_attr("armed", False)
            req.slot = None
            req.suspended = True
            req.pf_pos = 0
            if self._metrics is not None:
                self._metrics["suspended"].inc()
                self._metrics["queue_depth"].set(len(self._active))
            return False
        self._active.remove(req)
        # the draft slot never swaps — a suspended draft is cheaper to
        # re-prefill at the next speculative window (lazy re-attach)
        # than to hold pages or pool space for
        self._spec_release(req)
        with _tracing.span("engine.swap_out") as sp:
            req.swap_handle = self.cache.swap_out(req.slot)
            sp.set_attr("rid", str(rid))
            sp.set_attr("armed", req.swap_handle is not None)
        req.slot = None
        req.suspended = True
        _capsule.get_capsule_store().event(
            rid, "suspend:swap" if req.swap_handle is not None
            else "suspend:drop")
        if self._metrics is not None:
            self._metrics["suspended"].inc()
            self._metrics["queue_depth"].set(len(self._active))
        return req.swap_handle is not None

    def resume(self, rid) -> str:
        """Re-admit a suspended request; it rejoins the decode batch
        at the next ``step()`` with tokens bit-identical to a run that
        was never preempted (greedy decoding — see the class
        docstring).  Returns the restore path taken: ``"swap_in"``
        (host pages copied back, no recompute) or ``"recompute"``
        (prompt replayed through the chunked-prefill program, the
        generated tokens through the compiled decode program — no new
        prefill compiles either way).  The caller must ensure capacity
        first (``capacity()``): the full page budget is re-reserved,
        exactly like admission."""
        enforce(rid in self.requests,
                f"unknown request id {rid!r} (never admitted to this "
                f"engine)")
        req = self.requests[rid]
        enforce(req.suspended and not req.done,
                f"request {rid!r} is not suspended")
        plen = len(req.prompt)
        total = plen + req.max_new
        if not req.out:
            # mid-prefill preemptee: re-reserve its budget and rejoin
            # the unified step's chunk stream — the prefill that ran
            # before the preemption recomputes (bit-identical rows)
            P = self.cache.page_size
            cached, shared_pages = 0, []
            if self.enable_prefix_caching:
                cacheable = ((plen - 1) // P) * P
                cached, shared_pages = self.cache.lookup_prefix(
                    req.prompt[:cacheable])
            req.slot = self.cache.allocate(total,
                                           shared_pages=shared_pages)
            req.pf_pos = cached
            req.suspended = False
            self._prefilling.append(req)
            if self._metrics is not None:
                self._metrics["resumed"].labels(
                    self.engine_id, "recompute").inc()
            return "recompute"
        path = None
        if req.swap_handle is not None:
            with _tracing.span("engine.swap_in") as sp:
                sp.set_attr("rid", str(rid))
                slot = self.cache.swap_in(req.swap_handle, total)
            req.swap_handle = None             # consumed either way
            if slot is not None:
                # KV restored byte-exact; length = prompt + generated
                # so far MINUS the last token (it is the next decode
                # input — its KV is appended by the next step)
                self.cache.set_len(slot, plen + len(req.out) - 1)
                path = "swap_in"
        if path is None:
            with RecordEvent("llm_engine.resume_recompute"):
                slot = self._recompute_resume(req)
            path = "recompute"
        req.slot = slot
        req.suspended = False
        self._active.append(req)
        _capsule.get_capsule_store().event(rid, f"resume:{path}")
        if self._metrics is not None:
            self._metrics["resumed"].labels(self.engine_id, path).inc()
            self._metrics["queue_depth"].set(len(self._active))
        return path

    def _recompute_resume(self, req):
        """Swapless resume: re-derive the suspended request's KV from
        its token history — the prompt through the SAME chunked
        prefill (prefix-cache hits still apply: the prompt's pages
        often still sit in the LRU pool), the generated tokens through
        the SAME decode program (``_replay_decode``).  Bit-identical
        state by construction: same programs, same inputs."""
        plen = len(req.prompt)
        P = self.cache.page_size
        cached, shared_pages = 0, []
        if self.enable_prefix_caching:
            cacheable = ((plen - 1) // P) * P
            cached, shared_pages = self.cache.lookup_prefix(
                req.prompt[:cacheable])
        slot = self.cache.allocate(plen + req.max_new,
                                   shared_pages=shared_pages)
        try:
            self._prefill_seq(slot, req.prompt, cached // P)
            self.cache.set_len(slot, plen)
            if self.enable_prefix_caching:
                self.cache.register_prefix(slot, req.prompt,
                                           upto=(plen // P) * P)
            self._replay_decode(slot, req.out[:-1])
        except BaseException:
            self.cache.release(slot)
            raise
        return slot

    # -- migration (multi-host drain/rebalance) --------------------------------
    def export_request(self, rid) -> dict:
        """Package a SUSPENDED request for migration to another engine:
        token history (prompt + generated so far) plus its swap entry
        serialized portably (``PagedKVCache.export_swap``), or
        ``swap=None`` when the entry was never armed / already dropped
        — the destination then resumes via recompute, bit-identical
        either way (same programs, same token history).  The request
        leaves THIS engine's map: after export it belongs to whoever
        imports the package.  Suspend first (``suspend(rid)``) —
        active requests hold device pages that must swap or release
        before their state can travel."""
        enforce(rid in self.requests,
                f"unknown request id {rid!r} (never admitted to this "
                f"engine)")
        req = self.requests[rid]
        enforce(req.suspended and not req.done,
                f"request {rid!r} is not suspended — suspend() before "
                f"export_request()")
        blob = self.cache.export_swap(req.swap_handle)
        req.swap_handle = None
        del self.requests[rid]
        if self._metrics is not None:
            self._metrics["migrated_out"].inc()
        # the request's capsule travels INSIDE the package (plain
        # JSON; transports ship it untouched) so a drained request's
        # capture history stays whole on the destination replica
        cs = _capsule.get_capsule_store()
        return {"rid": rid, "prompt": list(req.prompt),
                "out": list(req.out), "max_new": req.max_new,
                "eos": req.eos, "swap": blob,
                "capsule": cs.export(rid) if cs.enabled else None}

    def import_request(self, pkg: dict):
        """Adopt a migration package: the request registers here in
        the SUSPENDED state (no slot, no device pages) with its swap
        blob imported into this cache's host pool when it fits —
        ``resume(rid)`` then restores it exactly like a locally
        preempted request (swap-in, or recompute from the token
        history).  Raises when the request cannot fit this engine's
        limits or the blob's geometry mismatches the cache; the caller
        (a draining router) tries another destination.  Returns the
        rid."""
        rid = pkg["rid"]
        enforce(rid not in self.requests,
                f"duplicate request id {rid!r}")
        plen = len(pkg["prompt"])
        enforce(plen >= 1, "empty prompt in migration package")
        total = plen + pkg["max_new"]
        limit = min(self.max_len,
                    self.model.config.max_position_embeddings)
        enforce(total <= limit,
                f"migrated request {rid!r}: prompt ({plen}) + "
                f"max_new_tokens ({pkg['max_new']}) exceeds this "
                f"engine's limit {limit}")
        P = self.cache.page_size
        need = -(-total // P)
        enforce(need <= self.cache.n_pages - 1,
                f"migrated request {rid!r} needs {need} KV pages but "
                f"this cache holds {self.cache.n_pages - 1} usable")
        req = GenRequest(rid, pkg["prompt"], pkg["max_new"], pkg["eos"])
        req.out = list(pkg["out"])
        enforce(len(req.out) >= 1,
                f"migrated request {rid!r} carries no generated "
                f"tokens — it was never admitted; resubmit it instead")
        req.suspended = True
        req.swap_handle = self.cache.import_swap(pkg.get("swap"))
        self.requests[rid] = req
        cs = _capsule.get_capsule_store()
        if cs.enabled and pkg.get("capsule"):
            cs.adopt(pkg["capsule"])
        if self._metrics is not None:
            self._metrics["migrated_in"].inc()
        return rid

    def abort(self, rid) -> bool:
        """Cancel a request: release its KV pages and retire it with
        ``cancelled=True`` so ``result()`` has a defined answer (the
        tokens produced before the abort).  SUSPENDED requests cancel
        too — their host swap-pool entry is dropped (they hold no
        device pages), so an aborted preemptee cannot pin swap space.
        Returns True if the request was live and is now cancelled,
        False if it had already retired (idempotent — a race between
        natural completion and a client disconnect is not an error).
        Unknown rids raise."""
        enforce(rid in self.requests,
                f"unknown request id {rid!r} (never admitted to this "
                f"engine)")
        req = self.requests[rid]
        if req.done:
            return False
        req.done = True
        req.cancelled = True
        if req.suspended:
            self.cache.drop_swap(req.swap_handle)
            req.swap_handle = None
            req.suspended = False
        elif req in self._active:
            self._active.remove(req)
            self.cache.release(req.slot)
            self._spec_release(req)
        elif req in self._prefilling:
            self._prefilling.remove(req)
            self.cache.release(req.slot)
        if self._metrics is not None:
            self._metrics["aborted"].inc()
            self._metrics["queue_depth"].set(len(self._active))
        return True

    def result(self, rid) -> List[int]:
        """Final token list of a RETIRED request.

        Retirement contract: a request retires when it hits EOS, its
        max_new_tokens budget (its pages are released then), or is
        ``abort()``-ed (check ``requests[rid].cancelled`` to tell a
        partial stream from a completed one); until that point its
        tokens stream out of ``step()``'s return value and ``result``
        raises.  Unknown rids raise too — both are clear errors
        instead of a bare KeyError or a silently partial read.

        Retention: results stay readable after retirement for the
        engine's lifetime — the entry is only dropped by
        ``pop_result()``.  Long-running servers MUST use
        ``pop_result`` (the serving scheduler does), or the
        ``requests`` map grows by one retired entry per request
        forever."""
        enforce(rid in self.requests,
                f"unknown request id {rid!r} (never admitted to this "
                f"engine)")
        req = self.requests[rid]
        enforce(req.done,
                f"request {rid!r} is still generating ({len(req.out)} "
                f"tokens so far) — consume step() output to stream, "
                f"or call result() after it retires")
        return list(req.out)

    def pop_result(self, rid) -> List[int]:
        """``result(rid)``, then forget the request — the
        memory-retention primitive for long-running serving (a
        week-long server that never pops grows ``requests`` without
        bound).  Same contract as ``result``: only retired rids
        pop."""
        out = self.result(rid)
        del self.requests[rid]
        return out

    # -- observability ---------------------------------------------------------
    @staticmethod
    def prefill_compiles() -> int:
        """Number of distinct prefill XLA programs compiled — 1 for
        any request mix (the chunked program's shape is fixed by the
        engine geometry, not the prompt lengths; the int8 KV / int8
        weight variants are distinct engine CONFIGS, not request
        shapes, so each engine still sees exactly one)."""
        return _paged_prefill_chunk._cache_size()

    @staticmethod
    def decode_compiles() -> int:
        """Distinct compiled decode-side programs: the split
        multi-step decode program's window buckets PLUS the unified
        mixed-step program (the unified path's only decode program —
        counted here so existing >=1 / unchanged-across-runs checks
        keep holding on either path) PLUS the scanned on-device window
        programs — a window-bucket recompile must trip the same
        unchanged-across-runs assertions the host-chained programs
        live under."""
        return _paged_decode_step._cache_size() + \
            _paged_mixed_step._cache_size() + \
            LLMEngine.window_compiles()

    @staticmethod
    def mixed_compiles() -> int:
        """Distinct compiled unified-path programs: the mixed-step
        program (1 per engine geometry for ANY interleaving of prefill
        chunks and decode slots — every batch-mix input is traced
        data) plus, with ``scan_decode``, one mixed-window program per
        power-of-two window bucket — bounded by the CompileWatch
        allowances declared at engine construction
        (bit_length(steps_per_sync) − 1 buckets).  Like the other
        counters this reads a process-global jit cache: assert deltas,
        not absolutes, when several geometries share the process."""
        return _paged_mixed_step._cache_size() + \
            _paged_mixed_window._cache_size()

    @staticmethod
    def window_compiles() -> int:
        """Distinct compiled ON-DEVICE decode-window programs (both
        paths' while_loop windows).  Expected: one per power-of-two
        window bucket actually dispatched — {2, 4, ...,
        2^floor(log2(steps_per_sync))} at most; 0 when scan_decode is
        off or steps_per_sync == 1 (the degenerate window IS the plain
        step program)."""
        return _paged_decode_window._cache_size() + \
            _paged_mixed_window._cache_size()

    def metrics_snapshot(self) -> dict:
        """One JSON-able dict with everything an operator tunes
        against: TTFT/TPOT histogram snapshots, token counters,
        queue/occupancy, KV-page pressure, and the compile-count
        invariants.  Works with ``enable_metrics=False`` too (the
        registry-backed series are then absent; compile counts and
        page stats are always available)."""
        seen = self.prefix_stats["hit_tokens"] + \
            self.prefix_stats["miss_tokens"]
        snap = {
            "engine": self.engine_id,
            "tp": self._capsule_fp["tp"],
            "prefill_compiles": self.prefill_compiles(),
            "decode_compiles": self.decode_compiles(),
            "mixed_compiles": self.mixed_compiles(),
            "window_compiles": self.window_compiles(),
            "unified_step": self.unified_step,
            "scan_decode": self.scan_decode,
            "last_window_steps": int(self.last_window_steps),
            "prefill_token_budget": int(self.prefill_token_budget),
            "kv_cache": self.cache.metrics_snapshot(),
            "kv_page_utilization": self.cache.page_utilization(),
            "active_requests": len(self._active),
            "prefilling_requests": len(self._prefilling),
            "suspended_requests": self.suspended_count(),
            "free_slots": self.free_slots(),
            "prefix_caching": dict(
                self.prefix_stats,
                enabled=self.enable_prefix_caching,
                hit_rate=(self.prefix_stats["hit_tokens"] / seen
                          if seen else 0.0)),
        }
        if self._arch is not None:
            # per-expert load plane (host counters — present with
            # metrics off too, like the prefix stats): cumulative
            # routed slots summed over layers, the capacity-drop
            # total, and the max/mean imbalance SLO
            tot = self._moe_counts.sum(axis=0)
            snap["moe"] = {
                "num_experts": self._arch.num_experts,
                "top_k": self._arch.top_k,
                "dropless": self._arch.capacity == 0,
                "capacity": self._arch.capacity,
                "dispatch": self._arch.dispatch,
                "shared_experts": self._arch.shared,
                "expert_tokens": [int(v) for v in tot],
                "dropped_tokens": int(self._moe_dropped),
                "imbalance": (float(tot.max() / tot.mean())
                              if tot.sum() else 0.0),
            }
        if self._spec is not None:
            # speculative acceptance plane (host counters — present
            # with metrics off too): proposed counts DRAFT tokens
            # offered to verify, accepted the survivors, delivered
            # every token returned to requests (bonus / correction
            # included)
            st = self.spec_stats
            snap["spec"] = {
                "enabled": True,
                "k": self.spec_k,
                "mode": self._spec_mode,
                "draft_hash": self._capsule_fp["spec"]["draft_hash"],
                "windows": int(st["windows"]),
                "proposed": int(st["proposed"]),
                "accepted": int(st["accepted"]),
                "delivered": int(st["delivered"]),
                "acceptance_rate": (st["accepted"] / st["proposed"]
                                    if st["proposed"] else 0.0),
                "kv_cache_draft": self._spec_cache.metrics_snapshot(),
            }
        if self._metrics is not None:
            m = self._metrics
            snap.update({
                "ttft_seconds": m["ttft"]._snapshot_value(),
                "tpot_seconds": m["tpot"]._snapshot_value(),
                "prompt_tokens": int(m["prompt_tokens"].value),
                "generated_tokens": int(m["generated_tokens"].value),
                "requests": int(m["requests"].value),
                "queue_depth": m["queue_depth"].value,
                "batch_occupancy": m["occupancy"].value,
                "mixed_batch_decode_slots":
                    m["mixed_decode_slots"].value,
                "mixed_batch_prefill_tokens":
                    m["mixed_prefill_tokens"].value,
            })
        return snap
