"""MoE FFN step path for LLMEngine serving (ISSUE: ROADMAP item 3).

One traced function, :func:`moe_ffn`, replaces the dense SwiGLU FFN
inside every serving program's decoder-layer body when the engine's
backbone is an MoE family (Qwen2-MoE/DeepSeekMoE geometry): top-k
router → token→expert dispatch → per-expert SwiGLU → top-k combine,
plus the always-on shared expert.  All routing tensors are TRACED data
— descriptors never surface to the host — so the engine's one-compile
invariants (``mixed_compiles() == 1`` per geometry) survive untouched.

The static part of the configuration is ONE hashable :class:`MoEArch`
jit argument; everything else (which tokens, which experts) is data.

Two dispatch modes, BIT-IDENTICAL on CPU by construction:

- ``grouped`` — the production shape: sort routed slots by expert into
  the tile-aligned dropless layout (ops/pallas/grouped_matmul.py's
  ``make_dropless_plan_rows``) and run ONE grouped matmul per
  projection per layer (no per-expert programs).  On TPU the Pallas
  ``gmm`` kernels do the work; on CPU the per-row gathered-einsum
  oracle (``gmm_reference``'s idiom) does — which is exactly the
  row-wise math the dense mode runs, so the two modes agree bit for
  bit off-TPU (each row's contraction is independent of every other
  row's placement).
- ``dense`` — the per-row reference: gather each slot's expert weights
  and contract row-wise, no sorting.  The A/B comparator for tests and
  the bench's per-expert-loop baseline.

Token dropping: ``arch.capacity == 0`` is dropless (every routed slot
computes).  ``capacity > 0`` is the capacity-factor mode: within each
page-group (a prefill chunk; decode rows are singleton groups and can
never drop, since ``jax.lax.top_k`` returns distinct experts), an
expert keeps at most ``capacity`` slots in slot order and the rest
contribute exactly +0.0 to the combine — deterministic across the
split/unified/scanned paths because the group boundaries are page
chunks on every path (the unified planner packs whole page chunks in
capacity mode).

INT8 expert weights ride the quantization absmax path: stacks arrive
as ``(int8 values, f32 scale)`` pairs with per-(expert, out-channel)
scales that multiply the contraction OUTPUT — same fold the engine's
``_mm`` uses — so both dispatch modes stay bit-identical quantized.
"""
from __future__ import annotations

from typing import NamedTuple

__all__ = ["MoEArch", "moe_ffn"]


class MoEArch(NamedTuple):
    """Hashable static-jit MoE dispatch configuration.  ``capacity`` is
    the per-page-group per-expert slot cap (0 = dropless); ``dispatch``
    is ``"grouped"`` or ``"dense"`` (bit-identical on CPU — excluded
    from the capsule fingerprint like tp)."""
    num_experts: int
    top_k: int
    norm_topk: bool
    capacity: int
    shared: bool
    shared_gate: bool
    attn_bias: bool
    dispatch: str


def _mm(x, w):
    """x @ w for fp or weight-only-int8 (values, per-out-channel scale)
    stacked weights — the engine's fold, restated here to avoid a
    circular import."""
    import jax.numpy as jnp
    if isinstance(w, tuple):
        qw, sc = w
        return jnp.matmul(x, qw.astype(x.dtype)) * sc.astype(x.dtype)
    return jnp.matmul(x, w)


def _expert_rows_mm(x, w, row_expert):
    """Row-wise expert contraction: row i of ``x`` [M, K] against
    ``w[row_expert[i]]`` ([E, K, N] or int8 pair), f32 accumulate.
    Each output row depends only on its own inputs — row-order
    independent bitwise, which is the whole grouped≡dense argument."""
    import jax.numpy as jnp
    if isinstance(w, tuple):
        qw, sc = w
        wr = qw[row_expert]
        y = jnp.einsum("mk,mkn->mn", x.astype(jnp.float32),
                       wr.astype(jnp.float32))
        return y * sc[row_expert]
    wr = w[row_expert]
    return jnp.einsum("mk,mkn->mn", x.astype(jnp.float32),
                      wr.astype(jnp.float32))


def _gmm_apply(xs, w, tile_expert, gcounts, tm, on_tpu):
    """One grouped matmul over the sorted tile-aligned buffer: the
    Pallas kernel on TPU, the per-row oracle (same rows, same math as
    dense mode) on CPU."""
    import jax.numpy as jnp

    from ..ops.pallas.grouped_matmul import gmm, gmm_reference
    if not on_tpu:
        row_e = jnp.repeat(tile_expert, tm)
        return _expert_rows_mm(xs, w, row_e)
    if isinstance(w, tuple):
        # the kernel streams one weight dtype; upcast feeds the MXU
        # copy XLA fuses into the kernel's input stream, and the
        # per-out-channel scale folds into the output like _mm's
        qw, sc = w
        y = gmm(xs, qw.astype(xs.dtype), tile_expert, gcounts, tm=tm)
        return y * sc[jnp.repeat(tile_expert, tm)]
    return gmm(xs, w, tile_expert, gcounts, tm=tm)


def moe_ffn(hn, mw, arch, live, group_start=None):
    """The MoE decoder-layer FFN for one serving dispatch.

    hn [T, H] post-attention-layernorm rows; ``mw`` the per-layer
    weight tuple ``(rw, egw, euw, edw, sgw, suw, sdw, seg)`` (router
    [H, E] fp; expert stacks [E, H, F]/[E, F, H], fp or int8 pairs;
    shared-expert Linears, placeholder [1, 1] zeros when
    ``arch.shared`` is off); ``live`` [T] bool masks padding rows out
    of routing (their FFN output is unread); ``group_start`` [T] int32
    maps each row to its capacity page-group's first row (``None`` =
    every row its own group — the decode programs, where top-k's
    distinct experts make the in-group rank identically 0).

    Returns ``(ffn_out [T, H], counts [E] int32)`` — counts are the
    KEPT routed slots per expert (the observability plane's per-expert
    load; dropless ⇒ sum == live·k)."""
    import jax
    import jax.numpy as jnp

    from ..ops.pallas.grouped_matmul import (_auto_tm,
                                             make_dropless_plan_rows)
    from ..runtime.device import is_compiled_with_tpu

    rw, egw, euw, edw, sgw, suw, sdw, seg = mw
    t, h = hn.shape
    e, k = arch.num_experts, arch.top_k
    f32 = jnp.float32
    xf = hn.astype(f32)

    # router (nn/moe.py _router_parts math, serving subset): softmax
    # over ALL experts, then top-k; HF Qwen2-MoE ships norm_topk off
    logits = jnp.dot(xf, rw.astype(f32))
    probs = jax.nn.softmax(logits, axis=-1)                 # [T, E]
    gate_vals, expert_idx = jax.lax.top_k(probs, k)         # [T, k]
    if arch.norm_topk:
        gate_vals = gate_vals / jnp.clip(
            jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

    live_slot = jnp.repeat(live, k)                         # [T*k]
    eidx = expert_idx.reshape(-1)
    if arch.capacity and group_start is not None:
        # in-group rank of each slot = live same-expert slots before it
        # within its page group, via ONE exclusive cumsum over the flat
        # slot order minus the value at the group's first slot (slots
        # before the group cancel, so groups never contaminate each
        # other — the split-prefill chunk and the unified planner's
        # whole-page chunk rank identically)
        onehot = (jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)
                  * live[:, None, None].astype(jnp.int32)
                  ).reshape(t * k, e)
        ex_cum = jnp.cumsum(onehot, axis=0) - onehot        # exclusive
        first_slot = jnp.repeat(group_start, k) * k
        base = jnp.take(ex_cum, first_slot, axis=0)
        rank = jnp.take_along_axis(ex_cum - base,
                                   eidx[:, None], axis=1)[:, 0]
        keep = live_slot & (rank < arch.capacity)
    else:
        # dropless — or decode rows (singleton groups): top_k returns
        # distinct experts, so every in-group rank is 0 < capacity
        keep = live_slot
    row_expert = jnp.where(keep, eidx, e)                   # e = dropped
    counts = jnp.sum(
        jax.nn.one_hot(eidx, e, dtype=jnp.int32)
        * keep[:, None].astype(jnp.int32), axis=0)          # [E]

    if arch.dispatch == "grouped":
        on_tpu = is_compiled_with_tpu()
        tm = _auto_tm(e, t * k) if on_tpu else 8
        order, dest, valid_sorted, tile_expert, gcounts, m_pad = \
            make_dropless_plan_rows(row_expert, e, tm)
        xs = jnp.zeros((m_pad, h), f32).at[dest].set(
            xf[order // k], mode="drop")
        hg = _gmm_apply(xs, egw, tile_expert, gcounts, tm, on_tpu)
        hu = _gmm_apply(xs, euw, tile_expert, gcounts, tm, on_tpu)
        hs = (jax.nn.silu(hg.astype(f32))
              * hu.astype(f32)).astype(xs.dtype)
        ys = _gmm_apply(hs, edw, tile_expert, gcounts, tm, on_tpu)
        dest_safe = jnp.minimum(dest, m_pad - 1)
        y_sorted = jnp.where(valid_sorted[:, None],
                             ys[dest_safe].astype(f32), 0.0)
        y = jnp.zeros((t * k, h), f32).at[order].set(y_sorted)
    else:
        # dense per-expert reference: the same row-wise contractions
        # on the unsorted slot rows, dropped slots zeroed after
        safe = jnp.minimum(eidx, e - 1)
        xdup = jnp.repeat(xf, k, axis=0)                    # [T*k, H]
        hg = _expert_rows_mm(xdup, egw, safe)
        hu = _expert_rows_mm(xdup, euw, safe)
        hs = (jax.nn.silu(hg.astype(f32))
              * hu.astype(f32)).astype(xdup.dtype)
        ys = _expert_rows_mm(hs, edw, safe)
        y = jnp.where(keep[:, None], ys.astype(f32), 0.0)

    out = jnp.einsum("tk,tkh->th", gate_vals.astype(f32),
                     y.reshape(t, k, h))                    # [T, H]

    if arch.shared:
        # shared-expert SwiGLU (+ optional sigmoid token gate) — the
        # Qwen2-MoE composition (nn/moe.py MoELayer)
        sh = jax.nn.silu(_mm(xf, sgw)) * _mm(xf, suw)
        shared = _mm(sh, sdw)
        if arch.shared_gate:
            shared = shared * jax.nn.sigmoid(_mm(xf, seg))
        out = out + shared

    return out.astype(hn.dtype), counts
