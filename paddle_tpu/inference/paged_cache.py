"""Paged KV cache manager for the serving path.

Reference parity: the inference engine's KV memory management (the
reference grows per-request dense caches inside AnalysisPredictor's
memory optim; modern serving uses paged pools — the PAPERS.md ragged
paged attention blueprint).  Host-side page accounting (free list, per-
sequence page lists) stays in python; the page pools are device memory
consumed by ops.pallas.paged_attention.

One object manages ALL decoder layers (``num_layers`` pools sharing one
page table): a token occupies the same (page, slot) in every layer, the
length advances once per token — per-layer bookkeeping cannot drift.

``kv_dtype="int8"`` stores the pools quantized (per-token absmax, one
f32 scale per row kept in sibling scale pools [L, KVH, n_pages, P]):
write_prefill/append quantize on the way in, attend dequantizes inside
the kernel — KV HBM bytes drop ~2× vs fp16 / ~4× vs fp32, which is the
whole game for bandwidth-bound TPU decode and for page capacity at a
fixed HBM budget.
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common.errors import enforce
from ..observability import get_registry

__all__ = ["PagedKVCache"]

_CACHE_IDS = itertools.count()


class PagedKVCache:
    def __init__(self, n_pages: int, page_size: int, n_kv_heads: int,
                 head_dim: int, max_seqs: int, max_len: int,
                 dtype=np.float32, num_layers: int = 1,
                 kv_dtype: Optional[str] = None):
        import jax.numpy as jnp
        enforce(kv_dtype in (None, "int8"),
                f"unsupported kv_dtype {kv_dtype!r} (None or 'int8')")
        self.n_pages = n_pages
        self.page_size = page_size
        self.num_layers = num_layers
        self.kv_dtype = kv_dtype
        self.max_pages_per_seq = (max_len + page_size - 1) // page_size
        pool_dtype = jnp.int8 if kv_dtype == "int8" else dtype
        # [L, KVH, n_pages, P, D]
        self.k_pages = jnp.zeros((num_layers, n_kv_heads, n_pages,
                                  page_size, head_dim), pool_dtype)
        self.v_pages = jnp.zeros_like(self.k_pages)
        if kv_dtype == "int8":
            # per-token dequant scales; the kernels consume per-layer
            # [KVH, n_pages, 1, P] views (scale vector on the lanes)
            self.k_scales = jnp.zeros((num_layers, n_kv_heads, n_pages,
                                       page_size), jnp.float32)
            self.v_scales = jnp.zeros_like(self.k_scales)
        else:
            self.k_scales = None
            self.v_scales = None
        self._free = list(range(n_pages - 1, 0, -1))   # page 0 = pad
        self._pages: Dict[int, List[int]] = {}
        self._lens = np.zeros(max_seqs, np.int32)
        self._table = np.zeros((max_seqs, self.max_pages_per_seq),
                               np.int32)
        self._used = [False] * max_seqs
        # page-pressure telemetry (host-side counters — negligible next
        # to the device work these methods bracket); one label set per
        # cache instance so concurrent engines don't blur each other
        reg = get_registry()
        self.cache_id = str(next(_CACHE_IDS))
        lbl = ("cache",)
        self._m_alloc = reg.counter(
            "kv_cache_pages_allocated_total",
            "KV pages taken from the free list.", lbl).labels(
                self.cache_id)
        self._m_release = reg.counter(
            "kv_cache_pages_released_total",
            "KV pages returned to the free list.", lbl).labels(
                self.cache_id)
        self._m_oom = reg.counter(
            "kv_cache_oom_total",
            "Allocation/extension failures: not enough free pages.",
            lbl).labels(self.cache_id)
        self._m_util = reg.gauge(
            "kv_cache_page_utilization",
            "Fraction of usable pages in use (page 0 is the reserved "
            "pad page).", lbl).labels(self.cache_id)

    def page_utilization(self) -> float:
        """In-use fraction of the usable pool (excludes pad page 0)."""
        usable = self.n_pages - 1
        return 1.0 - len(self._free) / usable if usable else 0.0

    def _track_pages(self):
        self._m_util.set(self.page_utilization())

    # -- host-side accounting --------------------------------------------------
    def allocate(self, n_tokens: int) -> int:
        """Reserve a sequence slot with capacity for n_tokens; returns
        the slot id (batch row for the kernel)."""
        free_slots = [i for i, u in enumerate(self._used) if not u]
        enforce(free_slots, "paged cache: all sequence slots in use")
        slot = free_slots[0]
        need = (n_tokens + self.page_size - 1) // self.page_size
        if len(self._free) < need:
            self._m_oom.inc()
        enforce(len(self._free) >= need,
                f"paged cache OOM: need {need} pages, "
                f"{len(self._free)} free")
        pages = [self._free.pop() for _ in range(need)]
        self._m_alloc.inc(need)
        self._used[slot] = True
        self._pages[slot] = pages
        self._lens[slot] = 0
        self._table[slot, :] = 0
        self._table[slot, :need] = pages
        self._track_pages()
        return slot

    def extend(self, slot: int, n_tokens: int = 1):
        """Ensure capacity for n_tokens more; grabs pages as needed."""
        have = len(self._pages[slot]) * self.page_size
        need_total = int(self._lens[slot]) + n_tokens
        while have < need_total:
            if not self._free:
                self._m_oom.inc()
            enforce(self._free, "paged cache OOM on extend")
            pg = self._free.pop()
            self._m_alloc.inc()
            idx = len(self._pages[slot])
            self._pages[slot].append(pg)
            self._table[slot, idx] = pg
            have += self.page_size
        self._track_pages()

    def release(self, slot: int):
        pages = self._pages.pop(slot)
        self._free.extend(reversed(pages))
        self._m_release.inc(len(pages))
        self._used[slot] = False
        self._lens[slot] = 0
        self._table[slot, :] = 0
        self._track_pages()

    def set_len(self, slot: int, n: int):
        """Host-side length after an in-graph prefill wrote the pages
        directly (chunked prefill)."""
        self._lens[slot] = n

    def advance(self, slots, n: int = 1):
        for s in np.atleast_1d(slots):
            self._lens[s] += n

    @property
    def seq_lens(self) -> np.ndarray:
        return self._lens

    @property
    def page_table(self) -> np.ndarray:
        return self._table

    def free_page_count(self) -> int:
        return len(self._free)

    def kv_bytes_per_token(self) -> int:
        """HBM bytes one cached token costs across all layers and both
        pools — int8 counts its f32 scale rows, so capacity claims stay
        honest."""
        head_dim = self.k_pages.shape[-1]
        kvh = self.k_pages.shape[1]
        if self.kv_dtype == "int8":
            per_row = head_dim * 1 + 4          # int8 values + f32 scale
        else:
            per_row = head_dim * self.k_pages.dtype.itemsize
        return 2 * self.num_layers * kvh * per_row

    def metrics_snapshot(self) -> dict:
        """This cache's page-pressure counters (host view; the same
        series are in the global registry under label cache=<id>)."""
        return {"pages_allocated": int(self._m_alloc.value),
                "pages_released": int(self._m_release.value),
                "oom_events": int(self._m_oom.value),
                "free_pages": self.free_page_count(),
                "page_utilization": self.page_utilization()}

    # -- device-side ops -------------------------------------------------------
    def _norm_layers(self, k, v, tokens_axis: int):
        """Accept [S?, KVH, D]-style per-layer input when num_layers==1,
        else require a leading layer dim."""
        import jax.numpy as jnp
        k, v = jnp.asarray(k), jnp.asarray(v)
        if k.ndim == 3:
            enforce(self.num_layers == 1,
                    f"cache holds {self.num_layers} layers; pass "
                    f"[L, ...] keys/values")
            k, v = k[None], v[None]
        return k, v

    def write_prefill(self, slot: int, k, v):
        """Bulk-write a prefill's keys/values into the sequence's pages
        with ONE vectorized scatter per pool (int8 mode quantizes the
        rows on the way in and scatters the scales alongside).

        k/v: [S, KVH, D] (num_layers==1) or [L, S, KVH, D]."""
        import jax.numpy as jnp
        k, v = self._norm_layers(k, v, 1)
        s = k.shape[1]
        self.extend(slot, s)
        start = int(self._lens[slot])
        pos = np.arange(start, start + s)
        pages = jnp.asarray(self._table[slot, pos // self.page_size])
        slots_ = jnp.asarray(pos % self.page_size)
        # [L, S, KVH, D] -> [L, KVH, S, D] scatter at (pages, slots)
        kt = jnp.swapaxes(k, 1, 2)
        vt = jnp.swapaxes(v, 1, 2)
        if self.kv_dtype == "int8":
            from ..quantization.ops import quantize_rows_raw
            kt, ksc = quantize_rows_raw(kt)       # + [L, KVH, S] scales
            vt, vsc = quantize_rows_raw(vt)
            self.k_scales = self.k_scales.at[:, :, pages, slots_].set(ksc)
            self.v_scales = self.v_scales.at[:, :, pages, slots_].set(vsc)
        else:
            kt = kt.astype(self.k_pages.dtype)
            vt = vt.astype(self.v_pages.dtype)
        self.k_pages = self.k_pages.at[:, :, pages, slots_, :].set(kt)
        self.v_pages = self.v_pages.at[:, :, pages, slots_, :].set(vt)
        self._lens[slot] = start + s

    def append(self, slots, k_new, v_new):
        """Decode step: one new token for each sequence in ``slots``.

        k_new/v_new: [B, KVH, D] (num_layers==1) or [L, B, KVH, D];
        lengths advance by 1 (once, across all layers)."""
        import jax.numpy as jnp
        k_new, v_new = self._norm_layers(k_new, v_new, 1)
        slots = np.atleast_1d(slots)
        for s in slots:
            self.extend(int(s), 1)
        pos = self._lens[slots]
        pages = jnp.asarray(self._table[slots, pos // self.page_size])
        slot_in_page = jnp.asarray(pos % self.page_size)
        # ONE all-layer scatter: this method is EAGER (each op call
        # copies its output), so a per-layer dus chain would copy the
        # pool 2·L·B times per token; the jit-compiled serving path
        # (engine's fused append+attend kernel) never comes through here
        kt = jnp.swapaxes(k_new, 1, 2)
        vt = jnp.swapaxes(v_new, 1, 2)
        if self.kv_dtype == "int8":
            from ..quantization.ops import quantize_rows_raw
            kt, ksc = quantize_rows_raw(kt)       # + [L, KVH, B] scales
            vt, vsc = quantize_rows_raw(vt)
            self.k_scales = self.k_scales.at[
                :, :, pages, slot_in_page].set(ksc)
            self.v_scales = self.v_scales.at[
                :, :, pages, slot_in_page].set(vsc)
        else:
            kt = kt.astype(self.k_pages.dtype)
            vt = vt.astype(self.v_pages.dtype)
        self.k_pages = self.k_pages.at[:, :, pages, slot_in_page, :].set(kt)
        self.v_pages = self.v_pages.at[:, :, pages, slot_in_page, :].set(vt)
        self.advance(slots, 1)

    def attend(self, slots, q, layer: int = 0,
               use_kernel: Optional[bool] = None):
        """Decode attention for ``q`` [B, H, D] over the cached pages of
        ``slots`` in ``layer``.  Kernel on TPU, jnp reference elsewhere;
        int8 pools hand the kernel their per-token scales and dequantize
        in VMEM."""
        import jax.numpy as jnp
        from ..runtime.device import is_compiled_with_tpu
        from ..ops.pallas.paged_attention import (paged_attention_raw,
                                                  paged_attention_reference)
        slots = np.atleast_1d(slots)
        table = jnp.asarray(self._table[slots])
        lens = jnp.asarray(self._lens[slots])
        if use_kernel is None:
            use_kernel = is_compiled_with_tpu()
        fn = paged_attention_raw if use_kernel else \
            paged_attention_reference
        args = ()
        if self.kv_dtype == "int8":
            args = (self.k_scales[layer][:, :, None, :],
                    self.v_scales[layer][:, :, None, :])
        return fn(jnp.asarray(q), self.k_pages[layer],
                  self.v_pages[layer], table, lens, *args)
