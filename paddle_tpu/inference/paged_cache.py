"""Paged KV cache manager for the serving path.

Reference parity: the inference engine's KV memory management (the
reference grows per-request dense caches inside AnalysisPredictor's
memory optim; modern serving uses paged pools — the PAPERS.md ragged
paged attention blueprint).  Host-side page accounting (free list, per-
sequence page lists) stays in python; the page pools are device memory
consumed by ops.pallas.paged_attention.

One object manages ALL decoder layers (``num_layers`` pools sharing one
page table): a token occupies the same (page, slot) in every layer, the
length advances once per token — per-layer bookkeeping cannot drift.

``kv_dtype="int8"`` stores the pools quantized (per-token absmax, one
f32 scale per row kept in sibling scale pools [L, KVH, n_pages, P]):
write_prefill/append quantize on the way in, attend dequantizes inside
the kernel — KV HBM bytes drop ~2× vs fp16 / ~4× vs fp32, which is the
whole game for bandwidth-bound TPU decode and for page capacity at a
fixed HBM budget.

Automatic prefix caching (vLLM-style, host-side only): pages are
REF-COUNTED, and full, immutable prefill pages can be registered in a
hash index keyed by the CHAIN of token-block hashes — ``[sys][A]`` and
``[sys][B]`` share exactly the ``[sys]`` pages, because block k's key
digests block k-1's key.  ``lookup_prefix`` walks the chain,
``allocate(shared_pages=...)`` maps the hits into a new slot's page
table without touching the device, and ``release`` keeps unreferenced
registered pages CACHED (an LRU pool) instead of freeing them: a later
``allocate``/``extend`` evicts LRU-oldest only when the free list runs
dry.  Writes into a shared page copy-on-write (``extend`` grabs a
fresh page and device-copies the row — scales included — before any
mutation), so shared content is immutable by construction.  The int8
scale pools are indexed by the same physical page ids, so quantized
serving shares scales with their pages for free.

KV swap (preemptive scheduling, vLLM-style): ``swap_out(slot)`` copies
the slot's PRIVATE written pages (and int8 scale rows) into a bounded
host-side swap pool and releases every device page — prefix-cache
pages the slot maps read-shared are NOT copied, only unpinned, and
recorded by their chain key so ``swap_in`` can re-pin them (registered
pages are immutable, so the key still names the same bytes).
``swap_in(handle, n_tokens)`` restores the sequence into a fresh slot
with its full ``n_tokens`` page budget re-reserved.  Both degrade
gracefully: a full pool makes ``swap_out`` release-only (returns
``None``), and an evicted shared page makes ``swap_in`` fail cleanly
(returns ``None``) — in either case the caller recomputes the KV from
the token history instead.  The pool is host DRAM, deliberately
outside the device HBM budget: preemption trades host memory + PCIe
copies for freed device pages.
"""
from __future__ import annotations

import hashlib
import io
import itertools
import json
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common.errors import enforce
from ..observability import get_registry

__all__ = ["PagedKVCache"]

_CACHE_IDS = itertools.count()


def _chain_hash(prev: bytes, tokens) -> bytes:
    """Key for one full token block given the previous block's key —
    chaining makes the key identify the whole prefix, not the block in
    isolation (so equal blocks under different prefixes never alias)."""
    h = hashlib.blake2b(prev, digest_size=16)
    h.update(np.asarray(tokens, np.int64).tobytes())
    return h.digest()


class _SwapEntry:
    """Host-side record of one swapped-out sequence: per written page
    either ("data", j) — row j of the host arrays holds a private
    page's bytes — or ("key", chain_key) — a shared prefix page to
    re-pin through the index at swap-in time."""

    __slots__ = ("plan", "k_host", "v_host", "k_scale_host",
                 "v_scale_host", "n_host_pages")

    def __init__(self, plan, k_host, v_host, k_scale_host,
                 v_scale_host):
        self.plan = plan
        self.k_host = k_host
        self.v_host = v_host
        self.k_scale_host = k_scale_host
        self.v_scale_host = v_scale_host
        self.n_host_pages = 0 if k_host is None else k_host.shape[2]


class PagedKVCache:
    def __init__(self, n_pages: int, page_size: int, n_kv_heads: int,
                 head_dim: int, max_seqs: int, max_len: int,
                 dtype=np.float32, num_layers: int = 1,
                 kv_dtype: Optional[str] = None,
                 swap_pool_pages: int = 0, shardings=None):
        import jax.numpy as jnp
        enforce(kv_dtype in (None, "int8"),
                f"unsupported kv_dtype {kv_dtype!r} (None or 'int8')")
        self.n_pages = n_pages
        self.page_size = page_size
        self.num_layers = num_layers
        self.kv_dtype = kv_dtype
        self.max_pages_per_seq = (max_len + page_size - 1) // page_size
        pool_dtype = jnp.int8 if kv_dtype == "int8" else dtype
        # tensor-parallel pools (``shardings``: a distributed.sharding
        # TPShardings plan): the pools commit sharded on the KV-HEAD
        # axis — each shard holds n_kv_heads/tp heads of EVERY page, so
        # the page tables, free lists, prefix index and swap plans stay
        # global (host bookkeeping is tp-agnostic).  jax.device_get on
        # a sharded pool gathers the full logical array, which is what
        # keeps swap blobs portable across mesh shapes by construction.
        self._shardings = shardings
        if shardings is not None:
            enforce(n_kv_heads % shardings.tp == 0,
                    f"tp={shardings.tp} must divide n_kv_heads "
                    f"({n_kv_heads})")
        # [L, KVH, n_pages, P, D]
        self.k_pages = jnp.zeros((num_layers, n_kv_heads, n_pages,
                                  page_size, head_dim), pool_dtype)
        self.v_pages = jnp.zeros_like(self.k_pages)
        if kv_dtype == "int8":
            # per-token dequant scales; the kernels consume per-layer
            # [KVH, n_pages, 1, P] views (scale vector on the lanes)
            self.k_scales = jnp.zeros((num_layers, n_kv_heads, n_pages,
                                       page_size), jnp.float32)
            self.v_scales = jnp.zeros_like(self.k_scales)
        else:
            self.k_scales = None
            self.v_scales = None
        if shardings is not None:
            # commit on the mesh, KV-head axis sharded; the serving
            # programs donate the pools so the placement survives every
            # step, and eager .at[].set updates (swap-in, import)
            # re-scatter through it
            self.k_pages = shardings.put(self.k_pages, 1)
            self.v_pages = shardings.put(self.v_pages, 1)
            if self.k_scales is not None:
                self.k_scales = shardings.put(self.k_scales, 1)
                self.v_scales = shardings.put(self.v_scales, 1)
        self._free = list(range(n_pages - 1, 0, -1))   # page 0 = pad
        self._pages: Dict[int, List[int]] = {}
        self._lens = np.zeros(max_seqs, np.int32)
        self._table = np.zeros((max_seqs, self.max_pages_per_seq),
                               np.int32)
        self._used = [False] * max_seqs
        # prefix caching state: per-page reference counts (how many
        # slots map the page), the chain-hash index over registered
        # full prefill pages, and the LRU pool of registered pages with
        # ref 0 — cached content kept warm until page pressure evicts
        self._ref = np.zeros(n_pages, np.int64)
        self._index: Dict[bytes, int] = {}       # chain key -> page
        self._page_key: Dict[int, bytes] = {}    # page -> chain key
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        # swap state: bounded host pool of page copies for preempted
        # sequences (0 pages = swap disabled, recompute-only fallback)
        self.swap_pool_pages = int(swap_pool_pages)
        self._swap: Dict[int, _SwapEntry] = {}
        self._swap_used = 0
        self._swap_ids = itertools.count()
        # page-pressure telemetry (host-side counters — negligible next
        # to the device work these methods bracket); one label set per
        # cache instance so concurrent engines don't blur each other
        reg = get_registry()
        self.cache_id = str(next(_CACHE_IDS))
        lbl = ("cache",)
        self._m_alloc = reg.counter(
            "kv_cache_pages_allocated_total",
            "KV pages taken from the free list.", lbl).labels(
                self.cache_id)
        self._m_release = reg.counter(
            "kv_cache_pages_released_total",
            "KV pages returned to the free list.", lbl).labels(
                self.cache_id)
        self._m_oom = reg.counter(
            "kv_cache_oom_total",
            "Allocation/extension failures: not enough free pages.",
            lbl).labels(self.cache_id)
        self._m_util = reg.gauge(
            "kv_cache_page_utilization",
            "Fraction of usable pages referenced by live slots (page 0 "
            "is the reserved pad page; prefix-cached LRU pages count "
            "as reclaimable, not in use).", lbl).labels(self.cache_id)
        self._m_evict = reg.counter(
            "kv_cache_prefix_evicted_pages_total",
            "Prefix-cached pages evicted from the LRU pool under page "
            "pressure.", lbl).labels(self.cache_id)
        self._m_cow = reg.counter(
            "kv_cache_cow_pages_total",
            "Copy-on-write page copies (a write targeted a shared "
            "page).", lbl).labels(self.cache_id)
        self._m_cached = reg.gauge(
            "kv_cache_prefix_cached_pages",
            "Registered prefix pages currently unreferenced (the LRU "
            "pool).", lbl).labels(self.cache_id)
        self._m_swap_out = reg.counter(
            "kv_cache_swap_out_pages_total",
            "Device pages copied to the host swap pool by swap_out "
            "(shared prefix pages are unpinned, not copied).",
            lbl).labels(self.cache_id)
        self._m_swap_in = reg.counter(
            "kv_cache_swap_in_pages_total",
            "Host pages copied back to device pages by swap_in.",
            lbl).labels(self.cache_id)
        self._m_swap_fallback = reg.counter(
            "kv_cache_swap_fallback_total",
            "swap_out/swap_in attempts that degraded to the recompute "
            "path (pool full or disabled, entry dropped, or a shared "
            "prefix page evicted while suspended).", lbl).labels(
                self.cache_id)
        self._m_swap_pool = reg.gauge(
            "kv_cache_swap_pool_pages",
            "Host swap-pool pages currently holding preempted KV.",
            lbl).labels(self.cache_id)
        self._m_swap_export = reg.counter(
            "kv_cache_swap_exported_pages_total",
            "Swap-pool pages serialized into portable migration blobs "
            "(export_swap).", lbl).labels(self.cache_id)
        self._m_swap_import = reg.counter(
            "kv_cache_swap_imported_pages_total",
            "Swap-pool pages restored from portable migration blobs "
            "(import_swap).", lbl).labels(self.cache_id)

    def page_utilization(self) -> float:
        """Referenced fraction of the usable pool (excludes pad page 0
        and counts prefix-cached LRU pages as reclaimable — they are
        handed back by eviction before any allocation can fail)."""
        usable = self.n_pages - 1
        if not usable:
            return 0.0
        return 1.0 - (len(self._free) + len(self._lru)) / usable

    def _track_pages(self):
        self._m_util.set(self.page_utilization())
        self._m_cached.set(len(self._lru))

    # -- prefix-caching internals ----------------------------------------------
    def _unregister(self, pg: int):
        key = self._page_key.pop(pg)
        del self._index[key]

    def _grab_page(self, what: str) -> int:
        """One page off the free list, evicting the LRU-oldest cached
        prefix page when the list is dry; counts the OOM (and leaves
        the gauges honest) before raising when neither pool has one."""
        if self._free:
            pg = self._free.pop()
        elif self._lru:
            pg, _ = self._lru.popitem(last=False)      # oldest first
            self._unregister(pg)
            self._m_evict.inc()
        else:
            self._m_oom.inc()
            self._track_pages()
            enforce(False, f"paged cache OOM on {what}: no free or "
                           f"evictable pages")
        self._m_alloc.inc()
        self._ref[pg] = 1
        return pg

    def _unref(self, pg: int) -> bool:
        """Drop one reference; True if the page went back to the free
        list (registered pages park in the LRU pool instead)."""
        self._ref[pg] -= 1
        if self._ref[pg] > 0:
            return False
        if pg in self._page_key:
            self._lru[pg] = None                       # newest at end
            return False
        self._free.append(pg)
        return True

    def _copy_page(self, src: int, dst: int):
        """Device-copy one physical page (both pools, and the scale
        rows when quantized — scales travel with their pages)."""
        self.k_pages = self.k_pages.at[:, :, dst].set(
            self.k_pages[:, :, src])
        self.v_pages = self.v_pages.at[:, :, dst].set(
            self.v_pages[:, :, src])
        if self.kv_dtype == "int8":
            self.k_scales = self.k_scales.at[:, :, dst].set(
                self.k_scales[:, :, src])
            self.v_scales = self.v_scales.at[:, :, dst].set(
                self.v_scales[:, :, src])

    def _make_private(self, slot: int, idx: int):
        """Copy-on-write guard before writing into the slot's idx-th
        page: a shared page (ref > 1) is copied to a fresh page first;
        a solely-owned but registered page just unregisters (its cached
        content is about to diverge from the indexed prefix)."""
        pg = self._pages[slot][idx]
        if self._ref[pg] > 1:
            npg = self._grab_page("copy-on-write")
            self._copy_page(pg, npg)
            self._unref(pg)
            self._m_release.inc()
            self._pages[slot][idx] = npg
            self._table[slot, idx] = npg
            self._m_cow.inc()
        elif pg in self._page_key:
            self._unregister(pg)

    # -- host-side accounting --------------------------------------------------
    def allocate(self, n_tokens: int, shared_pages=()) -> int:
        """Reserve a sequence slot with capacity for n_tokens; returns
        the slot id (batch row for the kernel).  ``shared_pages``
        (from ``lookup_prefix``) are mapped read-shared into the front
        of the slot's page table — a reference each, no device work —
        and only the remainder comes off the free list."""
        free_slots = [i for i, u in enumerate(self._used) if not u]
        enforce(free_slots, "paged cache: all sequence slots in use")
        slot = free_slots[0]
        need = (n_tokens + self.page_size - 1) // self.page_size
        shared = list(shared_pages)
        enforce(len(shared) <= need,
                f"paged cache: {len(shared)} shared pages exceed the "
                f"{need}-page capacity request")
        # pin the shared pages FIRST so grabbing the remainder can
        # never evict them out from under this allocation
        for pg in shared:
            self._ref[pg] += 1
            if pg in self._lru:
                del self._lru[pg]
        avail = len(self._free) + len(self._lru)
        if avail < need - len(shared):
            self._m_oom.inc()
            for pg in reversed(shared):
                self._unref(pg)
            self._track_pages()
            enforce(False,
                    f"paged cache OOM: need {need - len(shared)} "
                    f"pages, {avail} free/evictable")
        self._m_alloc.inc(len(shared))      # the shared references
        pages = shared + [self._grab_page("allocate")
                          for _ in range(need - len(shared))]
        self._used[slot] = True
        self._pages[slot] = pages
        self._lens[slot] = 0
        self._table[slot, :] = 0
        self._table[slot, :need] = pages
        self._track_pages()
        return slot

    def extend(self, slot: int, n_tokens: int = 1):
        """Ensure capacity for n_tokens more; grabs pages as needed.
        Already-attached pages the new tokens will land in are made
        private first (copy-on-write), so appends after a shared
        prefix can never mutate another sequence's view."""
        pages = self._pages[slot]
        cur = int(self._lens[slot])
        need_total = cur + n_tokens
        if n_tokens > 0 and pages:
            first = cur // self.page_size
            last = (need_total - 1) // self.page_size
            for idx in range(first, min(last, len(pages) - 1) + 1):
                self._make_private(slot, idx)
        have = len(pages) * self.page_size
        while have < need_total:
            pg = self._grab_page("extend")
            idx = len(pages)
            pages.append(pg)
            self._table[slot, idx] = pg
            have += self.page_size
        self._track_pages()

    def release(self, slot: int):
        """Drop the slot's page references.  Unregistered pages return
        to the free list; registered prefix pages with no remaining
        reference stay cached in the LRU pool (still allocatable —
        eviction reclaims them oldest-first under pressure)."""
        pages = self._pages.pop(slot)
        for pg in reversed(pages):
            self._unref(pg)
        self._m_release.inc(len(pages))
        self._used[slot] = False
        self._lens[slot] = 0
        self._table[slot, :] = 0
        self._track_pages()

    # -- KV swap (preemption) --------------------------------------------------
    def swap_out(self, slot: int) -> Optional[int]:
        """Preempt ``slot``: copy its private WRITTEN pages (and int8
        scale rows) into the host swap pool, then release every device
        page the slot holds — the freed pages are what preemption buys.
        Shared prefix pages are not copied, only unpinned; their chain
        keys are recorded so ``swap_in`` can re-pin them (registered
        pages are immutable, so a key that still resolves names the
        same bytes).

        Returns a swap handle for ``swap_in``, or ``None`` when the
        bounded pool cannot hold the private pages (or swap is
        disabled) — the slot is released either way, and the caller
        falls back to recomputing the KV from the token history."""
        import jax

        P = self.page_size
        written = -(-int(self._lens[slot]) // P)
        pages = self._pages[slot]
        plan: List[tuple] = []
        data_pages: List[int] = []
        for i in range(written):
            pg = pages[i]
            if pg in self._page_key:
                plan.append(("key", self._page_key[pg]))
            else:
                plan.append(("data", len(data_pages)))
                data_pages.append(pg)
        handle = None
        if self.swap_pool_pages and \
                self._swap_used + len(data_pages) <= self.swap_pool_pages:
            k_host = v_host = ks_host = vs_host = None
            if data_pages:
                sel = np.asarray(data_pages)
                # device_get materializes host copies BEFORE the pages
                # return to the free list and get overwritten
                k_host = np.asarray(jax.device_get(
                    self.k_pages[:, :, sel]))
                v_host = np.asarray(jax.device_get(
                    self.v_pages[:, :, sel]))
                if self.kv_dtype == "int8":
                    ks_host = np.asarray(jax.device_get(
                        self.k_scales[:, :, sel]))
                    vs_host = np.asarray(jax.device_get(
                        self.v_scales[:, :, sel]))
            handle = next(self._swap_ids)
            self._swap[handle] = _SwapEntry(plan, k_host, v_host,
                                            ks_host, vs_host)
            self._swap_used += len(data_pages)
            self._m_swap_out.inc(len(data_pages))
            self._m_swap_pool.set(self._swap_used)
        else:
            self._m_swap_fallback.inc()
        self.release(slot)
        return handle

    def swap_in(self, handle: int, n_tokens: int) -> Optional[int]:
        """Restore a swapped-out sequence into a fresh slot with its
        full ``n_tokens`` page budget re-reserved (shared prefix pages
        re-pinned through the index, private pages device-written from
        the host pool, the unwritten remainder freshly grabbed).

        Returns the new slot id, or ``None`` when the entry cannot be
        restored (dropped, a shared prefix page was evicted while
        suspended, or the free/evictable pools cannot cover the
        budget).  The handle is CONSUMED either way — on ``None`` the
        caller must recompute, not retry."""
        import jax.numpy as jnp

        entry = self._swap.pop(handle, None)
        if entry is None:
            self._m_swap_fallback.inc()
            return None

        def _drop(n_shared_pinned=0, shared=()):
            for pg in list(shared)[:n_shared_pinned][::-1]:
                self._unref(pg)
            self._swap_used -= entry.n_host_pages
            self._m_swap_pool.set(self._swap_used)
            self._m_swap_fallback.inc()
            self._track_pages()
            return None

        # resolve the shared chain keys first (pure reads): any miss
        # means the prefix page was evicted while we were suspended
        shared: List[int] = []
        for kind, val in entry.plan:
            if kind == "key":
                pg = self._index.get(val)
                if pg is None:
                    return _drop()
                shared.append(pg)
        free_slots = [i for i, u in enumerate(self._used) if not u]
        if not free_slots:
            return _drop()
        slot = free_slots[0]
        need = -(-n_tokens // self.page_size)
        enforce(need >= len(entry.plan),
                f"swap_in budget {need} pages < {len(entry.plan)} "
                f"written pages")
        # pin shared pages FIRST (mirrors allocate: grabbing the
        # remainder can then never evict them out from under us)
        for pg in shared:
            self._ref[pg] += 1
            if pg in self._lru:
                del self._lru[pg]
        if len(self._free) + len(self._lru) < need - len(shared):
            return _drop(len(shared), shared)
        self._m_alloc.inc(len(shared))
        sit = iter(shared)
        pages: List[int] = []
        restore: List[tuple] = []              # (device page, host row)
        for kind, val in entry.plan:
            if kind == "key":
                pages.append(next(sit))
            else:
                pg = self._grab_page("swap-in")
                pages.append(pg)
                restore.append((pg, val))
        pages += [self._grab_page("swap-in")
                  for _ in range(need - len(entry.plan))]
        if restore:
            sel = np.asarray([pg for pg, _ in restore])
            src = np.asarray([j for _, j in restore])
            self.k_pages = self.k_pages.at[:, :, sel].set(
                jnp.asarray(entry.k_host[:, :, src]))
            self.v_pages = self.v_pages.at[:, :, sel].set(
                jnp.asarray(entry.v_host[:, :, src]))
            if self.kv_dtype == "int8":
                self.k_scales = self.k_scales.at[:, :, sel].set(
                    jnp.asarray(entry.k_scale_host[:, :, src]))
                self.v_scales = self.v_scales.at[:, :, sel].set(
                    jnp.asarray(entry.v_scale_host[:, :, src]))
        self._used[slot] = True
        self._pages[slot] = pages
        self._lens[slot] = 0                   # caller set_len()s
        self._table[slot, :] = 0
        self._table[slot, :need] = pages
        self._swap_used -= entry.n_host_pages
        self._m_swap_in.inc(len(restore))
        self._m_swap_pool.set(self._swap_used)
        self._track_pages()
        return slot

    def drop_swap(self, handle: Optional[int]) -> bool:
        """Free a swap entry without restoring it (the abort path for
        suspended requests).  ``None`` and already-consumed handles
        are no-ops — abort stays idempotent."""
        entry = self._swap.pop(handle, None) if handle is not None \
            else None
        if entry is None:
            return False
        self._swap_used -= entry.n_host_pages
        self._m_swap_pool.set(self._swap_used)
        return True

    def swap_pool_used(self) -> int:
        """Host swap-pool pages currently holding preempted KV."""
        return self._swap_used

    # -- KV migration (multi-host drain/rebalance) -----------------------------
    def _swap_geometry(self) -> dict:
        """The shape contract a migration blob must match: mismatched
        geometry would reinterpret page bytes, so import refuses it."""
        return {"page_size": self.page_size,
                "num_layers": self.num_layers,
                "n_kv_heads": int(self.k_pages.shape[1]),
                "head_dim": int(self.k_pages.shape[-1]),
                "kv_dtype": self.kv_dtype or "",
                "pool_dtype": str(np.dtype(self.k_pages.dtype))}

    def export_swap(self, handle: Optional[int]) -> Optional[bytes]:
        """Serialize one swap entry into a PORTABLE blob (self-described
        npz: a json meta record plus the host page arrays) for shipping
        to another host's cache.  The entry is CONSUMED — its pool pages
        free immediately, mirroring ``swap_in``'s handle semantics.
        Shared-prefix plan entries travel as their chain keys (hex), so
        the destination re-pins them through ITS index — a miss there
        degrades to the recompute path at resume, never to wrong bytes.
        ``None`` / already-consumed handles return ``None`` (the caller
        ships a recompute-only package)."""
        import jax

        entry = self._swap.pop(handle, None) if handle is not None \
            else None
        if entry is None:
            return None
        self._swap_used -= entry.n_host_pages
        self._m_swap_pool.set(self._swap_used)
        # MATERIALIZE shared-prefix plan entries whose chain key still
        # resolves locally: the destination's index almost never holds
        # this host's prefixes, so a key-only blob would degrade every
        # cross-host migration to recompute.  Registered pages are
        # immutable, so their bytes can be read out here; keys that no
        # longer resolve (evicted while suspended) stay keys — the
        # destination gets one last chance to re-pin, else recompute.
        n_data = entry.n_host_pages
        extra_sel: List[int] = []
        plan: List[tuple] = []
        for kind, val in entry.plan:
            if kind == "key":
                pg = self._index.get(val)
                if pg is not None:
                    plan.append(("data", n_data + len(extra_sel)))
                    extra_sel.append(pg)
                    continue
            plan.append((kind, val))
        k_host, v_host = entry.k_host, entry.v_host
        ks_host, vs_host = entry.k_scale_host, entry.v_scale_host
        if extra_sel:
            sel = np.asarray(extra_sel)
            ek = np.asarray(jax.device_get(self.k_pages[:, :, sel]))
            ev = np.asarray(jax.device_get(self.v_pages[:, :, sel]))
            k_host = ek if k_host is None else \
                np.concatenate([k_host, ek], axis=2)
            v_host = ev if v_host is None else \
                np.concatenate([v_host, ev], axis=2)
            if self.kv_dtype == "int8":
                eks = np.asarray(jax.device_get(
                    self.k_scales[:, :, sel]))
                evs = np.asarray(jax.device_get(
                    self.v_scales[:, :, sel]))
                ks_host = eks if ks_host is None else \
                    np.concatenate([ks_host, eks], axis=2)
                vs_host = evs if vs_host is None else \
                    np.concatenate([vs_host, evs], axis=2)
        meta = dict(self._swap_geometry())
        meta["plan"] = [["key", val.hex()] if kind == "key"
                        else ["data", int(val)]
                        for kind, val in plan]
        meta["n_host_pages"] = n_data + len(extra_sel)
        arrays = {"meta": np.frombuffer(
            json.dumps(meta).encode("utf-8"), np.uint8)}
        if k_host is not None:
            arrays["k_host"] = k_host
            arrays["v_host"] = v_host
            if ks_host is not None:
                arrays["k_scale_host"] = ks_host
                arrays["v_scale_host"] = vs_host
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        self._m_swap_export.inc(meta["n_host_pages"])
        return buf.getvalue()

    def import_swap(self, blob: Optional[bytes]) -> Optional[int]:
        """Adopt a migrated swap blob into THIS cache's host pool and
        return a local handle ``swap_in`` understands.  Geometry
        mismatches raise (an operator wiring error, not a degradable
        fault); a pool that cannot hold the blob's pages returns
        ``None`` — the caller resumes via recompute instead, so a small
        destination never blocks a drain."""
        if blob is None:
            return None
        with np.load(io.BytesIO(blob)) as z:
            meta = json.loads(bytes(z["meta"]).decode("utf-8"))
            geo = self._swap_geometry()
            for k, v in geo.items():
                enforce(meta.get(k) == v,
                        f"migration blob geometry mismatch: {k} is "
                        f"{meta.get(k)!r}, this cache has {v!r}")
            k_host = z["k_host"] if "k_host" in z else None
            v_host = z["v_host"] if "v_host" in z else None
            ks_host = z["k_scale_host"] if "k_scale_host" in z else None
            vs_host = z["v_scale_host"] if "v_scale_host" in z else None
        n_host = int(meta["n_host_pages"])
        if not self.swap_pool_pages or \
                self._swap_used + n_host > self.swap_pool_pages:
            self._m_swap_fallback.inc()
            return None
        plan = [("key", bytes.fromhex(val)) if kind == "key"
                else ("data", int(val)) for kind, val in meta["plan"]]
        handle = next(self._swap_ids)
        self._swap[handle] = _SwapEntry(plan, k_host, v_host,
                                        ks_host, vs_host)
        self._swap_used += n_host
        self._m_swap_import.inc(n_host)
        self._m_swap_pool.set(self._swap_used)
        return handle

    # -- prefix caching (public) -----------------------------------------------
    def lookup_prefix(self, token_ids) -> Tuple[int, List[int]]:
        """Longest page-aligned cached prefix of ``token_ids``: walks
        the chain of full-page block hashes through the index and
        returns (n_cached_tokens, pages).  Pure host work — pass the
        pages to ``allocate(shared_pages=...)`` to map them."""
        token_ids = list(token_ids)
        P = self.page_size
        key = b""
        pages: List[int] = []
        for i in range(len(token_ids) // P):
            key = _chain_hash(key, token_ids[i * P:(i + 1) * P])
            pg = self._index.get(key)
            if pg is None:
                break
            pages.append(pg)
        return len(pages) * P, pages

    def register_prefix(self, slot: int, token_ids, upto: Optional[int]
                        = None) -> int:
        """Publish the slot's full, already-written prefill pages into
        the prefix index (first ``upto`` tokens of ``token_ids``,
        rounded DOWN to whole pages and clamped to the written length).
        Pages whose chain key is already indexed are skipped — first
        writer wins, duplicates stay private.  Returns the number of
        pages newly registered."""
        P = self.page_size
        n = len(token_ids) if upto is None else min(upto, len(token_ids))
        n = min(n, int(self._lens[slot]))
        key = b""
        added = 0
        for i in range(n // P):
            key = _chain_hash(key, token_ids[i * P:(i + 1) * P])
            pg = self._pages[slot][i]
            if key not in self._index and pg not in self._page_key:
                self._index[key] = pg
                self._page_key[pg] = key
                added += 1
        self._track_pages()
        return added

    def cached_page_count(self) -> int:
        """Registered prefix pages currently unreferenced (evictable)."""
        return len(self._lru)

    def shared_page_count(self) -> int:
        """Physical pages mapped by more than one slot right now."""
        return int((self._ref > 1).sum())

    def page_ref_count(self, page: int) -> int:
        return int(self._ref[page])

    def set_len(self, slot: int, n: int):
        """Host-side length after an in-graph prefill wrote the pages
        directly (chunked prefill)."""
        self._lens[slot] = n

    def advance(self, slots, n: int = 1):
        for s in np.atleast_1d(slots):
            self._lens[s] += n

    def rollback(self, slot: int, n: int):
        """Un-append the last ``n`` tokens of ``slot`` (speculative
        decoding's rejected-suffix rollback): a host-side ``_lens``
        decrement and NOTHING else — the mirror of ``advance``'s
        under-advance contract.  The rejected rows' K/V (and, for int8
        pools, their scale rows) stay physically in the pages but are
        never attended (every attention path masks at ``kv_pos <
        len``) and the next append overwrites them in place, scale
        rows traveling alongside.  Pages stay attached to the slot —
        release-safe: ``release`` still walks the full table, and
        re-appending never re-grabs pages the slot already holds."""
        n = int(n)
        enforce(n >= 0, f"rollback of {n} tokens")
        enforce(self._used[slot], f"rollback on free slot {slot}")
        enforce(self._lens[slot] >= n,
                f"rollback of {n} tokens but slot {slot} holds "
                f"{int(self._lens[slot])}")
        self._lens[slot] -= n

    @property
    def seq_lens(self) -> np.ndarray:
        return self._lens

    @property
    def page_table(self) -> np.ndarray:
        return self._table

    def free_page_count(self) -> int:
        """Allocatable pages: truly free plus the prefix-cached LRU
        pool (reclaimed transparently by eviction)."""
        return len(self._free) + len(self._lru)

    def free_pages(self) -> int:
        """Admission-control view of capacity: pages an ``allocate``
        can obtain RIGHT NOW — the free list plus the evictable
        prefix-cached LRU pool.  A scheduler that checks
        ``free_pages() >= ceil(total_tokens / page_size)`` before
        admitting can never see the OOM raise (the engine reserves a
        request's full page budget at admission, so decode never grabs
        more)."""
        return len(self._free) + len(self._lru)

    def free_slot_count(self) -> int:
        """Sequence slots not currently bound to a live request."""
        return sum(1 for u in self._used if not u)

    def kv_bytes_per_token(self) -> int:
        """HBM bytes one cached token costs across all layers and both
        pools — int8 counts its f32 scale rows, so capacity claims stay
        honest."""
        head_dim = self.k_pages.shape[-1]
        kvh = self.k_pages.shape[1]
        if self.kv_dtype == "int8":
            per_row = head_dim * 1 + 4          # int8 values + f32 scale
        else:
            per_row = head_dim * self.k_pages.dtype.itemsize
        return 2 * self.num_layers * kvh * per_row

    def metrics_snapshot(self) -> dict:
        """This cache's page-pressure counters (host view; the same
        series are in the global registry under label cache=<id>)."""
        return {"pages_allocated": int(self._m_alloc.value),
                "pages_released": int(self._m_release.value),
                "oom_events": int(self._m_oom.value),
                "free_pages": self.free_page_count(),
                "page_utilization": self.page_utilization(),
                "prefix_cached_pages": self.cached_page_count(),
                "prefix_shared_pages": self.shared_page_count(),
                "prefix_evicted_pages": int(self._m_evict.value),
                "cow_pages": int(self._m_cow.value),
                "swap_pool_pages": self.swap_pool_pages,
                "swap_pool_used": self._swap_used,
                "swap_out_pages": int(self._m_swap_out.value),
                "swap_in_pages": int(self._m_swap_in.value),
                "swap_exported_pages": int(self._m_swap_export.value),
                "swap_imported_pages": int(self._m_swap_import.value),
                "swap_fallbacks": int(self._m_swap_fallback.value)}

    def memory_rows(self) -> dict:
        """Memory-plane accounting row (observability.introspection):
        actual bytes held by the device page pools (values + int8 scale
        planes) and by the host swap pool's staged page copies.

        Under tensor parallelism ``device_bytes`` stays the GLOBAL
        logical pool size (``jax.Array.nbytes`` is logical bytes, and
        fleet aggregation sums these rows — a tp=4 replica must not
        look 4× cheaper than it is); ``device_bytes_per_shard`` is
        what one chip's HBM actually holds (the /memz capacity-planning
        number), with ``tp`` alongside so the division is auditable."""
        dev = int(self.k_pages.nbytes) + int(self.v_pages.nbytes)
        if self.k_scales is not None:
            dev += int(self.k_scales.nbytes) + int(self.v_scales.nbytes)
        tp = self._shardings.tp if self._shardings is not None else 1
        host = 0
        for entry in self._swap.values():
            for arr in (entry.k_host, entry.v_host,
                        entry.k_scale_host, entry.v_scale_host):
                if arr is not None:
                    host += int(arr.nbytes)
        return {"device_bytes": dev,
                "device_bytes_per_shard": dev // tp,
                "tp": tp,
                "host_bytes": host,
                "pages": int(self.n_pages),
                "free_pages": self.free_page_count(),
                "bytes_per_token": self.kv_bytes_per_token(),
                "swap_pool_pages": int(self.swap_pool_pages),
                "swap_pool_used": int(self._swap_used)}

    # -- device-side ops -------------------------------------------------------
    def _norm_layers(self, k, v, tokens_axis: int):
        """Accept [S?, KVH, D]-style per-layer input when num_layers==1,
        else require a leading layer dim."""
        import jax.numpy as jnp
        k, v = jnp.asarray(k), jnp.asarray(v)
        if k.ndim == 3:
            enforce(self.num_layers == 1,
                    f"cache holds {self.num_layers} layers; pass "
                    f"[L, ...] keys/values")
            k, v = k[None], v[None]
        return k, v

    def write_prefill(self, slot: int, k, v):
        """Bulk-write a prefill's keys/values into the sequence's pages
        with ONE vectorized scatter per pool (int8 mode quantizes the
        rows on the way in and scatters the scales alongside).

        k/v: [S, KVH, D] (num_layers==1) or [L, S, KVH, D]."""
        import jax.numpy as jnp
        k, v = self._norm_layers(k, v, 1)
        s = k.shape[1]
        self.extend(slot, s)
        start = int(self._lens[slot])
        pos = np.arange(start, start + s)
        pages = jnp.asarray(self._table[slot, pos // self.page_size])
        slots_ = jnp.asarray(pos % self.page_size)
        # [L, S, KVH, D] -> [L, KVH, S, D] scatter at (pages, slots)
        kt = jnp.swapaxes(k, 1, 2)
        vt = jnp.swapaxes(v, 1, 2)
        if self.kv_dtype == "int8":
            from ..quantization.ops import quantize_rows_raw
            kt, ksc = quantize_rows_raw(kt)       # + [L, KVH, S] scales
            vt, vsc = quantize_rows_raw(vt)
            self.k_scales = self.k_scales.at[:, :, pages, slots_].set(ksc)
            self.v_scales = self.v_scales.at[:, :, pages, slots_].set(vsc)
        else:
            kt = kt.astype(self.k_pages.dtype)
            vt = vt.astype(self.v_pages.dtype)
        self.k_pages = self.k_pages.at[:, :, pages, slots_, :].set(kt)
        self.v_pages = self.v_pages.at[:, :, pages, slots_, :].set(vt)
        self._lens[slot] = start + s

    def append(self, slots, k_new, v_new):
        """Decode step: one new token for each sequence in ``slots``.

        k_new/v_new: [B, KVH, D] (num_layers==1) or [L, B, KVH, D];
        lengths advance by 1 (once, across all layers)."""
        import jax.numpy as jnp
        k_new, v_new = self._norm_layers(k_new, v_new, 1)
        slots = np.atleast_1d(slots)
        for s in slots:
            self.extend(int(s), 1)
        pos = self._lens[slots]
        pages = jnp.asarray(self._table[slots, pos // self.page_size])
        slot_in_page = jnp.asarray(pos % self.page_size)
        # ONE all-layer scatter: this method is EAGER (each op call
        # copies its output), so a per-layer dus chain would copy the
        # pool 2·L·B times per token; the jit-compiled serving path
        # (engine's fused append+attend kernel) never comes through here
        kt = jnp.swapaxes(k_new, 1, 2)
        vt = jnp.swapaxes(v_new, 1, 2)
        if self.kv_dtype == "int8":
            from ..quantization.ops import quantize_rows_raw
            kt, ksc = quantize_rows_raw(kt)       # + [L, KVH, B] scales
            vt, vsc = quantize_rows_raw(vt)
            self.k_scales = self.k_scales.at[
                :, :, pages, slot_in_page].set(ksc)
            self.v_scales = self.v_scales.at[
                :, :, pages, slot_in_page].set(vsc)
        else:
            kt = kt.astype(self.k_pages.dtype)
            vt = vt.astype(self.v_pages.dtype)
        self.k_pages = self.k_pages.at[:, :, pages, slot_in_page, :].set(kt)
        self.v_pages = self.v_pages.at[:, :, pages, slot_in_page, :].set(vt)
        self.advance(slots, 1)

    def attend(self, slots, q, layer: int = 0,
               use_kernel: Optional[bool] = None):
        """Decode attention for ``q`` [B, H, D] over the cached pages of
        ``slots`` in ``layer``.  Kernel on TPU, jnp reference elsewhere;
        int8 pools hand the kernel their per-token scales and dequantize
        in VMEM."""
        import jax.numpy as jnp
        from ..runtime.device import is_compiled_with_tpu
        from ..ops.pallas.paged_attention import (paged_attention_raw,
                                                  paged_attention_reference)
        slots = np.atleast_1d(slots)
        table = jnp.asarray(self._table[slots])
        lens = jnp.asarray(self._lens[slots])
        if use_kernel is None:
            use_kernel = is_compiled_with_tpu()
        fn = paged_attention_raw if use_kernel else \
            paged_attention_reference
        args = ()
        if self.kv_dtype == "int8":
            args = (self.k_scales[layer][:, :, None, :],
                    self.v_scales[layer][:, :, None, :])
        return fn(jnp.asarray(q), self.k_pages[layer],
                  self.v_pages[layer], table, lens, *args)
