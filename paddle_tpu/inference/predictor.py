"""paddle_inference-shaped predictor (SURVEY.md §1 L8, §3.6).

Reference parity: AnalysisPredictor — load a saved inference program +
params, feed/fetch by tensor name, Run().  TPU-native design: the
"analysis passes + NaiveExecutor" pipeline collapses into XLA — the
artifact is jit.save's StableHLO (.pdmodel/.pdiparams) and Run() is one
jitted call; zero-copy IO becomes device arrays that stay put between
runs.  TensorRT/ONNX subgraph knobs are accepted and ignored (documented
no-ops: XLA is the one compiler here).
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..common.errors import enforce

__all__ = ["Config", "PredictorTensor", "Predictor", "create_predictor"]


class Config:
    """paddle.inference.Config parity (the subset that makes sense on
    TPU; GPU/TRT/MKLDNN toggles are accepted no-ops)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self._prefix = prog_file
        self._device = "tpu"
        self._device_id = 0

    def _set_prefix(self, path: str):
        if path and path.endswith(".pdmodel"):
            path = path[:-len(".pdmodel")]
        self._prefix = path

    def set_prog_file(self, path: str):
        self._set_prefix(path)

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def params_file(self):
        return (self._prefix or "") + ".pdiparams"

    def set_model(self, path: str, params: Optional[str] = None):
        """Directory layout (`path/inference.pdmodel`) or prefix."""
        if os.path.isdir(path):
            for f in os.listdir(path):
                if f.endswith(".pdmodel"):
                    self._prefix = os.path.join(path, f[:-len(".pdmodel")])
                    return
            raise FileNotFoundError(f"no .pdmodel under {path}")
        self._set_prefix(path)

    # device selection
    def enable_use_gpu(self, memory_pool_init_size_mb=0, device_id=0):
        self._device, self._device_id = "tpu", device_id  # alias: GPU→TPU

    def enable_xpu(self, *a, **k):
        self._device = "tpu"

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device == "tpu"

    # accepted no-ops (XLA already fuses/optimizes; documented)
    def switch_ir_optim(self, x=True): ...
    def enable_memory_optim(self, x=True): ...
    def enable_tensorrt_engine(self, *a, **k): ...
    def set_cpu_math_library_num_threads(self, n): ...
    def switch_use_feed_fetch_ops(self, x): ...
    def switch_specify_input_names(self, x): ...


class PredictorTensor:
    """Input/output handle (paddle_inference Tensor parity): copy_from_cpu
    / copy_to_cpu / reshape.  The device array persists between runs."""

    def __init__(self, name: str):
        self.name = name
        self._host: Optional[np.ndarray] = None
        self._dev = None

    def reshape(self, shape: Sequence[int]):
        if self._host is not None:
            self._host = self._host.reshape(shape)

    def copy_from_cpu(self, arr: np.ndarray):
        import jax
        self._host = np.ascontiguousarray(arr)
        self._dev = jax.device_put(self._host)

    def copy_to_cpu(self) -> np.ndarray:
        import jax
        if self._dev is not None:
            return np.asarray(jax.device_get(self._dev))
        return self._host

    def shape(self):
        src = self._dev if self._dev is not None else self._host
        return tuple(src.shape) if src is not None else None


class Predictor:
    """Runs a jit.save'd artifact (or a live Layer) as one jitted call."""

    def __init__(self, config: Optional[Config] = None, layer=None,
                 input_names: Optional[List[str]] = None):
        self._inputs: Dict[str, PredictorTensor] = {}
        self._outputs: Dict[str, PredictorTensor] = {}
        if layer is not None:
            self._layer = layer
            n_in = len(input_names) if input_names else 1
        else:
            enforce(config is not None, "Predictor needs Config or layer")
            from ..jit.save_load import load as jit_load
            self._layer = jit_load(config._prefix)
            n_in = len(self._layer._input_specs)
        self._input_names = (list(input_names) if input_names
                             else [f"x{i}" for i in range(n_in)])
        for n in self._input_names:
            self._inputs[n] = PredictorTensor(n)
        self._output_names: List[str] = []

    # -- paddle_inference API -------------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> PredictorTensor:
        return self._inputs[name]

    def run(self, inputs: Optional[Sequence[np.ndarray]] = None):
        """Execute.  Either positional `inputs` (returns list of host
        arrays, the modern paddle_inference convenience) or via the
        feed/fetch handles."""
        if inputs is not None:
            for n, a in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(np.asarray(a))
        args = [self._inputs[n]._dev for n in self._input_names]
        enforce(all(a is not None for a in args),
                "copy_from_cpu every input handle before run()")
        out = self._layer(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        from ..tensor import Tensor
        vals = [o.value if isinstance(o, Tensor) else o for o in outs]
        if not self._output_names:
            self._output_names = [f"out{i}" for i in range(len(vals))]
            for n in self._output_names:
                self._outputs[n] = PredictorTensor(n)
        for n, v in zip(self._output_names, vals):
            self._outputs[n]._dev = v
        if inputs is not None:
            return [self._outputs[n].copy_to_cpu()
                    for n in self._output_names]
        return True

    def get_output_names(self) -> List[str]:
        return list(self._output_names)

    def get_output_handle(self, name: str) -> PredictorTensor:
        return self._outputs[name]

    def clone(self):
        p = Predictor.__new__(Predictor)
        p._layer = self._layer
        p._input_names = list(self._input_names)
        p._inputs = {n: PredictorTensor(n) for n in self._input_names}
        p._outputs = {}
        p._output_names = []
        return p


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
