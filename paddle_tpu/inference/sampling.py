"""Decode-window sampling-key contract.

The engine's decode window forks one subkey off the engine key per
window (``self._key, sub = jax.random.split(self._key)``) and then
chains INSIDE the window: every step splits the window key once and
samples with the subkey.  The on-device scanned window
(``scan_decode=True``) must reproduce the host-chained token stream
bit for bit, which reduces to reproducing this exact key sequence —
``jax.random.split`` is deterministic, so "same splits in the same
order" IS the whole contract.

This module is the single home of that derivation: the host-chained
step, the ``lax.scan``/``while_loop`` window bodies, and the tests all
derive step keys through ``split_step``, so a drive-by "optimization"
(folding in a step index, splitting n keys up front, reordering the
split against the sample) cannot silently fork the two paths.  Note
what the contract is NOT: keys are not indexed by ABSOLUTE step number
— step j of a window uses the j-th split of the WINDOW key, so early
exit inside a window (all rows done) skips splits without perturbing
the engine key, exactly like the host path which simply stops calling
``step()``.

Per-ROW draws fold the batch row index into the step subkey
(``fold_row``), so a request's token stream depends on the key chain
and its row id but NOT on which other requests share the batch.  The
live engine always folds the physical row (``draw_base=0`` + row i
folds i); capsule replay re-pins a request decoded in row r by placing
it in row 0 and passing ``draw_base=r``, so row 0 folds the original
r.  Greedy decoding ignores keys entirely, which is why it is
bit-identical across batch shapes without any of this.

``sample_logits`` is re-exported so window bodies import their whole
sampling surface from one place.
"""
from __future__ import annotations

from ..nn.generation import sample_logits

__all__ = ["split_step", "window_keys", "key_fingerprint",
           "key_from_fingerprint", "sample_logits", "fold_row",
           "spec_window_keys", "spec_draw_key"]

# Speculative windows fork ONE subkey off the engine key like every
# other window and derive every draw inside it from that fork via
# fold_in tags — the engine key stream is identical whether a window
# decodes plainly or speculatively, so capsules replay across both.
_SPEC_DRAFT_TAG = 0x5bec0d01     # draft propose chain root
_SPEC_ACCEPT_TAG = 0x5bec0d02    # acceptance-uniform root
_SPEC_RESAMPLE_TAG = 0x5bec0d03  # rejection-resample / bonus root


def spec_window_keys(key):
    """Derive one speculative window's (draft, accept, resample) key
    roots from its forked window key.  THE single definition — the
    live window and capsule replay both derive here, so the two
    cannot drift.  The draft root seeds the propose program's
    ``split_step`` chain; accept/resample roots seed per-(step, row)
    draws via ``spec_draw_key``."""
    import jax

    return (jax.random.fold_in(key, _SPEC_DRAFT_TAG),
            jax.random.fold_in(key, _SPEC_ACCEPT_TAG),
            jax.random.fold_in(key, _SPEC_RESAMPLE_TAG))


def spec_draw_key(root, step: int, row: int):
    """Per-(step, row) acceptance/resample draw key: the step folds
    first, then the row via ``fold_row`` — mirroring the decode
    window's ``split_step`` × ``fold_row`` grid, so a request's
    acceptance draws depend on its draw id (``draw_base + batch
    row``) and never on batch packing.  Replay re-pins a request by
    passing its CAPTURED row, exactly like token sampling."""
    import jax

    return fold_row(jax.random.fold_in(root, int(step)), int(row))


def fold_row(key, row):
    """Per-row sample key: ``jax.random.fold_in(step_subkey, row)``.

    THE single definition of the row fold — ``sample_logits`` (via
    ``row_ids=``), the window bodies, and the replay oracle all derive
    per-row keys here so they cannot drift.  ``row`` is the request's
    draw id: physical batch row on the live path, the CAPTURED row on
    replay (threaded in as ``draw_base + row_index``).
    """
    import jax

    return jax.random.fold_in(key, row)


def split_step(key):
    """One decode step's key derivation: ``(next_key, step_subkey)``.

    Exactly ``jax.random.split(key)`` unpacked — kept as THE single
    definition so host-chained dispatch and the scanned window bodies
    cannot drift.  Traceable (used inside jit/scan/while bodies) and
    callable eagerly (tests, host admission path).
    """
    import jax

    next_key, sub = jax.random.split(key)
    return next_key, sub


def key_fingerprint(key):
    """Portable record of a PRNG key: its raw uint32 words as a plain
    int list (JSON-able — request capsules carry window keys across
    replicas in migration packages and spill files).  Inverse of
    ``key_from_fingerprint``: round-tripping a key and splitting it
    reproduces the original split chain exactly, because the words ARE
    the key's whole state."""
    import jax
    import numpy as np

    try:
        words = jax.random.key_data(key)
    except (AttributeError, TypeError):
        words = key  # legacy raw uint32-vector key
    return [int(w) for w in np.asarray(words).ravel()]


def key_from_fingerprint(words):
    """Rebuild a decode-window key from ``key_fingerprint`` output.
    Returns the legacy uint32-vector form, which every sampling entry
    point in this repo accepts (``jax.random`` treats it as a
    threefry2x32 key)."""
    import jax.numpy as jnp

    return jnp.asarray(list(words), dtype=jnp.uint32)


def window_keys(key, n_steps: int):
    """Host-side mirror of an ``n_steps`` window's key sequence:
    ``([sub_0, ..., sub_{n_steps-1}], final_key)``.

    Reference oracle for tests that pin the scanned window's sampling
    draws against manual chaining; the engine itself never calls this
    (its windows derive keys step by step via ``split_step``).
    """
    subs = []
    for _ in range(int(n_steps)):
        key, sub = split_step(key)
        subs.append(sub)
    return subs, key
