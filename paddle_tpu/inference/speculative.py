"""Speculative decoding: draft-model propose, one-dispatch ragged
verify, bit-exact accept.

The engine (``LLMEngine(draft_model=...)``) runs decode windows in
three moves:

1. PROPOSE — the draft backbone (its own paged KV slot in a second
   ``PagedKVCache``) free-runs ``spec_k`` tokens per sequence in ONE
   compiled program (``_paged_draft_propose``, the same
   ``_decode_one_token_fn`` step body as plain decode, so the draft's
   key chain follows the standard ``split_step`` × ``fold_row`` grid).
2. VERIFY — the target scores the whole draft window per sequence in
   ONE ragged mixed dispatch (``engine._paged_mixed_step``): each
   sequence contributes ``k+1`` rows ``[last, d_1..d_k]`` described by
   per-sequence ``(q_start, q_len, kv_len)`` descriptors, split at
   page boundaries for the TPU kernel's ``kv_len % P + q_len <= P``
   contract.  ``k`` stays TRACED data inside the one static
   ``T_spec = max_seqs * (spec_k + 1)`` bucket, so churning the
   runtime ``k`` never recompiles.
3. ACCEPT — this module.  Greedy: the verify rows' argmaxes ARE the
   plain-greedy stream (row j's context is the prompt plus tokens the
   target itself confirmed), so the longest prefix where the draft
   matched plus the first correction is BIT-IDENTICAL to plain decode
   — no distributions, no draws.  Sampling: standard rejection
   acceptance (accept ``d_i`` w.p. ``min(1, p_i(d_i) / q_i(d_i))``,
   resample the first reject from ``normalize(max(p - q, 0))``), which
   preserves the target's post-filter distribution exactly for ANY
   proposal q.  The bonus token unifies as "always reject at row k
   with q := 0", whose residual is ``p`` itself.

Rejected suffixes roll back via ``PagedKVCache.rollback`` — a
host-side length decrement mirroring ``advance``; the stale rows are
never attended and the next append overwrites them, int8 scale rows
traveling alongside.
"""
from __future__ import annotations

import functools

import numpy as np

from ..common.errors import enforce

__all__ = ["greedy_accept", "rejection_accept", "residual_dist",
           "acceptance_uniforms"]


@functools.partial(
    __import__("jax").jit,
    static_argnames=("eps", "kvh", "head_dim", "transpose_head",
                     "strategy", "top_k", "top_p", "temperature",
                     "n_steps", "collect_probs", "shardings"),
    donate_argnames=("k_pages", "v_pages", "k_scales", "v_scales"))
def _paged_draft_propose(stack, norm_w, head_w, embed_w, rope,
                         k_pages, v_pages, k_scales, v_scales,
                         tokens, positions, tables, lens,
                         key, draw_base=0, *, eps: float, kvh: int,
                         head_dim: int, transpose_head: bool = False,
                         strategy: str = "greedy_search",
                         top_k: int = 0, top_p: float = 1.0,
                         temperature: float = 1.0, n_steps: int = 1,
                         collect_probs: bool = False, shardings=None):
    """The draft side of a speculative window: ``n_steps`` free-running
    draft tokens for every row as ONE XLA program — the same step body
    as ``_paged_decode_step`` (``_decode_one_token_fn``), dense
    backbones only (drafts are small; MoE drafts are refused at engine
    init).  Doubles as the draft CATCH-UP program with ``n_steps=1``
    and teacher-forced inputs (outputs ignored), so the engine needs
    exactly two trace shapes per draft geometry.

    Returns (tokens [n_steps, B], k_pages', v_pages', k_scales',
    v_scales') — plus a trailing post-filter draft distribution
    ``q [n_steps, B, V]`` when ``collect_probs`` (the rejection
    acceptance's q surface; greedy windows never pay for it)."""
    import jax

    from .engine import _decode_one_token_fn

    one_token = _decode_one_token_fn(
        stack, norm_w, head_w, embed_w, rope, tables,
        eps=eps, kvh=kvh, head_dim=head_dim,
        transpose_head=transpose_head, strategy=strategy, top_k=top_k,
        top_p=top_p, temperature=temperature, draw_base=draw_base,
        shardings=shardings, arch=None, collect_probs=collect_probs)

    carry0 = (tokens, positions, lens, k_pages, v_pages, k_scales,
              v_scales, key)

    if not collect_probs:
        def body(carry, _):
            carry = one_token(carry)
            return carry, carry[0]
    else:
        def body(carry, _):
            carry, probs = one_token(carry)
            return carry, (carry[0], probs)

    final, ys = jax.lax.scan(body, carry0, None, length=n_steps)
    (_, _, _, k_pages, v_pages, k_scales, v_scales, _) = final
    if not collect_probs:
        return ys, k_pages, v_pages, k_scales, v_scales
    toks, probs = ys
    return toks, k_pages, v_pages, k_scales, v_scales, probs


def greedy_accept(draft_toks, target_toks):
    """One row's greedy acceptance: ``draft_toks`` [k] are the draft's
    proposals, ``target_toks`` [k+1] the verify rows' argmaxes (row j
    = the target's next token after consuming ``[last, d_1..d_j]``).

    Delivered tokens are ``target_toks[:a+1]`` where ``a`` is the
    longest prefix with ``target_toks[j] == draft_toks[j]``: matched
    rows deliver the draft token (== the argmax), the first mismatch
    delivers the target's CORRECTION, full acceptance delivers the
    BONUS row.  Row j's verify context is exactly the plain-greedy
    context by induction, so the delivered stream is bit-identical to
    plain greedy decode — the tentpole invariant.

    Returns ``(tokens, n_accepted)``: the delivered token list
    (``n_accepted + 1`` long) and how many DRAFT tokens survived."""
    draft_toks = np.asarray(draft_toks)
    target_toks = np.asarray(target_toks)
    k = int(draft_toks.shape[0])
    enforce(target_toks.shape[0] == k + 1,
            "greedy_accept wants k+1 verify rows for k draft tokens")
    a = 0
    while a < k and int(target_toks[a]) == int(draft_toks[a]):
        a += 1
    return [int(t) for t in target_toks[:a + 1]], a


def residual_dist(p, q):
    """The rejection-resample distribution ``normalize(max(p - q, 0))``
    [V] f64.  Degenerates to ``p`` when the residual mass vanishes
    (p == q to rounding): the accept ratio was 1 everywhere, so any
    fallback is distributionally moot — ``p`` keeps the draw defined
    and deterministic."""
    r = np.maximum(np.asarray(p, np.float64) - np.asarray(q, np.float64),
                   0.0)
    s = float(r.sum())
    if s <= 1e-12:
        p = np.asarray(p, np.float64)
        return p / max(float(p.sum()), 1e-30)
    return r / s


def acceptance_uniforms(accept_root, steps: int, row: int):
    """The row's acceptance uniforms ``u_0..u_{steps-1}`` — one eager
    draw per step off ``spec_draw_key(accept_root, j, row)``.  Host
    numpy out: the acceptance walk is host-side (k and B are tiny)."""
    import jax

    from .sampling import spec_draw_key

    return [float(np.asarray(jax.random.uniform(
        spec_draw_key(accept_root, j, row)))) for j in range(steps)]


def rejection_accept(draft_toks, q_probs, p_probs, accept_root,
                     resample_root, row):
    """One row's rejection acceptance (sampled decoding).

    ``draft_toks`` [k]: the draft's sampled proposals; ``q_probs``
    [k, V]: the post-filter draft distribution each was drawn from;
    ``p_probs`` [k+1, V]: the target's post-filter distribution at the
    verify rows (row k is the bonus distribution).  ``accept_root`` /
    ``resample_root``: the window's ``spec_window_keys`` roots;
    ``row``: the request's draw id (``draw_base + batch row``), so
    draws are batch-packing independent and capsule replay can re-pin
    them.

    Accept ``d_j`` w.p. ``min(1, p_j(d_j) / q_j(d_j))`` against
    uniform ``u_j``; the first reject resamples from ``normalize(
    max(p_j - q_j, 0))``.  Full acceptance draws the bonus from
    ``p_k`` — the unified "reject at row k with q := 0" draw, keyed at
    step k of the SAME resample chain.  Marginals equal the target's
    post-filter distribution exactly (speculative-sampling identity),
    for any proposal q.

    Returns ``(tokens, n_accepted)`` like ``greedy_accept``."""
    import jax
    import jax.numpy as jnp

    from .sampling import spec_draw_key

    draft_toks = np.asarray(draft_toks)
    k = int(draft_toks.shape[0])
    q_probs = np.asarray(q_probs, np.float64)
    p_probs = np.asarray(p_probs, np.float64)
    enforce(p_probs.shape[0] == k + 1,
            "rejection_accept wants k+1 verify rows for k draft tokens")
    us = acceptance_uniforms(accept_root, k, row)
    out = []
    for j in range(k):
        d = int(draft_toks[j])
        ratio = p_probs[j, d] / max(q_probs[j, d], 1e-30)
        if us[j] < min(1.0, ratio):
            out.append(d)
            continue
        dist = residual_dist(p_probs[j], q_probs[j])
        tok = int(np.asarray(jax.random.categorical(
            spec_draw_key(resample_root, j, row),
            jnp.log(jnp.asarray(dist, jnp.float32)))))
        return out + [tok], j
    # full acceptance: bonus row = "reject at k with q := 0", whose
    # residual is p_k itself — same resample chain, step k
    tok = int(np.asarray(jax.random.categorical(
        spec_draw_key(resample_root, k, row),
        jnp.log(jnp.asarray(p_probs[k], jnp.float32)))))
    return out + [tok], k
