from .dataloader import (
    BatchSampler,
    ChainDataset,
    ComposeDataset,
    DataLoader,
    Dataset,
    DistributedBatchSampler,
    IterableDataset,
    RandomSampler,
    Sampler,
    SequenceSampler,
    Subset,
    TensorDataset,
    random_split,
)
from .token_dataset import TokenFileDataset, TokenFileLoader
