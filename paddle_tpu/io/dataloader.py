"""Dataset / Sampler / DataLoader.

Reference parity: python/paddle/io/ — Dataset, IterableDataset,
TensorDataset, Sampler family, DistributedBatchSampler, and DataLoader
(the reference's multiprocess loader uses shared-memory queues; here a
thread-based prefetcher feeds the accelerator since jax host→device
transfer releases the GIL and TPU input pipelines are normally grain /
tf.data-style prefetch pipelines — same API, TPU-appropriate engine).
"""
from __future__ import annotations

import itertools
import time
import queue
import threading
from typing import Any, Callable, Iterable, List, Optional, Sequence

import numpy as np

from ..common.errors import enforce
from ..tensor import Tensor, to_tensor

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "ConcatDataset", "Subset", "random_split", "Sampler",
    "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
    "SubsetRandomSampler", "BatchSampler", "DistributedBatchSampler",
    "DataLoader", "CheckpointableLoader", "get_worker_info",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        self.tensors = [t if isinstance(t, Tensor) else to_tensor(t)
                        for t in tensors]
        n = self.tensors[0].shape[0]
        enforce(all(t.shape[0] == n for t in self.tensors),
                "all tensors must share dim 0")

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets: Sequence[Dataset]):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            item = ds[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(ds) for ds in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets: Sequence[IterableDataset]):
        self.datasets = list(datasets)

    def __iter__(self):
        return itertools.chain(*self.datasets)


class Subset(Dataset):
    def __init__(self, dataset: Dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


class ConcatDataset(Dataset):
    """Map-style concatenation (paddle/torch ConcatDataset)."""

    def __init__(self, datasets: Sequence[Dataset]):
        self.datasets = list(datasets)
        enforce(len(self.datasets) > 0,
                "ConcatDataset needs at least one dataset")
        self.cumulative_sizes = np.cumsum(
            [len(d) for d in self.datasets]).tolist()

    def __getitem__(self, idx):
        if idx < 0:
            if idx < -len(self):
                raise ValueError(
                    "absolute value of index should not exceed dataset "
                    f"length ({len(self)})")
            idx += len(self)
        if idx >= len(self):
            # IndexError, not ValueError: plain for-loops over
            # map-style datasets terminate via the sequence protocol
            raise IndexError(
                f"index {idx} out of range for ConcatDataset of "
                f"length {len(self)}")
        ds = int(np.searchsorted(self.cumulative_sizes, idx,
                                 side="right"))
        prev = self.cumulative_sizes[ds - 1] if ds else 0
        return self.datasets[ds][idx - prev]

    def __len__(self):
        return self.cumulative_sizes[-1]


def random_split(dataset: Dataset, lengths: Sequence[int], generator=None):
    enforce(sum(lengths) == len(dataset), "lengths must sum to dataset size")
    perm = np.random.permutation(len(dataset))
    out, offset = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[offset:offset + n].tolist()))
        offset += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__(None)
        self.weights = np.asarray(weights, np.float64)
        self._num_samples = int(num_samples)
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(np.random.choice(
            len(self.weights), self._num_samples,
            replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self._num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices):
        super().__init__(None)
        self.indices = list(indices)

    def __iter__(self):
        return iter(np.random.permutation(self.indices).tolist())

    def __len__(self):
        return len(self.indices)


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        else:
            self.sampler = RandomSampler(dataset) if shuffle \
                else SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards sample indices across data-parallel ranks.

    On TPU the compiled path usually feeds a *global* batch sharded via
    jax.sharding, but the paddle-shaped per-rank loader is kept for API
    and multi-host (one process per host feeds its slice).
    """

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None:
            from ..distributed import env as dist_env
            num_replicas = dist_env.get_world_size()
        if rank is None:
            from ..distributed import env as dist_env
            rank = dist_env.get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        # pad to make divisible
        indices += indices[: self.total_size - len(indices)]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch: int):
        self.epoch = epoch


def default_collate_fn(batch: List[Any]):
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return [default_collate_fn([b[i] for b in batch])
                for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return to_tensor(np.stack([np.asarray(b.value) for b in batch]))
    if isinstance(sample, np.ndarray):
        return to_tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return to_tensor(np.asarray(batch))
    return batch


class WorkerInfo:
    def __init__(self, id, num_workers, dataset):  # noqa: A002
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_WORKER_INFO: "WorkerInfo | None" = None


def get_worker_info():
    """Inside a DataLoader worker process: its (id, num_workers,
    dataset); None in the main process (paddle/torch contract)."""
    return _WORKER_INFO


def _mp_worker_loop(dataset, task_q, res_q, init_fn, wid,
                    num_workers=0):
    """Subprocess worker: evaluates dataset[i] (numpy-level — workers
    must not touch jax; collation and device placement stay in the
    parent) and ships raw items back."""
    global _WORKER_INFO
    _WORKER_INFO = WorkerInfo(wid, num_workers, dataset)
    if init_fn is not None:
        init_fn(wid)
    while True:
        task = task_q.get()
        if task is None:
            return
        bid, idxs = task
        try:
            res_q.put((bid, [dataset[i] for i in idxs], None))
        except Exception as e:                     # surfaced in parent
            res_q.put((bid, None, repr(e)))
            return


class DataLoader:
    """paddle.io.DataLoader-shaped loader.

    ``num_workers=0``: synchronous in-process iteration.
    ``num_workers>0``: that many FORKED worker processes evaluate
    ``dataset[i]`` in parallel (the reference's multiprocess DataLoader
    contract); raw items return via queues, the parent collates and
    places on device.  IterableDataset streams use a thread prefetcher
    (a python iterator cannot be sharded across forks safely).
    """

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=False, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.prefetch_factor = max(2, prefetch_factor)
        self.num_workers = num_workers
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def _gen_batches(self):
        if self._iterable:
            batch = []
            for item in self.dataset:
                batch.append(item)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
        else:
            for idx_batch in self.batch_sampler:
                items = [self.dataset[i] for i in idx_batch]
                yield self.collate_fn(items)

    def _mp_iter(self):
        import multiprocessing as mp
        # prefer forkserver: forking a JAX-initialized (multi-threaded)
        # parent is deprecated on 3.12+ and can deadlock the child.
        # forkserver needs a picklable dataset — probe once per loader
        # (cached across epochs, null-sink pickler so no bytes are
        # materialized) and fall back to fork for closures/local
        # classes (documented constraint: fork-path datasets must be
        # fork-safe instead).
        method = getattr(self, "_mp_method", None)
        if method is None:
            import pickle

            class _NullSink:
                def write(self, _):
                    return None

            class _Probe(pickle.Pickler):
                # anything pickled BY REFERENCE to __main__ (classes,
                # functions, partial targets, nested transforms) would
                # fail to re-import in a forkserver child — reject it
                # wherever it appears in the object graph
                def reducer_override(self, obj):
                    if getattr(obj, "__module__", None) == "__main__" \
                            or getattr(type(obj), "__module__",
                                       None) == "__main__":
                        raise pickle.PicklingError(
                            "__main__-defined: use fork")
                    return NotImplemented
            try:
                _Probe(_NullSink(),
                       protocol=pickle.HIGHEST_PROTOCOL).dump(
                    (self.dataset, self.worker_init_fn))
                method = "forkserver"
            except Exception:
                method = "fork"
            self._mp_method = method
        ctx = mp.get_context(method)
        batches = list(self.batch_sampler)
        task_q = ctx.Queue()
        res_q = ctx.Queue()
        n_workers = min(self.num_workers, max(1, len(batches)))
        procs = [ctx.Process(target=_mp_worker_loop,
                             args=(self.dataset, task_q, res_q,
                                   self.worker_init_fn, w, n_workers),
                             daemon=True)
                 for w in range(n_workers)]
        for p in procs:
            p.start()
        try:
            # backpressure: keep only ~prefetch_factor batches in flight
            # per worker; refill as the consumer drains (an up-front full
            # enqueue lets workers materialize the whole epoch in RAM)
            inflight_cap = max(n_workers * self.prefetch_factor,
                               n_workers)
            issued = 0
            done_markers = 0

            def _issue():
                nonlocal issued, done_markers
                if issued < len(batches):
                    task_q.put((issued, list(batches[issued])))
                    issued += 1
                elif done_markers < n_workers:
                    task_q.put(None)
                    done_markers += 1

            for _ in range(min(inflight_cap, len(batches)) + n_workers):
                _issue()
            pending = {}
            expect = 0
            deadline = (time.monotonic() + self.timeout) \
                if self.timeout else None
            while expect < len(batches):
                if expect in pending:
                    items = pending.pop(expect)
                else:
                    try:
                        bid, items, err = res_q.get(timeout=1.0)
                    except queue.Empty:
                        # liveness: a silently-dead worker (OOM kill,
                        # unpicklable item) must not hang the loop
                        if not any(p.is_alive() for p in procs):
                            raise RuntimeError(
                                "DataLoader workers died without "
                                "reporting a result (killed? "
                                "unpicklable sample?)")
                        if deadline and time.monotonic() > deadline:
                            raise RuntimeError(
                                f"DataLoader timed out after "
                                f"{self.timeout}s waiting for batch "
                                f"{expect}")
                        continue
                    if err is not None:
                        raise RuntimeError(f"DataLoader worker failed: "
                                           f"{err}")
                    if bid != expect:
                        pending[bid] = items
                        continue
                yield self.collate_fn(items)
                expect += 1
                _issue()
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join(timeout=5)

    def __iter__(self):
        if self.num_workers == 0:
            yield from self._gen_batches()
            return
        if not self._iterable and self.num_workers > 0:
            yield from self._mp_iter()
            return
        # iterable streams: thread prefetcher
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch_factor)
        sentinel = object()

        def worker():
            try:
                for b in self._gen_batches():
                    q.put(b)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            b = q.get()
            if b is sentinel:
                break
            yield b

    def __len__(self):
        if self._iterable:
            raise RuntimeError("IterableDataset loader has no len()")
        return len(self.batch_sampler)


class CheckpointableLoader:
    """Deterministic, position-checkpointable batch loader — the data
    half of exact training resume (SURVEY.md §5 checkpoint/resume).

    Wraps a map-style dataset with its OWN seeded per-epoch shuffle
    (derived from ``(seed, epoch)`` via a private Generator — the global
    ``np.random`` stream is untouched), so the batch order of any epoch
    is reproducible in a fresh process.  The loader tracks its cursor as
    it yields: between two batches, ``state_dict()`` fully describes the
    stream position and ``set_state_dict`` fast-forwards to it WITHOUT
    materializing skipped items (skipped indices never hit
    ``dataset[i]``).  hapi ``fit(checkpoint_dir=..., auto_resume=True)``
    saves/restores this state alongside the model, so a resumed run
    consumes exactly the batches the interrupted run did not — the
    prerequisite for a bit-identical loss trajectory.

    Iterating resumes the CURRENT epoch at the cursor (mid-epoch after
    ``set_state_dict``, else batch 0) and auto-advances the epoch at
    exhaustion, so ``for epoch in ...: for batch in loader:`` walks
    distinct shuffles with no ``set_epoch`` bookkeeping.
    """

    def __init__(self, dataset, batch_size: int = 1, shuffle: bool = False,
                 seed: int = 0, drop_last: bool = False, collate_fn=None):
        enforce(not isinstance(dataset, IterableDataset),
                "CheckpointableLoader needs a map-style dataset (an "
                "iterable stream has no random-accessible position to "
                "checkpoint)")
        enforce(batch_size >= 1, "batch_size must be >= 1")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = int(seed)
        self.drop_last = drop_last
        self.collate_fn = collate_fn or default_collate_fn
        self._epoch = 0
        self._next_batch = 0

    def _order(self, epoch: int) -> np.ndarray:
        n = len(self.dataset)
        if not self.shuffle:
            return np.arange(n)
        rng = np.random.Generator(np.random.PCG64(
            np.random.SeedSequence([self.seed, int(epoch)])))
        return rng.permutation(n)

    def __len__(self):
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self):
        order = self._order(self._epoch)
        n_batches = len(self)
        for bi in range(self._next_batch, n_batches):
            idxs = order[bi * self.batch_size:(bi + 1) * self.batch_size]
            items = [self.dataset[int(i)] for i in idxs]
            # cursor advances BEFORE the yield: a state_dict() taken
            # after consuming this batch points at the next one
            self._next_batch = bi + 1
            yield self.collate_fn(items)
        self._epoch += 1
        self._next_batch = 0

    # -- position checkpointing ----------------------------------------------
    def state_dict(self):
        return {"epoch": self._epoch, "next_batch": self._next_batch,
                "seed": self.seed, "shuffle": self.shuffle,
                "batch_size": self.batch_size}

    def set_state_dict(self, state):
        # a position is only meaningful under the SAME ordering config —
        # resuming a seed-5 run with a seed-9 loader would silently
        # replay/skip the wrong samples
        for k in ("seed", "shuffle", "batch_size"):
            if k in state:
                enforce(state[k] == getattr(self, k),
                        f"loader {k} mismatch on resume: checkpoint has "
                        f"{state[k]!r}, this loader has "
                        f"{getattr(self, k)!r}")
        self._epoch = int(state["epoch"])
        self._next_batch = int(state["next_batch"])
