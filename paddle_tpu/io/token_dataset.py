"""Pretraining token-file reader over the native dataio core.

Reference parity: the reference trains from preprocessed binary token
shards via its C++ DataLoader core (SURVEY.md §2.2 io row; PaddleNLP
pretraining uses np.memmap'd .bin token files).  The native path
(core/csrc/dataio.cpp) mmaps the shard and assembles [batch, seq_len]
blocks on background C++ threads into a prefetch ring; the python
fallback is a plain np.memmap slice.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..common.errors import enforce
from ..core import load_native
from .dataloader import Dataset

__all__ = ["TokenFileDataset", "TokenFileLoader"]

_DTYPES = {2: np.uint16, 4: np.int32, 8: np.int64}


class TokenFileDataset(Dataset):
    """Map-style view: item i = tokens [i*seq_len, (i+1)*seq_len)."""

    def __init__(self, path: str, seq_len: int, dtype=np.int32):
        self.path = path
        self.seq_len = seq_len
        self.dtype = np.dtype(dtype)
        self._mm = np.memmap(path, dtype=self.dtype, mode="r")
        self._n = len(self._mm) // seq_len

    def __getitem__(self, i):
        s = i * self.seq_len
        return np.asarray(self._mm[s:s + self.seq_len])

    def __len__(self):
        return self._n


class TokenFileLoader:
    """High-throughput [batch, seq_len] iterator (the trainer hot path).

    Native: C++ mmap + worker threads + prefetch ring.  Fallback:
    memmap slicing in python (same batches, same shuffle order is NOT
    guaranteed between backends — seed the native path explicitly when
    bit-stable epochs matter)."""

    def __init__(self, path: str, seq_len: int, batch_size: int,
                 dtype=np.int32, num_threads: int = 2,
                 shuffle_seed: Optional[int] = None):
        enforce(os.path.exists(path), f"no token file at {path}")
        self.seq_len = seq_len
        self.batch_size = batch_size
        self.dtype = np.dtype(dtype)
        self._lib = load_native()
        self._h = None
        if self._lib is not None:
            self._h = self._lib.dataio_open(
                path.encode(), self.dtype.itemsize, seq_len, batch_size,
                num_threads,
                -1 if shuffle_seed is None else shuffle_seed)
        if self._h:
            self._n = int(self._lib.dataio_num_batches(self._h))
        else:                      # python fallback
            self._mm = np.memmap(path, dtype=self.dtype, mode="r")
            n_seqs = len(self._mm) // seq_len
            self._n = n_seqs // batch_size
            enforce(self._n > 0, "token file smaller than one batch")
            self._order = np.arange(n_seqs)
            if shuffle_seed is not None:
                np.random.default_rng(shuffle_seed).shuffle(self._order)
            self._i = 0

    @property
    def is_native(self) -> bool:
        return self._h is not None

    def __len__(self):
        return self._n

    def next(self) -> np.ndarray:
        """Next [batch, seq_len] block (wraps around epochs forever)."""
        out = np.empty((self.batch_size, self.seq_len), self.dtype)
        if self._h:
            rc = self._lib.dataio_next(
                self._h, out.ctypes.data_as(__import__("ctypes").c_void_p))
            enforce(rc >= 0, "dataio reader shut down")
            return out
        b = self._i % self._n
        self._i += 1
        idx = self._order[b * self.batch_size:(b + 1) * self.batch_size]
        for r, s in enumerate(idx):
            out[r] = self._mm[s * self.seq_len:(s + 1) * self.seq_len]
        return out

    def __iter__(self):
        for _ in range(self._n):
            yield self.next()

    def close(self):
        if self._h:
            self._lib.dataio_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
