from .to_static import InputSpec, StaticFunction, ignore_module, not_to_static, to_static
from .save_load import load, save
