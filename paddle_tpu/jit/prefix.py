"""Compiled-SEGMENT capture for to_static graph breaks (SOT parity).

Reference parity: the SOT bytecode tracer's break handling
(python/paddle/jit/sot — SURVEY.md §2.2 jit row): on a graph break SOT
compiles the code before the break, runs the breaking region eagerly,
RESUMES compiling after it, and stitches the compiled segments
together on later calls.  Round 4's capture was one-sided (only the
ops BEFORE the first break, and only non-differentiable ones —
VERDICT r4 Missing #1); round 5 completes it:

* The op stream of a broken call is recorded as a SEQUENCE of
  segments: a host read (``bool()/item()/.numpy()``) closes the
  current segment and the next op simply starts a new one, so the code
  on BOTH sides of every break compiles.  Unguardable ops (RNG,
  unhashable kwargs) become single "eager items" between segments —
  they re-execute on replay, and their outputs are wired into later
  segments.
* GRAD-PATH ops are captured too: in grad mode a whole segment replays
  as ONE ``jax.vjp`` over its boundary inputs, and the tape gets ONE
  GradNode for the segment (outputs = every captured op's outputs,
  in-edges = the differentiable boundary tensors), so a broken TRAIN
  step runs its op stream compiled while gradients flow exactly as
  eager's per-op tape would produce them.
* Replay substitutes op-by-op under the same guards as round 4 (op
  identity, static template/kwargs, input wiring by array identity;
  small captured constants by value); the first mismatch bails the
  rest of the call to plain eager — results stay correct either way.

The recording call itself always runs fully eagerly (correct results,
correct side effects); segments are built at ``seal()``.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..tensor import OBS_MISS, rebuild_from_template

__all__ = ["PrefixRecorder", "PrefixReplayer"]


def _canon(x):
    """Deep-tuple conversion so list-valued static args (reshape
    shapes, axis lists — ubiquitous in real models) stay guardable."""
    if isinstance(x, (list, tuple)):
        return tuple(_canon(v) for v in x)
    if isinstance(x, dict):
        return tuple(sorted((k, _canon(v)) for k, v in x.items()))
    return x


def _kwargs_sig(kwargs):
    try:
        sig = _canon(kwargs)
        hash(sig)
        return sig
    except TypeError:
        return None


def _static_template(template):
    """Hashable guard form of an op template; None if not hashable."""
    try:
        sig = tuple((k, None if k in ("t", "tl") else _canon(v))
                    for k, v in template)
        hash(sig)
        return sig
    except TypeError:
        return None


class _OpRec:
    __slots__ = ("raw_fn", "tmpl", "kwargs", "srcs", "n_out", "treedef",
                 "diff", "eager")

    def __init__(self, raw_fn, tmpl, kwargs, srcs, n_out, treedef,
                 diff, eager):
        self.raw_fn = raw_fn
        self.tmpl = tmpl
        self.kwargs = kwargs
        self.srcs = srcs
        self.n_out = n_out
        self.treedef = treedef
        self.diff = diff
        self.eager = eager


class _Segment:
    __slots__ = ("op_idxs", "boundary", "jitted", "has_diff")

    def __init__(self, op_idxs, boundary, jitted, has_diff):
        self.op_idxs = op_idxs
        self.boundary = boundary        # ordered list of external refs
        self.jitted = jitted            # fn(boundary_arrays) -> flat outs
        self.has_diff = has_diff


class PrefixRecorder:
    """Observes one eager call, recording the full op stream as
    segments separated by host reads / unguardable ops."""

    def __init__(self, ext_sources: Dict[int, Tuple]):
        # id(array) -> ("param", name) | ("buffer", name) | ("arg", i)
        self.ext_sources = dict(ext_sources)
        self.ops: List[_OpRec] = []
        self.items: List[Tuple] = []      # ("seg", [op idx]) | ("eager", idx)
        self.segments: List[Optional[_Segment]] = []  # parallel to items
        self.ext_desc: List[Tuple] = []   # source descriptor per ext slot
        self.consts: List[Any] = []
        self.ext_tensors: List[Any] = []  # pinned closure Tensors
        self._cur: List[int] = []
        self._ext_slot: Dict[int, int] = {}
        self._out_src: Dict[int, Tuple] = {}
        self._pins: List[Any] = []        # keep ids alive/stable
        self.active = True                # recording (vs sealed)

    # -- observer hooks ------------------------------------------------------
    def on_host_read(self):
        self._close_seg()                 # break: next op opens segment N+1

    def on_op(self, raw_fn, template, kwargs, arrays):
        return OBS_MISS                   # recording never substitutes

    def on_result(self, raw_fn, template, kwargs, arrays, out,
                  leaves=None):
        self._record(raw_fn, template, kwargs, arrays, out, diff=False,
                     leaves=leaves)

    def on_diff_op(self, raw_fn, template, kwargs, arrays, diff_idx,
                   leaves=None):
        return OBS_MISS

    def on_diff_result(self, raw_fn, template, kwargs, arrays, out,
                       diff_idx, leaves=None):
        self._record(raw_fn, template, kwargs, arrays, out, diff=True,
                     leaves=leaves)

    # -- recording -----------------------------------------------------------
    def _close_seg(self):
        if self._cur:
            self.items.append(("seg", self._cur))
            self._cur = []

    def _src_of(self, arr, leaf=None) -> Tuple:
        key = id(arr)
        src = self._out_src.get(key)
        if src is not None:
            return src
        ext = self.ext_sources.get(key)
        slot = self._ext_slot.get(key)
        if slot is None:
            slot = len(self.ext_desc)
            if ext is None:
                # unknown external array: if its leaf is a live Tensor
                # (e.g. a closure-captured parameter in function-style
                # to_static), pin the TENSOR — fetch reads its CURRENT
                # value each replay (so optimizer updates are seen) and
                # grad-mode segments get its tape edge.  Raw arrays
                # stay value-captured constants.
                if leaf is not None and hasattr(leaf, "stop_gradient") \
                        and getattr(leaf, "value", None) is arr:
                    ext = ("tensor", len(self.ext_tensors))
                    self.ext_tensors.append(leaf)
                else:
                    ext = ("const", len(self.consts))
                    self.consts.append(arr)
            self.ext_desc.append(ext)
            self._ext_slot[key] = slot
            self._pins.append(arr)
        return ("ext", slot)

    def _record(self, raw_fn, template, kwargs, arrays, out, diff,
                leaves=None):
        if not self.active:
            return
        ksig = _kwargs_sig(kwargs)
        tsig = _static_template(template)
        guardable = (ksig is not None and tsig is not None
                     and not getattr(raw_fn, "__module__", "").endswith(
                         "ops.random"))
        if leaves is None:
            leaves = [None] * len(arrays)
        srcs = tuple(self._src_of(a, l)
                     for a, l in zip(arrays, leaves))
        flat, treedef = jax.tree_util.tree_flatten(out)
        k = len(self.ops)
        for j, a in enumerate(flat):
            self._out_src[id(a)] = ("op", k, j)
            self._pins.append(a)
        self.ops.append(_OpRec(raw_fn, tuple(template), dict(kwargs),
                               srcs, len(flat), treedef, diff,
                               not guardable))
        if guardable:
            self._cur.append(k)
        else:
            # RNG / unhashable op: runs eagerly on replay too, but its
            # outputs are wired so later segments can consume them
            self._close_seg()
            self.items.append(("eager", k))

    # -- sealing -------------------------------------------------------------
    def _build_segment(self, op_idxs):
        inseg = set(op_idxs)
        refs: List[Tuple] = []
        ref_pos: Dict[Tuple, int] = {}
        for k in op_idxs:
            for s in self.ops[k].srcs:
                if s[0] == "op" and s[1] in inseg:
                    continue
                if s not in ref_pos:
                    ref_pos[s] = len(refs)
                    refs.append(s)
        ops = self.ops
        idxs = tuple(op_idxs)
        pos = dict(ref_pos)

        def replay(boundary):
            local: Dict[int, List[Any]] = {}
            outs_all: List[Any] = []
            for k in idxs:
                op = ops[k]
                ins = [local[s[1]][s[2]] if (s[0] == "op"
                                             and s[1] in local)
                       else boundary[pos[s]] for s in op.srcs]
                out = op.raw_fn(*rebuild_from_template(op.tmpl, ins),
                                **op.kwargs)
                flat = jax.tree_util.tree_flatten(out)[0]
                local[k] = flat
                outs_all.extend(flat)
            return tuple(outs_all)

        has_diff = any(ops[k].diff for k in idxs)
        return _Segment(idxs, refs, jax.jit(replay), has_diff)

    def seal(self):
        """Close the last segment, build the per-segment compiled
        replays, and drop recording-time pins (they would otherwise
        leak the recording call's activations for the cache's
        lifetime)."""
        self._close_seg()
        self.segments = [
            self._build_segment(payload) if kind == "seg" else None
            for kind, payload in self.items]
        self.active = False
        self._pins = []
        self._out_src = {}
        self._ext_slot = {}
        self.ext_sources = {}

    @property
    def captured_op_count(self):
        return sum(len(p) for k, p in self.items if k == "seg")


class PrefixReplayer:
    """Substitutes the recorded stream: each segment runs as ONE
    compiled call (a jax.vjp in grad mode, feeding one tape GradNode),
    eager items re-execute, everything is guard-checked op-by-op."""

    def __init__(self, rec: PrefixRecorder, fetch: Callable,
                 grad_mode: bool):
        self.rec = rec
        self._fetch = fetch               # desc -> (array, Tensor|None)
        self._grad = grad_mode
        self._item_i = 0
        self._op_in_item = 0
        # op_idx -> (flat arrays, flat edges) for produced outputs;
        # edges are tape wiring: ("n", node, idx) | ("l", tensor) | None
        self._bound_arr: Dict[int, List[Any]] = {}
        self._bound_edge: Dict[int, List[Any]] = {}
        self._ext_cache: Dict[int, Tuple] = {}
        self.live = True
        self.replayed = 0

    # -- plumbing ------------------------------------------------------------
    def on_host_read(self):
        pass                              # breaks are segment boundaries

    def _ext(self, slot):
        ent = self._ext_cache.get(slot)
        if ent is None:
            ent = self._fetch(self.rec.ext_desc[slot])
            self._ext_cache[slot] = ent
        return ent

    def _cursor_op(self):
        items = self.rec.items
        while self._item_i < len(items):
            kind, payload = items[self._item_i]
            if kind == "eager":
                if self._op_in_item == 0:
                    return payload, True
            else:
                if self._op_in_item < len(payload):
                    return payload[self._op_in_item], False
            self._item_i += 1
            self._op_in_item = 0
        return None, False

    def _advance(self):
        self._op_in_item += 1
        kind, payload = self.rec.items[self._item_i]
        size = 1 if kind == "eager" else len(payload)
        if self._op_in_item >= size:
            self._item_i += 1
            self._op_in_item = 0

    def _ids_match(self, srcs, arrays) -> bool:
        for s, a in zip(srcs, arrays):
            if s[0] == "op":
                ent = self._bound_arr.get(s[1])
                if ent is None:
                    return False
                want = ent[s[2]]
            else:
                want, _ = self._ext(s[1])
            if a is want:
                continue
            desc = self.rec.ext_desc[s[1]] if s[0] == "ext" else None
            if (desc is not None and desc[0] == "const"
                    and np.size(a) <= 4096
                    and np.shape(a) == np.shape(want)
                    and np.array_equal(np.asarray(a),
                                       np.asarray(want))):
                continue
            return False
        return True

    @staticmethod
    def _safe_eq(a, b):
        """Structural equality that never raises on array-valued
        kwargs (dict == would truth-test elementwise results)."""
        if type(a) is not type(b):
            if not (isinstance(a, (list, tuple))
                    and isinstance(b, (list, tuple))):
                try:
                    return bool(a == b)
                except Exception:  # noqa: BLE001
                    return False
        if isinstance(a, dict):
            return (a.keys() == b.keys()
                    and all(PrefixReplayer._safe_eq(a[k], b[k])
                            for k in a))
        if isinstance(a, (list, tuple)):
            return (len(a) == len(b)
                    and all(PrefixReplayer._safe_eq(x, y)
                            for x, y in zip(a, b)))
        if hasattr(a, "shape") or hasattr(b, "shape"):
            try:
                return np.array_equal(np.asarray(a), np.asarray(b))
            except Exception:  # noqa: BLE001
                return False
        try:
            return bool(a == b)
        except Exception:  # noqa: BLE001
            return False

    def _guards_ok(self, op: _OpRec, raw_fn, template, kwargs, arrays,
                   diff):
        return (raw_fn is op.raw_fn and tuple(template) == op.tmpl
                and self._safe_eq(kwargs, op.kwargs) and diff == op.diff
                and len(arrays) == len(op.srcs)
                and self._ids_match(op.srcs, arrays))

    # -- segment execution ---------------------------------------------------
    @staticmethod
    def _is_float(arr):
        try:
            return np.issubdtype(np.asarray(arr).dtype, np.floating) \
                or str(getattr(arr, "dtype", "")) == "bfloat16"
        except Exception:  # noqa: BLE001
            return False

    def _edge_of_tensor(self, t, arr=None):
        if t is None:
            return None
        if arr is not None and not self._is_float(arr):
            return None                   # ints carry no grad (eager parity)
        node = getattr(t, "_node", None)
        if node is not None:
            return ("n", node, t._out_idx)
        if not getattr(t, "stop_gradient", True):
            return ("l", t)
        return None

    def _run_segment(self, seg: _Segment):
        from ..autograd import tape as _tape

        arrays: List[Any] = []
        edges: List[Any] = []
        for ref in seg.boundary:
            if ref[0] == "ext":
                arr, tensor = self._ext(ref[1])
                arrays.append(arr)
                edges.append(self._edge_of_tensor(tensor, arr))
            else:
                _, k, j = ref
                arrays.append(self._bound_arr[k][j])
                edges.append(self._bound_edge[k][j])

        node = None
        if self._grad and seg.has_diff:
            diff_pos = [i for i, e in enumerate(edges) if e is not None]
            if diff_pos:
                def wrapped(*diffs):
                    merged = list(arrays)
                    for p, d in zip(diff_pos, diffs):
                        merged[p] = d
                    return seg.jitted(merged)

                flat, vjp = jax.vjp(wrapped,
                                    *[arrays[p] for p in diff_pos])
                out_tree = {
                    "treedef": jax.tree_util.tree_structure(
                        tuple(flat)),
                    "avals": [(np.shape(a), a.dtype) for a in flat],
                }
                node = _tape.GradNode(
                    "prefix_segment", vjp,
                    [edges[p] for p in diff_pos], len(flat), out_tree)
            else:
                flat = seg.jitted(arrays)
        else:
            flat = seg.jitted(arrays)

        it = iter(flat)
        base = 0
        for k in seg.op_idxs:
            op = self.rec.ops[k]
            outs = [next(it) for _ in range(op.n_out)]
            self._bound_arr[k] = outs
            if node is not None and op.diff:
                # non-float outputs (argmax indices etc.) carry no grad
                self._bound_edge[k] = [
                    ("n", node, base + j) if self._is_float(outs[j])
                    else None for j in range(op.n_out)]
            else:
                self._bound_edge[k] = [None] * op.n_out
            base += op.n_out
        return node

    # -- observer hooks ------------------------------------------------------
    def _substitute(self, raw_fn, template, kwargs, arrays, diff):
        if not self.live:
            return OBS_MISS
        k, is_eager = self._cursor_op()
        if k is None:
            self.live = False
            return OBS_MISS
        op = self.rec.ops[k]
        if not self._guards_ok(op, raw_fn, template, kwargs, arrays,
                               diff):
            self.live = False             # wiring diverged: bail to eager
            return OBS_MISS
        if is_eager:
            return OBS_MISS               # executes; bound via on_*_result
        if self._op_in_item == 0:         # entering the segment
            self._run_segment(self.rec.segments[self._item_i])
        self._advance()
        self.replayed += 1
        return op, self._bound_arr[k], self._bound_edge[k]

    def on_op(self, raw_fn, template, kwargs, arrays):
        sub = self._substitute(raw_fn, template, kwargs, arrays, False)
        if sub is OBS_MISS:
            return OBS_MISS
        op, outs, _ = sub
        return jax.tree_util.tree_unflatten(op.treedef, outs)

    def on_result(self, raw_fn, template, kwargs, arrays, out,
                  leaves=None):
        # an eager item (or post-bail op) actually executed: bind it
        self._bind_executed(out)

    def on_diff_op(self, raw_fn, template, kwargs, arrays, diff_idx,
                   leaves=None):
        sub = self._substitute(raw_fn, template, kwargs, arrays, True)
        if sub is OBS_MISS:
            return OBS_MISS
        op, outs, edges = sub
        # wrap with the segment node so grads flow through the ONE
        # compiled vjp (mirrors tensor._wrap_out)
        from ..common import dtype as _dt
        from ..tensor import Tensor
        wrapped = []
        for j, arr in enumerate(outs):
            e = edges[j] if j < len(edges) else None
            t = Tensor(arr, stop_gradient=(e is None))
            if e is not None:
                t._node = e[1]
                t._out_idx = e[2]
                if not _dt.is_floating_point(t.dtype):
                    t._stop_gradient = True
            wrapped.append(t)
        return jax.tree_util.tree_unflatten(op.treedef, wrapped)

    def on_diff_result(self, raw_fn, template, kwargs, arrays, out,
                       diff_idx, leaves=None):
        self._bind_executed(out)

    def _bind_executed(self, out):
        """Called when an op really executed during replay: if it is
        the expected EAGER item, bind its outputs for later segments;
        otherwise we already bailed (nothing to track)."""
        if not self.live:
            return
        k, is_eager = self._cursor_op()
        if k is None or not is_eager:
            return
        flat, _ = jax.tree_util.tree_flatten(out)
        if len(flat) != self.rec.ops[k].n_out:
            self.live = False
            return
        self._bound_arr[k] = list(flat)
        # eager diff ops wire their grads through their OWN per-op
        # node (apply_op built it); later segments reference them as
        # plain leaves via on_result_wrapped
        self._bound_edge[k] = [None] * len(flat)
        self._pending_wrap = k
        self._advance()

    def on_result_wrapped(self, res):
        """Receives the WRAPPED result of an executed op right after
        _wrap_out — captures eager items' tape edges for later
        segments' boundary wiring."""
        k = getattr(self, "_pending_wrap", None)
        if k is None:
            return
        self._pending_wrap = None
        from ..tensor import Tensor
        flat = [t for t in jax.tree_util.tree_flatten(
            res, is_leaf=lambda x: isinstance(x, Tensor))[0]
            if isinstance(t, Tensor)]
        if len(flat) == len(self._bound_arr.get(k, ())):
            self._bound_edge[k] = [self._edge_of_tensor(t)
                                   for t in flat]
