"""Compiled-prefix capture for to_static graph breaks (SOT parity).

Reference parity: the SOT bytecode tracer's break handling
(python/paddle/jit/sot — SURVEY.md §2.2 jit row): on a graph break SOT
compiles the code BEFORE the break, runs the breaking region eagerly,
and resumes.  Round 3's fallback re-ran the whole function eagerly —
one ``.item()`` branch un-compiled everything (VERDICT r3 Missing #4).

TPU-native design — memoized compiled prefix with guarded replay:

* The breaking call re-runs EAGERLY (correct results) while an op
  observer records the pre-break op stream: (raw_fn, template, kwargs,
  input wiring).  Inputs are classified as op outputs, external leaves
  (params / buffers / tensor args, by name/position), or captured
  constants.  The first host read (``bool()/item()/.numpy()``), grad-
  path op, RNG op, or unhashable op closes the prefix.
* Replay calls run ONE ``jax.jit``-compiled function reproducing the
  whole prefix (XLA-fused, like SOT's compiled segment), then execute
  the python function with a substituting observer: each op that
  matches the recording (same raw_fn identity, template, kwargs, and
  input wiring) returns its precomputed result with zero compute; the
  first mismatch — different op order, a lambda re-created per call,
  changed wiring — permanently bails this call to normal eager
  execution from that op on (results stay correct because substituted
  values are real arrays).
* Python between/after ops still executes (side effects preserved);
  everything AFTER the break runs eagerly, exactly as before.  Only
  NON-diff ops are captured: a grad-path op closes the prefix (the
  eager tape needs its per-op vjps), and the prefix cache keys on
  grad mode + arg stop-gradient flags so diff-ness cannot differ
  between recording and replay.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..tensor import OBS_MISS, rebuild_from_template

__all__ = ["PrefixRecorder", "PrefixReplayer", "build_prefix_replay"]


def _canon(x):
    """Deep-tuple conversion so list-valued static args (reshape
    shapes, axis lists — ubiquitous in real models) stay guardable."""
    if isinstance(x, (list, tuple)):
        return tuple(_canon(v) for v in x)
    if isinstance(x, dict):
        return tuple(sorted((k, _canon(v)) for k, v in x.items()))
    return x


def _kwargs_sig(kwargs):
    try:
        sig = _canon(kwargs)
        hash(sig)
        return sig
    except TypeError:
        return None


def _static_template(template):
    """Hashable guard form of an op template; None if not hashable."""
    try:
        sig = tuple((k, None if k in ("t", "tl") else _canon(v))
                    for k, v in template)
        hash(sig)
        return sig
    except TypeError:
        return None


class PrefixRecorder:
    """Observes one eager call, recording the pre-break op stream."""

    def __init__(self, ext_sources: Dict[int, Tuple]):
        # id(array) -> ("param", name) | ("buffer", name) | ("arg", i)
        self.ext_sources = dict(ext_sources)
        self.ops: List[Tuple] = []        # (raw_fn, tmpl, kwargs, srcs, n_out, treedef)
        self.ext_desc: List[Tuple] = []   # source descriptor per ext slot
        self.consts: List[Any] = []
        self._ext_slot: Dict[int, int] = {}
        self._out_src: Dict[int, Tuple] = {}
        self._pins: List[Any] = []        # keep ids alive/stable
        self.active = True

    def on_host_read(self):
        self.active = False               # break: prefix is closed

    def on_op(self, raw_fn, template, kwargs, arrays):
        return OBS_MISS                   # recording never substitutes

    def _src_of(self, arr) -> Tuple:
        key = id(arr)
        src = self._out_src.get(key)
        if src is not None:
            return src
        ext = self.ext_sources.get(key)
        slot = self._ext_slot.get(key)
        if slot is None:
            slot = len(self.ext_desc)
            if ext is None:
                ext = ("const", len(self.consts))
                self.consts.append(arr)
            self.ext_desc.append(ext)
            self._ext_slot[key] = slot
            self._pins.append(arr)
        return ("ext", slot)

    def on_result(self, raw_fn, template, kwargs, arrays, out):
        if not self.active:
            return
        ksig = _kwargs_sig(kwargs)
        tsig = _static_template(template)
        if (ksig is None or tsig is None
                or getattr(raw_fn, "__module__", "").endswith(
                    "ops.random")):
            self.active = False           # unguardable / stateful op
            return
        srcs = tuple(self._src_of(a) for a in arrays)
        flat, treedef = jax.tree_util.tree_flatten(out)
        k = len(self.ops)
        for j, a in enumerate(flat):
            self._out_src[id(a)] = ("op", k, j)
            self._pins.append(a)
        self.ops.append((raw_fn, tuple(template), dict(kwargs), srcs,
                         len(flat), treedef))

    def seal(self):
        """Drop recording-time state once the replay fn is built: the
        pinned intermediate arrays (id-stability was only needed while
        recording) would otherwise leak the whole recording call's
        activations for the StaticFunction's lifetime."""
        self._pins = []
        self._out_src = {}
        self._ext_slot = {}
        self.ext_sources = {}


def build_prefix_replay(rec: PrefixRecorder):
    """One jitted function replaying the recorded prefix: ext arrays in
    slot order -> tuple of every op's flat outputs (concatenated)."""
    ops = rec.ops

    def replay(ext_arrays):
        produced: List[List[Any]] = []
        for raw_fn, template, kwargs, srcs, n_out, treedef in ops:
            ins = [produced[s[1]][s[2]] if s[0] == "op"
                   else ext_arrays[s[1]] for s in srcs]
            out = raw_fn(*rebuild_from_template(template, ins), **kwargs)
            produced.append(jax.tree_util.tree_flatten(out)[0])
        return tuple(a for outs in produced for a in outs)

    return jax.jit(replay)


class PrefixReplayer:
    """Substitutes precomputed prefix results op-by-op with guards."""

    def __init__(self, rec: PrefixRecorder, prefix_flat: Tuple,
                 ext_arrays: List[Any]):
        self.rec = rec
        self._ext_arrays = ext_arrays
        # regroup flat outputs per op
        self._outs: List[List[Any]] = []
        it = iter(prefix_flat)
        for (_, _, _, _, n_out, _) in rec.ops:
            self._outs.append([next(it) for _ in range(n_out)])
        self._k = 0
        self.live = True
        self.replayed = 0

    def on_host_read(self):
        self.live = False

    def _ids_match(self, srcs, arrays) -> bool:
        for s, a in zip(srcs, arrays):
            if s[0] == "op":
                want = self._outs[s[1]][s[2]]
            else:
                want = self._ext_arrays[s[1]]
            if a is want:
                continue
            # captured constants are re-created per call (fresh array
            # objects): value-compare small ones, bail on big ones
            desc = self.rec.ext_desc[s[1]] if s[0] == "ext" else None
            if (desc is not None and desc[0] == "const"
                    and np.size(a) <= 4096
                    and np.shape(a) == np.shape(want)
                    and np.array_equal(np.asarray(a),
                                       np.asarray(want))):
                continue
            return False
        return True

    def on_op(self, raw_fn, template, kwargs, arrays):
        if not self.live or self._k >= len(self.rec.ops):
            self.live = False
            return OBS_MISS
        rfn, rtmpl, rkw, srcs, n_out, treedef = self.rec.ops[self._k]
        if (raw_fn is not rfn or tuple(template) != rtmpl
                or kwargs != rkw or len(arrays) != len(srcs)
                or not self._ids_match(srcs, arrays)):
            self.live = False             # wiring diverged: bail to eager
            return OBS_MISS
        out = jax.tree_util.tree_unflatten(treedef, self._outs[self._k])
        self._k += 1
        self.replayed += 1
        return out

    def on_result(self, raw_fn, template, kwargs, arrays, out):
        pass                              # a computed op: nothing to do
