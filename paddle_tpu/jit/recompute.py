"""Recompute (activation checkpointing / rematerialization).

Reference parity: paddle.distributed.fleet.utils.recompute (+
RecomputeConfig in DistributedStrategy) — re-run a layer's forward in
backward to trade FLOPs for memory.  TPU-native: ``jax.checkpoint``
(remat) applied to the layer's pure function, which XLA schedules —
strictly better than the reference's python re-execution (fusion + no
python in the bwd).
"""
from __future__ import annotations

import jax

from ..nn.layer import Layer, functional_state
from ..tensor import Tensor, apply_op

__all__ = ["recompute"]


def _resolve_policy(policy):
    """None = full remat; "core_attn" keeps tensors tagged "attn_out"
    (paddle recompute_granularity parity); "dots" keeps matmul outputs;
    or pass a jax.checkpoint_policies callable directly."""
    if policy is None or callable(policy):
        return policy
    if policy == "core_attn":
        # "attn_out" = the jnp attention path's saved output;
        # "flash_out"/"flash_lse" = the pallas kernel's (out, lse) pair
        # — saving BOTH lets the rematerialized backward skip the flash
        # forward kernel entirely (its outputs are dead ⇒ XLA drops it)
        return jax.checkpoint_policies.save_only_these_names(
            "attn_out", "flash_out", "flash_lse")
    if policy == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    raise ValueError(f"unknown recompute policy {policy!r}")


def recompute(function, *args, policy=None, **kwargs):
    """Run ``function(*args)`` under rematerialization.

    Works both eagerly (no-op semantics, correct grads) and inside the
    compiled train step (where it actually saves memory).
    """
    pol = _resolve_policy(policy)
    layer = function if isinstance(function, Layer) else None
    fn = function.forward if layer is not None else function

    if layer is not None:
        named = dict(layer.named_parameters())
        names = list(named.keys())

        def raw(param_list, *arg_arrays):
            def inner(param_list, *arg_arrays):
                tensors = jax.tree_util.tree_map(
                    lambda a: Tensor(a, stop_gradient=True), list(arg_arrays))
                with functional_state(layer, dict(zip(names, param_list))):
                    out = fn(*tensors, **kwargs)
                return jax.tree_util.tree_map(
                    lambda t: t.value if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda t: isinstance(t, Tensor))
            return jax.checkpoint(inner, policy=pol)(param_list,
                                                     *arg_arrays)
        raw.__name__ = "recompute"
        return apply_op(raw, [named[n] for n in names], *args)

    def raw_fn(*arg_arrays):
        def inner(*arg_arrays):
            tensors = jax.tree_util.tree_map(
                lambda a: Tensor(a, stop_gradient=True), list(arg_arrays))
            out = fn(*tensors, **kwargs)
            return jax.tree_util.tree_map(
                lambda t: t.value if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor))
        return jax.checkpoint(inner, policy=pol)(*arg_arrays)
    raw_fn.__name__ = "recompute"
    return apply_op(raw_fn, *args)
