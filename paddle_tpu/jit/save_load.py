"""paddle.jit.save / paddle.jit.load.

Reference parity: python/paddle/jit/api.py — exports a traced inference
program + params (.pdmodel/.pdiparams), reloadable as a TranslatedLayer.
TPU-native design: the traced program is serialized **StableHLO** via
``jax.export`` (the XLA-native interchange format — the analog of the
reference's ProgramDesc protobuf), params via the framework saver.
``load`` returns a callable TranslatedLayer running the deserialized
StableHLO, usable from pure Python without the original model code.
"""
from __future__ import annotations

import os
import pickle
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.errors import enforce
from ..framework import io as fio
from ..nn.layer import Layer, functional_state
from ..tensor import Tensor, to_tensor
from .to_static import InputSpec, StaticFunction

__all__ = ["save", "load", "TranslatedLayer"]


def save(layer, path: str, input_spec: Optional[Sequence] = None, **configs):
    """Serialize ``layer`` (or a StaticFunction) for inference.

    Produces ``{path}.pdmodel`` (StableHLO + metadata) and
    ``{path}.pdiparams`` (weights).
    """
    enforce(isinstance(layer, Layer), "jit.save expects a Layer")
    enforce(input_spec is not None and len(input_spec) > 0,
            "jit.save requires input_spec (static shapes)")
    specs = []
    for s in input_spec:
        if isinstance(s, InputSpec):
            specs.append(s)
        elif isinstance(s, Tensor):
            specs.append(InputSpec.from_tensor(s))
        else:
            raise TypeError(f"bad input_spec entry {s!r}")

    layer.eval()
    params = layer.raw_state_dict()
    buffers = {k: b.value for k, b in layer.named_buffers()}
    fn = layer.forward
    if isinstance(fn, StaticFunction):
        fn = fn.function

    def pure(param_vals, buffer_vals, *args):
        tensors = [Tensor(a, stop_gradient=True) for a in args]
        with functional_state(layer, param_vals, buffer_vals):
            out = fn(*tensors)
        flat, _ = jax.tree_util.tree_flatten(
            out, is_leaf=lambda x: isinstance(x, Tensor))
        return tuple(o.value if isinstance(o, Tensor) else o for o in flat)

    arg_shapes = [jax.ShapeDtypeStruct(tuple(s.shape), s.dtype)
                  for s in specs]
    param_shapes = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params)
    buffer_shapes = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), buffers)

    exported = jax.export.export(jax.jit(pure))(
        param_shapes, buffer_shapes, *arg_shapes)
    blob = exported.serialize()

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump({"stablehlo": blob,
                     "input_specs": [(s.shape, s.dtype.name) for s in specs]},
                    f)
    fio.save({"params": {k: Tensor(v) for k, v in params.items()},
              "buffers": {k: Tensor(v) for k, v in buffers.items()}},
             path + ".pdiparams")


class TranslatedLayer(Layer):
    """Inference-only layer reconstituted from serialized StableHLO."""

    def __init__(self, exported, params, buffers, input_specs):
        super().__init__()
        self._exported = exported
        self._params = params
        self._buffers_vals = buffers
        self._input_specs = input_specs
        self.eval()

    def forward(self, *args):
        arrs = [a.value if isinstance(a, Tensor) else jnp.asarray(a)
                for a in args]
        out = self._exported.call(self._params, self._buffers_vals, *arrs)
        wrapped = [Tensor(o) for o in out]
        return wrapped[0] if len(wrapped) == 1 else tuple(wrapped)


def load(path: str, **configs) -> TranslatedLayer:
    with open(path + ".pdmodel", "rb") as f:
        meta = pickle.load(f)
    exported = jax.export.deserialize(meta["stablehlo"])
    state = fio.load(path + ".pdiparams")
    params = {k: v.value for k, v in state["params"].items()}
    buffers = {k: v.value for k, v in state["buffers"].items()}
    return TranslatedLayer(exported, params, buffers, meta["input_specs"])
