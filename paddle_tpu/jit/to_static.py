"""to_static: the dygraph → compiled-program boundary.

Reference parity: ``paddle.jit.to_static`` (python/paddle/jit/ — the AST/
SOT bytecode tracers that build a static program, compiled by CINN).
TPU-native design: the "static program" IS an XLA computation traced by
``jax.jit`` — our eager Tensors wrap tracers transparently, so the user's
dygraph code traces as-is (jax tracing == SOT's symbolic tracing with the
same no-data-dependent-control-flow contract; CINN's fusion role is
played by XLA).

The returned StaticFunction:
  * caches compiled executables per (tree-structure, shapes, dtypes,
    static-args, training-mode) signature — mirroring SOT's guard cache;
  * threads the owning Layer's parameters/buffers as traced inputs, so
    param updates between calls do NOT trigger recompiles;
  * is differentiable: calling it under the eager tape records ONE
    GradNode whose vjp is the XLA-differentiated whole program, with
    grads flowing into the Layer's Parameters;
  * **graph-breaks like SOT**: with ``full_graph=False`` (the default,
    matching paddle 3.0), data-dependent python control flow that XLA
    tracing cannot capture (``if tensor > 0``, ``while tensor...``,
    ``int(tensor)``) does not error — the call falls back to eager
    execution, the signature is remembered as a fallback (no re-trace
    attempts), and the break is logged + counted
    (``.graph_break_count``).  ``full_graph=True`` keeps the strict
    contract and re-raises.
  * **compiled-segment capture** (round 5, SOT's compiled-segment
    behavior): the breaking call records its WHOLE op stream while
    running eagerly, split into segments at host reads (and at
    unguardable RNG/unhashable ops, which replay eagerly between
    them); subsequent same-signature calls execute each segment as
    ONE jitted XLA program — in grad mode as one ``jax.vjp`` feeding
    a single tape GradNode, so broken TRAIN steps run compiled on
    both sides of every break — substituting results op-by-op under
    guards (jit/prefix.py).  Stats: ``prefix_op_count``,
    ``prefix_segment_count``, ``prefix_replay_count``,
    ``last_replayed_ops``.  The cache keys on grad mode + arg
    stop-gradient flags.  On the one breaking call, python side
    effects before the break run twice (the aborted trace + the
    recording run); tensor/layer state is unaffected
    (functional_state and rng_guard unwind the aborted trace).

Known functional-purity caveat (documented parity gap): BatchNorm
running-stat mutation inside a to_static region is reverted at trace
exit; use the eager path or the hapi trainer for BN-stat updates.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.dtype import convert_dtype
from ..common.errors import enforce
from ..nn.layer import Layer, functional_state
from ..ops import random as _random
from ..tensor import Tensor, apply_op

__all__ = ["InputSpec", "to_static", "not_to_static", "ignore_module",
           "StaticFunction"]


class InputSpec:
    """paddle.static.InputSpec — static-shape signature declaration."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype.name})"

    @classmethod
    def from_tensor(cls, tensor: Tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name)


def _is_tensor_leaf(x):
    return isinstance(x, (Tensor, jax.Array, np.ndarray))


def _graph_break_errors():
    """Tracer-concretization error classes — the 'python needs the
    value, the trace only has a tracer' family that SOT graph-breaks
    on."""
    errs = []
    for name in ("ConcretizationTypeError", "TracerArrayConversionError",
                 "TracerBoolConversionError",
                 "TracerIntegerConversionError",
                 "NonConcreteBooleanIndexError"):
        cls = getattr(jax.errors, name, None)
        if cls is not None:
            errs.append(cls)
    return tuple(errs)


class StaticFunction:
    def __init__(self, function: Callable, input_spec=None,
                 build_strategy=None, backend=None, full_graph=False,
                 layer: Optional[Layer] = None):
        self._function = function
        self._input_spec = input_spec
        self._layer = layer
        self._cache = {}
        self._full_graph = full_graph
        self._fallback_keys = set()
        self._prefix_cache = {}
        self.graph_break_count = 0
        # prefix-capture stats (SOT parity): ops compiled into the
        # prefix segment / calls served by its compiled replay / ops
        # substituted on the most recent replayed call
        self.prefix_op_count = 0
        self.prefix_segment_count = 0
        self.prefix_replay_count = 0
        self.last_replayed_ops = 0
        functools.update_wrapper(self, function)

    def __get__(self, instance, owner):
        if instance is None:
            return self
        return StaticFunction(
            self._function.__get__(instance, owner), self._input_spec,
            full_graph=self._full_graph,
            layer=instance if isinstance(instance, Layer) else None)

    def __call__(self, *args, **kwargs):
        enforce(not any(_is_tensor_leaf(v) for v in kwargs.values()),
                "to_static: pass Tensor arguments positionally")
        layer = self._layer
        flat_args, arg_treedef = jax.tree_util.tree_flatten(
            list(args), is_leaf=lambda x: isinstance(x, Tensor))
        arrays = [a.value if isinstance(a, Tensor) else a for a in flat_args]
        tensor_idx = [i for i, a in enumerate(flat_args) if _is_tensor_leaf(a)]
        static_leaves = tuple((i, flat_args[i]) for i in range(len(flat_args))
                              if i not in tensor_idx)

        named = dict(layer.named_parameters()) if layer is not None else {}
        param_names = list(named.keys())
        buffer_vals = {k: b.value for k, b in layer.named_buffers()} \
            if layer is not None else {}
        training = layer.training if layer is not None else True

        key = (arg_treedef,
               tuple((jnp.shape(arrays[i]), str(jnp.result_type(arrays[i])))
                     for i in tensor_idx),
               tuple(sorted(kwargs.items())),
               static_leaves, tuple(param_names), training)
        try:
            hash(key)
        except TypeError:
            key = None

        if key is not None and key in self._fallback_keys:
            # known graph-break: eager, with the compiled prefix
            # replayed when one was captured for this signature
            return self._eager_with_prefix(key, args, kwargs, flat_args,
                                           tensor_idx)

        entry = self._cache.get(key) if key is not None else None
        if entry is None:
            fn = self._function
            out_tree_box = {}

            def jittable(param_vals: dict, buf_vals: dict, rng_key,
                         tensor_arrays: list):
                full = list(flat_args)
                for j, i in enumerate(tensor_idx):
                    full[i] = Tensor(tensor_arrays[j], stop_gradient=True)
                call_args = jax.tree_util.tree_unflatten(arg_treedef, full)

                def run():
                    with _random.rng_guard(rng_key):
                        return fn(*call_args, **kwargs)
                if layer is not None:
                    with functional_state(layer, param_vals, buf_vals):
                        out = run()
                else:
                    out = run()
                flat_out, out_tree = jax.tree_util.tree_flatten(
                    out, is_leaf=lambda x: isinstance(x, Tensor))
                out_tree_box["tree"] = out_tree
                return tuple(o.value if isinstance(o, Tensor) else o
                             for o in flat_out)

            jitted = jax.jit(jittable)
            entry = (jitted, out_tree_box)
            if key is not None:
                self._cache[key] = entry
        jitted, out_tree_box = entry

        rng_key = _random.split_key()
        params_list = [named[n] for n in param_names]

        def raw(param_list, tensor_arrays_list):
            return jitted(dict(zip(param_names, param_list)), buffer_vals,
                          rng_key, tensor_arrays_list)
        raw.__name__ = getattr(self._function, "__name__", "static_fn")

        tensor_arrays = [flat_args[i] for i in tensor_idx]
        try:
            out = apply_op(raw, params_list, tensor_arrays)
        except _graph_break_errors() as e:
            if self._full_graph:
                raise
            # SOT-style graph break: run this signature eagerly from now
            # on (the trace attempt left no state — functional_state and
            # rng_guard unwind on exception)
            self.graph_break_count += 1
            if key is not None:
                self._fallback_keys.add(key)
                self._cache.pop(key, None)
            import logging
            logging.getLogger("paddle_tpu.jit").warning(
                "to_static graph break in %r (compiled-prefix capture + "
                "eager tail for this signature): %s",
                getattr(self._function, "__name__", "?"),
                str(e).splitlines()[0] if str(e) else type(e).__name__)
            return self._eager_with_prefix(key, args, kwargs, flat_args,
                                           tensor_idx)
        flat_out = list(out) if isinstance(out, (tuple, list)) else [out]
        return jax.tree_util.tree_unflatten(out_tree_box["tree"], flat_out)

    def _eager_with_prefix(self, key, args, kwargs, flat_args,
                           tensor_idx):
        """Eager execution of a graph-broken signature with SOT-style
        compiled-SEGMENT capture (round 5): the first eager run records
        the WHOLE op stream as segments split at host reads (and at
        unguardable RNG/unhashable ops, which replay eagerly between
        them); later runs execute each segment as ONE compiled call —
        a jax.vjp feeding a single tape GradNode in grad mode, so
        broken TRAIN steps run compiled too — substituting results
        op-by-op under guards (see jit/prefix.py).  The cache is keyed
        on arg stop-gradient flags + grad mode so an op's diff-ness
        cannot differ between recording and replay."""
        from ..autograd import tape
        from ..tensor import set_op_observer
        from .prefix import PrefixRecorder, PrefixReplayer

        layer = self._layer
        if key is None:
            return self._function(*args, **kwargs)
        key = (key,
               tuple(bool(getattr(flat_args[i], "stop_gradient", True))
                     for i in tensor_idx),
               tape.is_grad_enabled())

        entry = self._prefix_cache.get(key)
        if entry is False:          # evicted: guards kept bailing
            return self._function(*args, **kwargs)
        if entry is None:
            ext_sources = {}
            if layer is not None:
                for n, p in layer.named_parameters():
                    ext_sources[id(p.value)] = ("param", n)
                for n, b in layer.named_buffers():
                    ext_sources[id(b.value)] = ("buffer", n)
            for i in tensor_idx:
                a = flat_args[i]
                ext_sources[id(a.value if isinstance(a, Tensor)
                               else a)] = ("arg", i)
            rec = PrefixRecorder(ext_sources)
            prev = set_op_observer(rec)
            try:
                out = self._function(*args, **kwargs)
            finally:
                set_op_observer(prev)
            rec.seal()
            if rec.captured_op_count:
                self._prefix_cache[key] = rec
                self.prefix_op_count = len(rec.ops)
                self.prefix_segment_count = sum(
                    1 for kind, _ in rec.items if kind == "seg")
            else:
                self._prefix_cache[key] = False     # nothing capturable
            return out

        rec = entry
        named = dict(layer.named_parameters()) if layer is not None \
            else {}
        bufs = dict(layer.named_buffers()) if layer is not None else {}

        def fetch(desc):
            """(array, Tensor-or-None) for an ext descriptor — the
            Tensor carries the tape edge for grad-mode segments."""
            kind, ref = desc
            if kind == "param":
                t = named[ref]
                return t.value, t
            if kind == "buffer":
                return bufs[ref].value, None
            if kind == "arg":
                a = flat_args[ref]
                if isinstance(a, Tensor):
                    return a.value, a
                return a, None
            if kind == "tensor":        # pinned closure Tensor (param)
                t = rec.ext_tensors[ref]
                return t.value, t
            return rec.consts[ref], None              # const

        rep = PrefixReplayer(rec, fetch, tape.is_grad_enabled())
        prev = set_op_observer(rep)
        try:
            out = self._function(*args, **kwargs)
        finally:
            set_op_observer(prev)
        self.prefix_replay_count += 1
        self.last_replayed_ops = rep.replayed
        if rep.replayed < max(1, rec.captured_op_count // 2):
            # guards bailed early: running compiled segments then
            # recomputing most ops eagerly costs ~2x — evict
            self._prefix_cache[key] = False
        return out

    @property
    def function(self):
        return self._function


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=False, **kwargs):
    """Decorator/wrapper: ``paddle.jit.to_static`` analog.  ``backend`` is
    accepted for parity (CINN in the reference); XLA is always the
    compiler here.  ``full_graph=False`` (paddle 3.0's default) enables
    the SOT-style graph-break fallback to eager on data-dependent
    python control flow; ``True`` raises instead."""

    def decorate(fn):
        if isinstance(fn, Layer):
            sf = StaticFunction(fn.forward, input_spec, build_strategy,
                                backend, full_graph, layer=fn)
            object.__setattr__(fn, "forward", sf)
            return fn
        return StaticFunction(fn, input_spec, build_strategy, backend,
                              full_graph)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn.__not_to_static__ = True
    return fn


def ignore_module(modules: Sequence):
    return None
