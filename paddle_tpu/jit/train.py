"""Compiled training step — the performance path.

Reference parity: this is where the reference's dygraph-to-static +
CINN-compiled training program lands (SURVEY.md §3.3/§3.5): ONE XLA
computation per step containing fwd, bwd, grad-clip, optimizer update —
no per-op python dispatch, no tape.  The eager path (loss.backward();
opt.step()) stays available for debugging; this class is what recipes and
benchmarks use.

Sharded training: pass ``mesh`` + ``param_sharding_fn`` (see
distributed/) and every state leaf gets a NamedSharding; XLA's SPMD
partitioner then inserts the collectives (GSPMD — the fleet replacement).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from ..autograd import tape
from ..nn.layer import Layer, functional_state
from ..observability import health as _health
from ..observability import introspection as _insp
from ..observability import tracing as _tracing
from ..ops import random as _random
from ..optimizer.optimizer import Optimizer
from ..tensor import Tensor

__all__ = ["CompiledTrainStep", "traced_forward"]


def _maybe_enable_debug_nans():
    """FLAGS_check_nan_inf for the compiled path: the reference scans op
    outputs per step (fluid nan_inf_utils); the XLA-idiomatic analog is
    jax_debug_nans, which re-runs the failing computation op-by-op and
    raises at the first NaN-producing op."""
    from ..common.flags import get_flag
    if get_flag("check_nan_inf"):
        jax.config.update("jax_debug_nans", True)


def _to_arrays(tree):
    return jax.tree_util.tree_map(
        lambda x: x.value if isinstance(x, Tensor) else jnp.asarray(x), tree,
        is_leaf=lambda x: isinstance(x, Tensor))



def traced_forward(model: Layer, fn: Callable, params, batch, key):
    """THE tracing contract for running a Layer functionally inside jit:
    wrap batch leaves as stop-gradient Tensors, swap in the params
    pytree, pin the RNG stream, run with the tape off, unwrap Tensor
    outputs.  Single definition — the fused step, eval steps, grad
    accumulation, and hapi all trace through here."""
    batch_t = jax.tree_util.tree_map(
        lambda a: Tensor(a, stop_gradient=True), batch)
    with tape.no_grad(), functional_state(model, params), \
            _random.rng_guard(key):
        out = fn(model, batch_t)
    return jax.tree_util.tree_map(
        lambda x: x.value if isinstance(x, Tensor) else x, out,
        is_leaf=lambda x: isinstance(x, Tensor))


class CompiledTrainStep:
    """Owns (params, opt_state) as jax pytrees; one call = one fused step.

    loss_fn(model, batch) -> scalar loss Tensor, where ``batch`` is the
    user's pytree with leaves delivered as Tensors.
    """

    def __init__(self, model: Layer, loss_fn: Callable, optimizer: Optimizer,
                 seed: int = 0, donate: bool = True,
                 state_sharding_fn=None, has_aux: bool = False,
                 fused_step: bool = True, grad_norm_tap: bool = False):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        params = model.raw_state_dict()
        self.state: Dict[str, Any] = {
            "params": params,
            "opt": optimizer.init_state(params),
        }
        if state_sharding_fn is not None:
            self.state = state_sharding_fn(self.state)
        self._key = jax.random.key(seed)
        self._step_fn = None
        self._donate = donate
        self._has_aux = has_aux
        # fused step regions (default on): the optimizer update runs
        # through Optimizer.apply_gradients_fused — global-norm clip
        # folded into one pass over each param/grad/slot triple, Pallas
        # kernel on TPU.  Bit-identical to fused_step=False by
        # construction (ops/pallas/fused_train.py), still ONE compiled
        # program per step path (step_compiles() asserts it).
        self._fused_step = fused_step
        # small-leaf packing: None = auto (on only when the Pallas
        # kernels are active, where it amortizes the tail's kernel
        # launches).  Off the kernel path the per-leaf fused program is
        # STRUCTURALLY the unfused program, which is what guarantees
        # bitwise parity — packing reshapes XLA's fusion clusters and
        # CPU codegen may contract FMAs differently at the last ulp.
        self._fused_pack_small: Optional[bool] = None
        self._timer = None
        self._flops_cache = None
        # optimizer-update count (fused __call__ + apply_grads); part of
        # the resumable state so a restored run knows where it is
        self._step_count = 0
        # first dispatch pays the jit trace+compile: the goodput meter
        # books it as "compile", every later step as "productive_step"
        self._compiled_once = False
        # grad-norm sentinel tap (default OFF): when on, the step also
        # returns the f32 global grad norm of the SYNCED gradients so
        # fit can feed AnomalySentinel a step before the loss spikes.
        # Off by default because the extra output perturbs XLA's fusion
        # clustering, which the bit-exactness parity tests pin down.
        self._grad_norm_tap = bool(grad_norm_tap)
        self.last_grad_norm = None

    # -- telemetry -----------------------------------------------------------
    def attach_timer(self, timer):
        """Attach an observability.StepTimer: every __call__ is then
        timed with a block_until_ready fence on the step's outputs
        (honest device-inclusive step time despite async dispatch)."""
        self._timer = timer

    def step_flops(self, batch) -> Optional[float]:
        """Estimated FLOPs of one fused step from XLA's cost model
        (the MFU numerator).  Cached after the first call; returns None
        when the backend's cost analysis is unavailable.  Note: this
        AOT-lowers the step once more (the dispatch-path executable is
        cached separately), so callers should ask once, not per step.

        Accounting: the step program contains fwd + bwd + grad-clip +
        optimizer update, so the cost model already counts the clip and
        update FLOPs whenever they lower to HLO — including the
        fused_step=True reference path off TPU.  When the update runs
        inside the Pallas fused kernel (TPU), those FLOPs are opaque to
        cost analysis, so the optimizer's analytic estimate
        (``Optimizer.update_flop_estimate``) is added back.  Pre- and
        post-fusion MFU therefore use the same denominator convention
        and stay comparable across BENCH rounds."""
        if self._flops_cache is not None:
            return self._flops_cache if self._flops_cache > 0 else None
        if self._step_fn is None:
            self._build()
        try:
            lowered = self._step_fn.lower(
                self.state, _to_arrays(batch), jax.random.key(0),
                self.optimizer.get_lr())
            try:
                cost = lowered.cost_analysis()
            except Exception:
                cost = lowered.compile().cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            flops = float(cost.get("flops", -1.0))
        except Exception:
            flops = -1.0
        if flops > 0 and self._fused_step:
            from ..ops.pallas import fused_train as FT
            if FT.kernels_active():
                # the kernel path hides the update from the cost model
                flops += self.optimizer.update_flop_estimate(
                    self.state["params"])
        self._flops_cache = flops if flops > 0 else -1.0
        return flops if flops > 0 else None

    def step_compiles(self) -> int:
        """Number of compiled executables behind the fused step path —
        the one-program-per-step invariant (0 before the first step;
        a second compile means a shape/dtype leak into the trace)."""
        if self._step_fn is None:
            return 0
        try:
            return int(self._step_fn._cache_size())
        except Exception:
            return 1

    def _apply_gradients_fn(self):
        """(params, grads, opt_state, lr) -> (params, opt_state): the
        fused or per-leaf reference update, per the fused_step knob."""
        optimizer = self.optimizer
        if self._fused_step:
            pack = self._fused_pack_small
            if pack is None:
                from ..ops.pallas import fused_train as FT
                pack = FT.kernels_active()
            return lambda p, g, s, lr: optimizer.apply_gradients_fused(
                p, g, s, lr=lr, pack_small=pack)
        return lambda p, g, s, lr: optimizer.apply_gradients(
            p, g, s, lr=lr)

    def _sync_grads(self, grads):
        """Hook between backward and the optimizer update — identity
        here; ShardedTrainStep overrides it with bucketed gradient
        collectives so communication overlaps backward compute."""
        return grads

    def _make_step(self):
        """The raw (un-jitted) fused step fn: fwd+bwd+clip+update."""
        model, loss_fn = self.model, self.loss_fn
        apply_gradients = self._apply_gradients_fn()
        sync_grads = self._sync_grads

        has_aux = self._has_aux
        grad_norm_tap = self._grad_norm_tap

        def step(state, batch, key, lr):
            def pure_loss(p):
                return traced_forward(model, loss_fn, p, batch, key)

            if has_aux:
                # loss_fn returns (loss, aux): aux rides along from the
                # SAME pre-update forward (hapi train metrics use this —
                # paddle computes metrics on the loss forward, not on a
                # second post-update pass)
                (loss, aux), grads = jax.value_and_grad(
                    pure_loss, has_aux=True)(state["params"])
            else:
                loss, grads = jax.value_and_grad(pure_loss)(
                    state["params"])
            grads = sync_grads(grads)
            if grad_norm_tap:
                # f32 global norm over the synced grads — the same
                # quantity the clip pass derives, so XLA CSEs the two
                gnorm = jnp.sqrt(sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in jax.tree_util.tree_leaves(grads)))
            new_params, new_opt = apply_gradients(
                state["params"], grads, state["opt"], lr)
            out = (loss, aux) if has_aux else loss
            if grad_norm_tap:
                out = (out, gnorm)
            return {"params": new_params, "opt": new_opt}, out

        return step

    def _build(self):
        _maybe_enable_debug_nans()
        self._step_fn = jax.jit(
            self._make_step(), donate_argnums=(0,) if self._donate else ())
        _insp.get_compile_watch().register_program(self._program_name)

    # CompileWatch program name for the fused step (ShardedTrainStep
    # overrides it so the two step families are attributed separately)
    _program_name = "train.compiled_step"

    def __call__(self, batch) -> jax.Array:
        if self._step_fn is None:
            self._build()
        self._key, sub = jax.random.split(self._key)
        lr = self.optimizer.get_lr()
        # one span per optimizer step (covers dispatch + the timer's
        # block_until_ready fence when attached, so the span extent is
        # device-inclusive); the shared NULL_SPAN when tracing is off
        span = _tracing.span("train.compiled_step")
        span.set_attr("step", self._step_count)
        with _health.goodput_region(
                "productive_step" if self._compiled_once
                else "compile"):
            if self._timer is not None:
                self._timer.start()
            self.state, out = _insp.watched_call(
                self._program_name, self._step_fn,
                self.state, _to_arrays(batch), sub, lr)
            if self._grad_norm_tap:
                out, self.last_grad_norm = out
            if self._timer is not None:
                self._timer.stop(fence=(self.state, out))
        self._compiled_once = True
        span.end()
        self._step_count += 1
        sched = self.optimizer._lr_scheduler
        if sched is not None:
            sched.step()
        return out

    def eval_step(self, eval_fn: Callable, batch):
        """Compile-once eval step (no grad, no state mutation)."""
        if not hasattr(self, "_eval_fns"):
            self._eval_fns = {}
        fn = self._eval_fns.get(id(eval_fn))
        if fn is None:
            model = self.model

            def run(params, batch, key):
                return traced_forward(model, eval_fn, params, batch, key)
            fn = jax.jit(run)
            self._eval_fns[id(eval_fn)] = fn
            # each distinct eval_fn legitimately compiles once
            _insp.get_compile_watch().register_program("train.eval_step")
        self._key, sub = jax.random.split(self._key)
        return _insp.watched_call("train.eval_step", fn,
                                  self.state["params"],
                                  _to_arrays(batch), sub)

    # -- gradient accumulation ----------------------------------------------
    def grad_step(self, batch):
        """fwd+bwd ONLY (no optimizer update): returns (loss, grads) for
        gradient accumulation (paddle train_batch(update=False))."""
        if not hasattr(self, "_grad_fn"):
            model, loss_fn = self.model, self.loss_fn
            has_aux = self._has_aux

            def gstep(params, batch, key):
                def pure_loss(p):
                    return traced_forward(model, loss_fn, p, batch, key)
                if has_aux:
                    (loss, _aux), grads = jax.value_and_grad(
                        pure_loss, has_aux=True)(params)
                    return loss, grads
                return jax.value_and_grad(pure_loss)(params)

            self._grad_fn = jax.jit(gstep)
            _insp.get_compile_watch().register_program("train.grad_step")
        self._key, sub = jax.random.split(self._key)
        return _insp.watched_call("train.grad_step", self._grad_fn,
                                  self.state["params"],
                                  _to_arrays(batch), sub)

    def apply_grads(self, grads):
        """Optimizer update from externally-computed (accumulated) grads."""
        if not hasattr(self, "_apply_fn"):
            apply_gradients = self._apply_gradients_fn()

            def apply(state, grads, lr):
                new_params, new_opt = apply_gradients(
                    state["params"], grads, state["opt"], lr)
                return {"params": new_params, "opt": new_opt}

            # donate the old state like the fused path — without it the
            # accumulation path holds params+opt twice at the update
            self._apply_fn = jax.jit(
                apply, donate_argnums=(0,) if self._donate else ())
            _insp.get_compile_watch().register_program("train.apply_grads")
        self.state = _insp.watched_call(
            "train.apply_grads", self._apply_fn, self.state, grads,
            self.optimizer.get_lr())
        self._step_count += 1
        sched = self.optimizer._lr_scheduler
        if sched is not None:
            sched.step()

    # -- checkpoint/resume ---------------------------------------------------
    def _ckpt_tree(self):
        """The resumable ARRAY state: params+opt (which carries the
        optimizer's own step counter), plus the RNG stream.  One
        definition shared by save and load so the trees can't drift.
        Literal state (LR-sched position, step count, trainer-loop
        extras) rides in the manifest's literals — see save_checkpoint."""
        return {"state": self.state,
                "rng_key": jax.random.key_data(self._key)}

    def save_checkpoint(self, path: str, async_save: bool = False,
                        extra_state=None):
        """Sharded checkpoint of the full training state (params, optimizer
        state incl. its step counter, RNG stream, LR-scheduler position,
        update count) — resumable on any mesh via
        distributed.checkpoint's reshard-on-load.  ``extra_state`` (a
        JSON-able dict — epoch/loader position from the training loop)
        rides along and comes back from ``load_checkpoint``.  With
        ``async_save=True`` returns an AsyncSaveHandle whose ``wait()``
        surfaces writer failures."""
        import json
        from ..distributed import checkpoint as dck
        sched = self.optimizer._lr_scheduler
        tree = self._ckpt_tree()
        # one JSON literal: scheduler state may hold lists (milestones,
        # boundaries) which must not be key-flattened into the manifest
        tree["lr_sched"] = json.dumps(sched.state_dict()) \
            if sched is not None else None
        tree["step_count"] = int(self._step_count)
        if extra_state is not None:
            tree["extra"] = json.dumps(extra_state)
        return dck.save_state_dict(tree, path, async_save=async_save)

    def load_checkpoint(self, path: str):
        """Restore from ``save_checkpoint`` output.  The current state tree
        (including its shardings — possibly on a different mesh than the
        checkpoint was written from) is the template.  Scheduler state is
        restored only when both sides have a scheduler, so resuming a
        scheduled run with a constant LR (or vice versa) still restores
        params/opt/RNG.  Every chunk read is sha256-verified; corruption
        raises CorruptCheckpointError BEFORE any state is mutated.
        Returns the ``extra_state`` dict saved alongside (None if none
        was)."""
        import json
        from ..distributed import checkpoint as dck
        meta = dck.get_checkpoint_metadata(path)
        tree = self._ckpt_tree()
        dck.load_state_dict(tree, path, metadata=meta)
        self.state = tree["state"]
        self._key = jax.random.wrap_key_data(tree["rng_key"])
        self._step_count = int(meta["literals"].get("step_count") or 0)
        sched = self.optimizer._lr_scheduler
        saved = meta["literals"].get("lr_sched")
        if sched is not None and saved:
            sched.set_state_dict(json.loads(saved))
        extra = meta["literals"].get("extra")
        return json.loads(extra) if extra else None

    # -- state sync with the eager model ------------------------------------
    def sync_to_model(self):
        """Write compiled-state params back into the Layer (for eager use,
        state_dict saving, etc.)."""
        self.model.load_raw_state_dict(self.state["params"])

    def sync_from_model(self):
        self.state["params"] = self.model.raw_state_dict()
