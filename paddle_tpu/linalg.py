"""paddle.linalg namespace (python/paddle/linalg.py parity): the
tensorized linear-algebra surface re-exported under its public home.
Implementations live in ops/_linalg.py (XLA lowerings; decompositions
run on the TPU's QR/eig units where available, CPU callback otherwise).
"""
from .ops.api import (  # noqa: F401
    cholesky, cholesky_solve, cond, corrcoef, cov, det, eig, eigh,
    eigvals, eigvalsh, householder_product, inv, lstsq, lu, lu_unpack,
    matrix_exp, matrix_norm, matrix_power, matrix_rank, matrix_transpose,
    norm, ormqr, pca_lowrank, pinv, qr, slogdet, solve, svd, svd_lowrank,
    svdvals, triangular_solve, vector_norm,
)

__all__ = ["cholesky", "cholesky_solve", "cond", "corrcoef", "cov",
           "det", "eig", "eigh", "eigvals", "eigvalsh",
           "householder_product", "inv", "lstsq", "lu", "lu_unpack",
           "matrix_exp", "matrix_norm", "matrix_power", "matrix_rank",
           "matrix_transpose", "multi_dot", "norm", "ormqr",
           "pca_lowrank", "pinv", "qr", "slogdet", "solve", "svd",
           "svd_lowrank", "svdvals", "triangular_solve", "vector_norm"]


def multi_dot(tensors):
    """paddle.linalg.multi_dot: chain matmul with optimal association
    order (classic matrix-chain DP on the host — shapes are static)."""
    from . import ops as P
    from .common.errors import enforce

    enforce(len(tensors) >= 2, "multi_dot needs >= 2 tensors")
    # paddle allows 1-D endpoints: promote to row/column vectors and
    # squeeze the result back
    head_vec = len(tensors[0].shape) == 1
    tail_vec = len(tensors[-1].shape) == 1
    tensors = list(tensors)
    if head_vec:
        tensors[0] = P.reshape(tensors[0], [1, -1])
    if tail_vec:
        tensors[-1] = P.reshape(tensors[-1], [-1, 1])
    if len(tensors) == 2:
        out = P.matmul(tensors[0], tensors[1])
        return _squeeze_ends(out, head_vec, tail_vec)
    dims = [t.shape[0] for t in tensors] + [tensors[-1].shape[1]]
    n = len(tensors)
    cost = [[0] * n for _ in range(n)]
    split = [[0] * n for _ in range(n)]
    for length in range(2, n + 1):
        for i in range(n - length + 1):
            j = i + length - 1
            cost[i][j] = float("inf")
            for k in range(i, j):
                c = (cost[i][k] + cost[k + 1][j]
                     + dims[i] * dims[k + 1] * dims[j + 1])
                if c < cost[i][j]:
                    cost[i][j] = c
                    split[i][j] = k

    def build(i, j):
        if i == j:
            return tensors[i]
        k = split[i][j]
        from . import ops as P
        return P.matmul(build(i, k), build(k + 1, j))

    return _squeeze_ends(build(0, n - 1), head_vec, tail_vec)


def _squeeze_ends(out, head_vec, tail_vec):
    from . import ops as P
    if tail_vec:
        out = P.squeeze(out, axis=-1)
    if head_vec:
        out = P.squeeze(out, axis=0)
    return out
