"""paddle.metric — streaming metrics.

Reference parity: python/paddle/metric/metrics.py (``Metric`` base with
update/accumulate/reset/name, ``Accuracy``, ``Precision``, ``Recall``,
``Auc``) — the objects hapi ``Model.fit`` threads through its callbacks.
Host-side numpy accumulation (these run between compiled steps, not
inside them — same as the reference, whose metrics are python too).
"""
from __future__ import annotations

import abc
from typing import List, Sequence, Union

import numpy as np

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc"]


def _to_np(x):
    from ..tensor import Tensor
    if isinstance(x, Tensor):
        return x.numpy()
    return np.asarray(x)


class Metric(abc.ABC):
    def __init__(self):
        pass

    @abc.abstractmethod
    def name(self):
        ...

    @abc.abstractmethod
    def update(self, *args):
        ...

    @abc.abstractmethod
    def accumulate(self):
        ...

    @abc.abstractmethod
    def reset(self):
        ...

    def compute(self, *args):
        """Optional pre-processing hook (runs on Tensors; the reference
        lets this part stay in-graph).  Default: identity."""
        return args


class Accuracy(Metric):
    """Top-k accuracy (paddle.metric.Accuracy)."""

    def __init__(self, topk: Union[int, Sequence[int]] = (1,),
                 name: str = None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _to_np(pred)
        label_np = _to_np(label)
        idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if label_np.ndim == pred_np.ndim:       # one-hot / [N,1] labels
            if label_np.shape[-1] == pred_np.shape[-1]:
                label_np = np.argmax(label_np, axis=-1)
            else:
                label_np = label_np[..., 0]
        return (idx == label_np[..., None]).astype(np.float32)

    def update(self, correct):
        correct = _to_np(correct)
        # samples = every leading dim (sequence-shaped preds count each
        # position, matching the paddle metric's prod(shape[:-1]))
        num = int(np.prod(correct.shape[:-1])) if correct.ndim else 1
        batch = []
        for i, k in enumerate(self.topk):
            hit = float(correct[..., :k].sum())
            self.total[i] += hit
            batch.append(hit / max(num, 1))
        self.count += num
        # paddle returns the CURRENT batch accuracy from update()
        return batch[0] if len(batch) == 1 else batch

    def accumulate(self):
        res = [t / max(self.count, 1) for t in self.total]
        return res[0] if len(res) == 1 else res

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = 0

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Binary precision (paddle.metric.Precision: pred > 0.5)."""

    def __init__(self, name: str = "precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = (_to_np(preds).reshape(-1) > 0.5).astype(np.int64)
        labels = _to_np(labels).reshape(-1).astype(np.int64)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def reset(self):
        self.tp = 0
        self.fp = 0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall (paddle.metric.Recall)."""

    def __init__(self, name: str = "recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = (_to_np(preds).reshape(-1) > 0.5).astype(np.int64)
        labels = _to_np(labels).reshape(-1).astype(np.int64)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def reset(self):
        self.tp = 0
        self.fn = 0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC-AUC via fixed-bucket histogram (paddle.metric.Auc ROC mode)."""

    def __init__(self, curve: str = "ROC", num_thresholds: int = 4095,
                 name: str = "auc"):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds)
        if preds.ndim == 2:                      # [N, 2] softmax scores
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        labels = _to_np(labels).reshape(-1).astype(np.int64)
        idx = np.clip((preds * self.num_thresholds).astype(np.int64),
                      0, self.num_thresholds)
        np.add.at(self._stat_pos, idx, labels == 1)
        np.add.at(self._stat_neg, idx, labels == 0)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # trapezoid over descending thresholds
        area = 0.0
        pos = neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = pos + self._stat_pos[i]
            new_neg = neg + self._stat_neg[i]
            area += (new_neg - neg) * (pos + new_pos) / 2.0
            pos, neg = new_pos, new_neg
        return float(area / (tot_pos * tot_neg))

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def name(self):
        return self._name
