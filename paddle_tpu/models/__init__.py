from . import gpt
from .gpt import GPTConfig, GPTForCausalLM, GPTModel, GPTPretrainingCriterion
