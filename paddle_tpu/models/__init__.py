from . import dit
from . import ernie
from . import gpt
from . import llama
from . import bert
from . import qwen2_moe
from .dit import AutoencoderKL, DiT, DiTConfig, DiTWithDiffusion
from .ernie import Ernie45Config, Ernie45ForCausalLM, Ernie45ForCausalLMPipe
from .gpt import GPTConfig, GPTForCausalLM, GPTModel, GPTPretrainingCriterion
from .llama import (LlamaConfig, LlamaForCausalLM, LlamaForCausalLMPipe,
                    LlamaModel, LlamaPretrainingCriterion)
from .bert import (BertConfig, BertForMaskedLM,
                   BertForSequenceClassification, BertModel)
from .qwen2_moe import Qwen2MoeConfig, Qwen2MoeForCausalLM
