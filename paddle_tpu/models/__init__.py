from . import gpt
from . import llama
from .gpt import GPTConfig, GPTForCausalLM, GPTModel, GPTPretrainingCriterion
from .llama import LlamaConfig, LlamaForCausalLM, LlamaModel, LlamaPretrainingCriterion
