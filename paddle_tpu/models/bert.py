"""BERT family (PaddleNLP bert parity: BertModel + task heads).

Reference parity: PaddleNLP paddlenlp/transformers/bert — encoder-side
coverage beyond the five BASELINE configs (the reference ecosystem's
most-used encoder).  TPU-native: rides the shared nn.TransformerEncoder
stack, whose attention routes through the fused flash path when
eligible; padding masks arrive as additive biases the Pallas kernel
consumes directly.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from .. import ops as P
from ..nn import functional as F
from ..nn.common import Dropout, Embedding, Linear
from ..nn.initializer import Normal
from ..nn.layer import Layer
from ..nn.norm import LayerNorm
from ..nn.transformer import TransformerEncoder, TransformerEncoderLayer
from ..tensor import Tensor

__all__ = ["BertConfig", "BertModel", "BertForSequenceClassification",
           "BertForMaskedLM", "bert_tiny_config"]


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0


def bert_tiny_config() -> BertConfig:
    return BertConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                      num_attention_heads=4, intermediate_size=128,
                      max_position_embeddings=64,
                      hidden_dropout_prob=0.0,
                      attention_probs_dropout_prob=0.0)


class BertEmbeddings(Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        init = Normal(0.0, c.initializer_range)
        self.word_embeddings = Embedding(c.vocab_size, c.hidden_size,
                                         weight_attr=init)
        self.position_embeddings = Embedding(c.max_position_embeddings,
                                             c.hidden_size,
                                             weight_attr=init)
        self.token_type_embeddings = Embedding(c.type_vocab_size,
                                               c.hidden_size,
                                               weight_attr=init)
        self.layer_norm = LayerNorm(c.hidden_size,
                                    epsilon=c.layer_norm_eps)
        self.dropout = Dropout(c.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = P.arange(s, dtype="int32")
        if token_type_ids is None:
            token_type_ids = P.zeros([b, s], dtype="int32")
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class BertPooler(Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        self.dense = Linear(c.hidden_size, c.hidden_size,
                            weight_attr=Normal(0.0, c.initializer_range))

    def forward(self, x):
        return P.tanh(self.dense(x[:, 0]))


class BertModel(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        c = config
        self.embeddings = BertEmbeddings(c)
        enc_layer = TransformerEncoderLayer(
            c.hidden_size, c.num_attention_heads, c.intermediate_size,
            dropout=c.hidden_dropout_prob, activation=c.hidden_act,
            attn_dropout=c.attention_probs_dropout_prob,
            act_dropout=c.hidden_dropout_prob,
            layer_norm_eps=c.layer_norm_eps)
        self.encoder = TransformerEncoder(enc_layer, c.num_hidden_layers)
        self.pooler = BertPooler(c)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        """attention_mask: [B, S] with 1 = attend (paddle/HF bert
        convention); converted to the additive bias the fused attention
        path consumes."""
        if attention_mask is not None:
            from ..tensor import to_tensor
            m = attention_mask if isinstance(attention_mask, Tensor) \
                else to_tensor(attention_mask)
            bias = P.scale(P.cast(m, "float32") - 1.0, 1e30)  # 0 / -1e30
            bias = P.unsqueeze(P.unsqueeze(bias, 1), 1)        # [B,1,1,S]
        else:
            bias = None
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        x = self.encoder(x, src_mask=bias)
        return x, self.pooler(x)


class BertForSequenceClassification(Layer):
    def __init__(self, config: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.classifier = Linear(config.hidden_size, num_classes,
                                 weight_attr=Normal(
                                     0.0, config.initializer_range))

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, labels=None):
        _, pooled = self.bert(input_ids, token_type_ids, position_ids,
                              attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            return F.cross_entropy(logits, labels)
        return logits


class BertForMaskedLM(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        c = config
        self.bert = BertModel(c)
        self.transform = Linear(c.hidden_size, c.hidden_size,
                                weight_attr=Normal(0.0,
                                                   c.initializer_range))
        self.layer_norm = LayerNorm(c.hidden_size,
                                    epsilon=c.layer_norm_eps)
        # decoder tied to the word embeddings (bert convention)
        self.decoder_bias = self.create_parameter(
            [c.vocab_size], default_initializer=Normal(0.0, 0.0))

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, labels=None, ignore_index=-100):
        seq, _ = self.bert(input_ids, token_type_ids, position_ids,
                           attention_mask)
        h = self.layer_norm(F.gelu(self.transform(seq)))
        logits = P.matmul(h, self.bert.embeddings.word_embeddings.weight,
                          transpose_y=True) + self.decoder_bias
        if labels is not None:
            return F.cross_entropy(
                P.reshape(logits, [-1, logits.shape[-1]]),
                P.reshape(labels, [-1]), ignore_index=ignore_index)
        return logits
