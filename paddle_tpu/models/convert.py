"""Checkpoint conversion: HuggingFace/torch weights -> paddle_tpu models.

The migration story ("switch from the reference and bring your
weights"): torch-format checkpoints (pytorch_model.bin / *.safetensors,
loaded with the bundled CPU torch) are renamed and re-laid-out into
this framework's state_dicts.  Two layout rules cover almost
everything:

* torch ``nn.Linear`` stores ``[out, in]``; paddle Linear stores
  ``[in, out]`` -> every ``*_proj/linear/dense`` weight is transposed.
* Embeddings / norms are layout-identical.

Supported families (round 3 — all five BASELINE configs): Llama,
BERT, GPT-2, ERNIE-4.5 (dense), Qwen2-MoE; plus the EXPORT direction
(paddle_tpu -> HF) for Llama.  The mapping tables are data, so new
families are one dict away.
"""
from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common.errors import enforce

__all__ = ["load_torch_checkpoint", "convert_hf_llama",
           "convert_hf_bert", "load_hf_llama", "load_hf_bert",
           "convert_hf_gpt2", "load_hf_gpt2", "convert_hf_ernie45",
           "load_hf_ernie45", "convert_hf_qwen2_moe",
           "load_hf_qwen2_moe", "export_hf_llama", "save_hf_llama"]


def load_torch_checkpoint(path: str) -> Dict[str, np.ndarray]:
    """Load a torch .bin/.pt (pickle) or .safetensors file into numpy."""
    if path.endswith(".safetensors"):
        # via torch: numpy has no bfloat16, and stock HF checkpoints are
        # bf16 — upcast to f32 on the way through
        from safetensors.torch import load_file
        return {k: v.to(dtype=__import__("torch").float32).numpy()
                if v.dtype == __import__("torch").bfloat16 else v.numpy()
                for k, v in load_file(path).items()}
    import torch
    state = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(state, dict) and "state_dict" in state:
        state = state["state_dict"]
    return {k: v.numpy() if hasattr(v, "numpy") else np.asarray(v)
            for k, v in state.items()}


def _apply(model, mapped: Dict[str, np.ndarray]
           ) -> Tuple[List[str], List[str]]:
    own = dict(model.named_parameters())
    missing = [k for k in own if k not in mapped]
    unexpected = [k for k in mapped if k not in own]
    for name, arr in mapped.items():
        p = own.get(name)
        if p is None:
            continue
        enforce(tuple(arr.shape) == tuple(p.shape),
                f"converted weight {name!r}: shape {arr.shape} vs model "
                f"{tuple(p.shape)}")
        p.set_value(np.ascontiguousarray(arr))
    return missing, unexpected


# ---------------------------------------------------------------------------
# Llama (HF LlamaForCausalLM layout)
# ---------------------------------------------------------------------------

_LLAMA_TRANSPOSE = re.compile(
    r"(q_proj|k_proj|v_proj|o_proj|gate_proj|up_proj|down_proj|lm_head)"
    r"\.weight$")


def convert_hf_llama(state: Dict[str, np.ndarray]
                     ) -> Dict[str, np.ndarray]:
    """HF ``model.layers.N...`` names -> ``llama.layers.N...`` (this
    framework's LlamaForCausalLM), transposing linear weights."""
    out = {}
    for k, v in state.items():
        nk = k
        if nk.startswith("model."):
            nk = "llama." + nk[len("model."):]
        if _LLAMA_TRANSPOSE.search(nk):
            v = np.asarray(v).T
        if "rotary_emb" in nk:        # recomputed, not a parameter
            continue
        out[nk] = np.asarray(v)
    return out


def load_hf_llama(model, path: str) -> Tuple[List[str], List[str]]:
    """Load an HF Llama checkpoint file into ``model`` in place; returns
    (missing, unexpected) parameter names."""
    return _apply(model, convert_hf_llama(load_torch_checkpoint(path)))


# ---------------------------------------------------------------------------
# BERT (HF BertModel layout)
# ---------------------------------------------------------------------------

_BERT_RENAMES = [
    (r"^bert\.", ""),
    (r"embeddings\.LayerNorm\.", "embeddings.layer_norm."),
    (r"encoder\.layer\.(\d+)\.attention\.self\.query\.",
     r"encoder.layers.\1.self_attn.q_proj."),
    (r"encoder\.layer\.(\d+)\.attention\.self\.key\.",
     r"encoder.layers.\1.self_attn.k_proj."),
    (r"encoder\.layer\.(\d+)\.attention\.self\.value\.",
     r"encoder.layers.\1.self_attn.v_proj."),
    (r"encoder\.layer\.(\d+)\.attention\.output\.dense\.",
     r"encoder.layers.\1.self_attn.out_proj."),
    (r"encoder\.layer\.(\d+)\.attention\.output\.LayerNorm\.",
     r"encoder.layers.\1.norm1."),
    (r"encoder\.layer\.(\d+)\.intermediate\.dense\.",
     r"encoder.layers.\1.linear1."),
    (r"encoder\.layer\.(\d+)\.output\.dense\.",
     r"encoder.layers.\1.linear2."),
    (r"encoder\.layer\.(\d+)\.output\.LayerNorm\.",
     r"encoder.layers.\1.norm2."),
]

_BERT_TRANSPOSE = re.compile(
    r"(q_proj|k_proj|v_proj|out_proj|linear1|linear2|pooler\.dense|"
    r"classifier)\.weight$")


def convert_hf_bert(state: Dict[str, np.ndarray], prefix: str = "bert."
                    ) -> Dict[str, np.ndarray]:
    """HF bert names -> this framework's BertModel names (use
    ``prefix`` for where BertModel sits in the target, e.g. ``"bert."``
    inside BertForSequenceClassification or ``""`` standalone)."""
    out = {}
    for k, v in state.items():
        nk = k
        for pat, rep in _BERT_RENAMES:
            nk = re.sub(pat, rep, nk)
        if "position_ids" in nk:      # HF buffer, not a parameter
            continue
        if _BERT_TRANSPOSE.search(nk):
            v = np.asarray(v).T
        out[prefix + nk] = np.asarray(v)
    return out


def load_hf_bert(model, path: str, prefix: str = ""
                 ) -> Tuple[List[str], List[str]]:
    return _apply(model, convert_hf_bert(load_torch_checkpoint(path),
                                         prefix=prefix))


# ---------------------------------------------------------------------------
# GPT-2 (HF GPT2LMHeadModel layout)
# ---------------------------------------------------------------------------

_GPT2_RENAMES = [
    (r"^transformer\.", "gpt."),
    (r"\.h\.(\d+)\.attn\.c_attn\.", r".h.\1.attn.qkv_proj."),
    (r"\.h\.(\d+)\.attn\.c_proj\.", r".h.\1.attn.out_proj."),
    (r"\.h\.(\d+)\.mlp\.c_fc\.", r".h.\1.mlp.fc_in."),
    (r"\.h\.(\d+)\.mlp\.c_proj\.", r".h.\1.mlp.fc_out."),
]


def convert_hf_gpt2(state: Dict[str, np.ndarray]
                    ) -> Dict[str, np.ndarray]:
    """HF GPT-2 names -> this framework's GPTForCausalLM.  HF GPT-2
    uses Conv1D modules that ALREADY store [in, out] — no transposes,
    only renames; the tied lm_head is dropped (reused from wte)."""
    out = {}
    for k, v in state.items():
        if k.endswith(".attn.bias") or k.endswith(".attn.masked_bias"):
            continue                  # causal-mask buffers, not params
        if k == "lm_head.weight":
            continue                  # tied to wte
        nk = k
        for pat, rep in _GPT2_RENAMES:
            nk = re.sub(pat, rep, nk)
        out[nk] = np.asarray(v)
    return out


def load_hf_gpt2(model, path: str) -> Tuple[List[str], List[str]]:
    return _apply(model, convert_hf_gpt2(load_torch_checkpoint(path)))


# ---------------------------------------------------------------------------
# ERNIE-4.5 dense (HF Ernie4_5ForCausalLM layout — llama-shaped)
# ---------------------------------------------------------------------------

def _deinterleave_heads(v: np.ndarray, head_dim: int,
                        axis: int) -> np.ndarray:
    """Permute per-head lanes (0,2,4,..,1,3,5,..) along ``axis``.

    ERNIE-4.5's rope pairs lanes (2i, 2i+1) with angle θ_i (GPT-J
    style).  Attention scores are invariant under a joint permutation
    of q/k head lanes, so baking this permutation into the q/k
    projection weights makes the checkpoint numerically exact under
    the standard contiguous-half rope — which is ~8% faster end to end
    on TPU than strided interleaved rotates (measured on the v5e ERNIE
    bench row)."""
    v = np.moveaxis(np.asarray(v), axis, -1)
    shp = v.shape
    heads = v.reshape(shp[:-1] + (shp[-1] // head_dim, head_dim))
    perm = np.concatenate([np.arange(0, head_dim, 2),
                           np.arange(1, head_dim, 2)])
    heads = heads[..., perm]
    return np.moveaxis(heads.reshape(shp), -1, axis)


_ERNIE_QK = re.compile(r"(q_proj|k_proj)\.(weight|bias)$")


def convert_hf_ernie45(state: Dict[str, np.ndarray],
                       head_dim: Optional[int] = None
                       ) -> Dict[str, np.ndarray]:
    """HF ``model.layers.N...`` -> this framework's Ernie45ForCausalLM
    (which keeps the layer stack at the TOP level: ``layers.N...``).
    Same linear-transpose rule as Llama, plus the q/k lane permutation
    that converts ERNIE's interleaved rope into the fast contiguous
    layout (see _deinterleave_heads).  ``head_dim`` is required for the
    permutation (load_hf_ernie45 reads it off the target model)."""
    enforce(head_dim is not None and head_dim > 0,
            "convert_hf_ernie45 needs head_dim for the rope lane "
            "permutation (it is shape-preserving, so skipping it would "
            "load cleanly but attend with silently wrong numerics); "
            "use load_hf_ernie45(model, path) to infer it")
    out = {}
    for k, v in state.items():
        nk = k
        if nk.startswith("model."):
            nk = nk[len("model."):]
        if "rotary_emb" in nk:
            continue
        v = np.asarray(v)
        if _ERNIE_QK.search(nk):
            v = _deinterleave_heads(v, head_dim, axis=0)
        if _LLAMA_TRANSPOSE.search(nk):
            v = v.T
        out[nk] = np.asarray(v)
    return out


def load_hf_ernie45(model, path: str) -> Tuple[List[str], List[str]]:
    head_dim = model.layers[0].self_attn.head_dim
    return _apply(model, convert_hf_ernie45(load_torch_checkpoint(path),
                                            head_dim=head_dim))


# ---------------------------------------------------------------------------
# Qwen2-MoE (HF Qwen2MoeForCausalLM layout)
# ---------------------------------------------------------------------------

_QWEN_EXPERT = re.compile(
    r"^model\.layers\.(\d+)\.mlp\.experts\.(\d+)\.(gate|up|down)_proj"
    r"\.weight$")
_QWEN_RENAMES = [
    (r"^model\.", ""),
    (r"\.mlp\.shared_expert\.gate_proj\.", ".mlp.shared_gate."),
    (r"\.mlp\.shared_expert\.up_proj\.", ".mlp.shared_up."),
    (r"\.mlp\.shared_expert\.down_proj\.", ".mlp.shared_down."),
]
_QWEN_TRANSPOSE = re.compile(
    r"(q_proj|k_proj|v_proj|o_proj|lm_head|mlp\.gate|shared_gate|"
    r"shared_up|shared_down|shared_expert_gate)\.weight$")


def convert_hf_qwen2_moe(state: Dict[str, np.ndarray]
                         ) -> Dict[str, np.ndarray]:
    """HF Qwen2-MoE -> this framework's Qwen2MoeForCausalLM: per-expert
    ``experts.N.{gate,up,down}_proj [F, H]`` stack into the batched
    ``experts.{gate,up,down}_w`` ([E, H, F] / [E, F, H]); the router and
    shared-expert linears transpose like every torch Linear."""
    out: Dict[str, np.ndarray] = {}
    experts: Dict[Tuple[int, str], Dict[int, np.ndarray]] = {}
    for k, v in state.items():
        m = _QWEN_EXPERT.match(k)
        if m:
            layer, eid, kind = int(m.group(1)), int(m.group(2)), m.group(3)
            experts.setdefault((layer, kind), {})[eid] = np.asarray(v)
            continue
        nk = k
        for pat, rep in _QWEN_RENAMES:
            nk = re.sub(pat, rep, nk)
        if "rotary_emb" in nk:
            continue
        if _QWEN_TRANSPOSE.search(nk):
            v = np.asarray(v).T
        out[nk] = np.asarray(v)
    for (layer, kind), by_id in experts.items():
        enforce(sorted(by_id) == list(range(len(by_id))),
                f"layer {layer} {kind}_proj: expert ids "
                f"{sorted(by_id)} are not contiguous from 0 — partial "
                "checkpoint shard? merge all shards before converting")
        stack = np.stack([by_id[i].T for i in range(len(by_id))])
        # gate/up: [E, H, F]; down: [E, F, H] — both from [out,in].T
        out[f"layers.{layer}.mlp.experts.{kind}_w"] = stack
    return out


def load_hf_qwen2_moe(model, path: str) -> Tuple[List[str], List[str]]:
    return _apply(model,
                  convert_hf_qwen2_moe(load_torch_checkpoint(path)))


# ---------------------------------------------------------------------------
# export: paddle_tpu -> HF (the other migration direction)
# ---------------------------------------------------------------------------

def export_hf_llama(model) -> Dict[str, np.ndarray]:
    """Inverse of convert_hf_llama: this framework's LlamaForCausalLM
    state -> HF LlamaForCausalLM names/layouts (numpy arrays; wrap with
    torch.save / safetensors to ship)."""
    out = {}
    for name, p in model.named_parameters():
        v = np.asarray(p.numpy())
        nk = name
        if nk.startswith("llama."):
            nk = "model." + nk[len("llama."):]
        if _LLAMA_TRANSPOSE.search(nk):
            v = v.T
        out[nk] = np.ascontiguousarray(v)
    return out


def save_hf_llama(model, path: str) -> None:
    """Write an HF-loadable torch checkpoint for a LlamaForCausalLM."""
    import torch
    torch.save({k: torch.from_numpy(v)
                for k, v in export_hf_llama(model).items()}, path)
