"""Checkpoint conversion: HuggingFace/torch weights -> paddle_tpu models.

The migration story ("switch from the reference and bring your
weights"): torch-format checkpoints (pytorch_model.bin / *.safetensors,
loaded with the bundled CPU torch) are renamed and re-laid-out into
this framework's state_dicts.  Two layout rules cover almost
everything:

* torch ``nn.Linear`` stores ``[out, in]``; paddle Linear stores
  ``[in, out]`` -> every ``*_proj/linear/dense`` weight is transposed.
* Embeddings / norms are layout-identical.

Supported families: Llama (HF ``LlamaForCausalLM``) and BERT
(HF ``BertModel``/``BertFor*``); the mapping tables are data, so new
families are one dict away.
"""
from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common.errors import enforce

__all__ = ["load_torch_checkpoint", "convert_hf_llama",
           "convert_hf_bert", "load_hf_llama", "load_hf_bert"]


def load_torch_checkpoint(path: str) -> Dict[str, np.ndarray]:
    """Load a torch .bin/.pt (pickle) or .safetensors file into numpy."""
    if path.endswith(".safetensors"):
        # via torch: numpy has no bfloat16, and stock HF checkpoints are
        # bf16 — upcast to f32 on the way through
        from safetensors.torch import load_file
        return {k: v.to(dtype=__import__("torch").float32).numpy()
                if v.dtype == __import__("torch").bfloat16 else v.numpy()
                for k, v in load_file(path).items()}
    import torch
    state = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(state, dict) and "state_dict" in state:
        state = state["state_dict"]
    return {k: v.numpy() if hasattr(v, "numpy") else np.asarray(v)
            for k, v in state.items()}


def _apply(model, mapped: Dict[str, np.ndarray]
           ) -> Tuple[List[str], List[str]]:
    own = dict(model.named_parameters())
    missing = [k for k in own if k not in mapped]
    unexpected = [k for k in mapped if k not in own]
    for name, arr in mapped.items():
        p = own.get(name)
        if p is None:
            continue
        enforce(tuple(arr.shape) == tuple(p.shape),
                f"converted weight {name!r}: shape {arr.shape} vs model "
                f"{tuple(p.shape)}")
        p.set_value(np.ascontiguousarray(arr))
    return missing, unexpected


# ---------------------------------------------------------------------------
# Llama (HF LlamaForCausalLM layout)
# ---------------------------------------------------------------------------

_LLAMA_TRANSPOSE = re.compile(
    r"(q_proj|k_proj|v_proj|o_proj|gate_proj|up_proj|down_proj|lm_head)"
    r"\.weight$")


def convert_hf_llama(state: Dict[str, np.ndarray]
                     ) -> Dict[str, np.ndarray]:
    """HF ``model.layers.N...`` names -> ``llama.layers.N...`` (this
    framework's LlamaForCausalLM), transposing linear weights."""
    out = {}
    for k, v in state.items():
        nk = k
        if nk.startswith("model."):
            nk = "llama." + nk[len("model."):]
        if _LLAMA_TRANSPOSE.search(nk):
            v = np.asarray(v).T
        if "rotary_emb" in nk:        # recomputed, not a parameter
            continue
        out[nk] = np.asarray(v)
    return out


def load_hf_llama(model, path: str) -> Tuple[List[str], List[str]]:
    """Load an HF Llama checkpoint file into ``model`` in place; returns
    (missing, unexpected) parameter names."""
    return _apply(model, convert_hf_llama(load_torch_checkpoint(path)))


# ---------------------------------------------------------------------------
# BERT (HF BertModel layout)
# ---------------------------------------------------------------------------

_BERT_RENAMES = [
    (r"^bert\.", ""),
    (r"embeddings\.LayerNorm\.", "embeddings.layer_norm."),
    (r"encoder\.layer\.(\d+)\.attention\.self\.query\.",
     r"encoder.layers.\1.self_attn.q_proj."),
    (r"encoder\.layer\.(\d+)\.attention\.self\.key\.",
     r"encoder.layers.\1.self_attn.k_proj."),
    (r"encoder\.layer\.(\d+)\.attention\.self\.value\.",
     r"encoder.layers.\1.self_attn.v_proj."),
    (r"encoder\.layer\.(\d+)\.attention\.output\.dense\.",
     r"encoder.layers.\1.self_attn.out_proj."),
    (r"encoder\.layer\.(\d+)\.attention\.output\.LayerNorm\.",
     r"encoder.layers.\1.norm1."),
    (r"encoder\.layer\.(\d+)\.intermediate\.dense\.",
     r"encoder.layers.\1.linear1."),
    (r"encoder\.layer\.(\d+)\.output\.dense\.",
     r"encoder.layers.\1.linear2."),
    (r"encoder\.layer\.(\d+)\.output\.LayerNorm\.",
     r"encoder.layers.\1.norm2."),
]

_BERT_TRANSPOSE = re.compile(
    r"(q_proj|k_proj|v_proj|out_proj|linear1|linear2|pooler\.dense|"
    r"classifier)\.weight$")


def convert_hf_bert(state: Dict[str, np.ndarray], prefix: str = "bert."
                    ) -> Dict[str, np.ndarray]:
    """HF bert names -> this framework's BertModel names (use
    ``prefix`` for where BertModel sits in the target, e.g. ``"bert."``
    inside BertForSequenceClassification or ``""`` standalone)."""
    out = {}
    for k, v in state.items():
        nk = k
        for pat, rep in _BERT_RENAMES:
            nk = re.sub(pat, rep, nk)
        if "position_ids" in nk:      # HF buffer, not a parameter
            continue
        if _BERT_TRANSPOSE.search(nk):
            v = np.asarray(v).T
        out[prefix + nk] = np.asarray(v)
    return out


def load_hf_bert(model, path: str, prefix: str = ""
                 ) -> Tuple[List[str], List[str]]:
    return _apply(model, convert_hf_bert(load_torch_checkpoint(path),
                                         prefix=prefix))
