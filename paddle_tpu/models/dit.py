"""DiT / SD3-class diffusion models (config #4 of BASELINE.json).

Reference parity: the reference's diffusion recipe class (PaddleMIX /
ppdiffusers DiT + Stable-Diffusion VAE components — the "DiT/SD3
(conv+groupnorm)" row of BASELINE.json configs): patchify Conv2D,
timestep/label embedders, adaLN-Zero transformer blocks, unpatchify
head, DDPM epsilon-prediction training objective; plus the
AutoencoderKL-style conv+GroupNorm encoder/decoder SD3 trains under.

TPU-native design: everything is plain Layer code lowered by XLA —
Conv2D maps onto the MXU via implicit GEMM, GroupNorm fuses into the
surrounding elementwise ops, attention routes through the shared fused
path (F.scaled_dot_product_attention, bidirectional).  The diffusion
timestep sampling uses the framework RNG (ops.random) so the whole
training step stays inside one compiled program.  Weights carry
Megatron ``dist_spec`` annotations on the transformer blocks for the
DP(+TP) ladder row.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

from .. import ops as P
from ..nn import functional as F
from ..nn.common import Embedding, Linear
from ..nn.container import LayerList, Sequential
from ..nn.conv import Conv2D
from ..nn.initializer import Constant, Normal, XavierUniform
from ..nn.layer import Layer
from ..nn.norm import GroupNorm, LayerNorm
from ..tensor import Tensor, apply_op

__all__ = ["DiTConfig", "DiT", "DiTWithDiffusion", "AutoencoderKL",
           "dit_tiny_config", "dit_s2_config"]


@dataclass
class DiTConfig:
    input_size: int = 32           # latent H=W
    patch_size: int = 2
    in_channels: int = 4
    hidden_size: int = 384
    depth: int = 12
    num_heads: int = 6
    mlp_ratio: float = 4.0
    num_classes: int = 1000
    class_dropout_prob: float = 0.1
    num_train_timesteps: int = 1000
    initializer_range: float = 0.02


def dit_s2_config() -> DiTConfig:
    """DiT-S/2 shape."""
    return DiTConfig()


def dit_tiny_config() -> DiTConfig:
    return DiTConfig(input_size=8, patch_size=2, in_channels=4,
                     hidden_size=64, depth=2, num_heads=4, num_classes=10,
                     num_train_timesteps=100)


class TimestepEmbedder(Layer):
    """Sinusoidal timestep features -> 2-layer SiLU MLP."""

    def __init__(self, hidden_size: int, freq_dim: int = 256):
        super().__init__()
        self.freq_dim = freq_dim
        self.mlp = Sequential(
            Linear(freq_dim, hidden_size, weight_attr=Normal(0.0, 0.02)),
            _SiLU(),
            Linear(hidden_size, hidden_size, weight_attr=Normal(0.0, 0.02)))

    def forward(self, t):
        def feats(tt, *, dim):
            import jax.numpy as jnp
            half = dim // 2
            freqs = jnp.exp(-math.log(10000.0) * jnp.arange(half) / half)
            args = tt.astype(jnp.float32)[:, None] * freqs[None]
            return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)
        return self.mlp(apply_op(feats, t, dim=self.freq_dim))


class _SiLU(Layer):
    def forward(self, x):
        return F.silu(x)


class LabelEmbedder(Layer):
    """Class-label embedding with classifier-free-guidance dropout (the
    dropped label becomes the extra `num_classes` row)."""

    def __init__(self, num_classes: int, hidden_size: int, dropout_prob: float):
        super().__init__()
        self.num_classes = num_classes
        self.dropout_prob = dropout_prob
        self.table = Embedding(num_classes + 1, hidden_size,
                               weight_attr=Normal(0.0, 0.02))

    def forward(self, labels, train: bool = True):
        if train and self.dropout_prob > 0:
            b = labels.shape[0]
            drop = P.rand([b]) < self.dropout_prob
            labels = P.where(drop, P.full_like(labels, self.num_classes),
                             labels)
        return self.table(labels)


class DiTBlock(Layer):
    """adaLN-Zero transformer block (DiT paper): the conditioning vector
    produces shift/scale/gate for both the attention and MLP branches;
    gates start at zero (identity block at init)."""

    def __init__(self, c: DiTConfig):
        super().__init__()
        h = c.hidden_size
        self.num_heads = c.num_heads
        self.norm1 = LayerNorm(h, epsilon=1e-6, weight_attr=False,
                               bias_attr=False)
        self.qkv = Linear(h, 3 * h, weight_attr=XavierUniform())
        self.proj = Linear(h, h, weight_attr=XavierUniform())
        self.norm2 = LayerNorm(h, epsilon=1e-6, weight_attr=False,
                               bias_attr=False)
        mh = int(h * c.mlp_ratio)
        self.fc1 = Linear(h, mh, weight_attr=XavierUniform())
        self.fc2 = Linear(mh, h, weight_attr=XavierUniform())
        self.adaLN = Linear(h, 6 * h, weight_attr=Constant(0.0))
        # Megatron TP layout for the DP(+TP) recipe
        self.qkv.weight.dist_spec = (None, "mp")
        self.proj.weight.dist_spec = ("mp", None)
        self.fc1.weight.dist_spec = (None, "mp")
        self.fc2.weight.dist_spec = ("mp", None)

    def forward(self, x, cond):
        b, n, h = x.shape
        mods = P.chunk(self.adaLN(F.silu(cond)), 6, axis=-1)
        shift_a, scale_a, gate_a, shift_m, scale_m, gate_m = [
            P.unsqueeze(m, 1) for m in mods]
        xa = self.norm1(x) * (1 + scale_a) + shift_a
        qkv = P.reshape(self.qkv(xa), [b, n, 3, self.num_heads,
                                       h // self.num_heads])
        q, k, v = [P.squeeze(t, 2) for t in P.split(qkv, 3, axis=2)]
        attn = F.scaled_dot_product_attention(q, k, v, is_causal=False)
        x = x + gate_a * self.proj(P.reshape(attn, [b, n, h]))
        xm = self.norm2(x) * (1 + scale_m) + shift_m
        x = x + gate_m * self.fc2(F.gelu(self.fc1(xm), approximate=True))
        return x


class FinalLayer(Layer):
    def __init__(self, c: DiTConfig, out_channels: int):
        super().__init__()
        h = c.hidden_size
        self.norm = LayerNorm(h, epsilon=1e-6, weight_attr=False,
                              bias_attr=False)
        self.adaLN = Linear(h, 2 * h, weight_attr=Constant(0.0))
        self.linear = Linear(h, c.patch_size * c.patch_size * out_channels,
                             weight_attr=Constant(0.0))

    def forward(self, x, cond):
        shift, scale = [P.unsqueeze(m, 1)
                        for m in P.chunk(self.adaLN(F.silu(cond)), 2,
                                         axis=-1)]
        return self.linear(self.norm(x) * (1 + scale) + shift)


class DiT(Layer):
    """Diffusion Transformer: eps-prediction network over latents."""

    def __init__(self, config: DiTConfig):
        super().__init__()
        self.config = c = config
        self.out_channels = c.in_channels
        self.x_embed = Conv2D(c.in_channels, c.hidden_size,
                              kernel_size=c.patch_size, stride=c.patch_size)
        self.t_embed = TimestepEmbedder(c.hidden_size)
        self.y_embed = LabelEmbedder(c.num_classes, c.hidden_size,
                                     c.class_dropout_prob)
        n_patches = (c.input_size // c.patch_size) ** 2
        self.pos_embed = self.create_parameter(
            [1, n_patches, c.hidden_size],
            default_initializer=Normal(0.0, 0.02))
        self.blocks = LayerList([DiTBlock(c) for _ in range(c.depth)])
        self.final = FinalLayer(c, self.out_channels)

    def forward(self, x, t, y, train: bool = True):
        """x [B,C,H,W] latents; t [B] timesteps; y [B] labels -> eps
        prediction [B,C,H,W]."""
        c = self.config
        b = x.shape[0]
        x = self.x_embed(x)                       # [B, hid, H/p, W/p]
        hp = x.shape[2]
        x = P.transpose(P.reshape(x, [b, c.hidden_size, hp * hp]),
                        [0, 2, 1])                # [B, N, hid]
        x = x + self.pos_embed
        cond = self.t_embed(t) + self.y_embed(y, train=train)
        for blk in self.blocks:
            x = blk(x, cond)
        x = self.final(x, cond)                   # [B, N, p*p*C]
        # unpatchify
        p = c.patch_size
        x = P.reshape(x, [b, hp, hp, p, p, self.out_channels])
        x = P.transpose(x, [0, 5, 1, 3, 2, 4])    # B C h p w p
        return P.reshape(x, [b, self.out_channels, hp * p, hp * p])


class DiTWithDiffusion(Layer):
    """DiT + DDPM epsilon-prediction objective: one call = one training
    loss on a batch of (latents, labels) — timesteps and noise drawn from
    the framework RNG inside the compiled step."""

    def __init__(self, config: DiTConfig):
        super().__init__()
        self.dit = DiT(config)
        self.config = config
        # linear beta schedule -> alpha_bar table
        betas = np.linspace(1e-4, 2e-2, config.num_train_timesteps,
                            dtype=np.float64)
        abar = np.cumprod(1.0 - betas).astype(np.float32)
        self.register_buffer("sqrt_abar", Tensor(np.sqrt(abar)),
                             persistable=False)
        self.register_buffer("sqrt_1m_abar", Tensor(np.sqrt(1 - abar)),
                             persistable=False)

    def forward(self, x, y):
        c = self.config
        b = x.shape[0]
        t = P.randint(0, c.num_train_timesteps, [b])
        eps = P.randn(x.shape, dtype=x.dtype)
        sa = P.reshape(P.index_select(self.sqrt_abar, t), [b, 1, 1, 1])
        s1 = P.reshape(P.index_select(self.sqrt_1m_abar, t), [b, 1, 1, 1])
        x_t = x * sa + eps * s1
        pred = self.dit(x_t, t, y, train=self.training)
        return F.mse_loss(pred, eps)


# ---------------------------------------------------------------------------
# AutoencoderKL-style VAE (SD3 component): conv + GroupNorm
# ---------------------------------------------------------------------------

class ResnetBlock(Layer):
    def __init__(self, cin: int, cout: int, groups: int = 8):
        super().__init__()
        self.norm1 = GroupNorm(groups, cin, epsilon=1e-6)
        self.conv1 = Conv2D(cin, cout, 3, padding=1)
        self.norm2 = GroupNorm(groups, cout, epsilon=1e-6)
        self.conv2 = Conv2D(cout, cout, 3, padding=1)
        self.skip = Conv2D(cin, cout, 1) if cin != cout else None

    def forward(self, x):
        h = self.conv1(F.silu(self.norm1(x)))
        h = self.conv2(F.silu(self.norm2(h)))
        return (self.skip(x) if self.skip is not None else x) + h


class AutoencoderKL(Layer):
    """Compact SD-style KL autoencoder: conv/GroupNorm encoder to a
    diagonal-Gaussian latent, mirrored decoder; ``training_loss`` is
    recon MSE + KL."""

    def __init__(self, in_channels: int = 3, latent_channels: int = 4,
                 base: int = 32, groups: int = 8):
        super().__init__()
        self.enc = Sequential(
            Conv2D(in_channels, base, 3, padding=1),
            ResnetBlock(base, base, groups),
            Conv2D(base, base * 2, 3, stride=2, padding=1),   # /2
            ResnetBlock(base * 2, base * 2, groups),
            GroupNorm(groups, base * 2, epsilon=1e-6),
        )
        self.to_moments = Conv2D(base * 2, 2 * latent_channels, 1)
        self.dec_in = Conv2D(latent_channels, base * 2, 1)
        self.dec = Sequential(
            ResnetBlock(base * 2, base * 2, groups),
            _Upsample2x(),
            Conv2D(base * 2, base, 3, padding=1),
            ResnetBlock(base, base, groups),
            GroupNorm(groups, base, epsilon=1e-6),
        )
        self.dec_out = Conv2D(base, in_channels, 3, padding=1)

    def encode(self, x):
        moments = self.to_moments(F.silu(self.enc(x)))
        mean, logvar = P.chunk(moments, 2, axis=1)
        return mean, P.clip(logvar, -30.0, 20.0)

    def decode(self, z):
        return self.dec_out(F.silu(self.dec(self.dec_in(z))))

    def forward(self, x):
        mean, logvar = self.encode(x)
        z = mean + P.exp(0.5 * logvar) * P.randn(mean.shape,
                                                 dtype=mean.dtype)
        return self.decode(z), mean, logvar

    def training_loss(self, x, kl_weight: float = 1e-4):
        recon, mean, logvar = self(x)
        rec = F.mse_loss(recon, x)
        kl = 0.5 * P.mean(P.exp(logvar) + mean * mean - 1.0 - logvar)
        return rec + kl_weight * kl


class _Upsample2x(Layer):
    def forward(self, x):
        return F.interpolate(x, scale_factor=2.0, mode="nearest")
