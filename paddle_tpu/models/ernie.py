"""ERNIE-4.5-class model family (config #3 of BASELINE.json).

Reference parity: the reference's ERNIE-4.5 recipe class (PaddleNLP
``ernie`` model family: RMSNorm + RoPE + GQA + SwiGLU backbone with
ERNIE-4.5's heterogeneous MoE — leading dense layers, then MoE layers
with shared experts and top-k routing with a load-balance aux loss) and
its fleet TP+PP hybrid launch (SURVEY.md §2.3; BASELINE.json configs
row "ERNIE-4.5 (TP+PP)").

TPU-native design: weights carry Megatron ``dist_spec`` annotations so
the same model runs 1-chip or on any (dp, sharding, mp) mesh; the TP+PP
recipe is ``Ernie45ForCausalLMPipe`` — the dense backbone lowered
through the SPMD GPipe engine (stage-stacked params on the ``pp`` axis,
see distributed/pipeline.py), with Megatron TP specs on the trailing
dims.  The heterogeneous-MoE variant (``moe_num_experts > 0``) runs on
the eager/compiled path with GShard dense dispatch (nn/moe.py) whose
all-to-all is emitted by GSPMD over the EP fold.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .. import ops as P
from ..nn import functional as F
from ..nn.common import Embedding, Linear
from ..nn.container import LayerList
from ..nn.initializer import Normal
from ..nn.layer import Layer
from ..nn.moe import MoELayer
from ..nn.norm import RMSNorm
from ..tensor import Tensor
from .llama import (LlamaAttention, LlamaConfig, LlamaForCausalLMPipe,
                    LlamaMLP, LlamaPretrainingCriterion, _rope_cos_sin)

__all__ = ["Ernie45Config", "Ernie45ForCausalLM", "Ernie45ForCausalLMPipe",
           "ernie45_tiny_config", "ernie45_a3b_config"]


@dataclass
class Ernie45Config:
    vocab_size: int = 103424
    hidden_size: int = 2560
    intermediate_size: int = 12288
    num_hidden_layers: int = 28
    num_attention_heads: int = 20
    num_key_value_heads: int = 4
    max_position_embeddings: int = 131072
    rms_norm_eps: float = 1e-5
    rope_theta: float = 500000.0
    # ERNIE-4.5 checkpoints use GPT-J-interleaved rope; the converter
    # permutes q/k lanes so the model runs the fast contiguous rope with
    # identical numerics (set True only for unconverted parity checks)
    rope_interleaved: bool = False
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    use_flash_attention: bool = True
    recompute: bool = False
    fuse_linear_cross_entropy: bool = True
    # heterogeneous MoE (0 experts = dense model)
    moe_num_experts: int = 0
    moe_k: int = 6
    moe_intermediate_size: int = 1536
    moe_num_shared_experts: int = 2
    moe_layer_start_index: int = 1      # leading layers stay dense
    moe_aux_loss_coef: float = 0.001

    def as_llama(self) -> LlamaConfig:
        return LlamaConfig(
            vocab_size=self.vocab_size, hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            num_hidden_layers=self.num_hidden_layers,
            num_attention_heads=self.num_attention_heads,
            num_key_value_heads=self.num_key_value_heads,
            max_position_embeddings=self.max_position_embeddings,
            rms_norm_eps=self.rms_norm_eps, rope_theta=self.rope_theta,
            initializer_range=self.initializer_range,
            tie_word_embeddings=self.tie_word_embeddings,
            rope_interleaved=self.rope_interleaved,
            use_flash_attention=self.use_flash_attention,
            recompute=self.recompute,
            fuse_linear_cross_entropy=self.fuse_linear_cross_entropy)


def ernie45_a3b_config() -> Ernie45Config:
    """ERNIE-4.5-21B-A3B-class shape: 64 experts top-6 + 2 shared,
    first layer dense."""
    return Ernie45Config(moe_num_experts=64, moe_k=6,
                         moe_num_shared_experts=2,
                         moe_layer_start_index=1)


def ernie45_tiny_config(moe: bool = False) -> Ernie45Config:
    return Ernie45Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rope_theta=10000.0,
        moe_num_experts=8 if moe else 0, moe_k=2,
        moe_intermediate_size=32, moe_num_shared_experts=1,
        moe_layer_start_index=1)


class Ernie45DecoderLayer(Layer):
    def __init__(self, config: Ernie45Config, layer_idx: int):
        super().__init__()
        c = config
        self.input_layernorm = RMSNorm(c.hidden_size, epsilon=c.rms_norm_eps)
        self.self_attn = LlamaAttention(c.as_llama())
        self.post_attention_layernorm = RMSNorm(c.hidden_size,
                                                epsilon=c.rms_norm_eps)
        self.is_moe = (c.moe_num_experts > 0
                       and layer_idx >= c.moe_layer_start_index)
        if self.is_moe:
            self.mlp = MoELayer(
                c.hidden_size, c.moe_num_experts, c.moe_intermediate_size,
                k=c.moe_k,
                shared_expert_intermediate=(c.moe_num_shared_experts
                                            * c.moe_intermediate_size),
                balance_loss_weight=1.0,
                init_std=c.initializer_range,
                num_layers_scale=c.num_hidden_layers)
        else:
            self.mlp = LlamaMLP(c.as_llama())

    def forward(self, x, cos_sin):
        x = x + self.self_attn(self.input_layernorm(x), cos_sin)
        x = x + self.mlp(self.post_attention_layernorm(x))
        aux = self.mlp.aux_loss if self.is_moe else None
        return x, aux


class Ernie45ForCausalLM(Layer):
    """Eager/compiled ERNIE-4.5-class causal LM (dense or hetero-MoE)."""

    def __init__(self, config: Ernie45Config):
        super().__init__()
        self.config = config
        c = config
        init = Normal(0.0, c.initializer_range)
        self.embed_tokens = Embedding(c.vocab_size, c.hidden_size,
                                      weight_attr=init)
        self.embed_tokens.weight.dist_spec = ("mp", None)
        self.layers = LayerList([Ernie45DecoderLayer(c, i)
                                 for i in range(c.num_hidden_layers)])
        self.norm = RMSNorm(c.hidden_size, epsilon=c.rms_norm_eps)
        if c.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = Linear(c.hidden_size, c.vocab_size,
                                  bias_attr=False, weight_attr=init)
            self.lm_head.weight.dist_spec = (None, "mp")
        hd = c.hidden_size // c.num_attention_heads
        rope = _rope_cos_sin(c.max_position_embeddings, hd, c.rope_theta)
        self.register_buffer("rope_cos", Tensor(np.cos(rope)),
                             persistable=False)
        self.register_buffer("rope_sin", Tensor(np.sin(rope)),
                             persistable=False)

    def forward(self, input_ids, labels=None):
        c = self.config
        b, s = input_ids.shape
        x = self.embed_tokens(input_ids)
        cos_sin = (self.rope_cos[:s], self.rope_sin[:s])
        aux_losses = []
        for layer in self.layers:
            if c.recompute:
                from ..jit.recompute import recompute
                x, aux = recompute(layer, x, cos_sin)
            else:
                x, aux = layer(x, cos_sin)
            if aux is not None:
                aux_losses.append(aux)
        x = self.norm(x)
        aux_total = None
        if aux_losses:
            aux_total = aux_losses[0]
            for a in aux_losses[1:]:
                aux_total = aux_total + a
            aux_total = aux_total * c.moe_aux_loss_coef

        if labels is not None and c.fuse_linear_cross_entropy:
            if self.lm_head is None:
                loss = F.fused_linear_cross_entropy(
                    x, self.embed_tokens.weight, labels,
                    transpose_weight=True)
            else:
                loss = F.fused_linear_cross_entropy(
                    x, self.lm_head.weight, labels)
            return loss + aux_total if aux_total is not None else loss
        if self.lm_head is None:
            logits = P.matmul(x, self.embed_tokens.weight, transpose_y=True)
        else:
            logits = self.lm_head(x)
        if labels is not None:
            loss = LlamaPretrainingCriterion()(logits, labels)
            return loss + aux_total if aux_total is not None else loss
        return logits


class Ernie45ForCausalLMPipe(LlamaForCausalLMPipe):
    """The TP+PP recipe: ERNIE-4.5 dense backbone through the SPMD GPipe
    engine (stage-stacked params sharded over ``pp``, Megatron TP specs
    over ``mp``).  The heterogeneous-MoE variant is served by
    Ernie45ForCausalLM (MoE layer stacks are non-uniform across stages,
    which the stacked-scan pipe deliberately does not model)."""

    def __init__(self, config: Ernie45Config, n_microbatches: int = 4):
        from ..common.errors import enforce
        enforce(config.moe_num_experts == 0,
                "Ernie45ForCausalLMPipe is the dense TP+PP recipe; "
                "use Ernie45ForCausalLM for the MoE variant")
        super().__init__(config.as_llama(), n_microbatches=n_microbatches)
        self.ernie_config = config
